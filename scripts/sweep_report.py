"""Summarize healthy-window experiment artifacts into a defaults table.

``scripts/tpu-experiments.sh`` banks budget-capped north-star variants as
``bench-artifacts/exp-<tag>-<stamp>.json``. This reads them all, groups by
configuration (rng x chunk x check), and prints per-config best rates plus
a recommendation line — the evidence trail for changing bench defaults
(e.g. ``--chunk``) between rounds. Partial runs are rate-bearing (the
bench verifies what it measured before stopping), so they count, flagged.

Also summarizes the batched-ingest rider artifacts
(``bench-artifacts/ingest-<stamp>.json``, written by bench.py's
measure_batched_ingest): host sealing, client build, and REST ingest
rates plus the measured telemetry overhead, one row per run — the
host-plane trend line next to the device-plane sweep table.

Also tabulates the clerking-pipeline rider artifacts
(``bench-artifacts/clerking-<stamp>.json``, written by bench.py's
measure_clerking_pipeline): one row per delivery config (monolithic
baseline + each paged chunk size) with throughput, the ratio against the
monolithic baseline from the SAME run, peak clerk RSS, and the measured
download-overlap efficiency.

Also tabulates the reveal-pipeline rider artifacts
(``bench-artifacts/reveal-<stamp>.json``, written by bench.py's
measure_reveal_pipeline) in the same shape: monolithic vs chunked reveal
per cohort size, with peak recipient RSS and overlap efficiency — the
evidence that reveal memory stays flat in N.

Also tabulates the committee-scaling rider artifacts
(``bench-artifacts/committee-<stamp>.json``, written by bench.py's
measure_committee_scaling): one row per crypto plane (clerking / reveal /
ingest) per SDA_WORKERS count, plus the sqlite read-pool thread probe,
with a scaling-efficiency column (speedup over the serial run divided by
the worker count; 1.0 = perfect scaling) and the host cpu_count the run
measured on.

Also tabulates the wire-transport rider artifacts
(``bench-artifacts/wire-<stamp>.json``, written by bench.py's
measure_wire_transport): one row per run with the JSON-leg and binary-leg
ingest rates measured over the same live keep-alive server, the
binary-vs-json ratio, the ratio against the recorded ~11K/s pre-binary
JSON baseline (the wire plane's acceptance bar), the clerking-fetch and
reveal ratios, and whether server RSS stayed flat across the legs.

Also tabulates the tier-fanout rider artifacts
(``bench-artifacts/tier-<stamp>.json``, written by bench.py's
measure_tier_fanout): one row per fan-out config (flat baseline + each
2-tier fan-out m) with the largest clerk job in columns, its ratio
against the flat N, mean stage seconds per clerk job, clerked inputs
per clerk-second, and the honestly-reported single-core round wall —
the evidence that hierarchical committees shrink the per-clerk bound
even where one CPU serializes every committee. Artifacts that carry the
promotion A/B leg get a second table: per-node driver promotion latency
under the reveal round-trip vs share-promotion, side by side.

Also tabulates the sustained-soak rider artifacts (``soak-<stamp>.json``
and the fault-axis variants ``replica-soak-*`` / ``grow-soak-*``, written
by scripts/load_soak.py) and the flagship campaign artifacts
(``flagship-<stamp>.json``, written by scripts/flagship.py): one row per
campaign with the process/shard/replica topology, the certified-max-
cohort headline and its implied scale factor against the simulated
population, rungs certified vs attempted, the peak certified
phones-per-second, and the merged cross-process telemetry coverage.
Flagship artifacts carrying the within-run arrivals A/B leg get a second
table: the serial vs pipelined ``rung.arrivals`` walls at the same
cohort, side by side with the gated speedup ratio.

Also tabulates the sketch-accuracy rider artifacts
(``bench-artifacts/sketch-<stamp>.json``, written by bench.py's
measure_sketch_accuracy): the accuracy-vs-dimension table — one row per
sketch family per wire dimension with the observed error, the analytic
bound, the headroom ratio (bound / observed error, >= 1 inside bound),
the end-to-end items/s, and whether the secure sum stayed byte-exact.

Also rolls the churn harness's banked cells (``scenario-<name>-*.json``,
written by scripts/scenarios.py) into the survivability matrix: scenario
rows x (store, transport) columns, latest artifact per cell, OK / FAIL /
``--`` for never-run — plus any retry-layer overhead A/B records
(``overhead-ab-*.json``).

Usage: python scripts/sweep_report.py [artifact_dir]
"""

from __future__ import annotations

import json
import pathlib
import sys


def load(artdir: pathlib.Path):
    rows = []
    for f in sorted(artdir.glob("exp-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        if not isinstance(d, dict) or not d.get("value"):
            continue  # error lines / empty artifacts carry no rate
        rows.append(
            {
                "artifact": f.name,
                # None = not recorded (pre-r5): tag_of falls back to the
                # filename tag instead of assuming the defaults
                "rng": d.get("rng"),
                "check": d.get("check"),
                "chunk": d.get("chunk"),
                "value": d["value"],
                "steady_s": d.get("steady_s"),
                "partial": bool(d.get("partial")),
                "dim": d.get("dim"),
                "participants": d.get("participants"),
            }
        )
    return rows


#: rate/overhead columns lifted from each ingest artifact (absent keys —
#: older artifacts — render as "-")
_INGEST_COLS = (
    "seal_batch_per_s",
    "build_per_s",
    "participate_many_per_s",
    "rest_sqlite_batch_per_s",
    "rest_mem_batch_per_s",
    "telemetry_overhead_pct",
)


def load_ingest(artdir: pathlib.Path):
    rows = []
    for f in sorted(artdir.glob("ingest-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        if not isinstance(d, dict) or all(d.get(k) is None for k in _INGEST_COLS):
            continue  # no rate-bearing fields: nothing to tabulate
        rows.append({"artifact": f.name, **{k: d.get(k) for k in _INGEST_COLS}})
    return rows


def print_ingest(rows) -> None:
    print("\nbatched-ingest riders (ingest-*.json):")
    print(
        f"{'seal/s':>8} {'build/s':>8} {'many/s':>8} {'sqlite/s':>9} "
        f"{'mem/s':>8} {'tel_ov%':>8}  artifact"
    )
    for r in rows:
        cells = [
            (r["seal_batch_per_s"], 8),
            (r["build_per_s"], 8),
            (r["participate_many_per_s"], 8),
            (r["rest_sqlite_batch_per_s"], 9),
            (r["rest_mem_batch_per_s"], 8),
            (r["telemetry_overhead_pct"], 8),
        ]
        row = " ".join(
            f"{v if v is not None else '-':>{w}}" for v, w in cells
        )
        print(f"{row}  {r['artifact']}")


def load_clerking(artdir: pathlib.Path):
    """One row per delivery config per clerking-*.json artifact."""
    rows = []
    for f in sorted(artdir.glob("clerking-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        configs = d.get("configs") if isinstance(d, dict) else None
        if not isinstance(configs, dict):
            continue
        n = (d.get("config") or {}).get("n_participants")
        for tag, cfg in sorted(configs.items()):
            if not isinstance(cfg, dict) or cfg.get("encryptions_per_s") is None:
                continue
            rows.append(
                {
                    "artifact": f.name,
                    "tag": tag,
                    "n": n,
                    "chunk": cfg.get("chunk_size"),
                    "encs_per_s": cfg.get("encryptions_per_s"),
                    "vs_mono": cfg.get("vs_monolithic"),
                    "rss_mib": cfg.get("peak_rss_mib"),
                    "overlap": cfg.get("overlap_efficiency"),
                }
            )
    return rows


def print_clerking(rows) -> None:
    print("\nclerking-pipeline riders (clerking-*.json):")
    print(
        f"{'config':>14} {'n':>7} {'chunk':>6} {'encs/s':>9} {'vs_mono':>8} "
        f"{'rss_mib':>8} {'overlap':>8}  artifact"
    )
    for r in rows:
        overlap = f"{r['overlap']:.2f}" if r["overlap"] is not None else "-"
        print(
            f"{r['tag']:>14} {r['n'] if r['n'] is not None else '-':>7} "
            f"{r['chunk'] if r['chunk'] is not None else '-':>6} "
            f"{r['encs_per_s']:>9} "
            f"{r['vs_mono'] if r['vs_mono'] is not None else '-':>8} "
            f"{r['rss_mib'] if r['rss_mib'] is not None else '-':>8} "
            f"{overlap:>8}  {r['artifact']}"
        )


def load_reveal(artdir: pathlib.Path):
    """One row per delivery config per reveal-*.json artifact."""
    rows = []
    for f in sorted(artdir.glob("reveal-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        configs = d.get("configs") if isinstance(d, dict) else None
        if not isinstance(configs, dict):
            continue
        for tag, cfg in sorted(configs.items()):
            if not isinstance(cfg, dict) or cfg.get("encryptions_per_s") is None:
                continue
            rows.append(
                {
                    "artifact": f.name,
                    "tag": tag,
                    "n": cfg.get("n_participants"),
                    "chunk": cfg.get("chunk_size"),
                    "encs_per_s": cfg.get("encryptions_per_s"),
                    "vs_mono": cfg.get("vs_monolithic"),
                    "rss_mib": cfg.get("peak_rss_mib"),
                    "overlap": cfg.get("overlap_efficiency"),
                }
            )
    return rows


def print_reveal(rows) -> None:
    print("\nreveal-pipeline riders (reveal-*.json):")
    print(
        f"{'config':>16} {'n':>7} {'chunk':>6} {'encs/s':>9} {'vs_mono':>8} "
        f"{'rss_mib':>8} {'overlap':>8}  artifact"
    )
    for r in rows:
        overlap = f"{r['overlap']:.2f}" if r["overlap"] is not None else "-"
        print(
            f"{r['tag']:>16} {r['n'] if r['n'] is not None else '-':>7} "
            f"{r['chunk'] if r['chunk'] is not None else '-':>6} "
            f"{r['encs_per_s']:>9} "
            f"{r['vs_mono'] if r['vs_mono'] is not None else '-':>8} "
            f"{r['rss_mib'] if r['rss_mib'] is not None else '-':>8} "
            f"{overlap:>8}  {r['artifact']}"
        )


def load_committee(artdir: pathlib.Path):
    """One row per plane x worker count (plus the read-pool thread probe)
    per committee-*.json artifact, with scaling efficiency = speedup over
    the serial run divided by the worker count (1.0 = perfect scaling)."""
    rows = []
    for f in sorted(artdir.glob("committee-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        if not isinstance(d, dict):
            continue
        cpu = d.get("cpu_count")
        planes = d.get("planes") if isinstance(d.get("planes"), dict) else {}
        for plane, configs in sorted(planes.items()):
            if not isinstance(configs, dict):
                continue
            for _, cfg in sorted(configs.items()):
                if not isinstance(cfg, dict) or cfg.get("per_s") is None:
                    continue
                w, vs = cfg.get("workers"), cfg.get("vs_w1")
                rows.append(
                    {
                        "artifact": f.name,
                        "plane": plane,
                        "workers": w,
                        "per_s": cfg.get("per_s"),
                        "vs_w1": vs,
                        "efficiency": (
                            round(vs / w, 2) if vs is not None and w else None
                        ),
                        "rss_mib": cfg.get("peak_rss_mib"),
                        "identical": cfg.get("identical_to_serial"),
                        "cpu": cpu,
                    }
                )
        pool = d.get("read_pool") if isinstance(d.get("read_pool"), dict) else {}
        for _, cfg in sorted(pool.items()):
            if not isinstance(cfg, dict) or cfg.get("reads_per_s") is None:
                continue
            t, vs = cfg.get("threads"), cfg.get("vs_t1")
            rows.append(
                {
                    "artifact": f.name,
                    "plane": "read_pool",
                    "workers": t,
                    "per_s": cfg.get("reads_per_s"),
                    "vs_w1": vs,
                    "efficiency": (
                        round(vs / t, 2) if vs is not None and t else None
                    ),
                    "rss_mib": None,
                    # byte-identity is asserted on the crypto planes; the
                    # read probe verifies row counts instead
                    "identical": None,
                    "cpu": cpu,
                }
            )
    return rows


def print_committee(rows) -> None:
    print("\ncommittee-scaling riders (committee-*.json):")
    print(
        f"{'plane':>10} {'workers':>7} {'per_s':>9} {'vs_w1':>6} "
        f"{'scal_eff':>8} {'rss_mib':>8} {'ident':>5} {'cpus':>4}  artifact"
    )
    for r in rows:
        ident = "-" if r["identical"] is None else ("yes" if r["identical"] else "NO")
        print(
            f"{r['plane']:>10} {r['workers'] if r['workers'] is not None else '-':>7} "
            f"{r['per_s']:>9} "
            f"{r['vs_w1'] if r['vs_w1'] is not None else '-':>6} "
            f"{r['efficiency'] if r['efficiency'] is not None else '-':>8} "
            f"{r['rss_mib'] if r['rss_mib'] is not None else '-':>8} "
            f"{ident:>5} {r['cpu'] if r['cpu'] is not None else '-':>4}  "
            f"{r['artifact']}"
        )


def load_wire(artdir: pathlib.Path):
    """One row per wire-*.json artifact: both legs' ingest rates plus the
    ratio columns (vs the same-run JSON leg and vs the recorded pre-binary
    baseline)."""
    rows = []
    for f in sorted(artdir.glob("wire-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        if not isinstance(d, dict):
            continue
        json_leg = d.get("json") if isinstance(d.get("json"), dict) else {}
        binary_leg = d.get("binary") if isinstance(d.get("binary"), dict) else {}
        if binary_leg.get("ingest_per_s") is None:
            continue  # no rate: nothing to tabulate
        rows.append(
            {
                "artifact": f.name,
                "n": d.get("n_participants"),
                "store": d.get("store"),
                "json_ingest_per_s": json_leg.get("ingest_per_s"),
                "binary_ingest_per_s": binary_leg.get("ingest_per_s"),
                "vs_json": d.get("ingest_binary_vs_json"),
                "vs_baseline": d.get("ingest_binary_vs_baseline"),
                "fetch_ratio": d.get("clerking_fetch_binary_vs_json"),
                "reveal_ratio": d.get("reveal_binary_vs_json"),
                "rss_flat": d.get("rss_flat"),
            }
        )
    return rows


def print_wire(rows) -> None:
    print("\nwire-transport riders (wire-*.json):")
    print(
        f"{'n':>7} {'store':>6} {'json/s':>8} {'binary/s':>9} {'vs_json':>8} "
        f"{'vs_base':>8} {'fetch_x':>8} {'reveal_x':>8} {'rss':>5}  artifact"
    )
    for r in rows:
        rss = "-" if r["rss_flat"] is None else ("flat" if r["rss_flat"] else "GREW")
        print(
            f"{r['n'] if r['n'] is not None else '-':>7} "
            f"{r['store'] if r['store'] is not None else '-':>6} "
            f"{r['json_ingest_per_s'] if r['json_ingest_per_s'] is not None else '-':>8} "
            f"{r['binary_ingest_per_s']:>9} "
            f"{r['vs_json'] if r['vs_json'] is not None else '-':>8} "
            f"{r['vs_baseline'] if r['vs_baseline'] is not None else '-':>8} "
            f"{r['fetch_ratio'] if r['fetch_ratio'] is not None else '-':>8} "
            f"{r['reveal_ratio'] if r['reveal_ratio'] is not None else '-':>8} "
            f"{rss:>5}  {r['artifact']}"
        )


def load_tier(artdir: pathlib.Path):
    """One row per fan-out config per tier-*.json artifact (flat baseline
    first, then each 2-tier fan-out), with the per-clerk-bound columns and
    the honestly-reported single-core wall ratio."""
    rows = []
    for f in sorted(artdir.glob("tier-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        configs = d.get("configs") if isinstance(d, dict) else None
        if not isinstance(configs, dict):
            continue
        n = (d.get("config") or {}).get("n_participants")
        # flat first, then fan-outs ascending — sorted() would interleave
        for tag in ["flat"] + sorted(
            (t for t in configs if t != "flat"),
            key=lambda t: configs[t].get("fanout") or 0,
        ):
            cfg = configs.get(tag)
            if not isinstance(cfg, dict) or cfg.get("max_job_participations") is None:
                continue
            rows.append(
                {
                    "artifact": f.name,
                    "tag": tag,
                    "n": n,
                    "nodes": cfg.get("nodes"),
                    "max_job": cfg.get("max_job_participations"),
                    "vs_flat": cfg.get("vs_flat_max_job"),
                    "per_job_s": cfg.get("per_job_stage_s"),
                    "inputs_per_clerk_s": cfg.get("inputs_per_clerk_s"),
                    "wall_s": cfg.get("wall_s"),
                    "exact": cfg.get("exact"),
                }
            )
    return rows


def print_tier(rows) -> None:
    print("\ntier-fanout riders (tier-*.json):")
    print(
        f"{'config':>8} {'n':>6} {'nodes':>5} {'max_job':>8} {'vs_flat':>8} "
        f"{'job_s':>8} {'in/clk_s':>9} {'wall_s':>7} {'exact':>5}  artifact"
    )
    for r in rows:
        per_job = f"{r['per_job_s']:.5f}" if r["per_job_s"] is not None else "-"
        exact = "-" if r["exact"] is None else ("yes" if r["exact"] else "NO")
        print(
            f"{r['tag']:>8} {r['n'] if r['n'] is not None else '-':>6} "
            f"{r['nodes'] if r['nodes'] is not None else '-':>5} "
            f"{r['max_job']:>8} "
            f"{r['vs_flat'] if r['vs_flat'] is not None else '-':>8} "
            f"{per_job:>8} "
            f"{r['inputs_per_clerk_s'] if r['inputs_per_clerk_s'] is not None else '-':>9} "
            f"{r['wall_s'] if r['wall_s'] is not None else '-':>7} "
            f"{exact:>5}  {r['artifact']}"
        )


def load_promotion_ab(artdir: pathlib.Path):
    """One row per promotion path per tier-*.json artifact carrying the
    reveal-vs-share-promotion A/B leg (bench.py measure_tier_fanout):
    per-node driver promotion latency, its inverse rate, the clerk-side
    re-share cost reported alongside, and the reshare-vs-reveal ratio."""
    rows = []
    for f in sorted(artdir.glob("tier-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        ab = d.get("promotion_ab") if isinstance(d, dict) else None
        if not isinstance(ab, dict):
            continue
        for path in ("reveal", "reshare"):
            leg = ab.get(path)
            if not isinstance(leg, dict):
                continue
            rows.append(
                {
                    "artifact": f.name,
                    "path": path,
                    "nodes": leg.get("promoted_nodes"),
                    "per_node_s": leg.get("per_node_promotion_s"),
                    "nodes_per_s": leg.get("promote_nodes_per_s"),
                    "clerk_reshare_s": leg.get("clerk_reshare_s"),
                    "wall_s": leg.get("wall_s"),
                    "vs_reveal": leg.get("vs_reveal_per_node"),
                    "exact": leg.get("exact"),
                }
            )
    return rows


def print_promotion_ab(rows) -> None:
    print("\ntier promotion A/B (reveal vs share-promotion, tier-*.json):")
    print(
        f"{'path':>8} {'nodes':>5} {'node_s':>9} {'nodes/s':>8} "
        f"{'clk_rshr_s':>10} {'wall_s':>7} {'vs_reveal':>9} {'exact':>5}  artifact"
    )
    for r in rows:
        per_node = f"{r['per_node_s']:.5f}" if r["per_node_s"] is not None else "-"
        exact = "-" if r["exact"] is None else ("yes" if r["exact"] else "NO")
        print(
            f"{r['path']:>8} "
            f"{r['nodes'] if r['nodes'] is not None else '-':>5} "
            f"{per_node:>9} "
            f"{r['nodes_per_s'] if r['nodes_per_s'] is not None else '-':>8} "
            f"{r['clerk_reshare_s'] if r['clerk_reshare_s'] is not None else '-':>10} "
            f"{r['wall_s'] if r['wall_s'] is not None else '-':>7} "
            f"{r['vs_reveal'] if r['vs_reveal'] is not None else '-':>9} "
            f"{exact:>5}  {r['artifact']}"
        )


def load_soak(artdir: pathlib.Path):
    """One row per soak-family artifact (soak-* / replica-soak-* /
    grow-soak-*, scripts/load_soak.py): rounds and
    exactness, sample count, mean/max total request rate, the worst
    windowed p99 over the hottest route, the RSS trajectory, and the
    sampler overhead A/B."""
    rows = []
    # the fault axes bank their own families (replica-soak-*, grow-soak-*)
    # so bench_compare stays apples-to-apples, but the report shows them
    # side by side — the artifact name carries the family
    names = sorted(
        f for pat in ("soak-*.json", "replica-soak-*.json", "grow-soak-*.json")
        for f in artdir.glob(pat)
    )
    for f in names:
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        if not isinstance(d, dict) or d.get("kind") != "soak":
            continue
        summary = d.get("summary") if isinstance(d.get("summary"), dict) else {}
        p99s = summary.get("p99_s_by_route") or {}
        worst = None
        if p99s:
            worst_route = max(p99s, key=lambda r: p99s[r].get("max", 0))
            worst = (worst_route, p99s[worst_route].get("max"))
        rss = summary.get("rss_mib") or {}
        rows.append(
            {
                "artifact": f.name,
                "duration_s": (d.get("config") or {}).get("duration_s"),
                "rate": (d.get("config") or {}).get("rate"),
                "rounds": d.get("total_rounds"),
                "exact": d.get("exact_rounds"),
                "samples": len(d.get("samples") or []),
                "rps_mean": summary.get("rps_mean"),
                "rps_max": summary.get("rps_max"),
                "worst_p99": worst,
                "rss_start": rss.get("start"),
                "rss_peak": rss.get("peak"),
                "overhead_pct": d.get("sampler_overhead_pct"),
                "faults": (d.get("config") or {}).get("faults"),
            }
        )
    return rows


def print_soak(rows) -> None:
    print("\nsustained-soak riders (soak-*/replica-soak-*/grow-soak-*.json):")
    print(
        f"{'dur_s':>6} {'rate':>6} {'rounds':>6} {'exact':>6} {'smpls':>5} "
        f"{'rps_mean':>8} {'rps_max':>8} {'worst_p99':>24} "
        f"{'rss_mib':>13} {'smplr%':>7}  artifact"
    )
    for r in rows:
        exact = (
            "-" if r["exact"] is None
            else (f"{r['exact']}/{r['rounds']}" if r["exact"] != r["rounds"]
                  else "all")
        )
        worst = (
            f"{r['worst_p99'][1]:.4f}s {r['worst_p99'][0][-16:]}"
            if r["worst_p99"] and r["worst_p99"][1] is not None else "-"
        )
        rss = (
            f"{r['rss_start']}->{r['rss_peak']}"
            if r["rss_start"] is not None and r["rss_peak"] is not None else "-"
        )
        ov = f"{r['overhead_pct']:+.2f}" if r["overhead_pct"] is not None else "-"
        tag = " +faults" if r["faults"] else ""
        print(
            f"{r['duration_s'] if r['duration_s'] is not None else '-':>6} "
            f"{r['rate'] if r['rate'] is not None else '-':>6} "
            f"{r['rounds'] if r['rounds'] is not None else '-':>6} "
            f"{exact:>6} {r['samples']:>5} "
            f"{r['rps_mean'] if r['rps_mean'] is not None else '-':>8} "
            f"{r['rps_max'] if r['rps_max'] is not None else '-':>8} "
            f"{worst:>24} {rss:>13} {ov:>7}  {r['artifact']}{tag}"
        )


def load_flagship(artdir: pathlib.Path):
    """One row per flagship-*.json campaign (scripts/flagship.py): the
    composed-topology headline — certified max cohort, implied scale
    factor against the simulated population, rung ladder shape, peak
    certified phones/s, and the merged cross-process telemetry span."""
    rows = []
    for f in sorted(artdir.glob("flagship-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        if not isinstance(d, dict) or d.get("kind") != "flagship":
            continue
        topo = d.get("topology") if isinstance(d.get("topology"), dict) else {}
        ladder = d.get("ladder") if isinstance(d.get("ladder"), list) else []
        certified = [r for r in ladder
                     if isinstance(r, dict) and r.get("certified")]
        rates = [
            r["cohort"] / r["round_s"] for r in certified
            if isinstance(r.get("cohort"), (int, float))
            and isinstance(r.get("round_s"), (int, float)) and r["round_s"] > 0
        ]
        merged = d.get("merged_samples") or []
        procs = [s.get("procs", 0) for s in merged if isinstance(s, dict)]
        rows.append(
            {
                "artifact": f.name,
                "frontends": topo.get("frontend_processes"),
                "shards": topo.get("shards"),
                "replicas": topo.get("replicas"),
                "tiers": topo.get("tiers"),
                "certified_max": d.get("certified_max_cohort"),
                "scale_factor": d.get("scale_factor"),
                "rungs": (len(certified), len(ladder)),
                "peak_per_s": max(rates) if rates else None,
                "buckets": len(merged),
                "peak_procs": max(procs) if procs else None,
                "campaign_s": d.get("campaign_s"),
            }
        )
    return rows


def print_flagship(rows) -> None:
    print("\nflagship campaigns (flagship-*.json):")
    print(
        f"{'topology':>12} {'cert_max':>8} {'scale_x':>8} {'rungs':>6} "
        f"{'peak/s':>8} {'buckets':>7} {'procs':>5} {'wall_s':>7}  artifact"
    )
    for r in rows:
        topo = (
            f"{r['frontends']}fx{r['shards']}sx{r['replicas']}r"
            if None not in (r["frontends"], r["shards"], r["replicas"])
            else "-"
        )
        rungs = f"{r['rungs'][0]}/{r['rungs'][1]}"
        peak = f"{r['peak_per_s']:.1f}" if r["peak_per_s"] is not None else "-"
        print(
            f"{topo:>12} "
            f"{r['certified_max'] if r['certified_max'] is not None else '-':>8} "
            f"{r['scale_factor'] if r['scale_factor'] is not None else '-':>8} "
            f"{rungs:>6} "
            f"{peak:>8} "
            f"{r['buckets']:>7} "
            f"{r['peak_procs'] if r['peak_procs'] is not None else '-':>5} "
            f"{r['campaign_s'] if r['campaign_s'] is not None else '-':>7}  "
            f"{r['artifact']}"
        )


def load_arrivals_ab(artdir: pathlib.Path):
    """One row per flagship-*.json campaign carrying the within-run
    arrivals A/B (scripts/flagship.py): the serial and pipelined
    rung.arrivals walls at the same cohort on the same live plane, the
    drift-immune speedup ratio bench_compare gates, and both legs'
    exactness flags."""
    rows = []
    for f in sorted(artdir.glob("flagship-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        ab = d.get("arrivals_ab") if isinstance(d, dict) else None
        if not isinstance(ab, dict):
            continue
        legs = ab.get("legs") if isinstance(ab.get("legs"), dict) else {}
        serial = legs.get("serial") if isinstance(legs.get("serial"), dict) else {}
        pipe = (
            legs.get("pipelined")
            if isinstance(legs.get("pipelined"), dict) else {}
        )
        rows.append(
            {
                "artifact": f.name,
                "cohort": ab.get("cohort"),
                "serial_s": serial.get("arrivals_s"),
                "pipelined_s": pipe.get("arrivals_s"),
                "speedup": ab.get("arrivals_pipeline_speedup"),
                "churned": (serial.get("churned"), pipe.get("churned")),
                "exact": (
                    serial.get("exact") and serial.get("flat_byte_match")
                    and pipe.get("exact") and pipe.get("flat_byte_match")
                ),
            }
        )
    return rows


def print_arrivals_ab(rows) -> None:
    print("\narrivals ingest A/B (serial vs pipelined, flagship-*.json):")
    print(
        f"{'cohort':>7} {'serial_s':>9} {'pipe_s':>8} {'speedup':>8} "
        f"{'churned':>9} {'exact':>5}  artifact"
    )
    for r in rows:
        churned = (
            f"{r['churned'][0]}/{r['churned'][1]}"
            if None not in r["churned"] else "-"
        )
        exact = "-" if r["exact"] is None else ("yes" if r["exact"] else "NO")
        print(
            f"{r['cohort'] if r['cohort'] is not None else '-':>7} "
            f"{r['serial_s'] if r['serial_s'] is not None else '-':>9} "
            f"{r['pipelined_s'] if r['pipelined_s'] is not None else '-':>8} "
            f"{r['speedup'] if r['speedup'] is not None else '-':>8} "
            f"{churned:>9} {exact:>5}  {r['artifact']}"
        )


def load_tier_close_ab(artdir: pathlib.Path):
    """One row per flagship-*.json campaign carrying the within-run
    tier-close A/B (scripts/flagship.py): the SDA_TIER_FANOUT=1 serial
    and default-fanout post-ingest tier walls (all tier.* stages —
    falling back to tier.close alone for older artifacts) at the same
    cohort on the same live plane, the drift-immune
    ``tier_close_fanout_speedup`` ratio bench_compare gates, the fanout
    leg's lane occupancy, and both legs' exactness flags."""
    rows = []
    for f in sorted(artdir.glob("flagship-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        ab = d.get("tier_close_ab") if isinstance(d, dict) else None
        if not isinstance(ab, dict):
            continue
        legs = ab.get("legs") if isinstance(ab.get("legs"), dict) else {}
        serial = legs.get("serial") if isinstance(legs.get("serial"), dict) else {}
        fan = legs.get("fanout") if isinstance(legs.get("fanout"), dict) else {}
        rows.append(
            {
                "artifact": f.name,
                "cohort": ab.get("cohort"),
                "serial_s": serial.get("tier_s", serial.get("tier_close_s")),
                "fanout_s": fan.get("tier_s", fan.get("tier_close_s")),
                "speedup": ab.get("tier_close_fanout_speedup"),
                "overlap": fan.get("overlap_efficiency"),
                "exact": (
                    serial.get("exact") and serial.get("flat_byte_match")
                    and fan.get("exact") and fan.get("flat_byte_match")
                ),
            }
        )
    return rows


def print_tier_close_ab(rows) -> None:
    print("\ntier close A/B (serial vs fanned-out siblings, flagship-*.json):")
    print(
        f"{'cohort':>7} {'serial_s':>9} {'fanout_s':>9} {'speedup':>8} "
        f"{'overlap':>8} {'exact':>5}  artifact"
    )
    for r in rows:
        exact = "-" if r["exact"] is None else ("yes" if r["exact"] else "NO")
        print(
            f"{r['cohort'] if r['cohort'] is not None else '-':>7} "
            f"{r['serial_s'] if r['serial_s'] is not None else '-':>9} "
            f"{r['fanout_s'] if r['fanout_s'] is not None else '-':>9} "
            f"{r['speedup'] if r['speedup'] is not None else '-':>8} "
            f"{r['overlap'] if r['overlap'] is not None else '-':>8} "
            f"{exact:>5}  {r['artifact']}"
        )


def load_sketch(artdir: pathlib.Path):
    """One row per sketch family per wire dimension per sketch-*.json
    artifact (bench.py's measure_sketch_accuracy): the accuracy-vs-
    dimension trend — observed error vs analytic bound, headroom, and
    the end-to-end secure-round throughput."""
    rows = []
    for f in sorted(artdir.glob("sketch-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        fams = d.get("families") if isinstance(d, dict) else None
        if not isinstance(fams, dict):
            continue
        for fam, body in sorted(fams.items()):
            legs = body.get("legs") if isinstance(body, dict) else None
            if not isinstance(legs, dict):
                continue
            # ascending wire dimension, so each family reads as a trend
            for tag, leg in sorted(
                legs.items(), key=lambda kv: (kv[1] or {}).get("dim") or 0
            ):
                if not isinstance(leg, dict) or leg.get("dim") is None:
                    continue
                rows.append(
                    {
                        "artifact": f.name,
                        "family": fam,
                        "tag": tag,
                        "dim": leg.get("dim"),
                        # countmin legs carry max_err, cardinality abs_err
                        "err": (
                            leg.get("max_err")
                            if leg.get("max_err") is not None
                            else leg.get("abs_err")
                        ),
                        "bound": leg.get("bound"),
                        "headroom": leg.get("bound_headroom"),
                        "within": leg.get("within_bound"),
                        "items_per_s": leg.get("items_per_s"),
                        "exact": leg.get("byte_exact"),
                    }
                )
    return rows


def print_sketch(rows) -> None:
    print("\nsketch-accuracy riders (sketch-*.json):")
    print(
        f"{'family':>12} {'leg':>6} {'dim':>6} {'err':>8} {'bound':>8} "
        f"{'headroom':>8} {'in_bnd':>6} {'items/s':>8} {'exact':>5}  artifact"
    )
    for r in rows:
        within = "-" if r["within"] is None else ("yes" if r["within"] else "NO")
        exact = "-" if r["exact"] is None else ("yes" if r["exact"] else "NO")
        print(
            f"{r['family']:>12} {r['tag']:>6} {r['dim']:>6} "
            f"{r['err'] if r['err'] is not None else '-':>8} "
            f"{r['bound'] if r['bound'] is not None else '-':>8} "
            f"{r['headroom'] if r['headroom'] is not None else '-':>8} "
            f"{within:>6} "
            f"{r['items_per_s'] if r['items_per_s'] is not None else '-':>8} "
            f"{exact:>5}  {r['artifact']}"
        )


def load_scenarios(artdir: pathlib.Path):
    """Latest record per (scenario, store, transport) cell from the churn
    harness's scenario-*.json artifacts (scripts/scenarios.py), plus any
    overhead-ab-*.json retry-layer A/B records."""
    cells: dict = {}
    for f in sorted(artdir.glob("scenario-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        if not isinstance(d, dict) or not all(
            k in d for k in ("scenario", "store", "transport", "ok")
        ):
            continue
        # sorted() walks stamps ascending, so the last write wins = latest
        cells[(d["scenario"], d["store"], d["transport"])] = {
            "artifact": f.name,
            "ok": bool(d["ok"]),
            "exact": bool(d.get("exact")),
            "error": d.get("error"),
        }
    overheads = []
    for f in sorted(artdir.glob("overhead-ab-*.json")):
        try:
            d = json.loads(f.read_text())
        except (OSError, ValueError):
            continue
        if isinstance(d, dict) and d.get("overhead_pct") is not None:
            overheads.append({"artifact": f.name, **d})
    return cells, overheads


def print_scenarios(cells, overheads) -> None:
    """The survivability matrix: scenario rows x (store, transport)
    columns, latest artifact per cell; '--' = cell never run."""
    print("\nchurn-scenario survivability (scenario-*.json, latest per cell):")
    scenarios = sorted({k[0] for k in cells})
    cols = sorted({(k[1], k[2]) for k in cells})
    header = " ".join(f"{s[:4]}/{t[:4]:<4}" for s, t in cols)
    print(f"{'scenario':<28} {header}")
    for name in scenarios:
        row = []
        for s, t in cols:
            cell = cells.get((name, s, t))
            row.append("--" if cell is None else ("OK" if cell["ok"] else "FAIL"))
        print(f"{name:<28} " + " ".join(f"{c:<9}" for c in row))
    bad = [(k, c) for k, c in cells.items() if not c["ok"]]
    if bad:
        print("failing cells:")
        for (name, s, t), c in bad:
            print(f"  {name} [{s}/{t}]: {c['error']}  ({c['artifact']})")
    else:
        print(f"all {len(cells)} banked cells green")
    for o in overheads:
        print(
            f"retry-layer overhead A/B: {o['overhead_pct']:+.2f}% over "
            f"{o.get('requests_per_arm', '?')} requests/arm "
            f"({'OK' if o.get('ok') else 'OVER BOUND'})  ({o['artifact']})"
        )


def tag_of(row):
    # prefer the metric line (bench.py records rng/chunk/check since r5,
    # ADVICE r4 #2); filename tag as fallback for pre-r5 artifacts
    # (exp-<rng>-c<chunk>-<stamp>.json / exp-<rng>-<check>-<stamp>.json).
    # The old fallback recovered only the c<chunk> part, so pre-r5
    # check-variant artifacts (exp-rbg-probe-*, exp-threefry-off-*) fell
    # through to check="full" and collapsed into the full-check group —
    # mislabeled, and eligible to win the full-check recommendation with
    # a rate the full check never produced.
    rng, chunk, check = row.get("rng"), row.get("chunk"), row.get("check")
    for p in row["artifact"].rsplit(".", 1)[0].split("-")[1:]:
        if chunk is None and p.startswith("c") and p[1:].isdigit():
            chunk = p[1:]
        elif check is None and p in ("probe", "off"):
            check = p
        elif rng is None and p in ("threefry", "rbg"):
            rng = p
    return (
        rng or "threefry",
        str(chunk) if chunk is not None else None,
        check or "full",
    )


def main() -> int:
    artdir = pathlib.Path(sys.argv[1] if len(sys.argv) > 1 else "bench-artifacts")
    rows = load(artdir)
    ingest_rows = load_ingest(artdir)
    clerking_rows = load_clerking(artdir)
    reveal_rows = load_reveal(artdir)
    committee_rows = load_committee(artdir)
    wire_rows = load_wire(artdir)
    tier_rows = load_tier(artdir)
    promotion_rows = load_promotion_ab(artdir)
    soak_rows = load_soak(artdir)
    flagship_rows = load_flagship(artdir)
    arrivals_rows = load_arrivals_ab(artdir)
    tier_close_rows = load_tier_close_ab(artdir)
    sketch_rows = load_sketch(artdir)
    scenario_cells, overhead_rows = load_scenarios(artdir)
    if (
        not rows
        and not ingest_rows
        and not clerking_rows
        and not reveal_rows
        and not committee_rows
        and not wire_rows
        and not tier_rows
        and not soak_rows
        and not flagship_rows
        and not sketch_rows
        and not scenario_cells
    ):
        print(
            f"no rate-bearing exp-*.json, ingest-*.json, clerking-*.json, "
            f"reveal-*.json, committee-*.json, wire-*.json, tier-*.json, "
            f"soak-*.json, flagship-*.json, sketch-*.json, or "
            f"scenario-*.json artifacts under {artdir}/",
            file=sys.stderr,
        )
        return 1

    if rows:
        best: dict[tuple, dict] = {}
        for r in rows:
            key = tag_of(r)
            if key not in best or r["value"] > best[key]["value"]:
                best[key] = r

        print(f"{'rng':>9} {'chunk':>6} {'check':>6} {'elems/s':>12} "
              f"{'steady_s':>9} {'partial':>7}  artifact")
        for key in sorted(best, key=lambda k: tuple(x or "" for x in k)):
            r = best[key]
            rng, chunk, check = key
            print(
                f"{rng:>9} {chunk or '-':>6} {check:>6} {r['value']:>12.3e} "
                f"{r['steady_s'] if r['steady_s'] is not None else float('nan'):>9} "
                f"{'yes' if r['partial'] else 'no':>7}  {r['artifact']}"
            )

        # recommendation: fastest full-check config is eligible to become the
        # bench default (the headline keeps the strongest verification); the
        # fastest overall quantifies the scaffolding/rng headroom
        full = [r for k, r in best.items() if k[2] == "full"]
        if full:
            top = max(full, key=lambda r: r["value"])
            print(f"\nfastest full-check config: {tag_of(top)} at {top['value']:.3e} el/s "
                  f"({top['artifact']})")
        top_any = max(best.values(), key=lambda r: r["value"])
        print(f"fastest overall:           {tag_of(top_any)} at {top_any['value']:.3e} el/s "
              f"({top_any['artifact']})")

    if ingest_rows:
        print_ingest(ingest_rows)
    if clerking_rows:
        print_clerking(clerking_rows)
    if reveal_rows:
        print_reveal(reveal_rows)
    if committee_rows:
        print_committee(committee_rows)
    if wire_rows:
        print_wire(wire_rows)
    if tier_rows:
        print_tier(tier_rows)
    if promotion_rows:
        print_promotion_ab(promotion_rows)
    if soak_rows:
        print_soak(soak_rows)
    if flagship_rows:
        print_flagship(flagship_rows)
    if arrivals_rows:
        print_arrivals_ab(arrivals_rows)
    if tier_close_rows:
        print_tier_close_ab(tier_close_rows)
    if sketch_rows:
        print_sketch(sketch_rows)
    if scenario_cells:
        print_scenarios(scenario_cells, overhead_rows)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
