#!/bin/sh
# One-command TPU revalidation for a freshly healthy chip: probe cheaply,
# then run the smoke shape and the full north-star config, saving each
# metric line (with crypto-plane rates and on-device parity evidence)
# under bench-artifacts/. Run from the repo root with the ambient axon env.
#
# Usage: sh scripts/tpu-revalidate.sh [outdir]   (default bench-artifacts)
set -e
cd "$(dirname "$0")/.."
out="${1:-bench-artifacts}"
mkdir -p "$out"
stamp=$(date +%Y%m%d-%H%M%S)

# CPU dress rehearsal (VERDICT r4 #1): SDA_REVALIDATE_SMOKE=1 shrinks
# every bench call so the whole banking chain — ordering, flags,
# artifact paths — runs in minutes without a chip. Run it after every
# chain edit: a healthy window must never be spent debugging banking.
#   SDA_REVALIDATE_SMOKE=1 JAX_PLATFORMS=cpu sh scripts/tpu-revalidate.sh /tmp/reh
SMOKE="${SDA_REVALIDATE_SMOKE:+--participants 3000 --dim 800 --chunk 500 --segments 3}"
LADDER_SMOKE="${SDA_REVALIDATE_SMOKE:+--quick}"

# a chip that wedges *mid-revalidate* (after the cheap probe passed) must
# not hold the window hostage for bench.py's default 50-minute deadline:
# healthy-path pre-measurement time is ~80 s (parity ~70 s + compile), so
# 900 s is generous slack while letting the probe loop retry a re-surfaced
# chip ~4x sooner. Callers can still override for debugging.
SDA_BENCH_DEADLINE="${SDA_BENCH_DEADLINE:-900}"
export SDA_BENCH_DEADLINE

# the bench's crypto-plane riders measure the native extension when it is
# importable; build it in place first so a fresh checkout reports real
# native rates instead of the Python fallback (native_ext: false)
python setup.py build_ext --inplace >/dev/null 2>&1 || true

echo "[revalidate] probing device..." >&2
# the shared probe (scripts/tpu-probe.sh) carries the two load-bearing
# details: JAX_PLATFORMS re-assertion and SIGKILL escalation
if ! sh scripts/tpu-probe.sh 150 >&2; then
    echo "[revalidate] device unreachable; aborting (nothing written)" >&2
    exit 2
fi

# Banking order is value order — observed windows can close in ~4 min
# (PROBE_r04.log 03:18 UTC). The r4 verdict ranks the MISSING evidence
# first: the participant engine (the real protocol-plane path,
# client/src/participate.rs:37-113 analog) has never been witnessed on
# silicon, while the sum-first north-star has two banked artifacts. So:
#   1. participant engine, smoke shape (fast, guaranteed early bank)
#   2. participant engine, fused Pallas limb kernel (XLA-vs-Pallas rate)
#   3. north-star with parity riders + roofline decomposition (targets
#      the observed-best 14.6 s, docs/tpu.md)
#   4. participant engine at the north-star shape, budget-capped (the
#      "largest shape that fits" number; ~10x slower by design)
#   5. quick smoke, pallas smoke, rbg north-star
# No pipes around bench.py: `bench | tee` would report tee's status and a
# mid-run crash (chip wedging after the probe passed) would masquerade as
# success — the probe loop charges its revalidate cooldown off this
# script's exit code. Write the artifact, then show it.
# Engine-specific artifacts are non-fatal (||): a failure must not void
# the window for everything after it — but the FIRST artifact failing
# fails the script so the loop doesn't charge its cooldown on nothing.
echo "[revalidate] participant engine (per-participant MXU share matmuls)..." >&2
# --roofline: the protocol-plane engine's first on-silicon artifact also
# names its binding stage (check / rng_expand / share_combine); the
# decomposition runs after the measured result with a bail timer, so a
# wedge mid-decomposition still banks the headline value
python bench.py --engine participant --roofline --no-parity $SMOKE > "$out/participant-$stamp.json"
cat "$out/participant-$stamp.json"

echo "[revalidate] participant engine, fused Pallas limb kernel..." >&2
# same shape through parallel/limb_pallas.py: does the hand-written
# kernel beat XLA's own fusion on silicon? (compile+parity alone is
# proven by the smoke; this is the rate comparison)
python bench.py --engine participant --pallas --no-parity $SMOKE \
    > "$out/participant-pallas-$stamp.json" \
    || echo "[revalidate] participant --pallas FAILED (artifact saved)" >&2
cat "$out/participant-pallas-$stamp.json"

echo "[revalidate] north-star shape (1M x 100K, 61-bit) + roofline..." >&2
python bench.py --roofline $SMOKE > "$out/northstar-$stamp.json" \
    || echo "[revalidate] north-star FAILED (artifact saved)" >&2
cat "$out/northstar-$stamp.json"

echo "[revalidate] participant engine at the north-star shape (budget-capped)..." >&2
python bench.py --engine participant --northstar --budget 240 --no-parity $SMOKE \
    > "$out/participant-northstar-$stamp.json" \
    || echo "[revalidate] participant north-star FAILED (artifact saved)" >&2
cat "$out/participant-northstar-$stamp.json"

echo "[revalidate] smoke shape (--quick, parity covered above)..." >&2
python bench.py --quick --no-parity $SMOKE > "$out/quick-$stamp.json" \
    || echo "[revalidate] quick smoke FAILED (artifact saved)" >&2
cat "$out/quick-$stamp.json"

echo "[revalidate] pallas kernel compile + parity + throughput smoke..." >&2
# per-kernel compile/parity evidence (ops/chacha_pallas.py,
# parallel/limb_pallas.py) — recorded even when a kernel fails, so a
# round that catches a healthy chip always leaves an artifact either way.
if ! python scripts/pallas_smoke.py > "$out/pallas-$stamp.json"; then
    echo "[revalidate] pallas smoke FAILED (artifact saved); continuing" >&2
fi
cat "$out/pallas-$stamp.json"

echo "[revalidate] north-star with rbg generation (isolates threefry cost)..." >&2
python bench.py --rng rbg --no-parity $SMOKE > "$out/northstar-rbg-$stamp.json" \
    || echo "[revalidate] rbg north-star FAILED (artifact saved)" >&2
cat "$out/northstar-rbg-$stamp.json"

echo "[revalidate] device-mode baseline ladder (configs 2-4 on the chip)..." >&2
# VERDICT r4 #4: config 4 took 712.9 s on host — the exact shape the TPU
# fabric exists for; bank the device-mode ladder columns in a window.
# The ladder guards the probe loop itself: a cooperative per-config
# budget (SDA_LADDER_BUDGET) stops slow-but-healthy runs with verified
# partial results, and an internal wedge watchdog (SDA_LADDER_DEADLINE)
# dumps-and-exits if a native call blocks — no external SIGKILL, which
# could wedge a HEALTHY chip mid-device-op.
python scripts/baseline_ladder.py --device --configs 2,3,4 $LADDER_SMOKE \
    --out "$out/ladder-device-$stamp.json" >/dev/null \
    || echo "[revalidate] device ladder FAILED (artifact saved)" >&2
cat "$out/ladder-device-$stamp.json" 2>/dev/null || true

echo "[revalidate] done; artifacts in $out/ — update README.md/docs/tpu.md" \
     "provenance notes with these numbers" >&2
