#!/bin/sh
# One-command TPU revalidation for a freshly healthy chip: probe cheaply,
# then run the smoke shape and the full north-star config, saving each
# metric line (with crypto-plane rates and on-device parity evidence)
# under bench-artifacts/. Run from the repo root with the ambient axon env.
#
# Usage: sh scripts/tpu-revalidate.sh [outdir]   (default bench-artifacts)
set -e
cd "$(dirname "$0")/.."
out="${1:-bench-artifacts}"
mkdir -p "$out"
stamp=$(date +%Y%m%d-%H%M%S)

# a chip that wedges *mid-revalidate* (after the cheap probe passed) must
# not hold the window hostage for bench.py's default 50-minute deadline:
# healthy-path pre-measurement time is ~80 s (parity ~70 s + compile), so
# 900 s is generous slack while letting the probe loop retry a re-surfaced
# chip ~4x sooner. Callers can still override for debugging.
SDA_BENCH_DEADLINE="${SDA_BENCH_DEADLINE:-900}"
export SDA_BENCH_DEADLINE

# the bench's crypto-plane riders measure the native extension when it is
# importable; build it in place first so a fresh checkout reports real
# native rates instead of the Python fallback (native_ext: false)
python setup.py build_ext --inplace >/dev/null 2>&1 || true

echo "[revalidate] probing device..." >&2
# the shared probe (scripts/tpu-probe.sh) carries the two load-bearing
# details: JAX_PLATFORMS re-assertion and SIGKILL escalation
if ! sh scripts/tpu-probe.sh 150 >&2; then
    echo "[revalidate] device unreachable; aborting (nothing written)" >&2
    exit 2
fi

# Banking order is value order — observed windows can close in ~4 min
# (PROBE_r04.log 03:18 UTC), so the headline artifact goes FIRST:
#   1. north-star with full parity riders (THE number + on-device parity)
#   2. quick smoke, parity skipped (the north-star's rider just covered it)
#   3. pallas compile/parity/throughput smoke
#   4. rbg north-star (isolates threefry generation cost)
# No pipes around bench.py: `bench | tee` would report tee's status and a
# mid-run crash (chip wedging after the probe passed) would masquerade as
# success — the probe loop charges its revalidate cooldown off this
# script's exit code. Write the artifact, then show it.
echo "[revalidate] north-star shape (1M x 100K, 61-bit)..." >&2
python bench.py > "$out/northstar-$stamp.json"
cat "$out/northstar-$stamp.json"

echo "[revalidate] smoke shape (--quick, parity covered above)..." >&2
python bench.py --quick --no-parity > "$out/quick-$stamp.json"
cat "$out/quick-$stamp.json"

echo "[revalidate] pallas kernel compile + parity + throughput smoke..." >&2
# per-kernel compile/parity evidence (ops/chacha_pallas.py,
# parallel/limb_pallas.py) — recorded even when a kernel fails, so a
# round that catches a healthy chip always leaves an artifact either way.
if ! python scripts/pallas_smoke.py > "$out/pallas-$stamp.json"; then
    echo "[revalidate] pallas smoke FAILED (artifact saved); continuing" >&2
fi
cat "$out/pallas-$stamp.json"

echo "[revalidate] north-star with rbg generation (isolates threefry cost)..." >&2
python bench.py --rng rbg --no-parity > "$out/northstar-rbg-$stamp.json"
cat "$out/northstar-rbg-$stamp.json"

echo "[revalidate] participant engine (per-participant MXU share matmuls)..." >&2
# the second engine's witnessed number (VERDICT r3 #1 asks for both):
# materializes every share by design, so it runs the smaller smoke shape
# non-fatal (|| below): these run last and are the least-proven on
# silicon — a failure must not void the already-banked artifacts above
# (a nonzero exit would skip the probe loop's sweep + auto-commit)
python bench.py --engine participant --no-parity > "$out/participant-$stamp.json" \
    || echo "[revalidate] participant engine FAILED (artifact saved)" >&2
cat "$out/participant-$stamp.json"

echo "[revalidate] participant engine, fused Pallas limb kernel..." >&2
# same shape through parallel/limb_pallas.py: does the hand-written
# kernel beat XLA's own fusion on silicon? (compile+parity alone is
# proven by the smoke; this is the rate comparison)
python bench.py --engine participant --pallas --no-parity \
    > "$out/participant-pallas-$stamp.json" \
    || echo "[revalidate] participant --pallas FAILED (artifact saved)" >&2
cat "$out/participant-pallas-$stamp.json"

echo "[revalidate] done; artifacts in $out/ — update README.md/docs/tpu.md" \
     "provenance notes with these numbers" >&2
