"""libsodium bindings via ctypes.

The reference leans on sodiumoxide for sealed boxes (Curve25519/XSalsa20/
Poly1305) and Ed25519 detached signatures (client/src/crypto/encryption/
sodium.rs, signing/mod.rs). We bind the same primitives from the system
libsodium, so ciphertexts and signatures are interoperable with any libsodium
consumer. Batch throughput (thousands of seals per call) lives in
``sda_tpu/native`` — this module is the always-available scalar path.
"""

from __future__ import annotations

import ctypes
import ctypes.util


class SodiumError(Exception):
    pass


_lib = None


def _sodium():
    global _lib
    if _lib is None:
        name = ctypes.util.find_library("sodium") or "libsodium.so.23"
        lib = ctypes.CDLL(name)
        if lib.sodium_init() < 0:
            raise SodiumError("sodium_init failed")
        _lib = lib
    return _lib


BOX_PUBLICKEYBYTES = 32
BOX_SECRETKEYBYTES = 32
SEALBYTES = 48  # crypto_box_SEALBYTES = PUBLICKEYBYTES + MACBYTES
SIGN_PUBLICKEYBYTES = 32
SIGN_SECRETKEYBYTES = 64
SIGN_BYTES = 64


def box_keypair() -> tuple[bytes, bytes]:
    """Generate a Curve25519 box keypair -> (public, secret)."""
    lib = _sodium()
    pk = ctypes.create_string_buffer(BOX_PUBLICKEYBYTES)
    sk = ctypes.create_string_buffer(BOX_SECRETKEYBYTES)
    if lib.crypto_box_keypair(pk, sk) != 0:
        raise SodiumError("crypto_box_keypair failed")
    return pk.raw, sk.raw


def seal(message: bytes, public_key: bytes) -> bytes:
    """Anonymous sealed box: ephemeral-key encrypt to ``public_key``."""
    lib = _sodium()
    out = ctypes.create_string_buffer(len(message) + SEALBYTES)
    if lib.crypto_box_seal(out, message, ctypes.c_ulonglong(len(message)), public_key) != 0:
        raise SodiumError("crypto_box_seal failed")
    return out.raw


def seal_open(ciphertext: bytes, public_key: bytes, secret_key: bytes) -> bytes:
    """Open a sealed box; raises SodiumError on forgery/corruption."""
    lib = _sodium()
    if len(ciphertext) < SEALBYTES:
        raise SodiumError("ciphertext too short")
    out = ctypes.create_string_buffer(len(ciphertext) - SEALBYTES)
    rc = lib.crypto_box_seal_open(
        out, ciphertext, ctypes.c_ulonglong(len(ciphertext)), public_key, secret_key
    )
    if rc != 0:
        raise SodiumError("sealed box open failed")
    return out.raw


def sign_keypair() -> tuple[bytes, bytes]:
    """Generate an Ed25519 keypair -> (verify 32B, signing 64B)."""
    lib = _sodium()
    vk = ctypes.create_string_buffer(SIGN_PUBLICKEYBYTES)
    sk = ctypes.create_string_buffer(SIGN_SECRETKEYBYTES)
    if lib.crypto_sign_keypair(vk, sk) != 0:
        raise SodiumError("crypto_sign_keypair failed")
    return vk.raw, sk.raw


def sign_detached(message: bytes, signing_key: bytes) -> bytes:
    lib = _sodium()
    sig = ctypes.create_string_buffer(SIGN_BYTES)
    siglen = ctypes.c_ulonglong(0)
    rc = lib.crypto_sign_detached(
        sig, ctypes.byref(siglen), message, ctypes.c_ulonglong(len(message)), signing_key
    )
    if rc != 0:
        raise SodiumError("crypto_sign_detached failed")
    return sig.raw


def verify_detached(signature: bytes, message: bytes, verify_key: bytes) -> bool:
    lib = _sodium()
    rc = lib.crypto_sign_verify_detached(
        signature, message, ctypes.c_ulonglong(len(message)), verify_key
    )
    return rc == 0
