"""Transport encryption of share vectors: sodium sealed boxes over varints.

Parity with /root/reference/client/src/crypto/encryption/sodium.rs: each
share vector is zigzag-LEB128 encoded then sealed to the receiver's box
public key; decryption opens and decodes the stream. The reference pays one
FFI call per i64 (VarInt::encode_var in a loop); here encoding is one
vectorized pass and sealing one libsodium call per vector (batched further
by sda_tpu/native when built).
"""

from __future__ import annotations

import numpy as np

from .. import native
from ..protocol import B32, Binary, Encryption, EncryptionKey, SodiumEncryptionScheme
from . import sodium, varint
from .keystore import DecryptionKey, EncryptionKeypair


class ShareEncryptor:
    def encrypt(self, shares: np.ndarray) -> Encryption:
        raise NotImplementedError


class ShareDecryptor:
    def decrypt(self, encryption: Encryption) -> np.ndarray:
        raise NotImplementedError


class SodiumEncryptor(ShareEncryptor):
    def __init__(self, ek: EncryptionKey):
        self.pk = ek.data

    def encrypt(self, shares):
        encoded = native.varint_encode(np.asarray(shares, dtype=np.int64))
        return Encryption(Binary(sodium.seal(encoded, self.pk)))

    def encrypt_batch(self, share_vectors) -> list:
        """Seal many share vectors in one native batch call."""
        encoded = [native.varint_encode(np.asarray(v, dtype=np.int64)) for v in share_vectors]
        return [
            Encryption(Binary(ct)) for ct in native.seal_batch(encoded, self.pk)
        ]


class SodiumDecryptor(ShareDecryptor):
    def __init__(self, keypair: EncryptionKeypair):
        self.pk = keypair.ek.data
        self.sk = keypair.dk.data

    def decrypt(self, encryption):
        raw = sodium.seal_open(bytes(encryption.inner), self.pk, self.sk)
        return native.varint_decode(raw)

    def decrypt_batch(self, encryptions) -> list:
        """Open many sealed boxes in one native batch call (the clerk-side
        per-participant loop, clerk.rs:79-82)."""
        raws = native.open_batch(
            [bytes(e.inner) for e in encryptions], self.pk, self.sk
        )
        return [native.varint_decode(r) for r in raws]


def generate_encryption_keypair() -> EncryptionKeypair:
    pk, sk = sodium.box_keypair()
    return EncryptionKeypair(ek=EncryptionKey(B32(pk)), dk=DecryptionKey(B32(sk)))


def new_share_encryptor(ek: EncryptionKey, scheme) -> ShareEncryptor:
    if isinstance(scheme, SodiumEncryptionScheme):
        return SodiumEncryptor(ek)
    raise TypeError(f"unknown encryption scheme {scheme!r}")


def new_share_decryptor(keypair: EncryptionKeypair, scheme) -> ShareDecryptor:
    if isinstance(scheme, SodiumEncryptionScheme):
        return SodiumDecryptor(keypair)
    raise TypeError(f"unknown encryption scheme {scheme!r}")
