"""Transport encryption of share vectors: sodium sealed boxes over varints.

Parity with /root/reference/client/src/crypto/encryption/sodium.rs: each
share vector is zigzag-LEB128 encoded then sealed to the receiver's box
public key; decryption opens and decodes the stream. The reference pays one
FFI call per i64 (VarInt::encode_var in a loop); here encoding is one
vectorized pass and sealing one libsodium call per vector (batched further
by sda_tpu/native when built).
"""

from __future__ import annotations

import numpy as np

from .. import native
from ..utils import workpool
from ..protocol import (
    B32,
    Binary,
    Encryption,
    EncryptionKey,
    PackedPaillierEncryptionScheme,
    PaillierEncryptionKey,
    SodiumEncryptionScheme,
)
from ..ops import paillier
from . import sodium
from .keystore import DecryptionKey, EncryptionKeypair


class ShareEncryptor:
    def encrypt(self, shares: np.ndarray) -> Encryption:
        raise NotImplementedError


class ShareDecryptor:
    def decrypt(self, encryption: Encryption) -> np.ndarray:
        raise NotImplementedError

    def decrypt_batch(self, encryptions) -> list:
        """Default batch: a plain loop (sodium overrides with one native
        batched call)."""
        return [self.decrypt(e) for e in encryptions]


class SodiumEncryptor(ShareEncryptor):
    def __init__(self, ek: EncryptionKey):
        self.pk = ek.data

    def encrypt(self, shares):
        encoded = native.varint_encode(np.asarray(shares, dtype=np.int64))
        return Encryption(Binary(sodium.seal(encoded, self.pk)))

    def encrypt_batch(self, share_vectors) -> list:
        """Seal many share vectors in one native batch call, split across
        the shared worker pool when ``SDA_WORKERS`` > 1."""
        encoded = [native.varint_encode(np.asarray(v, dtype=np.int64)) for v in share_vectors]
        cts = workpool.map_items(
            "seal",
            encoded,
            lambda sub, nt: native.seal_batch(sub, self.pk, n_threads=nt),
        )
        return [Encryption(Binary(ct)) for ct in cts]


class SodiumDecryptor(ShareDecryptor):
    def __init__(self, keypair: EncryptionKeypair):
        self.pk = keypair.ek.data
        self.sk = keypair.dk.data

    def decrypt(self, encryption):
        if encryption.variant != "Sodium":
            raise ValueError(f"sodium decryptor got a {encryption.variant} ciphertext")
        raw = sodium.seal_open(bytes(encryption.inner), self.pk, self.sk)
        return native.varint_decode(raw)

    def decrypt_batch(self, encryptions) -> list:
        """Open many sealed boxes in one native batch call (the clerk-side
        per-participant loop, clerk.rs:79-82)."""
        for e in encryptions:
            if e.variant != "Sodium":
                raise ValueError(f"sodium decryptor got a {e.variant} ciphertext")
        raws = workpool.map_items(
            "open",
            [bytes(e.inner) for e in encryptions],
            lambda sub, nt: native.open_batch(sub, self.pk, self.sk, n_threads=nt),
        )
        return [native.varint_decode(r) for r in raws]


def encrypt_share_matrix(clerk_keys, scheme, share_rows) -> list:
    """Seal a whole committee's share matrix in one engine call.

    ``share_rows`` is a list over participants of ``(n_clerks, dim)`` share
    arrays; the result is a list over participants of per-clerk
    ``Encryption`` lists (``result[p][c]`` sealed to ``clerk_keys[c]``).

    For the sodium scheme this routes the full ``P x C`` matrix through
    ``native.seal_participations`` — one ephemeral keypair per participant
    shared across its clerk boxes, comb-table-amortized scalarmults — which
    is several times faster than per-share ``crypto_box_seal`` while
    producing standard sealed boxes.  Other schemes fall back to the
    per-clerk encryptor loop."""
    n_clerks = len(clerk_keys)
    if isinstance(scheme, SodiumEncryptionScheme):
        matrix = [
            [
                native.varint_encode(np.asarray(row[c], dtype=np.int64))
                for c in range(n_clerks)
            ]
            for row in share_rows
        ]
        pks = [ek.data for ek in clerk_keys]
        sealed = workpool.map_items(
            "share_matrix",
            matrix,
            lambda sub, nt: native.seal_participations(sub, pks, n_threads=nt),
        )
        return [[Encryption(Binary(ct)) for ct in prow] for prow in sealed]
    encryptors = [new_share_encryptor(ek, scheme) for ek in clerk_keys]
    return [
        [enc.encrypt(row[c]) for c, enc in enumerate(encryptors)]
        for row in share_rows
    ]


def generate_encryption_keypair() -> EncryptionKeypair:
    pk, sk = sodium.box_keypair()
    return EncryptionKeypair(ek=EncryptionKey(B32(pk)), dk=DecryptionKey(B32(sk)))


# -- Paillier wire format ----------------------------------------------------
# One Encryption (variant "Paillier"): 4-byte big-endian value count, then
# fixed-width big-endian ciphertext blocks (2 * key bytes each, c < n^2).
# The count header exists because block packing pads: padding must not
# change the vector length on the way back through decrypt. These three
# helpers are the single definition of that format — encryptor, decryptor,
# and the server-side combine all go through them.


def _paillier_block_bytes(n: int) -> int:
    return 2 * ((n.bit_length() + 7) // 8)


def _paillier_encode(blocks, count: int, block_bytes: int) -> "Encryption":
    raw = count.to_bytes(4, "big") + b"".join(
        c.to_bytes(block_bytes, "big") for c in blocks
    )
    return Encryption(Binary(raw), variant="Paillier")


def _paillier_decode(encryption, block_bytes: int):
    """-> (count, blocks). Validates the variant tag and block alignment."""
    if encryption.variant != "Paillier":
        raise ValueError(f"expected a Paillier ciphertext, got {encryption.variant}")
    raw = bytes(encryption.inner)
    count, raw = int.from_bytes(raw[:4], "big"), raw[4:]
    if len(raw) % block_bytes:
        raise ValueError("ciphertext length not a multiple of the block width")
    blocks = [
        int.from_bytes(raw[i : i + block_bytes], "big")
        for i in range(0, len(raw), block_bytes)
    ]
    return count, blocks


class PaillierEncryptor(ShareEncryptor):
    """Packed-Paillier encryption of nonnegative bounded value vectors.

    Values must be canonical nonnegative residues below
    2^max_value_bitsize (the mask path guarantees this; shares can be
    negative and stay on sodium).
    """

    def __init__(self, ek: PaillierEncryptionKey, scheme: PackedPaillierEncryptionScheme):
        if not isinstance(ek, PaillierEncryptionKey):
            raise TypeError("PackedPaillier scheme requires a Paillier public key")
        if ek.n.bit_length() < scheme.min_modulus_bitsize:
            raise ValueError("Paillier key smaller than the scheme's minimum")
        self.pk = paillier.PaillierPublicKey(ek.n)
        self.packing = paillier.Packing(
            scheme.component_count, scheme.component_bitsize, scheme.max_value_bitsize
        )
        self.block_bytes = _paillier_block_bytes(ek.n)

    def encrypt(self, shares):
        values = [int(v) for v in np.asarray(shares, dtype=np.int64)]
        if any(v < 0 for v in values):
            raise ValueError("Paillier packing requires nonnegative values")
        blocks = paillier.encrypt_vector(self.pk, self.packing, values)
        return _paillier_encode(blocks, len(values), self.block_bytes)


class PaillierDecryptor(ShareDecryptor):
    def __init__(self, keypair, scheme: PackedPaillierEncryptionScheme):
        self.sk = paillier.PaillierPrivateKey(keypair.ek.n, keypair.lam, keypair.mu)
        self.packing = paillier.Packing(
            scheme.component_count, scheme.component_bitsize, scheme.max_value_bitsize
        )
        self.block_bytes = _paillier_block_bytes(keypair.ek.n)

    def decrypt(self, encryption):
        count, blocks = _paillier_decode(encryption, self.block_bytes)
        values = paillier.decrypt_vector(self.sk, self.packing, blocks, count)
        # component_bitsize <= 62 (scheme invariant): sums fit int64
        return np.asarray(values, dtype=np.int64)


def combine_encryptions(ek, scheme, encryptions: list) -> "Encryption":
    """Homomorphic server-side combine: product of ciphertext blocks ==
    encryption of the componentwise sum. Public-key only — callable by the
    untrusted server. All inputs must have identical block counts (same
    vector dimension), and the caller bounds how many are combined
    (scheme additions capacity)."""
    if not isinstance(ek, PaillierEncryptionKey):
        raise TypeError("combine requires a Paillier public key")
    pk = paillier.PaillierPublicKey(ek.n)
    block_bytes = _paillier_block_bytes(ek.n)

    combined, count0 = None, None
    for e in encryptions:
        count, b = _paillier_decode(e, block_bytes)
        if combined is None:
            combined, count0 = b, count
        else:
            if count != count0:
                raise ValueError("mismatched vector lengths in combine")
            combined = paillier.add_vectors(pk, combined, b)
    return _paillier_encode(combined, count0, block_bytes)


def paillier_ciphertext_well_formed(
    encryption, ek: PaillierEncryptionKey, scheme, expected_values: int | None
) -> bool:
    """Cheap *public* well-formedness check of one Paillier Encryption:
    variant tag, count header, block alignment, block count consistent with
    the packing, and every block in (0, n²). Lets the server reject
    malformed uploads at the participation door — where a garbage blob
    would otherwise surface only at snapshot-combine or recipient-decrypt
    time, after the participant's shares are already in the aggregate."""
    try:
        block_bytes = _paillier_block_bytes(ek.n)
        count, blocks = _paillier_decode(encryption, block_bytes)
    except ValueError:
        return False
    if expected_values is not None and count != expected_values:
        return False
    expected_blocks = -(-count // scheme.component_count) if count else 0
    if len(blocks) != expected_blocks:
        return False
    n_sq = ek.n * ek.n
    return all(0 < b < n_sq for b in blocks)


def generate_paillier_keypair(modulus_bits: int = 2048):
    """-> keystore.PaillierKeypair with fresh primes."""
    from .keystore import PaillierKeypair

    pk, sk = paillier.keygen(modulus_bits)
    return PaillierKeypair(ek=PaillierEncryptionKey(pk.n), lam=sk.lam, mu=sk.mu)


def new_share_encryptor(ek: EncryptionKey, scheme) -> ShareEncryptor:
    if isinstance(scheme, SodiumEncryptionScheme):
        return SodiumEncryptor(ek)
    if isinstance(scheme, PackedPaillierEncryptionScheme):
        return PaillierEncryptor(ek, scheme)
    raise TypeError(f"unknown encryption scheme {scheme!r}")


def new_share_decryptor(keypair: EncryptionKeypair, scheme) -> ShareDecryptor:
    if isinstance(scheme, SodiumEncryptionScheme):
        return SodiumDecryptor(keypair)
    if isinstance(scheme, PackedPaillierEncryptionScheme):
        return PaillierDecryptor(keypair, scheme)
    raise TypeError(f"unknown encryption scheme {scheme!r}")
