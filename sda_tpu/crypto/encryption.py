"""Transport encryption of share vectors: sodium sealed boxes over varints.

Parity with /root/reference/client/src/crypto/encryption/sodium.rs: each
share vector is zigzag-LEB128 encoded then sealed to the receiver's box
public key; decryption opens and decodes the stream. The reference pays one
FFI call per i64 (VarInt::encode_var in a loop); here encoding is one
vectorized pass and sealing one libsodium call per vector (batched further
by sda_tpu/native when built).
"""

from __future__ import annotations

import numpy as np

from ..protocol import B32, Binary, Encryption, EncryptionKey, SodiumEncryptionScheme
from . import sodium, varint
from .keystore import DecryptionKey, EncryptionKeypair


class ShareEncryptor:
    def encrypt(self, shares: np.ndarray) -> Encryption:
        raise NotImplementedError


class ShareDecryptor:
    def decrypt(self, encryption: Encryption) -> np.ndarray:
        raise NotImplementedError


class SodiumEncryptor(ShareEncryptor):
    def __init__(self, ek: EncryptionKey):
        self.pk = ek.data

    def encrypt(self, shares):
        encoded = varint.encode_i64(np.asarray(shares, dtype=np.int64))
        return Encryption(Binary(sodium.seal(encoded, self.pk)))


class SodiumDecryptor(ShareDecryptor):
    def __init__(self, keypair: EncryptionKeypair):
        self.pk = keypair.ek.data
        self.sk = keypair.dk.data

    def decrypt(self, encryption):
        raw = sodium.seal_open(bytes(encryption.inner), self.pk, self.sk)
        return varint.decode_i64(raw)


def generate_encryption_keypair() -> EncryptionKeypair:
    pk, sk = sodium.box_keypair()
    return EncryptionKeypair(ek=EncryptionKey(B32(pk)), dk=DecryptionKey(B32(sk)))


def new_share_encryptor(ek: EncryptionKey, scheme) -> ShareEncryptor:
    if isinstance(scheme, SodiumEncryptionScheme):
        return SodiumEncryptor(ek)
    raise TypeError(f"unknown encryption scheme {scheme!r}")


def new_share_decryptor(keypair: EncryptionKeypair, scheme) -> ShareDecryptor:
    if isinstance(scheme, SodiumEncryptionScheme):
        return SodiumDecryptor(keypair)
    raise TypeError(f"unknown encryption scheme {scheme!r}")
