"""File-based client store and keystore.

Mirrors the reference's jfs-backed ``Filebased`` store (client-store/src/
file.rs): one JSON file per object under a directory, plus alias indirection
(``alias -> id -> object``, store.rs:11-40) used by the CLI to remember "the
agent identity in this directory". Built on the shared atomic JsonDir
(private 0600/0700 permissions — these files hold secret keys).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.jsondir import JsonDir

from ..protocol import B32, PaillierEncryptionKey
from ..protocol.schemes import EncryptionKey, SigningKey, VerificationKey, _untag


@dataclass
class DecryptionKey:
    """Sodium box secret key (client/src/crypto/encryption/mod.rs:8-10)."""

    inner: B32

    def to_json(self):
        return {"Sodium": self.inner.to_json()}

    @classmethod
    def from_json(cls, obj):
        _, payload = _untag(obj, ("Sodium",))
        return cls(B32.from_json(payload))

    @property
    def data(self) -> bytes:
        return self.inner.data


@dataclass
class EncryptionKeypair:
    ek: EncryptionKey
    dk: DecryptionKey

    def to_json(self):
        return {"ek": self.ek.to_json(), "dk": self.dk.to_json()}

    @classmethod
    def from_json(cls, obj):
        dk = obj["dk"]
        if isinstance(dk, dict) and "Paillier" in dk:
            return PaillierKeypair.from_json(obj)
        return cls(
            ek=EncryptionKey.from_json(obj["ek"]), dk=DecryptionKey.from_json(obj["dk"])
        )


@dataclass
class PaillierKeypair:
    """Paillier keypair: public n, private (lam, mu) — the PackedPaillier
    extension's key material, stored alongside sodium pairs."""

    ek: "PaillierEncryptionKey"
    lam: int
    mu: int

    def to_json(self):
        return {
            "ek": self.ek.to_json(),
            "dk": {"Paillier": {"lam": str(self.lam), "mu": str(self.mu)}},
        }

    @classmethod
    def from_json(cls, obj):
        dk = obj["dk"]["Paillier"]
        return cls(
            ek=PaillierEncryptionKey.from_json(obj["ek"]),
            lam=int(dk["lam"]),
            mu=int(dk["mu"]),
        )


@dataclass
class SignatureKeypair:
    vk: VerificationKey
    sk: SigningKey

    def to_json(self):
        return {"vk": self.vk.to_json(), "sk": self.sk.to_json()}

    @classmethod
    def from_json(cls, obj):
        return cls(
            vk=VerificationKey.from_json(obj["vk"]), sk=SigningKey.from_json(obj["sk"])
        )


class Filebased:
    """One JSON file per object; safe for ids and aliases used here."""

    def __init__(self, path):
        self._dir = JsonDir(path)
        self.path = self._dir.path

    def put(self, id: str, obj) -> None:
        payload = obj.to_json() if hasattr(obj, "to_json") else obj
        self._dir.put(id, payload)

    def get(self, id: str, from_json=None):
        payload = self._dir.get(id)
        if payload is None:
            return None
        return from_json(payload) if from_json else payload

    def list_ids(self) -> list:
        return self._dir.list_ids()

    # alias indirection (client-store/src/store.rs:11-40)

    def put_aliased(self, alias: str, obj) -> None:
        ident = str(obj.id)
        self.put(ident, obj)
        self.put(f"alias-{alias}", {"id": ident})

    def get_aliased(self, alias: str, from_json=None):
        pointer = self.get(f"alias-{alias}")
        if pointer is None:
            return None
        return self.get(pointer["id"], from_json)


class Keystore(Filebased):
    """Keypair storage keyed by EncryptionKeyId / VerificationKeyId."""

    def put_encryption_keypair(self, key_id, pair: EncryptionKeypair) -> None:
        self.put(str(key_id), pair)

    def get_encryption_keypair(self, key_id) -> EncryptionKeypair | None:
        return self.get(str(key_id), EncryptionKeypair.from_json)

    def put_signature_keypair(self, key_id, pair: SignatureKeypair) -> None:
        self.put(str(key_id), pair)

    def get_signature_keypair(self, key_id) -> SignatureKeypair | None:
        return self.get(str(key_id), SignatureKeypair.from_json)
