"""sda_tpu.crypto — scheme implementations and the CryptoModule.

The ``CryptoModule`` is the scheme-dispatch layer (reference:
client/src/crypto/mod.rs): it owns the keystore and constructs
maskers/sharers/encryptors/signers from the scheme descriptors carried on
the Aggregation resource. Configuration travels on the wire, so new backends
(e.g. the TPU batch plane in sda_tpu.parallel) slot in without protocol
changes.
"""

from __future__ import annotations

from ..protocol import Agent, AgentId, EncryptionKeyId, Labelled, VerificationKeyId
from . import encryption, masking, sharing, signing
from .keystore import (
    DecryptionKey,
    EncryptionKeypair,
    Filebased,
    Keystore,
    SignatureKeypair,
)


class CryptoModule:
    """Keystore-backed factory for all per-scheme crypto operations."""

    def __init__(self, keystore: Keystore):
        self.keystore = keystore

    # -- key generation ------------------------------------------------------

    def new_encryption_key(self) -> EncryptionKeyId:
        """Generate + store a sodium box keypair; returns its id."""
        pair = encryption.generate_encryption_keypair()
        key_id = EncryptionKeyId.random()
        self.keystore.put_encryption_keypair(key_id, pair)
        return key_id

    def new_paillier_encryption_key(self, modulus_bits: int = 2048) -> EncryptionKeyId:
        """Generate + store a Paillier keypair (PackedPaillier extension);
        returns its id. 2048-bit modulus for real use."""
        pair = encryption.generate_paillier_keypair(modulus_bits)
        key_id = EncryptionKeyId.random()
        self.keystore.put_encryption_keypair(key_id, pair)
        return key_id

    def new_signature_key(self) -> Labelled:
        """Generate + store an Ed25519 keypair; returns Labelled[id, vk]."""
        pair = signing.generate_signature_keypair()
        key_id = VerificationKeyId.random()
        self.keystore.put_signature_keypair(key_id, pair)
        return Labelled(key_id, pair.vk)

    # -- masking -------------------------------------------------------------

    def new_secret_masker(self, scheme):
        return masking.new_secret_masker(scheme)

    def new_mask_combiner(self, scheme):
        return masking.new_mask_combiner(scheme)

    def new_secret_unmasker(self, scheme):
        return masking.new_secret_unmasker(scheme)

    # -- sharing -------------------------------------------------------------

    def new_share_generator(self, scheme):
        return sharing.new_share_generator(scheme)

    def new_share_combiner(self, scheme):
        return sharing.new_share_combiner(scheme)

    def new_secret_reconstructor(self, scheme, dimension: int):
        return sharing.new_secret_reconstructor(scheme, dimension)

    # -- transport encryption ------------------------------------------------

    def new_share_encryptor(self, ek, scheme):
        return encryption.new_share_encryptor(ek, scheme)

    def encrypt_share_matrix(self, clerk_keys, scheme, share_rows):
        """Committee-wide batch sealing; see encryption.encrypt_share_matrix."""
        return encryption.encrypt_share_matrix(clerk_keys, scheme, share_rows)

    def new_share_decryptor(self, key_id: EncryptionKeyId, scheme):
        pair = self.keystore.get_encryption_keypair(key_id)
        if pair is None:
            raise KeyError(f"no keypair for {key_id} in keystore")
        return encryption.new_share_decryptor(pair, scheme)

    # -- signing -------------------------------------------------------------

    def sign_encryption_key(self, signer: Agent, key_id: EncryptionKeyId):
        """Export the stored public key as a Signed Labelled EncryptionKey."""
        pair = self.keystore.get_encryption_keypair(key_id)
        if pair is None:
            return None
        sig_pair = self.keystore.get_signature_keypair(signer.verification_key.id)
        if sig_pair is None:
            return None
        body = Labelled(key_id, pair.ek)
        return signing.sign(body, signer.id, sig_pair)


__all__ = [
    "CryptoModule",
    "Keystore",
    "Filebased",
    "EncryptionKeypair",
    "SignatureKeypair",
    "DecryptionKey",
    "encryption",
    "masking",
    "sharing",
    "signing",
]
