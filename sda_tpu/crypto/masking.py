"""Masking schemes: None / Full / ChaCha.

Semantics mirror /root/reference/client/src/crypto/masking/: the participant
produces ``(recipient_mask, masked_secrets)``; the recipient later combines
all participants' masks and subtracts. Vectors are numpy int64 throughout
(the reference loops element-wise; here each op is one vectorized kernel).
"""

from __future__ import annotations

import logging

import numpy as np

from ..native import chacha_combine, chacha_expand as expand_seed
from ..ops.modular import mod_sum_wide_np, rust_rem_np
from ..ops.rng import uniform_mod_host
from ..protocol import ChaChaMasking, FullMasking, NoMasking


class SecretMasker:
    def mask(self, secrets: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """secrets -> (mask-for-recipient, masked-secrets-for-committee)."""
        raise NotImplementedError


class MaskCombiner:
    def combine(self, masks: list) -> np.ndarray:
        """Combine all participants' uploaded masks into one."""
        raise NotImplementedError

    def accumulator(self) -> "MaskAccumulator":
        """Streaming equivalent of ``combine``: fold the cohort's masks
        chunk by chunk, holding one chunk plus one combined partial at a
        time — the ``sumfirst`` discipline (parallel/sumfirst.py) applied
        to the reveal plane, so recipient memory stays flat in cohort
        size. ``finish()`` is byte-identical to the monolithic
        ``combine`` over the concatenated chunks (see MaskAccumulator)."""
        return MaskAccumulator(self)


class MaskAccumulator:
    """Chunk-by-chunk mask folding with an exactness contract: every
    per-chunk partial (``combine``) and every pairwise fold below is a
    CANONICAL residue in ``[0, m)``, and modular addition of canonical
    representatives is associative — so the folded result is
    byte-identical to the monolithic combine REGARDLESS of chunk
    boundaries (asserted across the full matrix in
    tests/test_reveal_chunks.py). The pairwise fold adds in uint64 (two
    canonical values each < m sum below 2**64 for any m <= 2**63 —
    the same width discipline as ``chacha_combine``'s host path)."""

    def __init__(self, combiner: MaskCombiner):
        self._combiner = combiner
        self._acc: np.ndarray | None = None

    def fold(self, masks: list) -> None:
        if not masks:
            return
        partial = self._combiner.combine(masks)
        if self._acc is None or self._acc.size == 0:
            self._acc = partial
        elif partial.size:
            total = self._acc.astype(np.uint64) + partial.astype(np.uint64)
            self._acc = (total % np.uint64(self._combiner.modulus)).astype(np.int64)

    def finish(self) -> np.ndarray:
        if self._acc is None:
            # no chunks at all: each scheme's own empty-cohort shape
            # (NoMasking/Full: empty vector; ChaCha: zeros(dimension))
            return self._combiner.combine([])
        return self._acc


class SecretUnmasker:
    def unmask(self, mask: np.ndarray, masked: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class NoMasker(SecretMasker, MaskCombiner, SecretUnmasker):
    """Zero masking: empty mask, secrets pass through (masking/none.rs)."""

    def mask(self, secrets):
        return np.empty(0, dtype=np.int64), np.asarray(secrets, dtype=np.int64).copy()

    def combine(self, masks):
        assert all(len(m) == 0 for m in masks)
        return np.empty(0, dtype=np.int64)

    def unmask(self, mask, masked):
        assert len(mask) == 0
        return np.asarray(masked, dtype=np.int64).copy()


class FullMasker(SecretMasker, MaskCombiner, SecretUnmasker):
    """Per-element uniform masks from OS entropy (masking/full.rs)."""

    def __init__(self, modulus: int):
        self.modulus = modulus

    def mask(self, secrets):
        secrets = np.asarray(secrets, dtype=np.int64)
        masks = uniform_mod_host(secrets.shape, self.modulus)
        masked = rust_rem_np(secrets + masks, self.modulus)
        return masks, masked

    def combine(self, masks):
        if not masks:
            return np.empty(0, dtype=np.int64)
        stack = np.stack([np.asarray(m, dtype=np.int64) for m in masks])
        return mod_sum_wide_np(stack, self.modulus, axis=0)

    def unmask(self, mask, masked):
        return rust_rem_np(np.asarray(masked, np.int64) - np.asarray(mask, np.int64), self.modulus)


class ChaChaMasker(SecretMasker, MaskCombiner, SecretUnmasker):
    """Seed-compressed masks (masking/chacha.rs): upload only the seed.

    The uploaded "mask" is the seed's u32 words as i64s (matching the
    reference's wire shape, chacha.rs:48-52), and the expansion is
    BIT-EXACT to the reference's rand-0.3 ``ChaChaRng::from_seed`` +
    ``gen_range(0, m)`` (see ``sda_tpu.ops.chacha`` module doc; oracle
    test in tests/test_ops_field.py) — a mixed deployment (reference
    participant with this recipient, or vice versa) unmasks correctly.
    """

    def __init__(self, modulus: int, dimension: int, seed_bitsize: int):
        self.modulus = modulus
        self.dimension = dimension
        self.seed_words = (seed_bitsize + 31) // 32

    def mask(self, secrets):
        secrets = np.asarray(secrets, dtype=np.int64)
        if len(secrets) != self.dimension:
            raise ValueError("dimension mismatch")
        seed = uniform_mod_host((self.seed_words,), 1 << 32).astype(np.uint32)
        mask = expand_seed(seed, self.dimension, self.modulus)
        masked = rust_rem_np(secrets + mask, self.modulus)
        return seed.astype(np.int64), masked

    #: below this many expanded elements the host loop beats device dispatch
    DEVICE_COMBINE_THRESHOLD = 1 << 22
    #: distinct failures already warned about — a jax-less deployment warns
    #: once, while a *new* failure mode (e.g. device OOM) still surfaces
    _device_combine_warned: set = set()

    def combine(self, seeds):
        seed_rows = [np.asarray(s, dtype=np.int64).astype(np.uint32) for s in seeds]
        if len(seed_rows) * self.dimension >= self.DEVICE_COMBINE_THRESHOLD:
            # reveal hot loop (receive.rs:102-118): expand + sum on device,
            # Pallas ChaCha kernel when available (ops/chacha_pallas.py)
            try:
                from ..ops.chacha_pallas import combine_masks_device

                return np.asarray(
                    combine_masks_device(np.stack(seed_rows), self.dimension, self.modulus)
                )
            except Exception as e:
                # any failure falls back to the host loop (results stay
                # correct); each *distinct* failure mode is warned once —
                # no per-reveal spam on jax-less hosts, but a new problem
                # (e.g. device OOM) can't hide behind an old warning
                failure = f"{type(e).__name__}: {e}"
                if failure not in ChaChaMasker._device_combine_warned:
                    ChaChaMasker._device_combine_warned.add(failure)
                    logging.getLogger(__name__).warning(
                        "device mask combine unavailable (%s); using host loop", failure
                    )
        if not seed_rows:
            return np.zeros(self.dimension, dtype=np.int64)
        # one C call expands + folds the whole cohort (19x the numpy loop;
        # falls back to it when the extension isn't built)
        return chacha_combine(np.stack(seed_rows), self.dimension, self.modulus)

    def unmask(self, mask, masked):
        return rust_rem_np(np.asarray(masked, np.int64) - np.asarray(mask, np.int64), self.modulus)


def new_secret_masker(scheme) -> SecretMasker:
    return _dispatch(scheme)


def new_mask_combiner(scheme) -> MaskCombiner:
    return _dispatch(scheme)


def new_secret_unmasker(scheme) -> SecretUnmasker:
    return _dispatch(scheme)


def _dispatch(scheme):
    if isinstance(scheme, NoMasking):
        return NoMasker()
    if isinstance(scheme, FullMasking):
        return FullMasker(scheme.modulus)
    if isinstance(scheme, ChaChaMasking):
        return ChaChaMasker(scheme.modulus, scheme.dimension, scheme.seed_bitsize)
    raise TypeError(f"unknown masking scheme {scheme!r}")
