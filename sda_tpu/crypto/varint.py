"""Zigzag + LEB128 varint codec for share vectors.

Wire parity with the reference's ``integer_encoding::VarInt`` for i64
(client/src/crypto/encryption/sodium.rs:36-41, 85-91): signed values zigzag
to u64 then little-endian base-128 with continuation bits. Share payloads can
be negative (truncated-remainder representatives), so zigzag is load-bearing.

Implemented as fixed-depth vectorized numpy passes (10 columns max for u64),
not a per-element Python loop; the C extension in ``sda_tpu/native`` replaces
this on the bulk path when built.
"""

from __future__ import annotations

import numpy as np


def zigzag_encode(values: np.ndarray) -> np.ndarray:
    v = values.astype(np.int64)
    return ((v << 1) ^ (v >> 63)).astype(np.uint64)


def zigzag_decode(z: np.ndarray) -> np.ndarray:
    z = z.astype(np.uint64)
    return ((z >> np.uint64(1)).astype(np.int64)) ^ -((z & np.uint64(1)).astype(np.int64))


def encode_i64(values: np.ndarray) -> bytes:
    """Encode an int64 vector to concatenated zigzag-LEB128 varints."""
    z = zigzag_encode(np.ascontiguousarray(values))
    n = len(z)
    cols = np.empty((n, 10), dtype=np.uint8)
    valid = np.empty((n, 10), dtype=bool)
    for i in range(10):
        shifted = z >> np.uint64(7 * i)
        more = (z >> np.uint64(min(7 * (i + 1), 63))) != 0 if i < 9 else np.zeros(n, bool)
        if i == 9:
            cols[:, i] = (shifted & np.uint64(0x7F)).astype(np.uint8)
        else:
            cols[:, i] = ((shifted & np.uint64(0x7F)) | (np.uint64(0x80) * more)).astype(
                np.uint8
            )
        valid[:, i] = (shifted != 0) if i > 0 else True
    return cols[valid].tobytes()


def decode_i64(buf: bytes) -> np.ndarray:
    """Decode concatenated zigzag-LEB128 varints to an int64 vector."""
    data = np.frombuffer(buf, dtype=np.uint8)
    if len(data) == 0:
        return np.empty(0, dtype=np.int64)
    ends = np.nonzero(data < 0x80)[0]
    if len(ends) == 0 or ends[-1] != len(data) - 1:
        raise ValueError("truncated varint stream")
    starts = np.concatenate([[0], ends[:-1] + 1])
    lengths = ends - starts + 1
    if lengths.max() > 10:
        raise ValueError("varint too long for u64")
    z = np.zeros(len(starts), dtype=np.uint64)
    for i in range(int(lengths.max())):
        mask = lengths > i
        part = data[starts[mask] + i].astype(np.uint64) & np.uint64(0x7F)
        z[mask] |= part << np.uint64(7 * i)
    return zigzag_decode(z)
