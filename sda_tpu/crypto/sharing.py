"""Secret sharing: additive and packed Shamir, with batching semantics.

Mirrors /root/reference/client/src/crypto/sharing/: a ``ShareGenerator``
turns a dim-length secret vector into one share-vector per clerk; the
``Combiner`` sums share-vectors mod m (the clerk hot loop); a
``SecretReconstructor`` rebuilds the dim-length vector from indexed clerk
results.

Batching semantics match batched.rs:30-49 exactly: the dim axis is chopped
into ``input_size``-sized batches, the last batch zero-padded, shares
transposed per clerk, and reconstruction truncates the pad — but the loops
become one (batches, k) reshape + one mod-p matmul over the whole tensor.
"""

from __future__ import annotations

import numpy as np

from ..ops import shamir
from ..ops.modular import MAX_SAFE_MODULUS, mod_sum_wide_np, rust_rem_np
from ..ops.rng import uniform_mod_host
from ..protocol import AdditiveSharing, BasicShamirSharing, PackedShamirSharing


class ShareGenerator:
    def generate(self, secrets: np.ndarray) -> np.ndarray:
        """(dim,) secrets -> (share_count, per_clerk_len) shares."""
        raise NotImplementedError


class ShareCombiner:
    def combine(self, share_vectors) -> np.ndarray:
        raise NotImplementedError


class SecretReconstructor:
    def reconstruct(self, indexed_shares) -> np.ndarray:
        """[(clerk_index, share_vector), ...] -> (dim,) secrets."""
        raise NotImplementedError


def _batched(secrets: np.ndarray, input_size: int) -> np.ndarray:
    """Chop (dim,) into (n_batches, input_size), zero-padding the tail."""
    secrets = np.asarray(secrets, dtype=np.int64)
    dim = len(secrets)
    n_batches = (dim + input_size - 1) // input_size
    padded = np.zeros(n_batches * input_size, dtype=np.int64)
    padded[:dim] = secrets
    return padded.reshape(n_batches, input_size)


class AdditiveShareGenerator(ShareGenerator):
    """n-of-n additive sharing (sharing/additive.rs:42-48).

    The reference's per-element fold ``last = (last - share) % m`` over
    uniform draws reduces (proven in the truncated-remainder algebra) to
    ``last = rust_rem(secret - sum(draws), m)`` — one vectorized line.
    """

    def __init__(self, share_count: int, modulus: int):
        self.share_count = share_count
        self.modulus = modulus

    def generate(self, secrets):
        secrets = np.asarray(secrets, dtype=np.int64)
        dim = len(secrets)
        draws = uniform_mod_host((self.share_count - 1, dim), self.modulus)
        total = mod_sum_wide_np(draws, self.modulus, axis=0)
        last = rust_rem_np(secrets - total, self.modulus)
        return np.concatenate([draws, last[None, :]], axis=0)


class PackedShamirShareGenerator(ShareGenerator):
    """Shamir sharing (packed or basic) as one batched mod-p matmul
    (ops/shamir.py) — both schemes are linear maps; only the matrix and
    batch width (``input_size``: k for packed, 1 for basic) differ."""

    def __init__(self, scheme):
        self.scheme = scheme
        self.S = shamir.share_matrix(scheme)

    def generate(self, secrets):
        k = self.scheme.input_size
        t = self.scheme.privacy_threshold
        p = self.scheme.prime_modulus
        batches = _batched(secrets, k)  # (B, k)
        randomness = uniform_mod_host((batches.shape[0], t), p)
        shares = shamir.share_batches(batches, randomness, self.S, p)  # (B, n)
        return shares.T.copy()  # (share_count, B): one row per clerk


class Combiner(ShareCombiner):
    """Scheme-independent modular sum over participants (combiner.rs:16-30).

    int64 accumulate then a single truncated reduction — congruent to the
    reference's per-add ``+=; %=`` chain and identical after ``positive()``.
    """

    def __init__(self, modulus: int):
        self.modulus = modulus

    def combine(self, share_vectors):
        if not len(share_vectors):
            # empty snapshot cut: the reference yields the empty vector
            # (combiner.rs:17 — `map_or(0, Vec::len)` defaults the
            # dimension to 0 when there are no shares)
            return np.empty(0, dtype=np.int64)
        stack = np.stack([np.asarray(v, dtype=np.int64) for v in share_vectors])
        if self.modulus < MAX_SAFE_MODULUS and len(stack) < (1 << 32):
            return rust_rem_np(stack.sum(axis=0), self.modulus)
        return mod_sum_wide_np(stack, self.modulus, axis=0)


class AdditiveReconstructor(SecretReconstructor):
    def __init__(self, modulus: int):
        self.modulus = modulus

    def reconstruct(self, indexed_shares):
        stack = np.stack([np.asarray(v, dtype=np.int64) for _, v in indexed_shares])
        return mod_sum_wide_np(stack, self.modulus, axis=0)


class PackedShamirReconstructor(SecretReconstructor):
    """Gather surviving clerk rows, Lagrange-interpolate, truncate pad.

    Works from any ``reconstruction_threshold`` indexed shares — the
    dropout-recovery path (reference receive.rs:127-145, batched.rs:68-98).
    """

    def __init__(self, scheme, dimension: int):
        self.scheme = scheme
        self.dimension = dimension

    def reconstruct(self, indexed_shares):
        p = self.scheme.prime_modulus
        indices = [i for i, _ in indexed_shares]
        L = shamir.reconstruction_matrix(self.scheme, indices)  # (k, R)
        shares = np.stack(
            [np.asarray(v, dtype=np.int64) for _, v in indexed_shares]
        )  # (R, B)
        secrets = shamir.reconstruct_batches(shares.T, L, p)  # (B, k)
        return secrets.reshape(-1)[: self.dimension].copy()


def new_share_generator(scheme) -> ShareGenerator:
    if isinstance(scheme, AdditiveSharing):
        return AdditiveShareGenerator(scheme.share_count, scheme.modulus)
    if isinstance(scheme, (BasicShamirSharing, PackedShamirSharing)):
        return PackedShamirShareGenerator(scheme)
    raise TypeError(f"unknown sharing scheme {scheme!r}")


def new_share_combiner(scheme) -> ShareCombiner:
    if isinstance(scheme, AdditiveSharing):
        return Combiner(scheme.modulus)
    if isinstance(scheme, (BasicShamirSharing, PackedShamirSharing)):
        return Combiner(scheme.prime_modulus)
    raise TypeError(f"unknown sharing scheme {scheme!r}")


def new_secret_reconstructor(scheme, dimension: int) -> SecretReconstructor:
    if isinstance(scheme, AdditiveSharing):
        return AdditiveReconstructor(scheme.modulus)
    if isinstance(scheme, (BasicShamirSharing, PackedShamirSharing)):
        return PackedShamirReconstructor(scheme, dimension)
    raise TypeError(f"unknown sharing scheme {scheme!r}")
