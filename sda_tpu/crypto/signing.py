"""Ed25519 signing of labelled encryption keys.

Parity with /root/reference/client/src/crypto/signing/mod.rs: detached
Ed25519 over the canonical JSON bytes of ``Labelled<EncryptionKeyId,
EncryptionKey>``; verification additionally checks the claimed signer is the
agent whose verification key is used (signing/mod.rs:113).
"""

from __future__ import annotations

from ..protocol import (
    B32,
    B64,
    Agent,
    Signature,
    Signed,
    SigningKey,
    VerificationKey,
    canonical_bytes,
)
from . import sodium
from .keystore import SignatureKeypair


def generate_signature_keypair() -> SignatureKeypair:
    vk, sk = sodium.sign_keypair()
    return SignatureKeypair(vk=VerificationKey(B32(vk)), sk=SigningKey(B64(sk)))


def sign(body, signer_id, keypair: SignatureKeypair) -> Signed:
    """Sign ``body`` (any wire object) with the agent's signing key."""
    sig = sodium.sign_detached(canonical_bytes(body), keypair.sk.data)
    return Signed(signature=Signature(B64(sig)), signer=signer_id, body=body)


def signature_is_valid(agent: Agent, signed: Signed) -> bool:
    """Verify a Signed object against the agent's verification key."""
    if signed.signer != agent.id:
        raise ValueError("Agent differs from claimed signer")
    return sodium.verify_detached(
        signed.signature.data,
        canonical_bytes(signed.body),
        agent.verification_key.body.data,
    )
