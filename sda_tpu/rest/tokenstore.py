"""Client-side auth token store.

Parity with /root/reference/client-http/src/tokenstore.rs:8-23: a random
32-char alphanumeric token is generated on first use and persisted; the
server records it on first ``create_agent`` (trust-on-first-use) and demands
it on every later request.
"""

from __future__ import annotations

import os
import secrets
import string


class TokenStore:
    def __init__(self, path):
        self.path = os.path.join(str(path), "http_token")
        os.makedirs(str(path), mode=0o700, exist_ok=True)

    def get(self) -> str:
        try:
            with open(self.path) as f:
                return f.read().strip()
        except FileNotFoundError:
            alphabet = string.ascii_letters + string.digits
            token = "".join(secrets.choice(alphabet) for _ in range(32))
            fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "w") as f:
                f.write(token)
            return token
