"""sda_tpu.rest — the HTTP binding of the service seam (server + client),
plus the negotiated binary wire codec the hot routes ride (``wire``)."""

from . import wire
from .client import SdaHttpClient
from .server import (
    listen,
    make_handler,
    serve_background,
    serve_background_multi,
    serve_forever,
)
from .tokenstore import TokenStore

__all__ = [
    "SdaHttpClient",
    "TokenStore",
    "listen",
    "make_handler",
    "serve_background",
    "serve_background_multi",
    "serve_forever",
    "wire",
]
