"""Negotiated binary wire codec for the hot REST routes.

JSON carries every route fine, but the three bulk payloads — the
participation batch POST, the clerking-job chunk GET, and the
snapshot-result mask/clerk chunk GETs — pay base64 (+33% bytes) plus
per-field JSON encode/decode on both ends, and that is the measured
ingest ceiling once the host planes are batched and pooled. This module
defines ``application/x-sda-binary``: varint-framed *columns* of raw
sealed-box bytes, negotiated per request via ``Accept`` (GETs) /
``Content-Type`` (POSTs) so plain-JSON peers keep working unchanged.

Frame layout (pinned in docs/protocol.md):

    magic    4 bytes   b"SDAW"
    version  1 byte    0x01 — bumped on any layout change, never reused
    kind     1 byte    1=encryptions 2=participations 3=clerking results
    payload  columns, kind-specific

Column primitives:

    uvarint       unsigned LEB128 (framing counts and section lengths)
    i64 column    uvarint byte-length + zigzag-LEB128 stream, produced
                  and parsed by the native varint kernels
                  (``native/_sdanative.c``) with the vectorized
                  ``crypto/varint.py`` fallback when the extension is
                  absent — the same codec share vectors already use
    uuid column   count x 16 raw bytes (count always known from context)
    bytes column  uvarint count + i64 column of per-item lengths +
                  the items' raw bytes, concatenated
    encryption column
                  uvarint count + one variant-tag byte per item
                  (index into ``Encryption.VARIANTS``) + bytes column
                  of the ciphertexts (lengths + concatenated payload)

Every read is bounds-checked against the delivered body: a truncated or
oversized frame raises ``WireError`` (a ``ValueError``) before any
object is half-built, and trailing bytes after a frame are an error too.
Crypto is untouched — the sealed-box ciphertexts cross this layer as
opaque bytes, byte-identical to their base64 JSON form.
"""

from __future__ import annotations

import os

import numpy as np

from .. import native
from ..protocol import (
    AgentId,
    AggregationId,
    ClerkingJobId,
    ClerkingResult,
    Encryption,
    Participation,
    ParticipationId,
)

import uuid as _uuid

#: the negotiated binary media type; requests/responses carrying it hold
#: exactly one frame as described in the module docstring
CONTENT_TYPE = "application/x-sda-binary"

MAGIC = b"SDAW"
VERSION = 1

KIND_ENCRYPTIONS = 1
KIND_PARTICIPATIONS = 2
KIND_CLERKING_RESULTS = 3


class WireError(ValueError):
    """A binary frame that cannot be decoded safely: truncated, trailing
    bytes, bad magic/version/kind, or inconsistent column framing."""


def mode() -> str:
    """The client's transport preference: ``binary`` (default) sends the
    negotiated frames on the hot routes; ``SDA_WIRE=json`` forces the
    legacy JSON bodies everywhere (interop / bisection knob)."""
    return "json" if os.environ.get("SDA_WIRE", "").strip().lower() == "json" else "binary"


def is_binary(content_type) -> bool:
    """Does a Content-Type header name the binary media type?"""
    if not content_type:
        return False
    return content_type.split(";", 1)[0].strip().lower() == CONTENT_TYPE


def accepts_binary(accept) -> bool:
    """Does an Accept header offer the binary media type? (Substring is
    enough: the exact token cannot appear inside another media type.)"""
    return bool(accept) and CONTENT_TYPE in accept


# -- primitives -------------------------------------------------------------


def _uvarint(n: int) -> bytes:
    """Unsigned LEB128 — framing counts and section byte-lengths."""
    if n < 0:
        raise WireError("uvarint cannot encode a negative value")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class _Reader:
    """Bounds-checked cursor over one delivered frame body."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int):
        if n < 0 or self.pos + n > len(self.buf):
            raise WireError(
                f"truncated binary frame: need {n} bytes at offset {self.pos}, "
                f"have {len(self.buf) - self.pos}"
            )
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def uvarint(self) -> int:
        shift = 0
        value = 0
        while True:
            if self.pos >= len(self.buf):
                raise WireError("truncated binary frame: unterminated uvarint")
            if shift > 63:
                raise WireError("uvarint too long for u64")
            b = self.buf[self.pos]
            self.pos += 1
            value |= (b & 0x7F) << shift
            if not (b & 0x80):
                return value
            shift += 7

    def expect_eof(self) -> None:
        if self.pos != len(self.buf):
            raise WireError(
                f"trailing bytes after binary frame: {len(self.buf) - self.pos}"
            )


def _header(kind: int) -> bytes:
    return MAGIC + bytes((VERSION, kind))


def _open(buf: bytes, kind: int) -> _Reader:
    r = _Reader(bytes(buf))
    if bytes(r.take(len(MAGIC))) != MAGIC:
        raise WireError("bad magic: not an SDA binary frame")
    version = r.take(1)[0]
    if version != VERSION:
        raise WireError(f"unsupported binary wire version {version}")
    got = r.take(1)[0]
    if got != kind:
        raise WireError(f"unexpected binary payload kind {got} (wanted {kind})")
    return r


def _put_i64_column(parts: list, values) -> None:
    encoded = native.varint_encode(np.asarray(values, dtype=np.int64))
    parts.append(_uvarint(len(encoded)))
    parts.append(encoded)


def _get_i64_column(r: _Reader, count: int) -> np.ndarray:
    nbytes = r.uvarint()
    raw = bytes(r.take(nbytes))
    try:
        arr = native.varint_decode(raw)
    except ValueError as e:
        raise WireError(f"bad i64 column: {e}")
    if len(arr) != count:
        raise WireError(f"i64 column holds {len(arr)} values, framing says {count}")
    return arr


_VARIANT_TAG = {v: i for i, v in enumerate(Encryption.VARIANTS)}


def _put_encryptions(parts: list, encryptions) -> None:
    n = len(encryptions)
    parts.append(_uvarint(n))
    if not n:
        return
    # single pass; ``e.inner.data`` skips the ``data`` property descriptor,
    # which is measurable at thousands of ciphertexts per frame
    tags = bytearray(n)
    datas = []
    for i, e in enumerate(encryptions):
        tags[i] = _VARIANT_TAG[e.variant]
        datas.append(e.inner.data)
    parts.append(bytes(tags))
    _put_i64_column(
        parts, np.fromiter(map(len, datas), dtype=np.int64, count=n)
    )
    parts.append(b"".join(datas))


def _get_encryptions(r: _Reader) -> list:
    n = r.uvarint()
    if not n:
        return []
    variant_tags = bytes(r.take(n))
    lengths = _get_i64_column(r, n)
    if n and int(lengths.min()) < 0:
        raise WireError("negative ciphertext length in encryption column")
    blob = bytes(r.take(int(lengths.sum())))
    variants = Encryption.VARIANTS
    if max(variant_tags) >= len(variants):
        tag = next(t for t in variant_tags if t >= len(variants))
        raise WireError(f"unknown encryption variant tag {tag}")
    build = Encryption._from_wire
    ends = np.cumsum(lengths).tolist()
    starts = [0] + ends[:-1]
    if variant_tags.count(0) == n:
        # overwhelmingly common frame: every ciphertext is a sodium sealed
        # box — skip the per-item variant lookup entirely
        return [build(blob[s:e], "Sodium") for s, e in zip(starts, ends)]
    return [
        build(blob[s:e], variants[t])
        for s, e, t in zip(starts, ends, variant_tags)
    ]


def _put_uuid_column(parts: list, ids) -> None:
    parts.append(b"".join(i.uuid.bytes for i in ids))


def _get_uuid_column(r: _Reader, count: int, id_type, cache=None) -> list:
    """Parse ``count`` raw 16-byte uuids into ``id_type`` instances.

    ``cache`` (a per-frame, per-type dict keyed by the raw bytes) dedupes
    columns whose values repeat heavily — the participant / aggregation /
    clerk-agent columns of a participation batch hold a handful of
    distinct ids repeated thousands of times, so sharing the (immutable)
    instances turns most constructions into dict hits."""
    raw = bytes(r.take(16 * count))
    build = id_type._from_uuid_bytes
    if cache is None:
        return [build(raw[o : o + 16]) for o in range(0, 16 * count, 16)]
    out = []
    for o in range(0, 16 * count, 16):
        key = raw[o : o + 16]
        hit = cache.get(key)
        if hit is None:
            hit = cache[key] = build(key)
        out.append(hit)
    return out


# -- payloads ---------------------------------------------------------------


def encode_encryptions(encryptions) -> bytes:
    """One bare ciphertext column — the clerking-job chunk and
    snapshot-result mask chunk response payload."""
    parts = [_header(KIND_ENCRYPTIONS)]
    _put_encryptions(parts, list(encryptions))
    return b"".join(parts)


def decode_encryptions(buf) -> list:
    r = _open(buf, KIND_ENCRYPTIONS)
    out = _get_encryptions(r)
    r.expect_eof()
    return out


def encode_participations(participations) -> bytes:
    """The participation batch POST body: id/participant/aggregation uuid
    columns, a recipient-encryption presence bitmap (LSB-first) with the
    present ciphertexts packed densely, then the flattened clerk matrix
    (per-item clerk counts as an i64 column, clerk agent ids, and the
    ciphertexts in the same flattened order)."""
    ps = list(participations)
    for p in ps:
        if getattr(p, "tier_reshare", None) is not None:
            # the frame has no tag column; silently encoding a tagged row
            # would strip its promotion semantics server-side. Callers
            # route tagged batches through the JSON body (rest/client.py).
            raise WireError("tier_reshare-tagged participations have no binary encoding")
    parts = [_header(KIND_PARTICIPATIONS), _uvarint(len(ps))]
    if ps:
        _put_uuid_column(parts, [p.id for p in ps])
        _put_uuid_column(parts, [p.participant for p in ps])
        _put_uuid_column(parts, [p.aggregation for p in ps])
        bitmap = bytearray((len(ps) + 7) // 8)
        recipient_encs = []
        for i, p in enumerate(ps):
            if p.recipient_encryption is not None:
                bitmap[i >> 3] |= 1 << (i & 7)
                recipient_encs.append(p.recipient_encryption)
        parts.append(bytes(bitmap))
        _put_encryptions(parts, recipient_encs)
        _put_i64_column(
            parts,
            np.fromiter(
                (len(p.clerk_encryptions) for p in ps), dtype=np.int64, count=len(ps)
            ),
        )
        parts.append(
            b"".join(a.uuid.bytes for p in ps for (a, _e) in p.clerk_encryptions)
        )
        _put_encryptions(parts, [e for p in ps for (_a, e) in p.clerk_encryptions])
    return b"".join(parts)


def decode_participations(buf) -> list:
    r = _open(buf, KIND_PARTICIPATIONS)
    n = r.uvarint()
    if not n:
        r.expect_eof()
        return []
    agent_cache: dict = {}
    ids = _get_uuid_column(r, n, ParticipationId)
    participants = _get_uuid_column(r, n, AgentId, cache=agent_cache)
    aggregations = _get_uuid_column(r, n, AggregationId, cache={})
    bitmap = bytes(r.take((n + 7) // 8))
    recipient_encs = _get_encryptions(r)
    present = sum(bool(bitmap[i >> 3] & (1 << (i & 7))) for i in range(n))
    if present != len(recipient_encs):
        raise WireError(
            f"presence bitmap marks {present} recipient encryptions, "
            f"column holds {len(recipient_encs)}"
        )
    clerk_counts = _get_i64_column(r, n)
    if int(clerk_counts.min()) < 0:
        raise WireError("negative clerk count in participation frame")
    total = int(clerk_counts.sum())
    clerk_ids_raw = bytes(r.take(16 * total))
    clerk_encs = _get_encryptions(r)
    if len(clerk_encs) != total:
        raise WireError(
            f"clerk counts sum to {total}, encryption column holds {len(clerk_encs)}"
        )
    r.expect_eof()

    # The flattened clerk column names the same few committee agents over
    # and over; decode it once through the shared agent cache.
    build_agent = AgentId._from_uuid_bytes
    clerk_agents = []
    for o in range(0, 16 * total, 16):
        key = clerk_ids_raw[o : o + 16]
        hit = agent_cache.get(key)
        if hit is None:
            hit = agent_cache[key] = build_agent(key)
        clerk_agents.append(hit)

    out = []
    rec_pos = 0
    flat = 0
    for i, count in enumerate(clerk_counts.tolist()):
        recipient_encryption = None
        if bitmap[i >> 3] & (1 << (i & 7)):
            recipient_encryption = recipient_encs[rec_pos]
            rec_pos += 1
        end = flat + count
        clerk_encryptions = list(zip(clerk_agents[flat:end], clerk_encs[flat:end]))
        flat = end
        out.append(
            Participation(
                id=ids[i],
                participant=participants[i],
                aggregation=aggregations[i],
                recipient_encryption=recipient_encryption,
                clerk_encryptions=clerk_encryptions,
            )
        )
    return out


def encode_clerking_results(results) -> bytes:
    """The snapshot-result clerk chunk response payload: job and clerk
    uuid columns plus the combined-ciphertext column, row-aligned."""
    rs = list(results)
    parts = [_header(KIND_CLERKING_RESULTS), _uvarint(len(rs))]
    if rs:
        _put_uuid_column(parts, [c.job for c in rs])
        _put_uuid_column(parts, [c.clerk for c in rs])
        _put_encryptions(parts, [c.encryption for c in rs])
    return b"".join(parts)


def decode_clerking_results(buf) -> list:
    r = _open(buf, KIND_CLERKING_RESULTS)
    n = r.uvarint()
    if not n:
        r.expect_eof()
        return []
    jobs = _get_uuid_column(r, n, ClerkingJobId)
    clerks = _get_uuid_column(r, n, AgentId, cache={})
    encryptions = _get_encryptions(r)
    if len(encryptions) != n:
        raise WireError(
            f"clerking-result frame of {n} rows holds {len(encryptions)} ciphertexts"
        )
    r.expect_eof()
    return [
        ClerkingResult(job=jobs[i], clerk=clerks[i], encryption=encryptions[i])
        for i in range(n)
    ]
