"""REST binding of the SDA service — the server side.

Route table, auth model, and status-code mapping are wire-compatible with
the reference's rouille binding (/root/reference/server-http/src/lib.rs):

    GET    /v1/ping
    GET    /v1/agents/{AgentId}
    POST   /v1/agents/me
    GET    /v1/agents/{AgentId}/profile
    POST   /v1/agents/me/profile
    GET    /v1/agents/any/keys/{EncryptionKeyId}
    POST   /v1/agents/me/keys
    POST   /v1/aggregations
    GET    /v1/aggregations?title=&recipient=
    GET    /v1/aggregations/{AggregationId}
    DELETE /v1/aggregations/{AggregationId}
    GET    /v1/aggregations/{AggregationId}/committee/suggestions
    POST   /v1/aggregations/implied/committee
    GET    /v1/aggregations/{AggregationId}/committee
    POST   /v1/aggregations/participations
    POST   /v1/aggregations/participations/batch   (additive; JSON array)
    GET    /v1/aggregations/{AggregationId}/status
    POST   /v1/aggregations/implied/snapshot
    GET    /v1/aggregations/any/jobs
    GET    /v1/aggregations/implied/jobs/{ClerkingJobId}/chunks/{start}
                              (additive; one ciphertext range of a paged job)
    POST   /v1/aggregations/implied/jobs/{ClerkingJobId}/result
    GET    /v1/aggregations/{AggregationId}/snapshots/{SnapshotId}/result
    GET    /v1/aggregations/{AggregationId}/snapshots/{SnapshotId}/result/masks/{start}
    GET    /v1/aggregations/{AggregationId}/snapshots/{SnapshotId}/result/clerks/{start}
    GET    /v1/metrics        (additive; unauthenticated Prometheus text)
    GET    /v1/metrics.json   (additive; unauthenticated telemetry snapshot)

Observability: every request gets a fresh id, echoed as
``X-SDA-Request-Id`` and stamped on 404/500 log lines; an incoming
``X-SDA-Trace`` header is adopted for the handler thread (and echoed
back), so server-side spans — dispatch, service, store — carry the
client's trace id. Per-route request counts and latencies land in the
telemetry registry under a normalized route template (uuid segments
become ``{id}``). See docs/observability.md.

Auth: HTTP Basic, username = AgentId, password = token recorded on first
``create_agent`` (trust-on-first-use, lib.rs:298-315). Missing resources are
404 with a ``Resource-not-found: true`` header so clients can distinguish
"no resource" from "no route" (lib.rs:338-343). Errors map to
401 / 403 / 400 / 500 (lib.rs:112-117).

Built on the stdlib ThreadingHTTPServer: one import, zero deps, adequate for
a coordination plane whose heavy payloads are bulk base64 blobs (the math
plane never crosses this boundary per element).
"""

from __future__ import annotations

import base64
import contextlib
import json
import logging
import re
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import telemetry
from ..utils import faults
from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    ClerkingJobId,
    ClerkingResult,
    Committee,
    EncryptionKeyId,
    InvalidCredentialsError,
    InvalidRequestError,
    Labelled,
    Participation,
    PermissionDeniedError,
    Profile,
    Snapshot,
    SnapshotId,
    signed_encryption_key_from_json,
)

log = logging.getLogger("sda.rest.server")

_UUID = r"[0-9a-fA-F-]{36}"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    service = None  # SdaServerService, set by make_handler

    # per-request observability state, reset by _dispatch
    _request_id = None
    _trace_id = None
    _status = None
    # set by an SDA_FAULTS "truncate" draw: _send then declares the full
    # Content-Length but delivers only half the body
    _truncate_body = False

    # -- plumbing -----------------------------------------------------------

    def log_message(self, fmt, *args):
        log.debug("%s " + fmt, self.address_string(), *args)

    def _auth_token(self):
        header = (self.headers.get("Authorization") or "").strip()
        if not header.startswith("Basic "):
            raise InvalidCredentialsError("Basic Authorization required")
        try:
            decoded = base64.b64decode(header[len("Basic ") :]).decode("utf-8")
            username, _, password = decoded.partition(":")
            return Labelled(AgentId(username), password)
        except (ValueError, UnicodeDecodeError):
            raise InvalidCredentialsError("Invalid Auth header")

    def _caller(self) -> Agent:
        return self.service.server.check_auth_token(self._auth_token())

    #: request body cap — an authed client must not be able to stream
    #: arbitrary gigabytes into server memory by claiming a huge
    #: Content-Length. Sized ~30x the largest legitimate participation
    #: we target (100K dims x 8 clerks ~= 15 MB of sealed JSON).
    MAX_BODY_BYTES = 512 * 1024 * 1024

    def _read_json(self):
        def refuse(msg):
            # rejecting before draining the body would desync an HTTP/1.1
            # keep-alive stream (the unread bytes become the "next
            # request") — drop the connection after responding instead
            self.close_connection = True
            raise InvalidRequestError(msg)

        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            refuse("invalid Content-Length")
        if length <= 0:
            refuse("Expected a body")
        if length > self.MAX_BODY_BYTES:
            refuse(f"body exceeds the {self.MAX_BODY_BYTES}-byte limit")
        try:
            return json.loads(self.rfile.read(length))
        except json.JSONDecodeError as e:
            raise InvalidRequestError(f"malformed JSON body: {e}")

    def _read(self, from_json):
        """Read + decode the request body; malformed payloads are 400s
        (the reference maps these to 500 via its catch-all; fixed here)."""
        payload = self._read_json()
        try:
            return from_json(payload)
        except InvalidRequestError:
            raise
        except Exception as e:
            raise InvalidRequestError(f"malformed body: {e}")

    def _send(self, status: int, body: bytes = b"", headers=()):
        self._status = status
        self.send_response(status)
        have_type = False
        for k, v in headers:
            have_type = have_type or k.lower() == "content-type"
            self.send_header(k, v)
        if body and not have_type:
            self.send_header("Content-Type", "application/json")
        if self._request_id:
            self.send_header("X-SDA-Request-Id", self._request_id)
        if self._trace_id:
            self.send_header(telemetry.TRACE_HEADER, self._trace_id)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            if self._truncate_body and len(body) > 1:
                # injected truncation: the declared length stands, only
                # half the bytes arrive, and the connection dies — the
                # client's content read sees a short body (urllib3
                # enforces Content-Length) and surfaces a transport error
                self.wfile.write(body[: len(body) // 2])
                self.close_connection = True
            else:
                self.wfile.write(body)

    def _send_json_option(self, obj):
        if obj is None:
            self._send(404, headers=[("Resource-not-found", "true")])
        else:
            payload = obj.to_json() if hasattr(obj, "to_json") else obj
            # compact separators: the reference emits serde_json::to_string
            # (no whitespace, server-http/src/lib.rs:338-343); replay-interop
            # asserts response bodies byte-identical to that shape
            self._send(
                200, json.dumps(payload, separators=(",", ":")).encode("utf-8")
            )

    def _dispatch(self, method: str):
        path, _, query = self.path.partition("?")
        params = {}
        for pair in query.split("&"):
            if "=" in pair:
                k, _, v = pair.partition("=")
                from urllib.parse import unquote_plus

                params[k] = unquote_plus(v)

        self._request_id = uuid.uuid4().hex[:16]
        self._status = None
        self._trace_id = None
        self._truncate_body = False
        fault = faults.server_draw()
        if fault is not None:
            if fault.kind == "latency":
                time.sleep(fault.param)  # stall, then handle normally
            elif fault.kind == "drop":
                # connection death without an HTTP response; closing the
                # keep-alive stream keeps the next request in sync
                self.close_connection = True
                return
            elif fault.kind == "e503":
                # answering without draining a POST body would desync
                # the keep-alive stream (see _read_json) — drop the
                # connection after the response instead
                self.close_connection = True
                self._send(
                    503,
                    b"SDA_FAULTS: injected transient failure",
                    headers=[("Retry-After", f"{fault.param:g}"),
                             ("Content-Type", "text/plain")],
                )
                return
            elif fault.kind == "truncate":
                self._truncate_body = True
        if telemetry.enabled():
            # adopt the client's trace id (or mint one) for this handler
            # thread; echoed back by _send alongside the request id
            self._trace_id = telemetry.sanitize_trace_id(
                self.headers.get(telemetry.TRACE_HEADER)
            ) or telemetry.new_trace_id()
            telemetry.set_trace_id(self._trace_id)
        t0 = time.perf_counter()
        try:
            with telemetry.span("http.request", method=method) as span_record:
                handled = self._dispatch_inner(method, path, params)
                route = re.sub(_UUID, "{id}", path) if handled else "<unmatched>"
                if span_record is not None:
                    span_record["attrs"] = {
                        "method": method,
                        "route": route,
                        "status": self._status,
                        "request_id": self._request_id,
                    }
            if telemetry.enabled():
                telemetry.histogram(
                    "sda_http_request_seconds",
                    "REST request latency by route template",
                    method=method,
                    route=route,
                ).observe(time.perf_counter() - t0)
                telemetry.counter(
                    "sda_http_requests_total",
                    "REST requests served by route template and status",
                    method=method,
                    route=route,
                    status=str(self._status or 0),
                ).inc()
        finally:
            if self._trace_id is not None:
                telemetry.set_trace_id(None)

    def _dispatch_inner(self, method, path, params) -> bool:
        """Route + error mapping; returns whether the path was routed."""
        try:
            handled = self._route(method, path, params)
            if not handled:
                log.error(
                    "route not found: %s %s (request %s)",
                    method, path, self._request_id,
                )
                self._send(404)
            return handled
        except InvalidCredentialsError as e:
            self._send(401, str(e).encode())
        except PermissionDeniedError as e:
            self._send(403, str(e).encode())
        except InvalidRequestError as e:
            self._send(400, str(e).encode())
        except Exception as e:  # ServerError and unexpected -> 500
            log.error(
                "%s %s -> 500: %s (request %s)",
                method, path, e, self._request_id,
            )
            self._send(500, str(e).encode())
        return True  # an error from a handler still means the path routed

    # -- routes -------------------------------------------------------------

    def _route(self, method, path, params) -> bool:
        m = lambda pat: re.fullmatch(pat, path)
        svc = self.service

        if method == "GET" and path == "/v1/ping":
            self._send_json_option(svc.ping())
            return True

        if method == "GET" and path == "/v1/metrics":
            # additive observability route (not in the reference protocol):
            # Prometheus text exposition, unauthenticated like /v1/ping —
            # aggregate series only, no resource data (docs/observability.md)
            body = telemetry.prometheus_text().encode("utf-8")
            self._send(
                200,
                body,
                headers=[("Content-Type", telemetry.PROMETHEUS_CONTENT_TYPE)],
            )
            return True

        if method == "GET" and path == "/v1/metrics.json":
            # the same registry as JSON (plus recent spans), for tooling
            # that wants telemetry.snapshot() without Prometheus parsing
            body = json.dumps(
                telemetry.snapshot(), separators=(",", ":"), default=repr
            ).encode("utf-8")
            self._send(200, body)
            return True

        if method == "POST" and path == "/v1/agents/me":
            # TOFU: token recorded on successful agent creation (lib.rs:192-201)
            token = self._auth_token()
            agent = self._read(Agent.from_json)
            if agent.id != token.id:
                self._send(400, b"inconsistent agent ids")
                return True
            svc.server.register_auth_token(token)
            svc.create_agent(agent, agent)
            self._send(201)
            return True

        if method == "GET" and (match := m(rf"/v1/agents/({_UUID})")):
            self._send_json_option(svc.get_agent(self._caller(), AgentId(match.group(1))))
            return True

        if method == "GET" and (match := m(rf"/v1/agents/({_UUID})/profile")):
            self._send_json_option(svc.get_profile(self._caller(), AgentId(match.group(1))))
            return True

        if method == "POST" and path == "/v1/agents/me/profile":
            svc.upsert_profile(self._caller(), self._read(Profile.from_json))
            self._send(201)
            return True

        if method == "GET" and (match := m(rf"/v1/agents/any/keys/({_UUID})")):
            self._send_json_option(
                svc.get_encryption_key(self._caller(), EncryptionKeyId(match.group(1)))
            )
            return True

        if method == "POST" and path == "/v1/agents/me/keys":
            svc.create_encryption_key(
                self._caller(), self._read(signed_encryption_key_from_json)
            )
            self._send(201)
            return True

        if method == "POST" and path == "/v1/aggregations":
            svc.create_aggregation(self._caller(), self._read(Aggregation.from_json))
            self._send(201)
            return True

        if method == "GET" and path == "/v1/aggregations":
            recipient = params.get("recipient")
            ids = svc.list_aggregations(
                self._caller(),
                params.get("title"),
                AgentId(recipient) if recipient else None,
            )
            self._send_json_option([str(i) for i in ids])
            return True

        if method == "GET" and (match := m(rf"/v1/aggregations/({_UUID})/committee/suggestions")):
            out = svc.suggest_committee(self._caller(), AggregationId(match.group(1)))
            self._send_json_option([c.to_json() for c in out])
            return True

        if method == "POST" and path == "/v1/aggregations/implied/committee":
            svc.create_committee(self._caller(), self._read(Committee.from_json))
            self._send(201)
            return True

        if method == "GET" and (match := m(rf"/v1/aggregations/({_UUID})/committee")):
            self._send_json_option(
                svc.get_committee(self._caller(), AggregationId(match.group(1)))
            )
            return True

        if method == "POST" and path == "/v1/aggregations/participations":
            svc.create_participation(
                self._caller(), self._read(Participation.from_json)
            )
            self._send(201)
            return True

        if method == "POST" and path == "/v1/aggregations/participations/batch":
            # batched ingest (additive route, not in the reference): a JSON
            # array of participations, ONE auth check and ONE response for
            # the whole batch — the transport half of the pipeline. The
            # service layer accepts or rejects the array atomically.
            payload = self._read_json()
            if not isinstance(payload, list):
                raise InvalidRequestError("expected a JSON array of participations")
            try:
                participations = [Participation.from_json(p) for p in payload]
            except Exception as e:
                raise InvalidRequestError(f"malformed body: {e}")
            svc.create_participations(self._caller(), participations)
            self._send(201)
            return True

        if method == "GET" and (match := m(rf"/v1/aggregations/({_UUID})/status")):
            self._send_json_option(
                svc.get_aggregation_status(self._caller(), AggregationId(match.group(1)))
            )
            return True

        if method == "POST" and path == "/v1/aggregations/implied/snapshot":
            svc.create_snapshot(self._caller(), self._read(Snapshot.from_json))
            self._send(201)
            return True

        if method == "GET" and path == "/v1/aggregations/any/jobs":
            caller = self._caller()
            self._send_json_option(svc.get_clerking_job(caller, caller.id))
            return True

        if method == "GET" and (
            match := m(rf"/v1/aggregations/implied/jobs/({_UUID})/chunks/(\d+)")
        ):
            # one ciphertext range of a paged clerking job; the clerk is
            # implied by auth (chunk reads answer 404 unless the caller
            # owns the job). Response: bare JSON array of encryptions.
            chunk = svc.get_clerking_job_chunk(
                self._caller(), ClerkingJobId(match.group(1)), int(match.group(2))
            )
            self._send_json_option(
                None if chunk is None else [e.to_json() for e in chunk]
            )
            return True

        if method == "POST" and (match := m(rf"/v1/aggregations/implied/jobs/({_UUID})/result")):
            svc.create_clerking_result(
                self._caller(), self._read(ClerkingResult.from_json)
            )
            self._send(201)
            return True

        if method == "GET" and (
            match := m(rf"/v1/aggregations/({_UUID})/snapshots/({_UUID})/result/masks/(\d+)")
        ):
            # one recipient-mask-encryption range of a paged snapshot
            # result (recipient-only by ACL). Response: bare JSON array.
            chunk = svc.get_snapshot_result_masks(
                self._caller(),
                AggregationId(match.group(1)),
                SnapshotId(match.group(2)),
                int(match.group(3)),
            )
            self._send_json_option(
                None if chunk is None else [e.to_json() for e in chunk]
            )
            return True

        if method == "GET" and (
            match := m(rf"/v1/aggregations/({_UUID})/snapshots/({_UUID})/result/clerks/(\d+)")
        ):
            # one clerk-result range, in the canonical job-id order
            chunk = svc.get_snapshot_result_clerks(
                self._caller(),
                AggregationId(match.group(1)),
                SnapshotId(match.group(2)),
                int(match.group(3)),
            )
            self._send_json_option(
                None if chunk is None else [c.to_json() for c in chunk]
            )
            return True

        if method == "GET" and (
            match := m(rf"/v1/aggregations/({_UUID})/snapshots/({_UUID})/result")
        ):
            self._send_json_option(
                svc.get_snapshot_result(
                    self._caller(), AggregationId(match.group(1)), SnapshotId(match.group(2))
                )
            )
            return True

        if method == "GET" and (match := m(rf"/v1/aggregations/({_UUID})")):
            self._send_json_option(
                svc.get_aggregation(self._caller(), AggregationId(match.group(1)))
            )
            return True

        if method == "DELETE" and (match := m(rf"/v1/aggregations/({_UUID})")):
            svc.delete_aggregation(self._caller(), AggregationId(match.group(1)))
            self._send(200)
            return True

        return False

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")


def make_handler(service):
    return type("SdaHandler", (_Handler,), {"service": service})


def listen(addr: tuple, service) -> ThreadingHTTPServer:
    """Create (but do not start) an HTTP server bound to addr."""
    return ThreadingHTTPServer(addr, make_handler(service))


def serve_forever(addr: tuple, service) -> None:
    httpd = listen(addr, service)
    log.info("sda REST server listening on %s:%s", *addr)
    httpd.serve_forever()


@contextlib.contextmanager
def serve_background(service, host: str = "127.0.0.1", port: int = 0):
    """Run the REST server on a daemon thread; yields the base URL."""
    httpd = listen((host, port), service)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://{httpd.server_address[0]}:{httpd.server_address[1]}"
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=5)
