"""REST binding of the SDA service — the server side.

Route table, auth model, and status-code mapping are wire-compatible with
the reference's rouille binding (/root/reference/server-http/src/lib.rs):

    GET    /v1/ping
    GET    /v1/agents/{AgentId}
    POST   /v1/agents/me
    GET    /v1/agents/{AgentId}/profile
    POST   /v1/agents/me/profile
    GET    /v1/agents/any/keys/{EncryptionKeyId}
    POST   /v1/agents/me/keys
    POST   /v1/aggregations
    GET    /v1/aggregations?title=&recipient=
    GET    /v1/aggregations/{AggregationId}
    DELETE /v1/aggregations/{AggregationId}
    GET    /v1/aggregations/{AggregationId}/committee/suggestions
    POST   /v1/aggregations/implied/committee
    GET    /v1/aggregations/{AggregationId}/committee
    POST   /v1/aggregations/participations
    POST   /v1/aggregations/participations/batch   (additive; JSON array
                              or one application/x-sda-binary frame)
    GET    /v1/aggregations/{AggregationId}/status
    POST   /v1/aggregations/implied/snapshot
    GET    /v1/aggregations/any/jobs
    GET    /v1/aggregations/implied/jobs/{ClerkingJobId}/chunks/{start}
                              (additive; one ciphertext range of a paged job)
    POST   /v1/aggregations/implied/jobs/{ClerkingJobId}/result
    GET    /v1/aggregations/{AggregationId}/snapshots/{SnapshotId}/result
    GET    /v1/aggregations/{AggregationId}/snapshots/{SnapshotId}/result/masks/{start}
    GET    /v1/aggregations/{AggregationId}/snapshots/{SnapshotId}/result/clerks/{start}
    GET    /v1/metrics        (additive; unauthenticated Prometheus text)
    GET    /v1/metrics.json   (additive; unauthenticated telemetry snapshot)
    GET    /v1/metrics/history (additive; time-series sampler window)
    GET    /v1/healthz        (additive; liveness — process is serving)
    GET    /v1/readyz         (additive; readiness — store reachable, else 503)

Wire negotiation (docs/protocol.md): the hot bulk routes — the
participation batch POST and the three chunk GETs — speak
``application/x-sda-binary`` (``rest/wire.py``) when the request asks
for it via ``Content-Type`` / ``Accept``; every other request, and every
legacy client, gets the byte-identical JSON bodies as before.

Observability: every request gets a fresh id, echoed as
``X-SDA-Request-Id`` and stamped on 404/500 log lines; an incoming
``X-SDA-Trace`` header is adopted for the handler (and echoed back), so
server-side spans — dispatch, service, store — carry the client's trace
id. Per-route request counts and latencies land in the telemetry
registry under a normalized route template (uuid segments become
``{id}``), with the wire-format split tracked by
``sda_rest_route_seconds{route,wire}`` and payload volume by
``sda_wire_bytes_total{route,wire,direction}``. See docs/observability.md.

Auth: HTTP Basic, username = AgentId, password = token recorded on first
``create_agent`` (trust-on-first-use, lib.rs:298-315). Missing resources are
404 with a ``Resource-not-found: true`` header so clients can distinguish
"no resource" from "no route" (lib.rs:338-343). Errors map to
401 / 403 / 400 / 500 (lib.rs:112-117).

Transport: an asyncio event-loop server speaking HTTP/1.1 with
keep-alive (replacing the stdlib ThreadingHTTPServer, which burned one
thread and usually one fresh connection per sporadic phone). Idle
connections cost a coroutine, not a thread; request *handling* runs on a
bounded executor pool (``SDA_REST_WORKERS``) because the service layer
is synchronous by design. Keep-alive accounting: idle connections are
reaped after ``SDA_REST_IDLE_TIMEOUT_S`` (default 60), and ``shutdown()``
force-closes every live connection so teardown never waits out a
persistent client. The public surface is ThreadingHTTPServer-shaped —
``server_address``, ``serve_forever()``, ``shutdown()``,
``server_close()`` — so ``sdad``, the bench riders, and the scenario
harness did not have to change.
"""

from __future__ import annotations

import asyncio
import base64
import contextlib
import json
import logging
import os
import re
import socket
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from http import HTTPStatus
from urllib.parse import unquote_plus

from .. import telemetry
from ..telemetry import timeseries
from ..utils import faults
from . import wire
from ..protocol import (
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    ClerkingJobId,
    ClerkingResult,
    Committee,
    EncryptionKeyId,
    InvalidCredentialsError,
    InvalidRequestError,
    Labelled,
    Participation,
    PermissionDeniedError,
    Profile,
    Snapshot,
    SnapshotId,
    signed_encryption_key_from_json,
)

log = logging.getLogger("sda.rest.server")

_UUID = r"[0-9a-fA-F-]{36}"

#: request-header section cap per request (stdlib http.server allows 100
#: headers; a byte cap is the same guard in keep-alive-friendly form)
_MAX_HEADER_BYTES = 64 * 1024


def _idle_timeout_s() -> float:
    """How long a keep-alive connection may sit idle between requests
    before the server reaps it (``SDA_REST_IDLE_TIMEOUT_S``, default 60).
    Bounds the connection table against phones that connect once and
    vanish; ``shutdown()`` does not wait for it — live connections are
    force-closed at teardown."""
    return max(0.05, float(os.environ.get("SDA_REST_IDLE_TIMEOUT_S", "60")))


def _slow_request_s() -> float:
    """Latency above which a request earns a warning log line and an
    ``sda_slow_requests_total`` tick (``SDA_SLOW_REQUEST_S``, default 1s;
    0 disables)."""
    try:
        return max(0.0, float(os.environ.get("SDA_SLOW_REQUEST_S", "1.0")))
    except ValueError:
        return 1.0


def _max_inflight() -> int:
    """Admission-control target for concurrently *executing* requests
    (``SDA_REST_MAX_INFLIGHT``). 0 (the default) disables admission
    control entirely — the frontend admits everything, exactly the
    pre-sharding behaviour."""
    try:
        return max(0, int(os.environ.get("SDA_REST_MAX_INFLIGHT", "0")))
    except ValueError:
        return 0


def _queue_high_water() -> int:
    """Extra admitted-but-queued requests allowed on top of
    ``SDA_REST_MAX_INFLIGHT`` before the frontend starts shedding
    (``SDA_REST_QUEUE_HIGH_WATER``, default 0 = shed as soon as the
    in-flight target is reached). Together the two knobs bound the
    executor backlog: admitted = executing + queued <= max_inflight +
    queue_high_water."""
    try:
        return max(0, int(os.environ.get("SDA_REST_QUEUE_HIGH_WATER", "0")))
    except ValueError:
        return 0


def _retry_after_hint_s() -> float:
    """Retry-After seconds a shed (429) response advertises
    (``SDA_REST_RETRY_AFTER_S``, default 0.25). The PR-6 client honors it
    as the backoff floor, so a saturated frontend paces its own retry
    storm without the client guessing."""
    try:
        return max(0.0, float(os.environ.get("SDA_REST_RETRY_AFTER_S", "0.25")))
    except ValueError:
        return 0.25


#: routes admission control never sheds: liveness/readiness probes and
#: the metrics planes must answer *especially* when the frontend is
#: saturated — a 429'd readyz would make the balancer drain the node
#: for being busy, and a 429'd scrape would blind the operator to the
#: very saturation being shed
_ADMISSION_EXEMPT = frozenset(
    {
        "/v1/ping",
        "/v1/healthz",
        "/v1/readyz",
        "/v1/metrics",
        "/v1/metrics.json",
        "/v1/metrics/history",
    }
)


def _worker_count() -> int:
    """Executor threads that run the (synchronous) service layer
    (``SDA_REST_WORKERS``). Unlike the old thread-per-connection model
    this bounds *active requests*, not open connections — thousands of
    idle keep-alive phones cost coroutines only."""
    env = os.environ.get("SDA_REST_WORKERS")
    if env:
        return max(1, int(env))
    return max(8, min(32, (os.cpu_count() or 1) * 4))


class _Response:
    """One fully-assembled HTTP response, plus transport directives:
    ``close`` ends the keep-alive stream after writing, ``truncate``
    (fault injection) declares the full Content-Length but delivers half,
    ``drop`` (fault injection) kills the connection with no bytes at all,
    ``reset`` (fault injection) delivers half the body then aborts the
    transport — the mid-response RST a flaky load balancer produces."""

    __slots__ = ("status", "headers", "body", "close", "truncate", "drop",
                 "reset")

    def __init__(self, status=500, headers=(), body=b"", close=False,
                 truncate=False, drop=False, reset=False):
        self.status = status
        self.headers = list(headers)
        self.body = body
        self.close = close
        self.truncate = truncate
        self.drop = drop
        self.reset = reset


class Router:
    """Transport-independent request handling: routing, auth, fault
    injection, wire negotiation, error mapping, and telemetry. One
    ``handle()`` call maps a fully-read request to a ``_Response`` —
    the asyncio transport below feeds it, and tests can drive it
    directly without a socket."""

    #: request body cap — an authed client must not be able to stream
    #: arbitrary gigabytes into server memory by claiming a huge
    #: Content-Length. Sized ~30x the largest legitimate participation
    #: we target (100K dims x 8 clerks ~= 15 MB of sealed JSON).
    MAX_BODY_BYTES = 512 * 1024 * 1024

    def __init__(self, service):
        self.service = service

    def handle(self, method: str, target: str, headers: dict,
               body: bytes = b"", body_error: str | None = None) -> _Response:
        """Handle one request. ``headers`` is lower-cased-key dict;
        ``body`` is the fully-read request body; ``body_error`` is set by
        the transport when the body could not be framed (bad or oversized
        Content-Length) — the request must then 400 and the connection
        must close, since the stream position is unknowable."""
        if method not in ("GET", "POST", "DELETE"):
            return _Response(501, [], b"Unsupported method", close=False)
        ctx = _RequestContext(self.service, method, target, headers, body, body_error)
        ctx.dispatch()
        return ctx.response


class _RequestContext:
    """Per-request state and the route table (one instance per request)."""

    def __init__(self, service, method, target, headers, body, body_error):
        self.service = service
        self.method = method
        path, _, query = target.partition("?")
        params = {}
        for pair in query.split("&"):
            if "=" in pair:
                k, _, v = pair.partition("=")
                params[k] = unquote_plus(v)
        self.path = path
        self.params = params
        self.headers = headers
        self.body = body
        self.body_error = body_error
        self.request_id = uuid.uuid4().hex[:16]
        self.trace_id = None
        self.status = None
        #: which wire format served this request ("json" unless a binary
        #: frame was read or written) — telemetry label only
        self.wire = "json"
        self._truncate_body = False
        self._reset_body = False
        self._close = False
        self.response = _Response()

    # -- plumbing -----------------------------------------------------------

    def _auth_token(self):
        header = (self.headers.get("authorization") or "").strip()
        if not header.startswith("Basic "):
            raise InvalidCredentialsError("Basic Authorization required")
        try:
            decoded = base64.b64decode(header[len("Basic ") :]).decode("utf-8")
            username, _, password = decoded.partition(":")
            return Labelled(AgentId(username), password)
        except (ValueError, UnicodeDecodeError):
            raise InvalidCredentialsError("Invalid Auth header")

    def _caller(self) -> Agent:
        return self.service.server.check_auth_token(self._auth_token())

    def _read_body(self) -> bytes:
        def refuse(msg):
            # the transport could not (or must not) frame the body, so
            # the unread/unframed bytes would desync the keep-alive
            # stream — drop the connection after responding
            self._close = True
            raise InvalidRequestError(msg)

        if self.body_error:
            refuse(self.body_error)
        if not self.body:
            refuse("Expected a body")
        return self.body

    def _read_json(self):
        try:
            return json.loads(self._read_body())
        except json.JSONDecodeError as e:
            raise InvalidRequestError(f"malformed JSON body: {e}")

    def _read(self, from_json):
        """Read + decode the request body; malformed payloads are 400s
        (the reference maps these to 500 via its catch-all; fixed here)."""
        payload = self._read_json()
        try:
            return from_json(payload)
        except InvalidRequestError:
            raise
        except Exception as e:
            raise InvalidRequestError(f"malformed body: {e}")

    def _send(self, status: int, body: bytes = b"", headers=()):
        self.status = status
        hs = list(headers)
        have_type = any(k.lower() == "content-type" for k, _ in hs)
        if body and not have_type:
            hs.append(("Content-Type", "application/json"))
        if self.request_id:
            hs.append(("X-SDA-Request-Id", self.request_id))
        if self.trace_id:
            hs.append((telemetry.TRACE_HEADER, self.trace_id))
        resp = _Response(status, hs, bytes(body), close=self._close)
        if self._truncate_body and len(body) > 1:
            # injected truncation: the declared length stands, only half
            # the bytes arrive, and the connection dies — the client's
            # content read sees a short body (urllib3 enforces
            # Content-Length) and surfaces a transport error
            resp.truncate = True
            resp.close = True
        if self._reset_body and len(body) > 1:
            # injected mid-body reset: half the bytes then a transport
            # abort — unlike truncate's orderly FIN, the client sees the
            # connection die under it (ConnectionResetError / aborted
            # read) while already consuming the response
            resp.reset = True
            resp.close = True
        self.response = resp

    def _send_json_option(self, obj):
        if obj is None:
            self._send(404, headers=[("Resource-not-found", "true")])
        else:
            payload = obj.to_json() if hasattr(obj, "to_json") else obj
            # compact separators: the reference emits serde_json::to_string
            # (no whitespace, server-http/src/lib.rs:338-343); replay-interop
            # asserts response bodies byte-identical to that shape
            self._send(
                200, json.dumps(payload, separators=(",", ":")).encode("utf-8")
            )

    def _send_wire(self, frame: bytes):
        """A negotiated binary response body (one x-sda-binary frame)."""
        self.wire = "binary"
        self._send(200, frame, headers=[("Content-Type", wire.CONTENT_TYPE)])

    def _wants_binary(self) -> bool:
        return wire.accepts_binary(self.headers.get("accept"))

    # -- dispatch -----------------------------------------------------------

    def dispatch(self):
        fault = faults.server_draw()
        if fault is not None:
            if fault.kind == "latency":
                time.sleep(fault.param)  # stall, then handle normally
            elif fault.kind == "drop":
                # connection death without an HTTP response; closing the
                # keep-alive stream keeps the next request in sync
                self.response = _Response(drop=True, close=True)
                return
            elif fault.kind == "e503":
                self._close = True
                self._send(
                    503,
                    b"SDA_FAULTS: injected transient failure",
                    headers=[("Retry-After", f"{fault.param:g}"),
                             ("Content-Type", "text/plain")],
                )
                return
            elif fault.kind == "truncate":
                self._truncate_body = True
            elif fault.kind == "reset":
                self._reset_body = True
        if telemetry.enabled():
            # adopt the client's trace id (or mint one) for this handler;
            # echoed back by _send alongside the request id
            self.trace_id = telemetry.sanitize_trace_id(
                self.headers.get(telemetry.TRACE_HEADER.lower())
            ) or telemetry.new_trace_id()
            telemetry.set_trace_id(self.trace_id)
        t0 = time.perf_counter()
        try:
            with telemetry.span("http.request", method=self.method) as span_record:
                handled = self._dispatch_inner()
                route = re.sub(_UUID, "{id}", self.path) if handled else "<unmatched>"
                if span_record is not None:
                    span_record["attrs"] = {
                        "method": self.method,
                        "route": route,
                        "status": self.status,
                        "request_id": self.request_id,
                    }
            # slow-request visibility is independent of the metrics plane:
            # the warning line fires even with telemetry disabled
            elapsed = time.perf_counter() - t0
            slow_after = _slow_request_s()
            if slow_after and elapsed >= slow_after:
                log.warning(
                    "slow request: %s %s took %.3fs (threshold %.3gs, "
                    "status %s, request %s, trace %s)",
                    self.method, self.path, elapsed, slow_after,
                    self.status, self.request_id, self.trace_id,
                )
                if telemetry.enabled():
                    telemetry.counter(
                        "sda_slow_requests_total",
                        "requests slower than SDA_SLOW_REQUEST_S by route template",
                        route=route,
                    ).inc()
            if telemetry.enabled():
                telemetry.histogram(
                    "sda_http_request_seconds",
                    "REST request latency by route template",
                    method=self.method,
                    route=route,
                ).observe(elapsed)
                telemetry.counter(
                    "sda_http_requests_total",
                    "REST requests served by route template and status",
                    method=self.method,
                    route=route,
                    status=str(self.status or 0),
                ).inc()
                # wire-plane split: route latency by negotiated format,
                # and payload volume in each direction (docs/observability.md)
                telemetry.histogram(
                    "sda_rest_route_seconds",
                    "REST route latency by route template and wire format",
                    route=route,
                    wire=self.wire,
                ).observe(elapsed)
                telemetry.counter(
                    "sda_wire_bytes_total",
                    "REST payload bytes by route, wire format, and direction",
                    route=route,
                    wire=self.wire,
                    direction="in",
                ).inc(len(self.body or b""))
                telemetry.counter(
                    "sda_wire_bytes_total",
                    "REST payload bytes by route, wire format, and direction",
                    route=route,
                    wire=self.wire,
                    direction="out",
                ).inc(len(self.response.body))
        finally:
            if self.trace_id is not None:
                telemetry.set_trace_id(None)

    def _dispatch_inner(self) -> bool:
        """Route + error mapping; returns whether the path was routed."""
        try:
            if self.body_error:
                # unframeable body (bad/oversized Content-Length): the
                # stream position is unknowable, so 400 and close no
                # matter which route was asked for
                self._close = True
                raise InvalidRequestError(self.body_error)
            handled = self._route()
            if not handled:
                log.error(
                    "route not found: %s %s (request %s)",
                    self.method, self.path, self.request_id,
                )
                self._send(404)
            return handled
        except InvalidCredentialsError as e:
            self._send(401, str(e).encode())
        except PermissionDeniedError as e:
            self._send(403, str(e).encode())
        except InvalidRequestError as e:
            self._send(400, str(e).encode())
        except Exception as e:  # ServerError and unexpected -> 500
            log.error(
                "%s %s -> 500: %s (request %s)",
                self.method, self.path, e, self.request_id,
            )
            self._send(500, str(e).encode())
        return True  # an error from a handler still means the path routed

    # -- routes -------------------------------------------------------------

    def _route(self) -> bool:
        method, path, params = self.method, self.path, self.params
        m = lambda pat: re.fullmatch(pat, path)
        svc = self.service

        if method == "GET" and path == "/v1/ping":
            self._send_json_option(svc.ping())
            return True

        if method == "GET" and path == "/v1/metrics":
            # additive observability route (not in the reference protocol):
            # Prometheus text exposition, unauthenticated like /v1/ping —
            # aggregate series only, no resource data (docs/observability.md)
            body = telemetry.prometheus_text().encode("utf-8")
            self._send(
                200,
                body,
                headers=[("Content-Type", telemetry.PROMETHEUS_CONTENT_TYPE)],
            )
            return True

        if method == "GET" and path == "/v1/metrics.json":
            # the same registry as JSON (plus recent spans), for tooling
            # that wants telemetry.snapshot() without Prometheus parsing
            body = json.dumps(
                telemetry.snapshot(), separators=(",", ":"), default=repr
            ).encode("utf-8")
            self._send(200, body)
            return True

        if method == "GET" and path == "/v1/metrics/history":
            # the time-series sampler's in-memory window (docs/api.md):
            # unauthenticated like /v1/metrics — windowed rates/quantiles
            # only, no resource data. ?n= caps the returned samples.
            n = None
            raw_n = params.get("n")
            if raw_n:
                try:
                    n = int(raw_n)
                except ValueError:
                    raise InvalidRequestError("n must be a positive integer")
                if n <= 0:
                    raise InvalidRequestError("n must be a positive integer")
            body = json.dumps(
                timeseries.history(n), separators=(",", ":")
            ).encode("utf-8")
            self._send(200, body)
            return True

        if method == "GET" and path == "/v1/healthz":
            # liveness: the process is up and serving requests
            self._send(200, b'{"status":"ok"}')
            return True

        if method == "GET" and path == "/v1/readyz":
            # readiness: the service can actually reach its store; a
            # wedged backend answers 503 so a balancer drains this node
            try:
                svc.ping()
                self._send(200, b'{"status":"ready"}')
            except Exception as e:
                self._send(
                    503,
                    json.dumps(
                        {"status": "unready", "error": str(e)},
                        separators=(",", ":"),
                    ).encode("utf-8"),
                )
            return True

        if method == "POST" and path == "/v1/agents/me":
            # TOFU: token recorded on successful agent creation (lib.rs:192-201)
            token = self._auth_token()
            agent = self._read(Agent.from_json)
            if agent.id != token.id:
                self._send(400, b"inconsistent agent ids")
                return True
            svc.server.register_auth_token(token)
            svc.create_agent(agent, agent)
            self._send(201)
            return True

        if method == "GET" and (match := m(rf"/v1/agents/({_UUID})")):
            self._send_json_option(svc.get_agent(self._caller(), AgentId(match.group(1))))
            return True

        if method == "GET" and (match := m(rf"/v1/agents/({_UUID})/profile")):
            self._send_json_option(svc.get_profile(self._caller(), AgentId(match.group(1))))
            return True

        if method == "POST" and path == "/v1/agents/me/profile":
            svc.upsert_profile(self._caller(), self._read(Profile.from_json))
            self._send(201)
            return True

        if method == "GET" and (match := m(rf"/v1/agents/any/keys/({_UUID})")):
            self._send_json_option(
                svc.get_encryption_key(self._caller(), EncryptionKeyId(match.group(1)))
            )
            return True

        if method == "POST" and path == "/v1/agents/me/keys":
            svc.create_encryption_key(
                self._caller(), self._read(signed_encryption_key_from_json)
            )
            self._send(201)
            return True

        if method == "POST" and path == "/v1/aggregations":
            svc.create_aggregation(self._caller(), self._read(Aggregation.from_json))
            self._send(201)
            return True

        if method == "GET" and path == "/v1/aggregations":
            recipient = params.get("recipient")
            ids = svc.list_aggregations(
                self._caller(),
                params.get("title"),
                AgentId(recipient) if recipient else None,
            )
            self._send_json_option([str(i) for i in ids])
            return True

        if method == "GET" and (match := m(rf"/v1/aggregations/({_UUID})/committee/suggestions")):
            out = svc.suggest_committee(self._caller(), AggregationId(match.group(1)))
            self._send_json_option([c.to_json() for c in out])
            return True

        if method == "POST" and path == "/v1/aggregations/implied/committee":
            svc.create_committee(self._caller(), self._read(Committee.from_json))
            self._send(201)
            return True

        if method == "GET" and (match := m(rf"/v1/aggregations/({_UUID})/committee")):
            self._send_json_option(
                svc.get_committee(self._caller(), AggregationId(match.group(1)))
            )
            return True

        if method == "POST" and path == "/v1/aggregations/participations":
            svc.create_participation(
                self._caller(), self._read(Participation.from_json)
            )
            self._send(201)
            return True

        if method == "POST" and path == "/v1/aggregations/participations/batch":
            # batched ingest (additive route, not in the reference): one
            # auth check, one response, one store transaction for the
            # whole batch. Two negotiated body formats: the legacy JSON
            # array, or one binary frame of varint-framed columns
            # (Content-Type: application/x-sda-binary, rest/wire.py) that
            # skips base64 + per-field JSON entirely. The service layer
            # accepts or rejects the array atomically either way.
            if wire.is_binary(self.headers.get("content-type")):
                self.wire = "binary"
                raw = self._read_body()
                try:
                    participations = wire.decode_participations(raw)
                except wire.WireError as e:
                    raise InvalidRequestError(f"malformed binary body: {e}")
            else:
                payload = self._read_json()
                if not isinstance(payload, list):
                    raise InvalidRequestError("expected a JSON array of participations")
                try:
                    participations = [Participation.from_json(p) for p in payload]
                except Exception as e:
                    raise InvalidRequestError(f"malformed body: {e}")
            svc.create_participations(self._caller(), participations)
            self._send(201)
            return True

        if method == "GET" and (match := m(rf"/v1/aggregations/({_UUID})/status")):
            self._send_json_option(
                svc.get_aggregation_status(self._caller(), AggregationId(match.group(1)))
            )
            return True

        if method == "GET" and (match := m(rf"/v1/aggregations/({_UUID})/tiers")):
            # per-node readiness of a tiered aggregation's derived tree
            # (recipient-only by ACL); 404 for flat aggregations
            self._send_json_option(
                svc.get_tier_status(self._caller(), AggregationId(match.group(1)))
            )
            return True

        if method == "POST" and path == "/v1/aggregations/implied/snapshot":
            svc.create_snapshot(self._caller(), self._read(Snapshot.from_json))
            self._send(201)
            return True

        if method == "GET" and path == "/v1/aggregations/any/jobs":
            caller = self._caller()
            self._send_json_option(svc.get_clerking_job(caller, caller.id))
            return True

        if method == "GET" and (
            match := m(rf"/v1/aggregations/implied/jobs/({_UUID})/chunks/(\d+)")
        ):
            # one ciphertext range of a paged clerking job; the clerk is
            # implied by auth (chunk reads answer 404 unless the caller
            # owns the job). Response: bare JSON array of encryptions, or
            # one binary encryption column when the request Accepts it.
            chunk = svc.get_clerking_job_chunk(
                self._caller(), ClerkingJobId(match.group(1)), int(match.group(2))
            )
            if chunk is not None and self._wants_binary():
                self._send_wire(wire.encode_encryptions(chunk))
            else:
                self._send_json_option(
                    None if chunk is None else [e.to_json() for e in chunk]
                )
            return True

        if method == "POST" and (match := m(rf"/v1/aggregations/implied/jobs/({_UUID})/result")):
            result = self._read(ClerkingResult.from_json)
            # the route is job-scoped: a body naming a DIFFERENT job
            # would silently file the result under the body's job while
            # every URL-derived check looked at the route's — reject the
            # mismatch instead of trusting whichever id the caller likes
            # (the reference marks the equivalent hole "FIXME no job
            # spoofing", server.rs:351; closed here)
            if str(result.job) != match.group(1):
                raise InvalidRequestError(
                    f"result body names job {result.job}, "
                    f"route names {match.group(1)}"
                )
            svc.create_clerking_result(self._caller(), result)
            self._send(201)
            return True

        if method == "POST" and (
            match := m(rf"/v1/aggregations/implied/jobs/({_UUID})/complete")
        ):
            # resultless retirement (tier share-promotion): the clerk's
            # output went upward as tagged participations, so the job is
            # marked done with nothing to file. Bodyless + idempotent.
            svc.complete_clerking_job(self._caller(), ClerkingJobId(match.group(1)))
            self._send(201)
            return True

        if method == "GET" and (
            match := m(rf"/v1/aggregations/({_UUID})/snapshots/({_UUID})/result/masks/(\d+)")
        ):
            # one recipient-mask-encryption range of a paged snapshot
            # result (recipient-only by ACL). Response: bare JSON array,
            # or one binary encryption column when negotiated.
            chunk = svc.get_snapshot_result_masks(
                self._caller(),
                AggregationId(match.group(1)),
                SnapshotId(match.group(2)),
                int(match.group(3)),
            )
            if chunk is not None and self._wants_binary():
                self._send_wire(wire.encode_encryptions(chunk))
            else:
                self._send_json_option(
                    None if chunk is None else [e.to_json() for e in chunk]
                )
            return True

        if method == "GET" and (
            match := m(rf"/v1/aggregations/({_UUID})/snapshots/({_UUID})/result/clerks/(\d+)")
        ):
            # one clerk-result range, in the canonical job-id order
            chunk = svc.get_snapshot_result_clerks(
                self._caller(),
                AggregationId(match.group(1)),
                SnapshotId(match.group(2)),
                int(match.group(3)),
            )
            if chunk is not None and self._wants_binary():
                self._send_wire(wire.encode_clerking_results(chunk))
            else:
                self._send_json_option(
                    None if chunk is None else [c.to_json() for c in chunk]
                )
            return True

        if method == "GET" and (
            match := m(rf"/v1/aggregations/({_UUID})/snapshots/({_UUID})/result")
        ):
            self._send_json_option(
                svc.get_snapshot_result(
                    self._caller(), AggregationId(match.group(1)), SnapshotId(match.group(2))
                )
            )
            return True

        if method == "GET" and (match := m(rf"/v1/aggregations/({_UUID})")):
            self._send_json_option(
                svc.get_aggregation(self._caller(), AggregationId(match.group(1)))
            )
            return True

        if method == "DELETE" and (match := m(rf"/v1/aggregations/({_UUID})")):
            svc.delete_aggregation(self._caller(), AggregationId(match.group(1)))
            self._send(200)
            return True

        return False


# -- transport --------------------------------------------------------------


class SdaRestServer:
    """Asyncio HTTP/1.1 keep-alive server around a ``Router``.

    Mirrors the stdlib server surface the rest of the codebase already
    uses: bind in the constructor (so ``server_address`` is final
    immediately, port 0 included), ``serve_forever()`` blocks the calling
    thread, ``shutdown()`` from any other thread stops it and returns
    once the loop has exited, ``server_close()`` releases the socket.
    """

    def __init__(self, addr: tuple, service):
        self.router = Router(service)
        self._sock = socket.create_server(addr, backlog=128)
        self.server_address = self._sock.getsockname()
        self._loop = None
        self._stop_event = None  # asyncio.Event, created on the loop
        self._started = threading.Event()
        self._stopped = threading.Event()
        self._shutdown_requested = threading.Event()
        self._executor = None
        self._writers = set()
        self._conn_tasks = set()
        #: requests admitted to the executor (executing + queued); only
        #: touched on the event loop, so a plain int is race-free
        self._inflight = 0

    # -- lifecycle ----------------------------------------------------------

    def serve_forever(self) -> None:
        if self._shutdown_requested.is_set():
            self._stopped.set()
            return
        self._executor = ThreadPoolExecutor(
            max_workers=_worker_count(), thread_name_prefix="sda-rest"
        )
        # the time-series sampler rides the server lifecycle (refcounted:
        # N in-process servers share one thread); SDA_TS=0 opts out
        sampler_held = os.environ.get("SDA_TS", "1") != "0"
        if sampler_held:
            timeseries.acquire()
        try:
            asyncio.run(self._main())
        finally:
            self._started.set()  # unblock shutdown() even on startup failure
            self._stopped.set()
            self._executor.shutdown(wait=False)
            if sampler_held:
                timeseries.release()

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        self._stop_event = asyncio.Event()
        # the default 64 KiB StreamReader buffer makes readexactly() of a
        # multi-hundred-KB binary batch wake up dozens of times; a 1 MiB
        # limit lets typical hot-route bodies arrive in a few reads
        server = await asyncio.start_server(
            self._handle_connection, sock=self._sock, limit=1 << 20
        )
        self._started.set()
        if self._shutdown_requested.is_set():
            self._stop_event.set()
        try:
            await self._stop_event.wait()
        finally:
            server.close()
            # keep-alive accounting: force-close every live connection so
            # teardown is prompt no matter how many phones are parked on
            # open sockets (they reconnect-and-retry by contract)
            for writer in list(self._writers):
                with contextlib.suppress(Exception):
                    writer.close()
            with contextlib.suppress(Exception):
                await server.wait_closed()
            pending = [t for t in self._conn_tasks if not t.done()]
            if pending:
                await asyncio.wait(pending, timeout=5)

    def shutdown(self) -> None:
        """Stop ``serve_forever`` (thread-safe) and wait for it to exit,
        closing live keep-alive connections rather than waiting them out."""
        self._shutdown_requested.set()
        if not self._started.wait(timeout=1):
            return  # never started serving; nothing to unwind
        if self._loop is not None and self._stop_event is not None:
            with contextlib.suppress(RuntimeError):
                self._loop.call_soon_threadsafe(self._stop_event.set)
        self._stopped.wait(timeout=10)

    def server_close(self) -> None:
        with contextlib.suppress(OSError):
            self._sock.close()
        if self._executor is not None:
            self._executor.shutdown(wait=False)

    # -- connection handling ------------------------------------------------

    async def _handle_connection(self, reader, writer):
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        self._writers.add(writer)
        try:
            await self._serve_connection(reader, writer)
        except (ConnectionError, asyncio.IncompleteReadError, asyncio.TimeoutError,
                TimeoutError, BrokenPipeError):
            pass  # peer went away mid-request; nothing to answer
        except Exception:
            log.exception("connection handler failed")
        finally:
            self._writers.discard(writer)
            self._conn_tasks.discard(task)
            with contextlib.suppress(Exception):
                writer.close()

    async def _serve_connection(self, reader, writer):
        idle = _idle_timeout_s()
        loop = asyncio.get_running_loop()
        while True:
            try:
                line = await asyncio.wait_for(reader.readline(), timeout=idle)
            except (asyncio.TimeoutError, TimeoutError):
                return  # idle keep-alive connection expired
            if not line:
                return  # clean EOF between requests
            if line in (b"\r\n", b"\n"):
                continue  # stray CRLF between requests (RFC 7230 §3.5)
            try:
                parts = line.decode("latin-1").strip().split()
                method, target = parts[0], parts[1]
                version = parts[2] if len(parts) > 2 else "HTTP/1.0"
            except (IndexError, UnicodeDecodeError):
                await self._write_response(
                    writer, _Response(400, [], b"malformed request line", close=True)
                )
                return

            headers = {}
            header_bytes = 0
            overflow = False
            while True:
                hline = await asyncio.wait_for(reader.readline(), timeout=idle)
                if hline in (b"\r\n", b"\n", b""):
                    break
                header_bytes += len(hline)
                if header_bytes > _MAX_HEADER_BYTES:
                    overflow = True
                    continue  # keep draining to the blank line, then reject
                key, sep, value = hline.decode("latin-1").partition(":")
                if sep:
                    headers[key.strip().lower()] = value.strip()
            if overflow:
                await self._write_response(
                    writer,
                    _Response(431, [], b"request header section too large", close=True),
                )
                return

            body = b""
            body_error = None
            raw_length = headers.get("content-length")
            if headers.get("transfer-encoding"):
                # no SDA client chunks uploads; without a Content-Length
                # the stream cannot be reframed, so reject and close
                body_error = "chunked request bodies are not supported"
            elif raw_length is not None:
                try:
                    length = int(raw_length)
                except ValueError:
                    length = None
                if length is None:
                    body_error = "invalid Content-Length"
                elif length > Router.MAX_BODY_BYTES:
                    body_error = (
                        f"body exceeds the {Router.MAX_BODY_BYTES}-byte limit"
                    )
                elif length > 0:
                    if headers.get("expect", "").lower() == "100-continue":
                        writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
                    body = await asyncio.wait_for(
                        reader.readexactly(length), timeout=idle
                    )

            response = await self._dispatch(loop, method, target, headers,
                                            body, body_error)
            if response.drop:
                return  # injected connection death: no bytes at all
            if version != "HTTP/1.1" or headers.get("connection", "").lower() == "close":
                response.close = True
            await self._write_response(writer, response)
            if response.close:
                return

    async def _dispatch(self, loop, method, target, headers, body, body_error):
        """Admission control, then the executor. The body is already
        fully read, so shedding answers without consuming a worker
        thread — and the keep-alive stream stays in sync either way."""
        max_inflight = _max_inflight()
        if max_inflight:
            path = target.partition("?")[0]
            if (
                self._inflight >= max_inflight + _queue_high_water()
                and path not in _ADMISSION_EXEMPT
            ):
                return self._shed(method, path)
        self._inflight += 1
        try:
            return await loop.run_in_executor(
                self._executor, self.router.handle,
                method, target, headers, body, body_error,
            )
        finally:
            self._inflight -= 1

    def _shed(self, method: str, path: str) -> _Response:
        route = re.sub(_UUID, "{id}", path)
        if telemetry.enabled():
            telemetry.counter(
                "sda_rest_shed_total",
                "requests shed with 429 by admission control, by route template",
                route=route,
            ).inc()
        log.debug(
            "shedding %s %s: %d in flight (max %d + queue %d)",
            method, path, self._inflight, _max_inflight(), _queue_high_water(),
        )
        return _Response(
            429,
            [
                ("Retry-After", f"{_retry_after_hint_s():g}"),
                ("Content-Type", "text/plain"),
            ],
            b"server saturated; retry later",
        )

    @staticmethod
    async def _write_response(writer, response: _Response):
        body = response.body
        try:
            reason = HTTPStatus(response.status).phrase
        except ValueError:
            reason = ""
        head = [f"HTTP/1.1 {response.status} {reason}".rstrip()]
        for k, v in response.headers:
            head.append(f"{k}: {v}")
        head.append(f"Content-Length: {len(body)}")
        if response.close:
            head.append("Connection: close")
        payload = ("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
        if (response.truncate or response.reset) and len(body) > 1:
            payload += body[: len(body) // 2]
            response.close = True
        else:
            payload += body
        writer.write(payload)
        await writer.drain()
        if response.reset and len(body) > 1:
            # slam the connection mid-body: abort discards the FIN
            # handshake, so the peer's read fails hard instead of seeing
            # a short-but-orderly body
            writer.transport.abort()


# -- module API (shape-compatible with the ThreadingHTTPServer era) ---------


def make_handler(service):
    """Compat shim from the ThreadingHTTPServer era: the 'handler' for a
    service is now its transport-independent ``Router``."""
    return Router(service)


def listen(addr: tuple, service) -> SdaRestServer:
    """Create (but do not start) an HTTP server bound to addr."""
    return SdaRestServer(addr, service)


def serve_forever(addr: tuple, service) -> None:
    httpd = listen(addr, service)
    log.info("sda REST server listening on %s:%s", *httpd.server_address[:2])
    httpd.serve_forever()


@contextlib.contextmanager
def serve_background(service, host: str = "127.0.0.1", port: int = 0):
    """Run the REST server on a daemon thread; yields the base URL."""
    httpd = listen((host, port), service)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://{httpd.server_address[0]}:{httpd.server_address[1]}"
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=5)


@contextlib.contextmanager
def serve_background_multi(service, frontends: int, host: str = "127.0.0.1"):
    """Run ``frontends`` REST servers over one (typically sharded)
    service, each on its own daemon thread and kernel-assigned port;
    yields the list of base URLs in frontend order — the order the
    client-side router's hash ring indexes into. In-process frontends
    share the GIL, so this is the *coordination* shape (routing,
    failover, admission control) rather than a CPU-scaling one; the
    bench rider spawns separate ``sdad`` processes for honest scaling."""
    httpds = [listen((host, 0), service) for _ in range(frontends)]
    threads = [
        threading.Thread(target=h.serve_forever, daemon=True) for h in httpds
    ]
    for t in threads:
        t.start()
    try:
        yield [f"http://{h.server_address[0]}:{h.server_address[1]}" for h in httpds]
    finally:
        for h in httpds:
            h.shutdown()
            h.server_close()
        for t in threads:
            t.join(timeout=5)
