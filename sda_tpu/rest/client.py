"""REST binding of the SDA service — the client proxy.

Re-implements the full ``SdaService`` interface over HTTP (reference:
client-http/src/client.rs:173-370), decorating every authenticated request
with Basic auth from the ``TokenStore``. Response protocol: 404 with the
``Resource-not-found`` header means ``None``; 401/403/400 map back to the
protocol error types.

Transport: one ``requests.Session`` with a 32-connection keep-alive pool,
reused across the client's lifetime — the server side holds these
connections open (HTTP/1.1 keep-alive), so a round is mostly zero-
handshake. The hot bulk routes — the participation batch POST and the
clerking-job / snapshot-result chunk GETs — default to the negotiated
``application/x-sda-binary`` frames from ``rest/wire.py``; GETs advertise
it via ``Accept`` and parse whatever Content-Type the server answers
with, so a JSON-only server downgrades transparently. ``SDA_WIRE=json``
forces the legacy JSON bodies on every route.

Multi-frontend routing: constructed with a *list* of base URLs, the
client becomes its own router over the sharded coordination plane —
aggregation-keyed requests hash their aggregation id on the same
``HashRing`` the server-side ``ShardedStore`` uses (``route_key``
threading below), so one aggregation's traffic converges on one frontend
without coordination; unkeyed requests pin to the first frontend. A
frontend that fails at the transport level is quarantined for
``SDA_REST_QUARANTINE_S`` and the request falls over to the next
frontend in the key's ring-preference order; 429 (admission shed) is
pacing, not failure — it backs off against the *same* frontend honoring
Retry-After, preserving routing locality under saturation.
"""

from __future__ import annotations

import json
import os
import random
import re
import time
from typing import Optional
from urllib.parse import quote, urlencode

import requests

from .. import telemetry
from ..utils import faults
from . import wire
from ..protocol import (
    Agent,
    Aggregation,
    AggregationId,
    AggregationStatus,
    ClerkCandidate,
    ClerkingJob,
    Committee,
    InvalidCredentialsError,
    InvalidRequestError,
    PermissionDeniedError,
    Pong,
    SdaError,
    SdaService,
    SnapshotResult,
    TierStatus,
    signed_encryption_key_from_json,
)


#: connect + per-socket-read timeout (requests semantics: each socket
#: operation gets this long, NOT the whole request — a server dripping
#: bytes can still hold a connection open longer). No protocol call
#: long-polls (get_clerking_job returns immediately), so a stalled
#: socket is a sick server: surface it as SdaError instead of blocking
#: indefinitely. The reference client (hyper 0.10 defaults) has no
#: timeout; this is a deliberate hardening. Pass ``timeout=None`` to
#: restore reference behavior.
DEFAULT_TIMEOUT_S = 300.0


def _retry_budget() -> int:
    """Extra attempts after the first, for retryable requests
    (``SDA_REST_RETRIES``, default 4). 0 disables retrying."""
    return max(0, int(os.environ.get("SDA_REST_RETRIES", "4")))


def _backoff_base_s() -> float:
    return float(os.environ.get("SDA_REST_BACKOFF_BASE_S", "0.05"))


def _backoff_cap_s() -> float:
    return float(os.environ.get("SDA_REST_BACKOFF_CAP_S", "2.0"))


def _retry_after_cap_s() -> float:
    """Upper bound honored for a server's Retry-After header — a sick or
    hostile server must not be able to park the client for an hour."""
    return float(os.environ.get("SDA_REST_RETRY_AFTER_CAP_S", "30.0"))


def _quarantine_s() -> float:
    """How long a frontend that failed at the transport level sits out of
    the candidate rotation (``SDA_REST_QUARANTINE_S``, default 3.0) — long
    enough that a dead frontend is not re-probed on every request, short
    enough that a restarted one rejoins promptly."""
    try:
        return max(0.0, float(os.environ.get("SDA_REST_QUARANTINE_S", "3.0")))
    except ValueError:
        return 3.0


#: transient server-side statuses worth retrying; 4xx are the caller's
#: fault and never retried — except 429, which is the admission-control
#: plane explicitly asking for a paced retry (Retry-After honored)
_RETRYABLE_STATUSES = (429, 500, 502, 503, 504)


def _retry_after_s(resp) -> float:
    """Parse a delta-seconds Retry-After (the only form the SDA server
    emits), clamped to the cap; HTTP-date forms fall back to 0."""
    raw = resp.headers.get("Retry-After")
    if not raw:
        return 0.0
    try:
        return min(max(0.0, float(raw)), _retry_after_cap_s())
    except ValueError:
        return 0.0


class SdaHttpClient(SdaService):
    def __init__(self, server_root, token_store,
                 timeout: float | None = DEFAULT_TIMEOUT_S):
        """``server_root`` is one base URL, or a list of them (one per
        frontend of a sharded deployment, in frontend order — the order
        the ring indexes into; every client must agree on it)."""
        roots = [server_root] if isinstance(server_root, str) else list(server_root)
        if not roots:
            raise ValueError("SdaHttpClient needs at least one server root")
        self.roots = [r.rstrip("/") for r in roots]
        self.server_root = self.roots[0]
        self._ring = None
        if len(self.roots) > 1:
            from ..utils.hashring import HashRing

            self._ring = HashRing(len(self.roots))
        #: root -> monotonic quarantine expiry (transport failures only)
        self._quarantined = {}
        #: per-client RNG for quarantine full jitter (injectable in tests)
        self._jitter = random.Random()
        self.token_store = token_store
        self.timeout = timeout
        self.session = requests.Session()
        # urllib3's default pool keeps 10 connections per host; the
        # concurrent committee runner plus K-deep chunk prefetch can
        # exceed that against one server, and overflow connections are
        # discarded after use (reconnect churn). Size the pool for the
        # prefetch window times a committee's worth of clerks.
        adapter = requests.adapters.HTTPAdapter(pool_connections=4, pool_maxsize=32)
        self.session.mount("http://", adapter)
        self.session.mount("https://", adapter)
        self.session.headers["User-Agent"] = "sda-tpu client"

    # -- plumbing -----------------------------------------------------------

    def _quarantine_expiry(self, now: float) -> float:
        """Quarantine deadline for a frontend that just failed: full
        jitter over (0, SDA_REST_QUARANTINE_S]. A fixed sit-out would
        re-synchronize every client that watched the same frontend die —
        they would all re-probe the recovering process on the same tick,
        exactly the thundering herd the quarantine exists to prevent.
        Uniform jitter spreads the re-probes over the whole window; a
        short draw just means one early scout, not a stampede, because
        the other clients' deadlines stay spread out."""
        q = _quarantine_s()
        return now + (self._jitter.uniform(0.0, q) if q > 0 else 0.0)

    def route_index(self, route_key) -> int:
        """Which frontend (index into ``self.roots``) ``route_key``'s
        traffic homes on. The client-side face of the pure placement
        function (``protocol.tiers.frontend_for``): both compute
        ``HashRing(len(roots)).shard_for(str(key))``, so a launcher can
        place a node's committee daemon on the exact frontend the
        client's keyed requests will use (failover aside)."""
        if self._ring is None:
            return 0
        return self._ring.shard_for(str(route_key))

    def _candidate_roots(self, route_key) -> list:
        """Frontend base URLs in try-order for this request: the key's
        ring-preference order (or plain frontend order when unkeyed),
        with currently-quarantined frontends demoted to the back — never
        dropped, so a fully-quarantined plane still tries everything."""
        if len(self.roots) == 1:
            return self.roots
        if route_key is not None and self._ring is not None:
            ordered = [self.roots[ix] for ix in self._ring.preference(str(route_key))]
        else:
            ordered = list(self.roots)
        now = time.monotonic()
        live = [r for r in ordered if self._quarantined.get(r, 0.0) <= now]
        dead = [r for r in ordered if self._quarantined.get(r, 0.0) > now]
        return live + dead

    def _request(self, method: str, path: str, caller=None, body=None, params=None,
                 idempotent: bool | None = None, raw_body: bytes | None = None,
                 content_type: str | None = None, accept: str | None = None,
                 raw: bool = False, route_key=None):
        """One protocol call, with transient-failure hardening.

        ``raw_body``/``content_type`` send a pre-encoded body (the binary
        wire frames) instead of a JSON one; ``accept`` advertises an
        alternate response format; ``raw=True`` returns the
        ``requests.Response`` on 2xx so the caller can negotiate on the
        response Content-Type (``None``/error mapping is unchanged).

        ``idempotent=None`` (the default) retries GET/DELETE only. POST
        call sites whose server handlers are idempotent by construction
        (create-if-identical stores, upsert semantics, deterministic
        snapshot no-op) pass ``idempotent=True`` to opt in — a replayed
        create either matches byte-for-byte (absorbed) or conflicts
        (fails like the first attempt would have). Retries cover
        transport failures and transient 5xx/429 only, with full-jitter
        exponential backoff floored by the server's Retry-After; other
        4xx are never retried.

        ``route_key`` (an aggregation id, usually) picks the frontend on
        a multi-root client; a transport failure quarantines the frontend
        and the retry falls over to the next one in ring order, while a
        retryable *status* stays on the same frontend (it answered).
        """
        query = "?" + urlencode(params) if params else ""
        candidates = self._candidate_roots(route_key)
        root_ix = 0
        url = candidates[0] + path + query
        auth = (str(caller.id), self.token_store.get()) if caller is not None else None
        data = None
        headers = {}
        if raw_body is not None:
            data = raw_body
            headers["Content-Type"] = content_type or wire.CONTENT_TYPE
        elif body is not None:
            payload = body.to_json() if hasattr(body, "to_json") else body
            # compact, like the reference client's serde_json bodies
            data = json.dumps(payload, separators=(",", ":")).encode("utf-8")
            headers["Content-Type"] = "application/json"
        if accept is not None:
            headers["Accept"] = accept
        trace_id = telemetry.current_trace_id() if telemetry.enabled() else None
        if trace_id:
            # propagate the caller's trace id so server-side spans join it
            headers[telemetry.TRACE_HEADER] = trace_id
        if idempotent is None:
            idempotent = method in ("GET", "DELETE")
        attempts = 1 + (_retry_budget() if idempotent else 0)
        backoff = None  # built lazily: the happy path never touches it
        floor = 0.0
        t0 = time.perf_counter()
        for attempt in range(attempts):
            if attempt:
                if backoff is None:
                    backoff = faults.Backoff(
                        base=_backoff_base_s(), cap=_backoff_cap_s()
                    )
                backoff.sleep(floor)
                floor = 0.0
            try:
                fault = faults.client_draw()
                if fault is not None:
                    if fault.kind == "latency":
                        time.sleep(fault.param)
                    elif fault.kind == "drop":
                        # synthetic connection death, routed through the
                        # same except arm a real one would take
                        raise requests.ConnectionError(
                            "SDA_FAULTS: injected client-side connection drop"
                        )
                    elif fault.kind == "reset":
                        # a client-side reset surfaces the same way a
                        # server RST mid-body does: a dead connection
                        raise requests.ConnectionError(
                            "SDA_FAULTS: injected client-side connection reset"
                        )
                resp = self.session.request(
                    method, url, data=data, auth=auth, headers=headers,
                    timeout=self.timeout,
                )
            except requests.RequestException as exc:
                if attempt + 1 < attempts:
                    if len(candidates) > 1:
                        # this frontend is unreachable: bench it and fall
                        # over to the next one in the key's ring order
                        self._quarantined[candidates[root_ix]] = (
                            self._quarantine_expiry(time.monotonic())
                        )
                        root_ix = (root_ix + 1) % len(candidates)
                        url = candidates[root_ix] + path + query
                    self._count_retry(method, path, "transport")
                    continue
                # timeouts/connection failures join the documented error
                # surface — daemon loops (e.g. `sda clerk`) catch SdaError
                # and keep polling instead of dying on a transient stall
                raise SdaError(f"HTTP/REST transport failure: {exc}") from exc
            if resp.status_code in _RETRYABLE_STATUSES and attempt + 1 < attempts:
                floor = _retry_after_s(resp)
                self._count_retry(method, path, f"status_{resp.status_code}")
                continue
            break
        if telemetry.enabled():
            telemetry.histogram(
                "sda_http_client_request_seconds",
                "client-observed REST request latency by route template",
                method=method,
                route=re.sub(r"[0-9a-fA-F-]{36}", "{id}", path),
            ).observe(time.perf_counter() - t0)
        return self._process(resp, raw=raw)

    @staticmethod
    def _count_retry(method: str, path: str, reason: str) -> None:
        if telemetry.enabled():
            telemetry.counter(
                "sda_rest_retries_total",
                "REST client retries by route template and reason",
                method=method,
                route=re.sub(r"[0-9a-fA-F-]{36}", "{id}", path),
                reason=reason,
            ).inc()

    @staticmethod
    def _process(resp, raw: bool = False):
        if resp.status_code in (200, 201):
            if raw:
                return resp if resp.content else None
            return resp.json() if resp.content else None
        if resp.status_code == 404:
            if "Resource-not-found" in resp.headers:
                return None
            raise SdaError("HTTP/REST route not found")
        if resp.status_code == 401:
            raise InvalidCredentialsError(resp.text)
        if resp.status_code == 403:
            raise PermissionDeniedError(resp.text)
        if resp.status_code == 400:
            raise InvalidRequestError(resp.text)
        raise SdaError(f"HTTP/REST error: {resp.status_code} {resp.text}")

    # -- base ---------------------------------------------------------------

    def ping(self) -> Pong:
        return Pong.from_json(self._request("GET", "/v1/ping"))

    # -- observability (additive, unauthenticated) ---------------------------

    def get_metrics_history(self, n: int | None = None) -> dict:
        """The server's time-series window (``GET /v1/metrics/history``):
        ``{running, interval_s, samples: [...]}``, newest-last."""
        params = {"n": int(n)} if n else None
        return self._request("GET", "/v1/metrics/history", params=params)

    def get_healthz(self) -> dict:
        return self._request("GET", "/v1/healthz")

    def get_readyz(self) -> tuple:
        """Readiness probe: ``(ready, body)`` — unlike the protocol calls
        a 503 here is an *answer* (drain me), not an error, so this reads
        the raw response instead of the retrying error-mapped path."""
        resp = self.session.get(
            self.server_root + "/v1/readyz", timeout=self.timeout
        )
        try:
            body = resp.json()
        except ValueError:
            body = {"status": "unready", "error": resp.text}
        return resp.status_code == 200, body

    # -- agents -------------------------------------------------------------

    # The POSTs below opt into retries (idempotent=True): every matching
    # server handler is idempotent by construction — stores create via
    # create-if-identical (byte-identical replays absorbed, conflicting
    # ones rejected exactly like a first attempt), profiles are upserts,
    # snapshot creation is a deterministic no-op on retry, and clerking
    # results are job-keyed overwrites of identical bodies — so a replay
    # after a lost response cannot double-apply.

    def create_agent(self, caller, agent) -> None:
        # TOFU token registration accepts an identical re-registration
        self._request("POST", "/v1/agents/me", caller, agent, idempotent=True)

    def get_agent(self, caller, agent_id):
        obj = self._request("GET", f"/v1/agents/{quote(str(agent_id))}", caller)
        return None if obj is None else Agent.from_json(obj)

    def upsert_profile(self, caller, profile) -> None:
        self._request("POST", "/v1/agents/me/profile", caller, profile,
                      idempotent=True)

    def get_profile(self, caller, owner_id):
        from ..protocol import Profile

        obj = self._request("GET", f"/v1/agents/{quote(str(owner_id))}/profile", caller)
        return None if obj is None else Profile.from_json(obj)

    def create_encryption_key(self, caller, signed_key) -> None:
        self._request("POST", "/v1/agents/me/keys", caller, signed_key,
                      idempotent=True)

    def get_encryption_key(self, caller, key_id):
        obj = self._request("GET", f"/v1/agents/any/keys/{quote(str(key_id))}", caller)
        return None if obj is None else signed_encryption_key_from_json(obj)

    # -- aggregations -------------------------------------------------------

    def list_aggregations(self, caller, filter=None, recipient=None):
        params = {}
        if filter is not None:
            params["title"] = filter
        if recipient is not None:
            params["recipient"] = str(recipient)
        obj = self._request("GET", "/v1/aggregations", caller, params=params)
        return [AggregationId(i) for i in obj]

    def get_aggregation(self, caller, aggregation_id):
        obj = self._request("GET", f"/v1/aggregations/{quote(str(aggregation_id))}", caller,
                            route_key=aggregation_id)
        return None if obj is None else Aggregation.from_json(obj)

    def get_committee(self, caller, aggregation_id):
        obj = self._request(
            "GET", f"/v1/aggregations/{quote(str(aggregation_id))}/committee", caller,
            route_key=aggregation_id,
        )
        return None if obj is None else Committee.from_json(obj)

    # -- recipient ----------------------------------------------------------

    def create_aggregation(self, caller, aggregation) -> None:
        self._request("POST", "/v1/aggregations", caller, aggregation,
                      idempotent=True, route_key=aggregation.id)

    def delete_aggregation(self, caller, aggregation_id) -> None:
        self._request("DELETE", f"/v1/aggregations/{quote(str(aggregation_id))}", caller,
                      route_key=aggregation_id)

    def suggest_committee(self, caller, aggregation_id):
        obj = self._request(
            "GET",
            f"/v1/aggregations/{quote(str(aggregation_id))}/committee/suggestions",
            caller,
            route_key=aggregation_id,
        )
        return [ClerkCandidate.from_json(c) for c in obj]

    def create_committee(self, caller, committee) -> None:
        self._request("POST", "/v1/aggregations/implied/committee", caller,
                      committee, idempotent=True, route_key=committee.aggregation)

    def get_aggregation_status(self, caller, aggregation_id):
        obj = self._request(
            "GET", f"/v1/aggregations/{quote(str(aggregation_id))}/status", caller,
            route_key=aggregation_id,
        )
        return None if obj is None else AggregationStatus.from_json(obj)

    def get_tier_status(self, caller, aggregation_id):
        obj = self._request(
            "GET", f"/v1/aggregations/{quote(str(aggregation_id))}/tiers", caller,
            route_key=aggregation_id,
        )
        return None if obj is None else TierStatus.from_json(obj)

    def create_snapshot(self, caller, snapshot) -> None:
        self._request("POST", "/v1/aggregations/implied/snapshot", caller,
                      snapshot, idempotent=True, route_key=snapshot.aggregation)

    def get_snapshot_result(self, caller, aggregation_id, snapshot_id):
        obj = self._request(
            "GET",
            f"/v1/aggregations/{quote(str(aggregation_id))}/snapshots/{quote(str(snapshot_id))}/result",
            caller,
            route_key=aggregation_id,
        )
        return None if obj is None else SnapshotResult.from_json(obj)

    def _get_negotiated(self, path, caller, decode_binary, decode_json,
                        route_key=None):
        """A chunk GET that prefers the binary wire format: advertise it
        via Accept (unless ``SDA_WIRE=json``), then parse by the response
        Content-Type — a JSON-only server downgrades transparently."""
        if wire.mode() != "binary":
            obj = self._request("GET", path, caller, route_key=route_key)
            return None if obj is None else decode_json(obj)
        resp = self._request("GET", path, caller, accept=wire.CONTENT_TYPE,
                             raw=True, route_key=route_key)
        if resp is None:
            return None
        if wire.is_binary(resp.headers.get("Content-Type")):
            try:
                return decode_binary(resp.content)
            except wire.WireError as e:
                # a fully-delivered but undecodable frame is a server bug,
                # not a transport blip — surface it, never half-decode
                raise SdaError(f"undecodable binary response: {e}") from e
        return decode_json(resp.json())

    def get_snapshot_result_masks(self, caller, aggregation_id, snapshot_id, start):
        from ..protocol import Encryption

        return self._get_negotiated(
            f"/v1/aggregations/{quote(str(aggregation_id))}/snapshots/"
            f"{quote(str(snapshot_id))}/result/masks/{int(start)}",
            caller,
            wire.decode_encryptions,
            lambda obj: [Encryption.from_json(e) for e in obj],
            route_key=aggregation_id,
        )

    def get_snapshot_result_clerks(self, caller, aggregation_id, snapshot_id, start):
        from ..protocol import ClerkingResult

        return self._get_negotiated(
            f"/v1/aggregations/{quote(str(aggregation_id))}/snapshots/"
            f"{quote(str(snapshot_id))}/result/clerks/{int(start)}",
            caller,
            wire.decode_clerking_results,
            lambda obj: [ClerkingResult.from_json(c) for c in obj],
            route_key=aggregation_id,
        )

    # -- participation ------------------------------------------------------

    def create_participation(self, caller, participation) -> None:
        self._request("POST", "/v1/aggregations/participations", caller,
                      participation, idempotent=True,
                      route_key=participation.aggregation)

    def create_participations(self, caller, participations) -> None:
        """Batched submit: the whole array in one request on the batch
        route — one auth check, one response, one store transaction —
        over the session's persistent keep-alive connection. Overrides
        the interface's sequential (non-atomic) default. The body is one
        binary wire frame by default (columns of raw sealed boxes, no
        base64, no per-field JSON); ``SDA_WIRE=json`` restores the legacy
        JSON array for old servers. Tier-promotion rows (tier_reshare
        tagged — client/clerk.py, client/tiers.py) always go as the JSON
        body: the binary frame has no tag column, and tagged batches are
        a handful of rows per committee, never the ingest hot path."""
        tagged = any(p.tier_reshare is not None for p in participations)
        if wire.mode() == "binary" and not tagged:
            self._request(
                "POST",
                "/v1/aggregations/participations/batch",
                caller,
                raw_body=wire.encode_participations(participations),
                idempotent=True,
                route_key=participations[0].aggregation if participations else None,
            )
        else:
            self._request(
                "POST",
                "/v1/aggregations/participations/batch",
                caller,
                [p.to_json() for p in participations],
                idempotent=True,
                route_key=participations[0].aggregation if participations else None,
            )

    # -- clerking -----------------------------------------------------------

    def get_clerking_job(self, caller, clerk_id):
        # keyed by the polling clerk: spreads committee polling across
        # frontends; any frontend can answer (server-side polls fan out)
        obj = self._request("GET", "/v1/aggregations/any/jobs", caller,
                            route_key=clerk_id)
        return None if obj is None else ClerkingJob.from_json(obj)

    def get_clerking_job_chunk(self, caller, job_id, start):
        from ..protocol import Encryption

        return self._get_negotiated(
            f"/v1/aggregations/implied/jobs/{quote(str(job_id))}/chunks/{int(start)}",
            caller,
            wire.decode_encryptions,
            lambda obj: [Encryption.from_json(e) for e in obj],
            route_key=job_id,
        )

    def create_clerking_result(self, caller, result) -> None:
        self._request(
            "POST",
            f"/v1/aggregations/implied/jobs/{quote(str(result.job))}/result",
            caller,
            result,
            idempotent=True,
            route_key=result.job,
        )

    def complete_clerking_job(self, caller, job_id) -> None:
        self._request(
            "POST",
            f"/v1/aggregations/implied/jobs/{quote(str(job_id))}/complete",
            caller,
            idempotent=True,
            route_key=job_id,
        )
