"""REST binding of the SDA service — the client proxy.

Re-implements the full ``SdaService`` interface over HTTP (reference:
client-http/src/client.rs:173-370), decorating every authenticated request
with Basic auth from the ``TokenStore``. Response protocol: 404 with the
``Resource-not-found`` header means ``None``; 401/403/400 map back to the
protocol error types.
"""

from __future__ import annotations

import json
import re
import time
from typing import Optional
from urllib.parse import quote, urlencode

import requests

from .. import telemetry
from ..protocol import (
    Agent,
    Aggregation,
    AggregationId,
    AggregationStatus,
    ClerkCandidate,
    ClerkingJob,
    Committee,
    InvalidCredentialsError,
    InvalidRequestError,
    PermissionDeniedError,
    Pong,
    SdaError,
    SdaService,
    SnapshotResult,
    signed_encryption_key_from_json,
)


#: connect + per-socket-read timeout (requests semantics: each socket
#: operation gets this long, NOT the whole request — a server dripping
#: bytes can still hold a connection open longer). No protocol call
#: long-polls (get_clerking_job returns immediately), so a stalled
#: socket is a sick server: surface it as SdaError instead of blocking
#: indefinitely. The reference client (hyper 0.10 defaults) has no
#: timeout; this is a deliberate hardening. Pass ``timeout=None`` to
#: restore reference behavior.
DEFAULT_TIMEOUT_S = 300.0


class SdaHttpClient(SdaService):
    def __init__(self, server_root: str, token_store,
                 timeout: float | None = DEFAULT_TIMEOUT_S):
        self.server_root = server_root.rstrip("/")
        self.token_store = token_store
        self.timeout = timeout
        self.session = requests.Session()
        # urllib3's default pool keeps 10 connections per host; the
        # concurrent committee runner plus K-deep chunk prefetch can
        # exceed that against one server, and overflow connections are
        # discarded after use (reconnect churn). Size the pool for the
        # prefetch window times a committee's worth of clerks.
        adapter = requests.adapters.HTTPAdapter(pool_connections=4, pool_maxsize=32)
        self.session.mount("http://", adapter)
        self.session.mount("https://", adapter)
        self.session.headers["User-Agent"] = "sda-tpu client"

    # -- plumbing -----------------------------------------------------------

    def _request(self, method: str, path: str, caller=None, body=None, params=None):
        url = self.server_root + path
        if params:
            url += "?" + urlencode(params)
        auth = (str(caller.id), self.token_store.get()) if caller is not None else None
        data = None
        headers = {}
        if body is not None:
            payload = body.to_json() if hasattr(body, "to_json") else body
            # compact, like the reference client's serde_json bodies
            data = json.dumps(payload, separators=(",", ":")).encode("utf-8")
            headers["Content-Type"] = "application/json"
        trace_id = telemetry.current_trace_id() if telemetry.enabled() else None
        if trace_id:
            # propagate the caller's trace id so server-side spans join it
            headers[telemetry.TRACE_HEADER] = trace_id
        t0 = time.perf_counter()
        try:
            resp = self.session.request(
                method, url, data=data, auth=auth, headers=headers,
                timeout=self.timeout,
            )
        except requests.RequestException as exc:
            # timeouts/connection failures join the documented error
            # surface — daemon loops (e.g. `sda clerk`) catch SdaError
            # and keep polling instead of dying on a transient stall
            raise SdaError(f"HTTP/REST transport failure: {exc}") from exc
        if telemetry.enabled():
            telemetry.histogram(
                "sda_http_client_request_seconds",
                "client-observed REST request latency by route template",
                method=method,
                route=re.sub(r"[0-9a-fA-F-]{36}", "{id}", path),
            ).observe(time.perf_counter() - t0)
        return self._process(resp)

    @staticmethod
    def _process(resp) -> Optional[dict]:
        if resp.status_code in (200, 201):
            return resp.json() if resp.content else None
        if resp.status_code == 404:
            if "Resource-not-found" in resp.headers:
                return None
            raise SdaError("HTTP/REST route not found")
        if resp.status_code == 401:
            raise InvalidCredentialsError(resp.text)
        if resp.status_code == 403:
            raise PermissionDeniedError(resp.text)
        if resp.status_code == 400:
            raise InvalidRequestError(resp.text)
        raise SdaError(f"HTTP/REST error: {resp.status_code} {resp.text}")

    # -- base ---------------------------------------------------------------

    def ping(self) -> Pong:
        return Pong.from_json(self._request("GET", "/v1/ping"))

    # -- agents -------------------------------------------------------------

    def create_agent(self, caller, agent) -> None:
        self._request("POST", "/v1/agents/me", caller, agent)

    def get_agent(self, caller, agent_id):
        obj = self._request("GET", f"/v1/agents/{quote(str(agent_id))}", caller)
        return None if obj is None else Agent.from_json(obj)

    def upsert_profile(self, caller, profile) -> None:
        self._request("POST", "/v1/agents/me/profile", caller, profile)

    def get_profile(self, caller, owner_id):
        from ..protocol import Profile

        obj = self._request("GET", f"/v1/agents/{quote(str(owner_id))}/profile", caller)
        return None if obj is None else Profile.from_json(obj)

    def create_encryption_key(self, caller, signed_key) -> None:
        self._request("POST", "/v1/agents/me/keys", caller, signed_key)

    def get_encryption_key(self, caller, key_id):
        obj = self._request("GET", f"/v1/agents/any/keys/{quote(str(key_id))}", caller)
        return None if obj is None else signed_encryption_key_from_json(obj)

    # -- aggregations -------------------------------------------------------

    def list_aggregations(self, caller, filter=None, recipient=None):
        params = {}
        if filter is not None:
            params["title"] = filter
        if recipient is not None:
            params["recipient"] = str(recipient)
        obj = self._request("GET", "/v1/aggregations", caller, params=params)
        return [AggregationId(i) for i in obj]

    def get_aggregation(self, caller, aggregation_id):
        obj = self._request("GET", f"/v1/aggregations/{quote(str(aggregation_id))}", caller)
        return None if obj is None else Aggregation.from_json(obj)

    def get_committee(self, caller, aggregation_id):
        obj = self._request(
            "GET", f"/v1/aggregations/{quote(str(aggregation_id))}/committee", caller
        )
        return None if obj is None else Committee.from_json(obj)

    # -- recipient ----------------------------------------------------------

    def create_aggregation(self, caller, aggregation) -> None:
        self._request("POST", "/v1/aggregations", caller, aggregation)

    def delete_aggregation(self, caller, aggregation_id) -> None:
        self._request("DELETE", f"/v1/aggregations/{quote(str(aggregation_id))}", caller)

    def suggest_committee(self, caller, aggregation_id):
        obj = self._request(
            "GET",
            f"/v1/aggregations/{quote(str(aggregation_id))}/committee/suggestions",
            caller,
        )
        return [ClerkCandidate.from_json(c) for c in obj]

    def create_committee(self, caller, committee) -> None:
        self._request("POST", "/v1/aggregations/implied/committee", caller, committee)

    def get_aggregation_status(self, caller, aggregation_id):
        obj = self._request(
            "GET", f"/v1/aggregations/{quote(str(aggregation_id))}/status", caller
        )
        return None if obj is None else AggregationStatus.from_json(obj)

    def create_snapshot(self, caller, snapshot) -> None:
        self._request("POST", "/v1/aggregations/implied/snapshot", caller, snapshot)

    def get_snapshot_result(self, caller, aggregation_id, snapshot_id):
        obj = self._request(
            "GET",
            f"/v1/aggregations/{quote(str(aggregation_id))}/snapshots/{quote(str(snapshot_id))}/result",
            caller,
        )
        return None if obj is None else SnapshotResult.from_json(obj)

    def get_snapshot_result_masks(self, caller, aggregation_id, snapshot_id, start):
        from ..protocol import Encryption

        obj = self._request(
            "GET",
            f"/v1/aggregations/{quote(str(aggregation_id))}/snapshots/"
            f"{quote(str(snapshot_id))}/result/masks/{int(start)}",
            caller,
        )
        return None if obj is None else [Encryption.from_json(e) for e in obj]

    def get_snapshot_result_clerks(self, caller, aggregation_id, snapshot_id, start):
        from ..protocol import ClerkingResult

        obj = self._request(
            "GET",
            f"/v1/aggregations/{quote(str(aggregation_id))}/snapshots/"
            f"{quote(str(snapshot_id))}/result/clerks/{int(start)}",
            caller,
        )
        return None if obj is None else [ClerkingResult.from_json(c) for c in obj]

    # -- participation ------------------------------------------------------

    def create_participation(self, caller, participation) -> None:
        self._request("POST", "/v1/aggregations/participations", caller, participation)

    def create_participations(self, caller, participations) -> None:
        """Batched submit: the whole array in one request on the batch
        route — one auth check, one response, one store transaction —
        over the session's persistent keep-alive connection. Overrides
        the interface's sequential (non-atomic) default."""
        self._request(
            "POST",
            "/v1/aggregations/participations/batch",
            caller,
            [p.to_json() for p in participations],
        )

    # -- clerking -----------------------------------------------------------

    def get_clerking_job(self, caller, clerk_id):
        obj = self._request("GET", "/v1/aggregations/any/jobs", caller)
        return None if obj is None else ClerkingJob.from_json(obj)

    def get_clerking_job_chunk(self, caller, job_id, start):
        from ..protocol import Encryption

        obj = self._request(
            "GET",
            f"/v1/aggregations/implied/jobs/{quote(str(job_id))}/chunks/{int(start)}",
            caller,
        )
        return None if obj is None else [Encryption.from_json(e) for e in obj]

    def create_clerking_result(self, caller, result) -> None:
        self._request(
            "POST",
            f"/v1/aggregations/implied/jobs/{quote(str(result.job))}/result",
            caller,
            result,
        )
