/* Fixed-base comb scalar multiplication over the twisted-Edwards form of
 * Curve25519, used to amortize X25519 work across a batch of sealed boxes.
 *
 * Why this exists: crypto_box_seal spends ~95% of its time in two variable-
 * time-bounded Montgomery-ladder scalarmults (ephemeral keygen + shared
 * secret).  The ladder cannot share work between messages.  When a batch
 * seals many messages to the SAME recipient key, both scalarmults become
 * fixed-base: the base point G is fixed forever, and the recipient point is
 * fixed for the whole batch.  A radix-16 signed comb table (64 digit rows x
 * 8 odd multiples) turns each 255-bit scalarmult into 64 mixed additions
 * with no doublings, ~3-4x less field work than the ladder.
 *
 * Wire compatibility: outputs are X25519 u-coordinates, bit-identical to
 * crypto_scalarmult()/crypto_scalarmult_base() for the same inputs (the
 * Edwards<->Montgomery birational map preserves u regardless of the x-sign
 * chosen when lifting).  The sealing code composes them with libsodium's
 * own HSalsa20/XSalsa20-Poly1305, so sealed boxes remain openable by
 * crypto_box_seal_open.
 *
 * Constant-time posture: table lookups scan all entries with arithmetic
 * masks (no secret-indexed loads); digit recoding and conditional negation
 * are branch-free.  Field ops are the standard 51-bit-limb ref10 shapes.
 *
 * Every function here is checked against libsodium on random inputs by
 * tests/test_native.py (and by the COMB_TEST_MAIN harness used during
 * development).
 */

#include <stdint.h>
#include <string.h>

typedef struct { uint64_t v[5]; } fe; /* GF(2^255-19), 51-bit limbs */

#define MASK51 ((1ULL << 51) - 1)

static const fe fe_d2 = {{0x69b9426b2f159ULL, 0x35050762add7aULL, 0x3cf44c0038052ULL,
                          0x6738cc7407977ULL, 0x2406d9dc56dffULL}};
static const fe fe_d = {{0x34dca135978a3ULL, 0x1a8283b156ebdULL, 0x5e7a26001c029ULL,
                         0x739c663a03cbbULL, 0x52036cee2b6ffULL}};
static const fe fe_sqrtm1 = {{0x61b274a0ea0b0ULL, 0x0d5a5fc8f189dULL, 0x7ef5e9cbd0c60ULL,
                              0x78595a6804c9eULL, 0x2b8324804fc1dULL}};
static const fe fe_basex = {{0x62d608f25d51aULL, 0x412a4b4f6592aULL, 0x75b7171a4b31dULL,
                             0x1ff60527118feULL, 0x216936d3cd6e5ULL}};
static const fe fe_basey = {{0x6666666666658ULL, 0x4ccccccccccccULL, 0x1999999999999ULL,
                             0x3333333333333ULL, 0x6666666666666ULL}};

static void fe_0(fe *h) { memset(h, 0, sizeof *h); }
static void fe_1(fe *h) { fe_0(h); h->v[0] = 1; }

static void fe_add(fe *h, const fe *f, const fe *g)
{
    int i;
    for (i = 0; i < 5; i++) h->v[i] = f->v[i] + g->v[i];
}

/* h = f - g + 4p: the 4p bias keeps limbs positive even when g holds
 * uncarried sums (limbs up to ~2^53), which the add formulas produce */
static void fe_sub(fe *h, const fe *f, const fe *g)
{
    h->v[0] = f->v[0] + 0x1FFFFFFFFFFFB4ULL - g->v[0];
    h->v[1] = f->v[1] + 0x1FFFFFFFFFFFFCULL - g->v[1];
    h->v[2] = f->v[2] + 0x1FFFFFFFFFFFFCULL - g->v[2];
    h->v[3] = f->v[3] + 0x1FFFFFFFFFFFFCULL - g->v[3];
    h->v[4] = f->v[4] + 0x1FFFFFFFFFFFFCULL - g->v[4];
}

static void fe_neg(fe *h, const fe *f)
{
    fe zero; fe_0(&zero);
    fe_sub(h, &zero, f);
}

static void fe_cmov(fe *f, const fe *g, uint64_t mask)
{
    int i;
    for (i = 0; i < 5; i++) f->v[i] = (f->v[i] & ~mask) | (g->v[i] & mask);
}

/* branch-free swap of f and g when swap == 1 (must be 0 or 1) */
static void fe_cswap(fe *f, fe *g, uint64_t swap)
{
    uint64_t mask = (uint64_t)0 - swap;
    int i;
    for (i = 0; i < 5; i++) {
        uint64_t x = (f->v[i] ^ g->v[i]) & mask;
        f->v[i] ^= x;
        g->v[i] ^= x;
    }
}

/* h = 121666 * f, carried.  Inputs may carry the 4p-biased magnitudes the
 * sub/add formulas produce (limbs < 2^54): 2^54 * 121666 < 2^71 per limb
 * fits __uint128_t with room to spare. */
static void fe_mul121666(fe *h, const fe *f)
{
    __uint128_t r;
    uint64_t c, h0, h1, h2, h3, h4;
    r = (__uint128_t)f->v[0] * 121666;     h0 = (uint64_t)r & MASK51; c = (uint64_t)(r >> 51);
    r = (__uint128_t)f->v[1] * 121666 + c; h1 = (uint64_t)r & MASK51; c = (uint64_t)(r >> 51);
    r = (__uint128_t)f->v[2] * 121666 + c; h2 = (uint64_t)r & MASK51; c = (uint64_t)(r >> 51);
    r = (__uint128_t)f->v[3] * 121666 + c; h3 = (uint64_t)r & MASK51; c = (uint64_t)(r >> 51);
    r = (__uint128_t)f->v[4] * 121666 + c; h4 = (uint64_t)r & MASK51; c = (uint64_t)(r >> 51);
    h0 += 19 * c;
    h->v[0] = h0; h->v[1] = h1; h->v[2] = h2; h->v[3] = h3; h->v[4] = h4;
}

static void fe_mul(fe *h, const fe *f, const fe *g)
{
    uint64_t f0 = f->v[0], f1 = f->v[1], f2 = f->v[2], f3 = f->v[3], f4 = f->v[4];
    uint64_t g0 = g->v[0], g1 = g->v[1], g2 = g->v[2], g3 = g->v[3], g4 = g->v[4];
    uint64_t g1_19 = 19 * g1, g2_19 = 19 * g2, g3_19 = 19 * g3, g4_19 = 19 * g4;
    __uint128_t r0, r1, r2, r3, r4;
    uint64_t c, h0, h1, h2, h3, h4;

    r0 = (__uint128_t)f0 * g0 + (__uint128_t)f1 * g4_19 + (__uint128_t)f2 * g3_19
       + (__uint128_t)f3 * g2_19 + (__uint128_t)f4 * g1_19;
    r1 = (__uint128_t)f0 * g1 + (__uint128_t)f1 * g0 + (__uint128_t)f2 * g4_19
       + (__uint128_t)f3 * g3_19 + (__uint128_t)f4 * g2_19;
    r2 = (__uint128_t)f0 * g2 + (__uint128_t)f1 * g1 + (__uint128_t)f2 * g0
       + (__uint128_t)f3 * g4_19 + (__uint128_t)f4 * g3_19;
    r3 = (__uint128_t)f0 * g3 + (__uint128_t)f1 * g2 + (__uint128_t)f2 * g1
       + (__uint128_t)f3 * g0 + (__uint128_t)f4 * g4_19;
    r4 = (__uint128_t)f0 * g4 + (__uint128_t)f1 * g3 + (__uint128_t)f2 * g2
       + (__uint128_t)f3 * g1 + (__uint128_t)f4 * g0;

    c = (uint64_t)(r0 >> 51); h0 = (uint64_t)r0 & MASK51; r1 += c;
    c = (uint64_t)(r1 >> 51); h1 = (uint64_t)r1 & MASK51; r2 += c;
    c = (uint64_t)(r2 >> 51); h2 = (uint64_t)r2 & MASK51; r3 += c;
    c = (uint64_t)(r3 >> 51); h3 = (uint64_t)r3 & MASK51; r4 += c;
    c = (uint64_t)(r4 >> 51); h4 = (uint64_t)r4 & MASK51;
    h0 += 19 * c;
    c = h0 >> 51; h0 &= MASK51; h1 += c;
    c = h1 >> 51; h1 &= MASK51; h2 += c;
    h->v[0] = h0; h->v[1] = h1; h->v[2] = h2; h->v[3] = h3; h->v[4] = h4;
}

static void fe_sq(fe *h, const fe *f)
{
    fe_mul(h, f, f);
}

static void fe_sqn(fe *h, const fe *f, int n)
{
    int i;
    fe_sq(h, f);
    for (i = 1; i < n; i++) fe_sq(h, h);
}

/* z^(2^250 - 1), the shared prefix of the inversion and sqrt chains */
static void fe_pow250m1(fe *out, fe *t0_out, const fe *z)
{
    fe t0, t1, t2, t3;
    fe_sq(&t0, z);                      /* 2 */
    fe_sqn(&t1, &t0, 2);                /* 8 */
    fe_mul(&t1, z, &t1);                /* 9 */
    fe_mul(&t0, &t0, &t1);              /* 11 */
    fe_sq(&t2, &t0);                    /* 22 */
    fe_mul(&t1, &t1, &t2);              /* 2^5-1 */
    fe_sqn(&t2, &t1, 5);  fe_mul(&t1, &t2, &t1);   /* 2^10-1 */
    fe_sqn(&t2, &t1, 10); fe_mul(&t2, &t2, &t1);   /* 2^20-1 */
    fe_sqn(&t3, &t2, 20); fe_mul(&t2, &t3, &t2);   /* 2^40-1 */
    fe_sqn(&t2, &t2, 10); fe_mul(&t1, &t2, &t1);   /* 2^50-1 */
    fe_sqn(&t2, &t1, 50); fe_mul(&t2, &t2, &t1);   /* 2^100-1 */
    fe_sqn(&t3, &t2, 100); fe_mul(&t2, &t3, &t2);  /* 2^200-1 */
    fe_sqn(&t2, &t2, 50); fe_mul(&t1, &t2, &t1);   /* 2^250-1 */
    *out = t1;
    *t0_out = t0; /* z^11, needed by the inversion tail */
}

static void fe_invert(fe *out, const fe *z)
{
    fe t1, t0;
    fe_pow250m1(&t1, &t0, z);
    fe_sqn(&t1, &t1, 5);        /* 2^255 - 2^5 */
    fe_mul(out, &t1, &t0);      /* 2^255 - 21 = p - 2 */
}

/* z^((p-5)/8) = z^(2^252 - 3) */
static void fe_pow22523(fe *out, const fe *z)
{
    fe t1, t0;
    fe_pow250m1(&t1, &t0, z);
    fe_sqn(&t1, &t1, 2);        /* 2^252 - 4 */
    fe_mul(out, &t1, z);        /* 2^252 - 3 */
}

static void fe_carry_full(fe *h)
{
    uint64_t c;
    int pass;
    for (pass = 0; pass < 2; pass++) {
        c = h->v[0] >> 51; h->v[0] &= MASK51; h->v[1] += c;
        c = h->v[1] >> 51; h->v[1] &= MASK51; h->v[2] += c;
        c = h->v[2] >> 51; h->v[2] &= MASK51; h->v[3] += c;
        c = h->v[3] >> 51; h->v[3] &= MASK51; h->v[4] += c;
        c = h->v[4] >> 51; h->v[4] &= MASK51; h->v[0] += 19 * c;
    }
}

static void fe_tobytes(unsigned char *s, const fe *f)
{
    fe t = *f;
    uint64_t q, c;
    int i;
    fe_carry_full(&t);
    /* canonical: add 19, see if it overflows 2^255 */
    q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51;
    for (i = 0; i < 32; i++) {
        int limb = (i * 8) / 51, off = (i * 8) % 51;
        uint64_t b = t.v[limb] >> off;
        if (limb < 4 && off > 43) b |= t.v[limb + 1] << (51 - off);
        s[i] = (unsigned char)b;
    }
}

static void fe_frombytes(fe *h, const unsigned char *s)
{
    uint64_t lo, hi;
    memcpy(&lo, s, 8);      h->v[0] = lo & MASK51;
    memcpy(&hi, s + 6, 8);  h->v[1] = (hi >> 3) & MASK51;
    memcpy(&lo, s + 12, 8); h->v[2] = (lo >> 6) & MASK51;
    memcpy(&hi, s + 19, 8); h->v[3] = (hi >> 1) & MASK51;
    /* bit 255 (top of byte 31) falls outside the 51-bit mask: X25519 ignores it */
    memcpy(&lo, s + 24, 8); h->v[4] = (lo >> 12) & MASK51;
}

static int fe_iszero(const fe *f)
{
    unsigned char s[32];
    unsigned char acc = 0;
    int i;
    fe_tobytes(s, f);
    for (i = 0; i < 32; i++) acc |= s[i];
    return acc == 0;
}

static int fe_eq(const fe *f, const fe *g)
{
    unsigned char a[32], b[32];
    fe_tobytes(a, f);
    fe_tobytes(b, g);
    return memcmp(a, b, 32) == 0;
}

/* ---- group ops: a=-1 twisted Edwards, extended coordinates ---- */

typedef struct { fe X, Y, Z, T; } ge_p3;              /* T = XY/Z */
typedef struct { fe ypx, ymx, t2d; } ge_niels;        /* affine: y+x, y-x, 2dxy */

static void ge_identity(ge_p3 *h)
{
    fe_0(&h->X); fe_1(&h->Y); fe_1(&h->Z); fe_0(&h->T);
}

/* h = p + q, q in affine Niels form (add-2008-hwcd-3, 7M) */
static void ge_madd(ge_p3 *h, const ge_p3 *p, const ge_niels *q)
{
    fe A, B, C, D, E, F, G, H, t;
    fe_sub(&t, &p->Y, &p->X); fe_mul(&A, &t, &q->ymx);
    fe_add(&t, &p->Y, &p->X); fe_mul(&B, &t, &q->ypx);
    fe_mul(&C, &q->t2d, &p->T);
    fe_add(&D, &p->Z, &p->Z);
    fe_sub(&E, &B, &A);
    fe_sub(&F, &D, &C);
    fe_add(&G, &D, &C);
    fe_add(&H, &B, &A);
    fe_mul(&h->X, &E, &F);
    fe_mul(&h->Y, &G, &H);
    fe_mul(&h->T, &E, &H);
    fe_mul(&h->Z, &F, &G);
}

/* h = p + q, both extended (add-2008-hwcd-3 with Z2 != 1; table build only) */
static void ge_add(ge_p3 *h, const ge_p3 *p, const ge_p3 *q)
{
    fe A, B, C, D, E, F, G, H, t, u;
    fe_sub(&t, &p->Y, &p->X); fe_sub(&u, &q->Y, &q->X); fe_mul(&A, &t, &u);
    fe_add(&t, &p->Y, &p->X); fe_add(&u, &q->Y, &q->X); fe_mul(&B, &t, &u);
    fe_mul(&C, &p->T, &q->T); fe_mul(&C, &C, &fe_d2);
    fe_mul(&D, &p->Z, &q->Z); fe_add(&D, &D, &D);
    fe_sub(&E, &B, &A);
    fe_sub(&F, &D, &C);
    fe_add(&G, &D, &C);
    fe_add(&H, &B, &A);
    fe_mul(&h->X, &E, &F);
    fe_mul(&h->Y, &G, &H);
    fe_mul(&h->T, &E, &H);
    fe_mul(&h->Z, &F, &G);
}

/* h = 2p (dbl-2008-hwcd, a=-1: D=-A) */
static void ge_dbl(ge_p3 *h, const ge_p3 *p)
{
    fe A, B, C, D, E, F, G, H, t;
    fe_sq(&A, &p->X);
    fe_sq(&B, &p->Y);
    fe_sq(&C, &p->Z); fe_add(&C, &C, &C);
    fe_neg(&D, &A);
    fe_add(&t, &p->X, &p->Y); fe_sq(&t, &t);
    fe_sub(&E, &t, &A); fe_sub(&E, &E, &B);
    fe_add(&G, &D, &B);
    fe_sub(&F, &G, &C);
    fe_sub(&H, &D, &B);
    fe_mul(&h->X, &E, &F);
    fe_mul(&h->Y, &G, &H);
    fe_mul(&h->T, &E, &H);
    fe_mul(&h->Z, &F, &G);
}

/* ---- comb table: T[i][j] = (j+1) * 16^i * P in Niels form ---- */

#define COMB_DIGITS 64
#define COMB_WIDTH 8

typedef struct {
    ge_niels t[COMB_DIGITS][COMB_WIDTH];
} comb_table;

/* build the table from an extended point; one batched inversion at the end */
static void comb_table_from_p3(comb_table *tab, const ge_p3 *p)
{
    static const int N = COMB_DIGITS * COMB_WIDTH;
    ge_p3 rows[COMB_DIGITS * COMB_WIDTH];
    fe zs[COMB_DIGITS * COMB_WIDTH], zinvs[COMB_DIGITS * COMB_WIDTH], acc, accinv;
    ge_p3 row;
    int i, j;

    row = *p;
    for (i = 0; i < COMB_DIGITS; i++) {
        rows[i * COMB_WIDTH] = row;
        for (j = 1; j < COMB_WIDTH; j++)
            ge_add(&rows[i * COMB_WIDTH + j], &rows[i * COMB_WIDTH + j - 1], &row);
        if (i + 1 < COMB_DIGITS) {
            ge_dbl(&row, &row); ge_dbl(&row, &row);
            ge_dbl(&row, &row); ge_dbl(&row, &row);
        }
    }
    /* Montgomery batch inversion of all Z coordinates */
    fe_1(&acc);
    for (i = 0; i < N; i++) {
        zs[i] = acc;
        fe_mul(&acc, &acc, &rows[i].Z);
    }
    fe_invert(&accinv, &acc);
    for (i = N - 1; i >= 0; i--) {
        fe_mul(&zinvs[i], &zs[i], &accinv);
        fe_mul(&accinv, &accinv, &rows[i].Z);
    }
    for (i = 0; i < COMB_DIGITS; i++) {
        for (j = 0; j < COMB_WIDTH; j++) {
            fe x, y, xy;
            ge_niels *n = &tab->t[i][j];
            fe_mul(&x, &rows[i * COMB_WIDTH + j].X, &zinvs[i * COMB_WIDTH + j]);
            fe_mul(&y, &rows[i * COMB_WIDTH + j].Y, &zinvs[i * COMB_WIDTH + j]);
            fe_add(&n->ypx, &y, &x);
            fe_sub(&n->ymx, &y, &x);
            fe_carry_full(&n->ypx);
            fe_carry_full(&n->ymx);
            fe_mul(&xy, &x, &y);
            fe_mul(&n->t2d, &xy, &fe_d2);
        }
    }
}

/* comb table for the fixed base point G (built once, lazily) */
void sda_comb_table_base(comb_table *tab)
{
    ge_p3 B;
    B.X = fe_basex; B.Y = fe_basey; fe_1(&B.Z);
    fe_mul(&B.T, &fe_basex, &fe_basey);
    comb_table_from_p3(tab, &B);
}

/* Lift an X25519 public key (Montgomery u) to Edwards and build its comb
 * table.  Returns 0 on success, -1 if u does not lift to a curve point
 * (caller falls back to the scalar libsodium path). */
int sda_comb_table_from_u(comb_table *tab, const unsigned char u_bytes[32])
{
    fe u, num, den, deninv, y, y2, xnum, xden, x, x2, chk, t, xd7, xd3;
    ge_p3 p;

    fe_frombytes(&u, u_bytes);
    /* y = (u-1)/(u+1) */
    fe one; fe_1(&one);
    fe_sub(&num, &u, &one);
    fe_add(&den, &u, &one);
    if (fe_iszero(&den)) return -1; /* u = -1: order-4 point */
    fe_invert(&deninv, &den);
    fe_mul(&y, &num, &deninv);
    /* x^2 = (y^2 - 1) / (d y^2 + 1) */
    fe_sq(&y2, &y);
    fe_sub(&xnum, &y2, &one);
    fe_mul(&xden, &y2, &fe_d);
    fe_add(&xden, &xden, &one);
    /* x = xnum * xden^3 * (xnum * xden^7)^((p-5)/8) */
    fe_sq(&t, &xden); fe_mul(&xd3, &t, &xden);      /* xden^3 */
    fe_sq(&t, &xd3); fe_mul(&xd7, &t, &xden);       /* xden^7 */
    fe_mul(&t, &xnum, &xd7);
    fe_pow22523(&t, &t);
    fe_mul(&x, &xnum, &xd3);
    fe_mul(&x, &x, &t);
    /* verify: xden * x^2 == +-xnum */
    fe_sq(&x2, &x);
    fe_mul(&chk, &x2, &xden);
    if (!fe_eq(&chk, &xnum)) {
        fe_mul(&x, &x, &fe_sqrtm1);
        fe_sq(&x2, &x);
        fe_mul(&chk, &x2, &xden);
        if (!fe_eq(&chk, &xnum)) return -1; /* not on curve */
    }
    p.X = x; p.Y = y; fe_1(&p.Z);
    fe_mul(&p.T, &x, &y);
    comb_table_from_p3(tab, &p);
    return 0;
}

/* recode a 255-bit scalar into 64 signed radix-16 digits in [-8, 8] */
static void comb_recode(signed char e[COMB_DIGITS], const unsigned char s[32])
{
    int i;
    signed char carry = 0;
    for (i = 0; i < 32; i++) {
        e[2 * i] = s[i] & 15;
        e[2 * i + 1] = (s[i] >> 4) & 15;
    }
    for (i = 0; i < COMB_DIGITS - 1; i++) {
        e[i] = (signed char)(e[i] + carry);
        carry = (signed char)((e[i] + 8) >> 4);
        e[i] = (signed char)(e[i] - (carry << 4));
    }
    e[COMB_DIGITS - 1] = (signed char)(e[COMB_DIGITS - 1] + carry);
}

static uint64_t ct_eq_u64(uint64_t a, uint64_t b)
{
    uint64_t x = a ^ b;
    return (uint64_t)0 - (uint64_t)((x | (0 - x)) >> 63 ^ 1);
}

static void niels_select(ge_niels *out, const ge_niels row[COMB_WIDTH], signed char digit)
{
    uint64_t babs = (uint64_t)(digit < 0 ? -digit : digit);
    uint64_t negmask = (uint64_t)0 - (uint64_t)(digit < 0);
    fe negt2d, tmp;
    int j;
    fe_1(&out->ypx); fe_1(&out->ymx); fe_0(&out->t2d); /* identity */
    for (j = 0; j < COMB_WIDTH; j++) {
        uint64_t mask = ct_eq_u64(babs, (uint64_t)(j + 1));
        fe_cmov(&out->ypx, &row[j].ypx, mask);
        fe_cmov(&out->ymx, &row[j].ymx, mask);
        fe_cmov(&out->t2d, &row[j].t2d, mask);
    }
    /* conditional negation: swap ypx/ymx, negate t2d */
    tmp = out->ypx;
    fe_cmov(&out->ypx, &out->ymx, negmask);
    fe_cmov(&out->ymx, &tmp, negmask);
    fe_neg(&negt2d, &out->t2d);
    fe_carry_full(&negt2d);
    fe_cmov(&out->t2d, &negt2d, negmask);
}

/* scalar * table-point as a projective Montgomery-u fraction:
 * u = (Z + Y) / (Z - Y).  Numerator/denominator are returned separately so
 * callers can batch-invert across many results. */
void sda_comb_scalarmult_frac(fe *unum, fe *uden, const comb_table *tab,
                              const unsigned char scalar[32])
{
    signed char e[COMB_DIGITS];
    ge_p3 acc;
    ge_niels sel;
    int i;
    comb_recode(e, scalar);
    ge_identity(&acc);
    for (i = 0; i < COMB_DIGITS; i++) {
        niels_select(&sel, tab->t[i], e[i]);
        ge_madd(&acc, &acc, &sel);
    }
    fe_add(unum, &acc.Z, &acc.Y);
    fe_sub(uden, &acc.Z, &acc.Y);
}

/* batch-finalize: out[i] = num[i]/den[i] as 32 little-endian bytes via one
 * Montgomery batch inversion.  A zero denominator (the identity point)
 * yields all-zero bytes, matching the Montgomery ladder's encoding of the
 * point at infinity.  num/den are consumed as scratch; `scratch` must hold
 * n field elements. */
void sda_comb_finalize_u(unsigned char *out /* n*32 */, fe *num, fe *den,
                         fe *scratch, int n)
{
    fe acc, accinv;
    int i;
    fe_1(&acc);
    for (i = 0; i < n; i++) {
        if (fe_iszero(&den[i])) {
            fe_1(&den[i]);
            fe_0(&num[i]); /* identity encodes as zero bytes */
        }
        scratch[i] = acc;
        fe_mul(&acc, &acc, &den[i]);
    }
    fe_invert(&accinv, &acc);
    for (i = n - 1; i >= 0; i--) {
        fe dinv, u;
        fe_mul(&dinv, &scratch[i], &accinv);
        fe_mul(&accinv, &accinv, &den[i]);
        fe_mul(&u, &num[i], &dinv);
        fe_tobytes(out + 32 * (size_t)i, &u);
    }
}

/* ---- Montgomery ladder with deferred inversion ----
 *
 * The comb tables above only help FIXED-base scalarmults.  Opening a
 * batch of sealed boxes is the opposite shape: every ciphertext carries a
 * DIFFERENT ephemeral public key, and the recipient computes sk * epk_i —
 * a variable-base scalarmult per item that no table can amortize.  What
 * CAN be amortized is the final projective-to-affine division: the ladder
 * ends with u = X2/Z2, and libsodium pays a full field inversion (~254
 * squarings) per call.  This variant returns the (X2, Z2) fraction so the
 * caller batch-inverts across the whole chunk via sda_comb_finalize_u —
 * one inversion total instead of one per ciphertext.
 *
 * Standard RFC 7748 ladder, ref10 operation ordering (the z2 term uses
 * the BB + 121666*E form, equal to AA + 121665*E since AA = BB + E).
 * The scalar is clamped here exactly as crypto_scalarmult does, so a
 * zero output fraction reproduces libsodium's all-zero shared secret for
 * small-order points (callers treat it as an open failure, matching
 * crypto_box_beforenm).  Constant-time: bit-masked cswap, no
 * secret-dependent branches or loads. */
void sda_x25519_ladder_frac(fe *xout, fe *zout, const unsigned char scalar[32],
                            const unsigned char point[32])
{
    unsigned char e[32];
    fe x1, x2, z2, x3, z3, tmp0, tmp1;
    int pos;
    uint64_t swap = 0, b;

    memcpy(e, scalar, 32);
    e[0] &= 248; e[31] &= 127; e[31] |= 64; /* X25519 clamp */
    fe_frombytes(&x1, point);
    fe_1(&x2); fe_0(&z2);
    x3 = x1;   fe_1(&z3);
    for (pos = 254; pos >= 0; --pos) {
        b = (uint64_t)(e[pos / 8] >> (pos & 7)) & 1;
        swap ^= b;
        fe_cswap(&x2, &x3, swap);
        fe_cswap(&z2, &z3, swap);
        swap = b;
        fe_sub(&tmp0, &x3, &z3);
        fe_sub(&tmp1, &x2, &z2);
        fe_add(&x2, &x2, &z2);
        fe_add(&z2, &x3, &z3);
        fe_mul(&z3, &tmp0, &x2);
        fe_mul(&z2, &z2, &tmp1);
        fe_sq(&tmp0, &tmp1);
        fe_sq(&tmp1, &x2);
        fe_add(&x3, &z3, &z2);
        fe_sub(&z2, &z3, &z2);
        fe_mul(&x2, &tmp1, &tmp0);
        fe_sub(&tmp1, &tmp1, &tmp0);
        fe_sq(&z2, &z2);
        fe_mul121666(&z3, &tmp1);
        fe_sq(&x3, &x3);
        fe_add(&tmp0, &tmp0, &z3);
        fe_mul(&z3, &x1, &z2);
        fe_mul(&z2, &tmp1, &tmp0);
    }
    fe_cswap(&x2, &x3, swap);
    fe_cswap(&z2, &z3, swap);
    *xout = x2;
    *zout = z2;
}

/* single-shot u-coordinate scalarmult (tests + small batches) */
void sda_comb_scalarmult_u(unsigned char out[32], const comb_table *tab,
                           const unsigned char scalar[32])
{
    fe num, den, deninv, u;
    sda_comb_scalarmult_frac(&num, &den, tab, scalar);
    if (fe_iszero(&den)) { memset(out, 0, 32); return; }
    fe_invert(&deninv, &den);
    fe_mul(&u, &num, &deninv);
    fe_tobytes(out, &u);
}
