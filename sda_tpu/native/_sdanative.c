/* sda_tpu native extension: bulk varint codec + batched libsodium ops.
 *
 * The reference's crypto plane is native Rust over libsodium and pays one
 * FFI call per i64 varint and one per sealed box (client/src/crypto/
 * encryption/sodium.rs). This extension is the equivalent native layer for
 * the Python framework, shaped for bulk: whole share vectors encode/decode
 * in one call, and seal/open operate on batches with the GIL released so
 * server-side pipelines can thread over them.
 *
 * Wire formats are pinned to the reference:
 *   - varint: zigzag(i64) then little-endian base-128 with continuation
 *     bits (integer-encoding crate semantics).
 *   - sealed box: crypto_box_seal / crypto_box_seal_open.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>

/* The image ships libsodium.so.23 without dev headers; declare the stable
 * ABI we use (sizes are fixed constants of the library). */
#define crypto_box_PUBLICKEYBYTES 32U
#define crypto_box_SECRETKEYBYTES 32U
#define crypto_box_SEALBYTES 48U /* PUBLICKEYBYTES + MACBYTES */
#define crypto_box_MACBYTES 16U
#define crypto_box_NONCEBYTES 24U
extern int sodium_init(void);
extern void sodium_memzero(void *pnt, size_t len);
extern void randombytes_buf(void *buf, size_t size);
extern int crypto_box_seal(unsigned char *c, const unsigned char *m,
                           unsigned long long mlen, const unsigned char *pk);
extern int crypto_box_seal_open(unsigned char *m, const unsigned char *c,
                                unsigned long long clen, const unsigned char *pk,
                                const unsigned char *sk);
extern int crypto_core_hsalsa20(unsigned char *out, const unsigned char *in,
                                const unsigned char *k, const unsigned char *c);
extern int crypto_generichash(unsigned char *out, size_t outlen,
                              const unsigned char *in, unsigned long long inlen,
                              const unsigned char *key, size_t keylen);
extern int crypto_box_easy_afternm(unsigned char *c, const unsigned char *m,
                                   unsigned long long mlen, const unsigned char *n,
                                   const unsigned char *k);
extern int crypto_box_open_easy_afternm(unsigned char *m, const unsigned char *c,
                                        unsigned long long clen,
                                        const unsigned char *n,
                                        const unsigned char *k);
extern int crypto_stream_chacha20_xor_ic(unsigned char *c, const unsigned char *m,
                                         unsigned long long mlen,
                                         const unsigned char *n, uint64_t ic,
                                         const unsigned char *k);

/* Amalgamated (single translation unit) so the field ops inline into the
 * batch loops below.  Provides comb_table, sda_comb_table_base,
 * sda_comb_table_from_u, sda_comb_scalarmult_frac, sda_comb_finalize_u. */
#include "curve25519_comb.c"

/* ---------------- varint ---------------- */

static size_t encode_one(uint64_t z, uint8_t *out) {
    size_t n = 0;
    while (z >= 0x80) {
        out[n++] = (uint8_t)(z | 0x80);
        z >>= 7;
    }
    out[n++] = (uint8_t)z;
    return n;
}

/* varint_encode(values: bytes of little-endian int64) -> bytes */
static PyObject *varint_encode(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
    if (buf.len % 8 != 0) {
        PyBuffer_Release(&buf);
        return PyErr_Format(PyExc_ValueError, "input must be int64-aligned");
    }
    Py_ssize_t n = buf.len / 8;
    uint8_t *out = PyMem_Malloc((size_t)n * 10 + 1);
    if (!out) {
        PyBuffer_Release(&buf);
        return PyErr_NoMemory();
    }
    const int64_t *vals = (const int64_t *)buf.buf;
    size_t pos = 0;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < n; i++) {
        int64_t v = vals[i];
        uint64_t z = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63); /* zigzag */
        pos += encode_one(z, out + pos);
    }
    Py_END_ALLOW_THREADS
    PyObject *res = PyBytes_FromStringAndSize((const char *)out, (Py_ssize_t)pos);
    PyMem_Free(out);
    PyBuffer_Release(&buf);
    return res;
}

/* varint_decode(stream: bytes) -> bytes of little-endian int64 */
static PyObject *varint_decode(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
    const uint8_t *in = (const uint8_t *)buf.buf;
    Py_ssize_t len = buf.len;
    /* worst case one value per byte */
    int64_t *out = PyMem_Malloc(((size_t)len + 1) * 8);
    if (!out) {
        PyBuffer_Release(&buf);
        return PyErr_NoMemory();
    }
    Py_ssize_t count = 0;
    int ok = 1;
    Py_BEGIN_ALLOW_THREADS
    Py_ssize_t i = 0;
    while (i < len) {
        uint64_t z = 0;
        int shift = 0;
        for (;;) {
            if (i >= len || shift > 63) { ok = 0; break; }
            uint8_t b = in[i++];
            z |= ((uint64_t)(b & 0x7F)) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        if (!ok) break;
        out[count++] = (int64_t)((z >> 1) ^ (~(z & 1) + 1)); /* unzigzag */
    }
    Py_END_ALLOW_THREADS
    if (!ok) {
        PyMem_Free(out);
        PyBuffer_Release(&buf);
        return PyErr_Format(PyExc_ValueError, "truncated or overlong varint stream");
    }
    PyObject *res = PyBytes_FromStringAndSize((const char *)out, count * 8);
    PyMem_Free(out);
    PyBuffer_Release(&buf);
    return res;
}

/* ---------------- sealed boxes ----------------
 *
 * Batch entry points take an optional trailing ``n_threads`` (default 1).
 * The GIL is released once for the whole batch; with n_threads > 1 each
 * worker owns one CONTIGUOUS chunk of the batch (not a stride), so a
 * worker's reads/writes stay in one cache-warm region and the per-chunk
 * comb state (scalar fractions awaiting batch inversion) needs no
 * cross-thread coordination.  Every Python object is created before the
 * pool starts, so no Python API runs off-thread.  libsodium primitives
 * used here are thread-safe.  Failures record the lowest failing index so
 * the raised error is deterministic regardless of thread interleaving.
 *
 * Sealing to one recipient amortizes the expensive X25519 work with comb
 * tables (see curve25519_comb.c): the base-point table is built once per
 * process, the recipient table once per batch, and each seal then costs
 * 64+64 mixed Edwards additions instead of two Montgomery ladders, with
 * the per-item field inversions folded into one Montgomery batch
 * inversion per chunk.  The output is composed with libsodium's own
 * HSalsa20 + XSalsa20-Poly1305, so it remains a standard crypto_box_seal
 * sealed box (epk || box), openable by any existing client.  Batches
 * smaller than SDA_COMB_MIN_BATCH, and recipient keys that do not lift to
 * a curve point, fall back to plain crypto_box_seal per item. */

#define SDA_COMB_MIN_BATCH 8

static comb_table g_base_table;           /* esk*G table, built once */
static int g_base_table_ready = 0;        /* guarded by the GIL */

static int is_zero32(const unsigned char *p) {
    unsigned char acc = 0;
    int i;
    for (i = 0; i < 32; i++) acc |= p[i];
    return acc == 0;
}

/* Seal ins[lo..hi) to pk using comb tables; one ephemeral key per item.
 * Returns -1 on success or the lowest failing index. */
static Py_ssize_t comb_seal_range(const comb_table *pt, const unsigned char *pk,
                                  const unsigned char **ins,
                                  const Py_ssize_t *inlens, unsigned char **outs,
                                  Py_ssize_t lo, Py_ssize_t hi) {
    Py_ssize_t n = hi - lo, i;
    fe *num, *den, *scr;
    unsigned char *esks, *us;
    if (n <= 0) return -1;
    num = malloc(sizeof(fe) * (size_t)n * 2);
    den = malloc(sizeof(fe) * (size_t)n * 2);
    scr = malloc(sizeof(fe) * (size_t)n * 2);
    esks = malloc((size_t)n * 32);
    us = malloc((size_t)n * 64); /* per item: epk u (32) || shared u (32) */
    if (!num || !den || !scr || !esks || !us) {
        /* allocation pressure: do the slow, allocation-free thing */
        free(num); free(den); free(scr); free(esks); free(us);
        for (i = lo; i < hi; i++)
            if (crypto_box_seal(outs[i], ins[i], (unsigned long long)inlens[i],
                                pk) != 0)
                return i;
        return -1;
    }
    for (i = 0; i < n; i++) {
        unsigned char *esk = esks + i * 32;
        randombytes_buf(esk, 32);
        esk[0] &= 248; esk[31] &= 127; esk[31] |= 64; /* X25519 clamp */
        sda_comb_scalarmult_frac(&num[2 * i], &den[2 * i], &g_base_table, esk);
        sda_comb_scalarmult_frac(&num[2 * i + 1], &den[2 * i + 1], pt, esk);
    }
    sda_comb_finalize_u(us, num, den, scr, (int)(n * 2));
    for (i = 0; i < n; i++) {
        const unsigned char *epk = us + i * 64;
        const unsigned char *shared = us + i * 64 + 32;
        unsigned char k[32], nonce[crypto_box_NONCEBYTES], hin[64];
        static const unsigned char zero16[16] = {0};
        if (is_zero32(shared)) break; /* mirrors crypto_box_beforenm failure */
        crypto_core_hsalsa20(k, zero16, shared, NULL);
        memcpy(hin, epk, 32);
        memcpy(hin + 32, pk, 32);
        crypto_generichash(nonce, sizeof nonce, hin, sizeof hin, NULL, 0);
        memcpy(outs[lo + i], epk, 32);
        crypto_box_easy_afternm(outs[lo + i] + 32, ins[lo + i],
                                (unsigned long long)inlens[lo + i], nonce, k);
        sodium_memzero(k, sizeof k);
    }
    sodium_memzero(esks, (size_t)n * 32);
    sodium_memzero(us, (size_t)n * 64);
    free(num); free(den); free(scr); free(esks); free(us);
    return i < n ? lo + i : -1;
}

/* Open ins[lo..hi) addressed to (pk, sk), batching the expensive X25519
 * work: one variable-base ladder per ciphertext (independent ephemeral
 * keys — nothing to share), but the per-item projective division is
 * deferred into ONE Montgomery batch inversion for the whole chunk, and
 * the nonce-hash input's recipient half is hoisted out of the loop.  The
 * symmetric open is libsodium's own afternm primitive, so acceptance is
 * bit-for-bit crypto_box_seal_open.  Returns -1 on success or the lowest
 * failing index (zero shared secret and MAC failure both count, exactly
 * the cases crypto_box_seal_open rejects). */
static Py_ssize_t open_range(const unsigned char *pk, const unsigned char *sk,
                             const unsigned char **ins, const Py_ssize_t *inlens,
                             unsigned char **outs, Py_ssize_t lo, Py_ssize_t hi) {
    Py_ssize_t n = hi - lo, i;
    fe *num, *den, *scr;
    unsigned char *us;
    unsigned char hin[64];
    if (n <= 0) return -1;
    num = malloc(sizeof(fe) * (size_t)n);
    den = malloc(sizeof(fe) * (size_t)n);
    scr = malloc(sizeof(fe) * (size_t)n);
    us = malloc((size_t)n * 32);
    if (!num || !den || !scr || !us) {
        /* allocation pressure: the slow, allocation-free thing */
        free(num); free(den); free(scr); free(us);
        for (i = lo; i < hi; i++)
            if (crypto_box_seal_open(outs[i], ins[i],
                                     (unsigned long long)inlens[i], pk, sk) != 0)
                return i;
        return -1;
    }
    for (i = 0; i < n; i++) /* epk is the sealed box's first 32 bytes */
        sda_x25519_ladder_frac(&num[i], &den[i], sk, ins[lo + i]);
    sda_comb_finalize_u(us, num, den, scr, (int)n);
    memcpy(hin + 32, pk, 32); /* fixed for the chunk */
    for (i = 0; i < n; i++) {
        const unsigned char *shared = us + i * 32;
        unsigned char k[32], nonce[crypto_box_NONCEBYTES];
        static const unsigned char zero16[16] = {0};
        if (is_zero32(shared)) break; /* crypto_box_beforenm failure */
        crypto_core_hsalsa20(k, zero16, shared, NULL);
        memcpy(hin, ins[lo + i], 32);
        crypto_generichash(nonce, sizeof nonce, hin, sizeof hin, NULL, 0);
        if (crypto_box_open_easy_afternm(outs[lo + i], ins[lo + i] + 32,
                                         (unsigned long long)(inlens[lo + i] - 32),
                                         nonce, k) != 0) {
            sodium_memzero(k, sizeof k);
            break;
        }
        sodium_memzero(k, sizeof k);
    }
    sodium_memzero(us, (size_t)n * 32);
    free(num); free(den); free(scr); free(us);
    return i < n ? lo + i : -1;
}

typedef struct {
    Py_ssize_t lo, hi;
    const unsigned char **ins;
    const Py_ssize_t *inlens;
    unsigned char **outs;
    const unsigned char *pk, *sk; /* sk NULL => seal, else open */
    const comb_table *pt;         /* non-NULL => comb seal path */
    int batch_open;               /* non-zero => deferred-inversion open path */
    Py_ssize_t fail;              /* lowest failing index in chunk, or -1 */
} sealjob_t;

static void *seal_open_worker(void *arg) {
    sealjob_t *j = (sealjob_t *)arg;
    if (j->sk && j->batch_open) {
        j->fail = open_range(j->pk, j->sk, j->ins, j->inlens, j->outs,
                             j->lo, j->hi);
        return NULL;
    }
    if (j->pt && !j->sk) {
        j->fail = comb_seal_range(j->pt, j->pk, j->ins, j->inlens, j->outs,
                                  j->lo, j->hi);
        return NULL;
    }
    for (Py_ssize_t i = j->lo; i < j->hi; i++) {
        int rc;
        if (j->sk) {
            rc = crypto_box_seal_open(j->outs[i], j->ins[i],
                                      (unsigned long long)j->inlens[i], j->pk,
                                      j->sk);
        } else {
            rc = crypto_box_seal(j->outs[i], j->ins[i],
                                 (unsigned long long)j->inlens[i], j->pk);
        }
        if (rc != 0) {
            j->fail = i;
            return NULL; /* lowest index within the chunk; lowest across
                          * chunks picked at join */
        }
    }
    return NULL;
}

#define SEAL_MAX_THREADS 64

/* shared body: sk==NULL for seal, non-NULL for open */
static PyObject *seal_open_batch(PyObject *items, const unsigned char *pk,
                                 const unsigned char *sk, long n_threads) {
    Py_ssize_t n = PyList_Size(items);
    /* pin the inputs with strong refs: phase 2 runs with the GIL
     * released, and a caller thread mutating its list there would
     * otherwise drop the last ref to a bytes object whose buffer a
     * worker is still reading */
    items = PyList_GetSlice(items, 0, n);
    if (!items) return NULL;
    PyObject *out = PyList_New(n);
    if (!out) {
        Py_DECREF(items);
        return NULL;
    }
    const unsigned char **ins = PyMem_Malloc(sizeof(*ins) * (size_t)(n ? n : 1));
    Py_ssize_t *inlens = PyMem_Malloc(sizeof(*inlens) * (size_t)(n ? n : 1));
    unsigned char **outs = PyMem_Malloc(sizeof(*outs) * (size_t)(n ? n : 1));
    if (!ins || !inlens || !outs) {
        PyErr_NoMemory();
        goto fail;
    }
    /* phase 1 (GIL held): pin input pointers, allocate every output. The
     * list keeps each input bytes object alive for the whole call. */
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GetItem(items, i);
        char *buf; Py_ssize_t blen;
        if (PyBytes_AsStringAndSize(item, &buf, &blen) < 0) goto fail;
        Py_ssize_t outlen;
        if (sk) {
            if (blen < (Py_ssize_t)crypto_box_SEALBYTES) {
                PyErr_Format(PyExc_ValueError, "ciphertext %zd too short", i);
                goto fail;
            }
            outlen = blen - crypto_box_SEALBYTES;
        } else {
            outlen = blen + crypto_box_SEALBYTES;
        }
        PyObject *res = PyBytes_FromStringAndSize(NULL, outlen);
        if (!res) goto fail;
        PyList_SET_ITEM(out, i, res);
        ins[i] = (const unsigned char *)buf;
        inlens[i] = blen;
        outs[i] = (unsigned char *)PyBytes_AS_STRING(res);
    }
    /* phase 2 (GIL released): the crypto, chunked across the pool */
    if (n_threads < 1) n_threads = 1;
    if (n_threads > n) n_threads = n ? n : 1;
    if (n_threads > SEAL_MAX_THREADS) n_threads = SEAL_MAX_THREADS;
    {
        Py_ssize_t first_fail = -1;
        comb_table *pt = NULL;
        /* deferred-inversion open pays one batch inversion per chunk;
         * below the min batch the setup outweighs the saving */
        int batch_open = (sk != NULL && n >= SDA_COMB_MIN_BATCH);
        if (!sk && n >= SDA_COMB_MIN_BATCH) {
            pt = PyMem_Malloc(sizeof(comb_table));
            if (pt) {
                if (!g_base_table_ready) { /* GIL still held here */
                    sda_comb_table_base(&g_base_table);
                    g_base_table_ready = 1;
                }
                if (sda_comb_table_from_u(pt, pk) != 0) {
                    PyMem_Free(pt); /* pk does not lift: scalar fallback */
                    pt = NULL;
                }
            }
        }
        Py_BEGIN_ALLOW_THREADS
        if (n_threads <= 1) {
            sealjob_t job = {0, n, ins, inlens, outs, pk, sk, pt, batch_open, -1};
            seal_open_worker(&job);
            first_fail = job.fail;
        } else {
            sealjob_t jobs[SEAL_MAX_THREADS];
            pthread_t tids[SEAL_MAX_THREADS];
            int started[SEAL_MAX_THREADS];
            Py_ssize_t chunk = (n + n_threads - 1) / n_threads;
            for (long t = 0; t < n_threads; t++) {
                Py_ssize_t lo = t * chunk;
                Py_ssize_t hi = lo + chunk < n ? lo + chunk : n;
                sealjob_t j = {lo, hi, ins, inlens, outs, pk, sk, pt,
                               batch_open, -1};
                jobs[t] = j;
                started[t] =
                    pthread_create(&tids[t], NULL, seal_open_worker, &jobs[t]) == 0;
                if (!started[t]) seal_open_worker(&jobs[t]); /* inline fallback */
            }
            for (long t = 0; t < n_threads; t++) {
                if (started[t]) pthread_join(tids[t], NULL);
                if (jobs[t].fail >= 0 &&
                    (first_fail < 0 || jobs[t].fail < first_fail))
                    first_fail = jobs[t].fail;
            }
        }
        Py_END_ALLOW_THREADS
        PyMem_Free(pt);
        if (first_fail >= 0) {
            if (sk)
                PyErr_Format(PyExc_ValueError, "sealed box %zd failed to open",
                             first_fail);
            else
                PyErr_Format(PyExc_RuntimeError, "crypto_box_seal failed");
            goto fail;
        }
    }
    PyMem_Free(ins); PyMem_Free(inlens); PyMem_Free(outs);
    Py_DECREF(items);
    return out;
fail:
    PyMem_Free(ins); PyMem_Free(inlens); PyMem_Free(outs);
    Py_DECREF(items);
    Py_DECREF(out);
    return NULL;
}

/* seal_batch(messages: list[bytes], pk: bytes32, n_threads=1) -> list[bytes] */
static PyObject *seal_batch(PyObject *self, PyObject *args) {
    PyObject *msgs;
    Py_buffer pk;
    long n_threads = 1;
    if (!PyArg_ParseTuple(args, "O!y*|l", &PyList_Type, &msgs, &pk, &n_threads))
        return NULL;
    if (pk.len != crypto_box_PUBLICKEYBYTES) {
        PyBuffer_Release(&pk);
        return PyErr_Format(PyExc_ValueError, "public key must be 32 bytes");
    }
    PyObject *out = seal_open_batch(msgs, (const unsigned char *)pk.buf, NULL,
                                    n_threads);
    PyBuffer_Release(&pk);
    return out;
}

/* open_batch(cts: list[bytes], pk: bytes32, sk: bytes32, n_threads=1)
 * -> list[bytes]; raises ValueError naming the lowest forged index. */
static PyObject *open_batch(PyObject *self, PyObject *args) {
    PyObject *cts;
    Py_buffer pk, sk;
    long n_threads = 1;
    if (!PyArg_ParseTuple(args, "O!y*y*|l", &PyList_Type, &cts, &pk, &sk,
                          &n_threads))
        return NULL;
    if (pk.len != crypto_box_PUBLICKEYBYTES || sk.len != crypto_box_SECRETKEYBYTES) {
        PyBuffer_Release(&pk); PyBuffer_Release(&sk);
        return PyErr_Format(PyExc_ValueError, "keys must be 32 bytes");
    }
    PyObject *out = seal_open_batch(cts, (const unsigned char *)pk.buf,
                                    (const unsigned char *)sk.buf, n_threads);
    PyBuffer_Release(&pk);
    PyBuffer_Release(&sk);
    return out;
}

/* ---------------- committee sealing ----------------
 *
 * seal_participations(shares, pks, n_threads): P participants x C clerks.
 * shares[p][c] is sealed to pks[c].  One ephemeral keypair per PARTICIPANT
 * is shared across that participant's C sealed boxes (standard
 * multi-recipient construction: nonce = blake2b(epk || pk_c) and key =
 * HSalsa20(esk * pk_c) both differ per clerk, so no nonce/key reuse), which
 * drops the per-share X25519 cost from two scalarmults to (1 + 1/C).  Each
 * output is still a standard crypto_box_seal sealed box for its clerk.
 * The C shares of one participation are already linked publicly by the
 * participation record itself, so the shared epk leaks nothing new. */

typedef struct {
    Py_ssize_t plo, phi, C;
    const unsigned char **ins; /* flat [p*C + c] */
    const Py_ssize_t *inlens;
    unsigned char **outs;
    const unsigned char *pks;  /* C*32 contiguous */
    const comb_table *pts;     /* C tables, or NULL => scalar path */
    Py_ssize_t fail;
} partjob_t;

static void *participations_worker(void *arg) {
    partjob_t *j = (partjob_t *)arg;
    Py_ssize_t C = j->C, nP = j->phi - j->plo, p, c;
    j->fail = -1;
    if (nP <= 0 || C <= 0) return NULL;
    if (j->pts) {
        Py_ssize_t per = 1 + C, nf = nP * per;
        fe *num = malloc(sizeof(fe) * (size_t)nf);
        fe *den = malloc(sizeof(fe) * (size_t)nf);
        fe *scr = malloc(sizeof(fe) * (size_t)nf);
        unsigned char *esk = malloc((size_t)nP * 32);
        unsigned char *us = malloc((size_t)nf * 32);
        if (num && den && scr && esk && us) {
            for (p = 0; p < nP; p++) {
                unsigned char *e = esk + p * 32;
                Py_ssize_t b = p * per;
                randombytes_buf(e, 32);
                e[0] &= 248; e[31] &= 127; e[31] |= 64;
                sda_comb_scalarmult_frac(&num[b], &den[b], &g_base_table, e);
                for (c = 0; c < C; c++)
                    sda_comb_scalarmult_frac(&num[b + 1 + c], &den[b + 1 + c],
                                             &j->pts[c], e);
            }
            sda_comb_finalize_u(us, num, den, scr, (int)nf);
            for (p = 0; p < nP && j->fail < 0; p++) {
                const unsigned char *epk = us + p * per * 32;
                for (c = 0; c < C; c++) {
                    const unsigned char *shared = us + (p * per + 1 + c) * 32;
                    const unsigned char *pk = j->pks + c * 32;
                    Py_ssize_t flat = (j->plo + p) * C + c;
                    unsigned char k[32], nonce[crypto_box_NONCEBYTES], hin[64];
                    static const unsigned char zero16[16] = {0};
                    if (is_zero32(shared)) { j->fail = flat; break; }
                    crypto_core_hsalsa20(k, zero16, shared, NULL);
                    memcpy(hin, epk, 32);
                    memcpy(hin + 32, pk, 32);
                    crypto_generichash(nonce, sizeof nonce, hin, sizeof hin,
                                       NULL, 0);
                    memcpy(j->outs[flat], epk, 32);
                    crypto_box_easy_afternm(j->outs[flat] + 32, j->ins[flat],
                                            (unsigned long long)j->inlens[flat],
                                            nonce, k);
                    sodium_memzero(k, sizeof k);
                }
            }
            sodium_memzero(esk, (size_t)nP * 32);
            sodium_memzero(us, (size_t)nf * 32);
            free(num); free(den); free(scr); free(esk); free(us);
            return NULL;
        }
        free(num); free(den); free(scr); free(esk); free(us);
        /* allocation pressure: fall through to the scalar path */
    }
    for (p = j->plo; p < j->phi; p++) {
        for (c = 0; c < C; c++) {
            Py_ssize_t flat = p * C + c;
            if (crypto_box_seal(j->outs[flat], j->ins[flat],
                                (unsigned long long)j->inlens[flat],
                                j->pks + c * 32) != 0) {
                j->fail = flat;
                return NULL;
            }
        }
    }
    return NULL;
}

/* seal_participations(shares: list[list[bytes]] (P x C), pks: list[bytes32],
 * n_threads=1) -> list[list[bytes]] */
static PyObject *seal_participations(PyObject *self, PyObject *args) {
    PyObject *shares, *pklist;
    long n_threads = 1;
    if (!PyArg_ParseTuple(args, "O!O!|l", &PyList_Type, &shares, &PyList_Type,
                          &pklist, &n_threads))
        return NULL;
    Py_ssize_t P = PyList_Size(shares);
    Py_ssize_t C = PyList_Size(pklist);
    unsigned char *pks = NULL;
    const unsigned char **ins = NULL;
    Py_ssize_t *inlens = NULL;
    unsigned char **outs = NULL;
    comb_table *pts = NULL;
    PyObject *pinned = NULL, *out = NULL;
    Py_ssize_t total = P * C;

    pks = PyMem_Malloc((size_t)(C ? C : 1) * 32);
    if (!pks) return PyErr_NoMemory();
    for (Py_ssize_t c = 0; c < C; c++) {
        PyObject *item = PyList_GetItem(pklist, c);
        char *buf; Py_ssize_t blen;
        if (PyBytes_AsStringAndSize(item, &buf, &blen) < 0 ||
            blen != crypto_box_PUBLICKEYBYTES) {
            if (!PyErr_Occurred())
                PyErr_Format(PyExc_ValueError, "public key %zd must be 32 bytes", c);
            PyMem_Free(pks);
            return NULL;
        }
        memcpy(pks + c * 32, buf, 32);
    }
    /* pin every share buffer with a strong ref (callers may mutate lists
     * from another thread while the GIL is released below) */
    pinned = PyList_New(total);
    out = PyList_New(P);
    ins = PyMem_Malloc(sizeof(*ins) * (size_t)(total ? total : 1));
    inlens = PyMem_Malloc(sizeof(*inlens) * (size_t)(total ? total : 1));
    outs = PyMem_Malloc(sizeof(*outs) * (size_t)(total ? total : 1));
    if (!pinned || !out || !ins || !inlens || !outs) {
        PyErr_NoMemory();
        goto fail;
    }
    for (Py_ssize_t p = 0; p < P; p++) {
        PyObject *row = PyList_GetItem(shares, p);
        if (!PyList_Check(row) || PyList_Size(row) != C) {
            PyErr_Format(PyExc_ValueError,
                         "shares[%zd] must be a list of %zd messages", p, C);
            goto fail;
        }
        PyObject *orow = PyList_New(C);
        if (!orow) goto fail;
        PyList_SET_ITEM(out, p, orow);
        for (Py_ssize_t c = 0; c < C; c++) {
            PyObject *item = PyList_GetItem(row, c);
            char *buf; Py_ssize_t blen;
            if (PyBytes_AsStringAndSize(item, &buf, &blen) < 0) goto fail;
            Py_INCREF(item);
            PyList_SET_ITEM(pinned, p * C + c, item);
            PyObject *res = PyBytes_FromStringAndSize(NULL,
                                                      blen + crypto_box_SEALBYTES);
            if (!res) goto fail;
            PyList_SET_ITEM(orow, c, res);
            ins[p * C + c] = (const unsigned char *)buf;
            inlens[p * C + c] = blen;
            outs[p * C + c] = (unsigned char *)PyBytes_AS_STRING(res);
        }
    }
    if (total >= SDA_COMB_MIN_BATCH && C > 0) {
        pts = PyMem_Malloc(sizeof(comb_table) * (size_t)C);
        if (pts) {
            if (!g_base_table_ready) {
                sda_comb_table_base(&g_base_table);
                g_base_table_ready = 1;
            }
            for (Py_ssize_t c = 0; c < C; c++) {
                if (sda_comb_table_from_u(&pts[c], pks + c * 32) != 0) {
                    PyMem_Free(pts); /* some pk does not lift: scalar path */
                    pts = NULL;
                    break;
                }
            }
        }
    }
    {
        Py_ssize_t first_fail = -1;
        if (n_threads < 1) n_threads = 1;
        if (n_threads > P) n_threads = P ? P : 1;
        if (n_threads > SEAL_MAX_THREADS) n_threads = SEAL_MAX_THREADS;
        Py_BEGIN_ALLOW_THREADS
        if (n_threads <= 1) {
            partjob_t job = {0, P, C, ins, inlens, outs, pks, pts, -1};
            participations_worker(&job);
            first_fail = job.fail;
        } else {
            partjob_t jobs[SEAL_MAX_THREADS];
            pthread_t tids[SEAL_MAX_THREADS];
            int started[SEAL_MAX_THREADS];
            Py_ssize_t chunk = (P + n_threads - 1) / n_threads;
            for (long t = 0; t < n_threads; t++) {
                Py_ssize_t lo = t * chunk;
                Py_ssize_t hi = lo + chunk < P ? lo + chunk : P;
                partjob_t j = {lo, hi, C, ins, inlens, outs, pks, pts, -1};
                jobs[t] = j;
                started[t] = pthread_create(&tids[t], NULL, participations_worker,
                                            &jobs[t]) == 0;
                if (!started[t]) participations_worker(&jobs[t]);
            }
            for (long t = 0; t < n_threads; t++) {
                if (started[t]) pthread_join(tids[t], NULL);
                if (jobs[t].fail >= 0 &&
                    (first_fail < 0 || jobs[t].fail < first_fail))
                    first_fail = jobs[t].fail;
            }
        }
        Py_END_ALLOW_THREADS
        if (first_fail >= 0) {
            PyErr_Format(PyExc_RuntimeError, "crypto_box_seal failed");
            goto fail;
        }
    }
    PyMem_Free(pts);
    PyMem_Free(pks);
    PyMem_Free(ins); PyMem_Free(inlens); PyMem_Free(outs);
    Py_DECREF(pinned);
    return out;
fail:
    PyMem_Free(pts);
    PyMem_Free(pks);
    PyMem_Free(ins); PyMem_Free(inlens); PyMem_Free(outs);
    Py_XDECREF(pinned);
    Py_XDECREF(out);
    return NULL;
}

/* ---------------- ChaCha20 mask expansion ----------------
 *
 * Bit-identical to sda_tpu/ops/chacha.py expand_seed: classic djb
 * ChaCha20 keystream (zero nonce, 64-bit counter from 0 — libsodium's
 * crypto_stream_chacha20 layout), words consumed in order as u64 pairs
 * (w[2i] << 32) | w[2i+1], rejection-sampled below the rand-0.3
 * gen_range zone, reduced mod m. Used for the reveal hot loop: expand
 * every participant's seed and fold the masks into one running sum.
 */

#define CHACHA_CHUNK 65536 /* keystream buffer per refill; multiple of 64 */

/* expand one 32-byte key into vals[dim] (mod m), optionally accumulating
 * into acc[dim] (mod m) instead. Returns 0 on success. */
static void chacha_expand_key(const unsigned char *key, Py_ssize_t dim,
                              uint64_t m, int64_t *vals, int64_t *acc) {
    static const unsigned char nonce[8] = {0};
    unsigned char block[CHACHA_CHUNK];
    /* rand-0.3 gen_range(0, m) zone: u64::MAX - u64::MAX % m, accept
     * v < zone (ops/chacha.py rand03_zone — the Python/jnp planes use
     * the same formula; differs from 2^64 - 2^64 % m exactly when m
     * divides 2^64, where rand still rejects the top m values). */
    uint64_t u64_max = ~(uint64_t)0;
    uint64_t zone = u64_max - (u64_max % m);
    uint64_t counter = 0;
    size_t pos = 0, have = 0; /* empty buffer: first iteration refills */
    for (Py_ssize_t i = 0; i < dim;) {
        if (pos + 8 > have) {
            /* size the refill to what's left (+1 block of rejection
             * slack), not the full chunk — small dims would otherwise
             * pay for 64 KiB of keystream per key */
            size_t want = (size_t)(dim - i) * 8 + 64;
            have = want > CHACHA_CHUNK ? CHACHA_CHUNK : (want + 63) / 64 * 64;
            memset(block, 0, have);
            crypto_stream_chacha20_xor_ic(block, block, have, nonce,
                                          counter, key);
            counter += have / 64;
            pos = 0;
        }
        uint32_t w0, w1;
        memcpy(&w0, block + pos, 4); /* keystream words are little-endian */
        memcpy(&w1, block + pos + 4, 4);
        pos += 8;
        uint64_t v = ((uint64_t)w0 << 32) | (uint64_t)w1;
        if (v >= zone) continue;
        int64_t r = (int64_t)(v % m);
        if (acc) {
            acc[i] = (int64_t)(((uint64_t)acc[i] + (uint64_t)r) % m);
        } else {
            vals[i] = r;
        }
        i++;
    }
}

/* chacha_expand(key32: bytes, dim, modulus) -> bytes of int64 LE */
static PyObject *chacha_expand(PyObject *self, PyObject *args) {
    Py_buffer key;
    Py_ssize_t dim;
    unsigned long long modulus;
    if (!PyArg_ParseTuple(args, "y*nK", &key, &dim, &modulus)) return NULL;
    if (key.len != 32 || dim < 0 || modulus == 0 || modulus > (1ULL << 63)) {
        PyBuffer_Release(&key);
        return PyErr_Format(PyExc_ValueError,
                            "need 32-byte key, dim >= 0, 0 < modulus <= 2^63");
    }
    PyObject *res = PyBytes_FromStringAndSize(NULL, dim * 8);
    if (!res) { PyBuffer_Release(&key); return NULL; }
    int64_t *out = (int64_t *)PyBytes_AS_STRING(res);
    Py_BEGIN_ALLOW_THREADS
    chacha_expand_key((const unsigned char *)key.buf, dim, (uint64_t)modulus,
                      out, NULL);
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&key);
    return res;
}

/* chacha_combine(keys: bytes (n*32), dim, modulus) -> bytes of int64 LE:
 * elementwise sum mod m of every key's expanded mask. */
static PyObject *chacha_combine(PyObject *self, PyObject *args) {
    Py_buffer keys;
    Py_ssize_t dim;
    unsigned long long modulus;
    if (!PyArg_ParseTuple(args, "y*nK", &keys, &dim, &modulus)) return NULL;
    if (keys.len % 32 != 0 || dim < 0 || modulus == 0 || modulus > (1ULL << 63)) {
        PyBuffer_Release(&keys);
        return PyErr_Format(PyExc_ValueError,
                            "need n*32-byte keys, dim >= 0, 0 < modulus <= 2^63");
    }
    Py_ssize_t n = keys.len / 32;
    PyObject *res = PyBytes_FromStringAndSize(NULL, dim * 8);
    if (!res) { PyBuffer_Release(&keys); return NULL; }
    int64_t *acc = (int64_t *)PyBytes_AS_STRING(res);
    memset(acc, 0, (size_t)dim * 8);
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t s = 0; s < n; s++) {
        chacha_expand_key((const unsigned char *)keys.buf + s * 32, dim,
                          (uint64_t)modulus, NULL, acc);
    }
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&keys);
    return res;
}

static PyMethodDef methods[] = {
    {"varint_encode", varint_encode, METH_VARARGS,
     "zigzag-LEB128 encode a buffer of little-endian int64"},
    {"varint_decode", varint_decode, METH_VARARGS,
     "decode a zigzag-LEB128 stream to little-endian int64 bytes"},
    {"seal_batch", seal_batch, METH_VARARGS, "sealed-box encrypt a batch"},
    {"open_batch", open_batch, METH_VARARGS, "sealed-box decrypt a batch"},
    {"seal_participations", seal_participations, METH_VARARGS,
     "seal P x C share matrix to C clerk keys, one ephemeral per participant"},
    {"chacha_expand", chacha_expand, METH_VARARGS,
     "expand one 32-byte ChaCha20 key to int64 mask bytes mod m"},
    {"chacha_combine", chacha_combine, METH_VARARGS,
     "sum of expanded masks mod m over n concatenated 32-byte keys"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_sdanative", "native varint + sodium batch ops",
    -1, methods,
};

PyMODINIT_FUNC PyInit__sdanative(void) {
    if (sodium_init() < 0) {
        PyErr_SetString(PyExc_RuntimeError, "sodium_init failed");
        return NULL;
    }
    return PyModule_Create(&module);
}
