/* sda_tpu native extension: bulk varint codec + batched libsodium ops.
 *
 * The reference's crypto plane is native Rust over libsodium and pays one
 * FFI call per i64 varint and one per sealed box (client/src/crypto/
 * encryption/sodium.rs). This extension is the equivalent native layer for
 * the Python framework, shaped for bulk: whole share vectors encode/decode
 * in one call, and seal/open operate on batches with the GIL released so
 * server-side pipelines can thread over them.
 *
 * Wire formats are pinned to the reference:
 *   - varint: zigzag(i64) then little-endian base-128 with continuation
 *     bits (integer-encoding crate semantics).
 *   - sealed box: crypto_box_seal / crypto_box_seal_open.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>

/* The image ships libsodium.so.23 without dev headers; declare the stable
 * ABI we use (sizes are fixed constants of the library). */
#define crypto_box_PUBLICKEYBYTES 32U
#define crypto_box_SECRETKEYBYTES 32U
#define crypto_box_SEALBYTES 48U /* PUBLICKEYBYTES + MACBYTES */
extern int sodium_init(void);
extern int crypto_box_seal(unsigned char *c, const unsigned char *m,
                           unsigned long long mlen, const unsigned char *pk);
extern int crypto_box_seal_open(unsigned char *m, const unsigned char *c,
                                unsigned long long clen, const unsigned char *pk,
                                const unsigned char *sk);
extern int crypto_stream_chacha20_xor_ic(unsigned char *c, const unsigned char *m,
                                         unsigned long long mlen,
                                         const unsigned char *n, uint64_t ic,
                                         const unsigned char *k);

/* ---------------- varint ---------------- */

static size_t encode_one(uint64_t z, uint8_t *out) {
    size_t n = 0;
    while (z >= 0x80) {
        out[n++] = (uint8_t)(z | 0x80);
        z >>= 7;
    }
    out[n++] = (uint8_t)z;
    return n;
}

/* varint_encode(values: bytes of little-endian int64) -> bytes */
static PyObject *varint_encode(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
    if (buf.len % 8 != 0) {
        PyBuffer_Release(&buf);
        return PyErr_Format(PyExc_ValueError, "input must be int64-aligned");
    }
    Py_ssize_t n = buf.len / 8;
    uint8_t *out = PyMem_Malloc((size_t)n * 10 + 1);
    if (!out) {
        PyBuffer_Release(&buf);
        return PyErr_NoMemory();
    }
    const int64_t *vals = (const int64_t *)buf.buf;
    size_t pos = 0;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < n; i++) {
        int64_t v = vals[i];
        uint64_t z = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63); /* zigzag */
        pos += encode_one(z, out + pos);
    }
    Py_END_ALLOW_THREADS
    PyObject *res = PyBytes_FromStringAndSize((const char *)out, (Py_ssize_t)pos);
    PyMem_Free(out);
    PyBuffer_Release(&buf);
    return res;
}

/* varint_decode(stream: bytes) -> bytes of little-endian int64 */
static PyObject *varint_decode(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
    const uint8_t *in = (const uint8_t *)buf.buf;
    Py_ssize_t len = buf.len;
    /* worst case one value per byte */
    int64_t *out = PyMem_Malloc(((size_t)len + 1) * 8);
    if (!out) {
        PyBuffer_Release(&buf);
        return PyErr_NoMemory();
    }
    Py_ssize_t count = 0;
    int ok = 1;
    Py_BEGIN_ALLOW_THREADS
    Py_ssize_t i = 0;
    while (i < len) {
        uint64_t z = 0;
        int shift = 0;
        for (;;) {
            if (i >= len || shift > 63) { ok = 0; break; }
            uint8_t b = in[i++];
            z |= ((uint64_t)(b & 0x7F)) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        if (!ok) break;
        out[count++] = (int64_t)((z >> 1) ^ (~(z & 1) + 1)); /* unzigzag */
    }
    Py_END_ALLOW_THREADS
    if (!ok) {
        PyMem_Free(out);
        PyBuffer_Release(&buf);
        return PyErr_Format(PyExc_ValueError, "truncated or overlong varint stream");
    }
    PyObject *res = PyBytes_FromStringAndSize((const char *)out, count * 8);
    PyMem_Free(out);
    PyBuffer_Release(&buf);
    return res;
}

/* ---------------- sealed boxes ----------------
 *
 * Both batch entry points take an optional trailing ``n_threads`` (default
 * 1). The GIL is released for the whole batch either way; with n_threads
 * > 1 the batch is strided across a pthread pool — each item's
 * input/output buffer is touched by exactly one thread, and every Python
 * object is created before the pool starts, so no Python API runs
 * off-thread. libsodium seal/open are thread-safe (stateless; the
 * ephemeral keypair inside crypto_box_seal draws from thread-safe
 * randombytes). Failures record the lowest failing index so the raised
 * error is deterministic regardless of thread interleaving. */

typedef struct {
    Py_ssize_t n, start, step;
    const unsigned char **ins;
    const Py_ssize_t *inlens;
    unsigned char **outs;
    const unsigned char *pk, *sk; /* sk NULL => seal, else open */
    Py_ssize_t fail;              /* lowest failing index in stride, or -1 */
} sealjob_t;

static void *seal_open_worker(void *arg) {
    sealjob_t *j = (sealjob_t *)arg;
    for (Py_ssize_t i = j->start; i < j->n; i += j->step) {
        int rc;
        if (j->sk) {
            rc = crypto_box_seal_open(j->outs[i], j->ins[i],
                                      (unsigned long long)j->inlens[i], j->pk,
                                      j->sk);
        } else {
            rc = crypto_box_seal(j->outs[i], j->ins[i],
                                 (unsigned long long)j->inlens[i], j->pk);
        }
        if (rc != 0) {
            j->fail = i;
            return NULL; /* first failure in stride wins; lowest across
                          * strides picked at join */
        }
    }
    return NULL;
}

#define SEAL_MAX_THREADS 64

/* shared body: sk==NULL for seal, non-NULL for open */
static PyObject *seal_open_batch(PyObject *items, const unsigned char *pk,
                                 const unsigned char *sk, long n_threads) {
    Py_ssize_t n = PyList_Size(items);
    /* pin the inputs with strong refs: phase 2 runs with the GIL
     * released, and a caller thread mutating its list there would
     * otherwise drop the last ref to a bytes object whose buffer a
     * worker is still reading */
    items = PyList_GetSlice(items, 0, n);
    if (!items) return NULL;
    PyObject *out = PyList_New(n);
    if (!out) {
        Py_DECREF(items);
        return NULL;
    }
    const unsigned char **ins = PyMem_Malloc(sizeof(*ins) * (size_t)(n ? n : 1));
    Py_ssize_t *inlens = PyMem_Malloc(sizeof(*inlens) * (size_t)(n ? n : 1));
    unsigned char **outs = PyMem_Malloc(sizeof(*outs) * (size_t)(n ? n : 1));
    if (!ins || !inlens || !outs) {
        PyErr_NoMemory();
        goto fail;
    }
    /* phase 1 (GIL held): pin input pointers, allocate every output. The
     * list keeps each input bytes object alive for the whole call. */
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GetItem(items, i);
        char *buf; Py_ssize_t blen;
        if (PyBytes_AsStringAndSize(item, &buf, &blen) < 0) goto fail;
        Py_ssize_t outlen;
        if (sk) {
            if (blen < (Py_ssize_t)crypto_box_SEALBYTES) {
                PyErr_Format(PyExc_ValueError, "ciphertext %zd too short", i);
                goto fail;
            }
            outlen = blen - crypto_box_SEALBYTES;
        } else {
            outlen = blen + crypto_box_SEALBYTES;
        }
        PyObject *res = PyBytes_FromStringAndSize(NULL, outlen);
        if (!res) goto fail;
        PyList_SET_ITEM(out, i, res);
        ins[i] = (const unsigned char *)buf;
        inlens[i] = blen;
        outs[i] = (unsigned char *)PyBytes_AS_STRING(res);
    }
    /* phase 2 (GIL released): the crypto */
    if (n_threads < 1) n_threads = 1;
    if (n_threads > n) n_threads = n ? n : 1;
    if (n_threads > SEAL_MAX_THREADS) n_threads = SEAL_MAX_THREADS;
    {
        Py_ssize_t first_fail = -1;
        Py_BEGIN_ALLOW_THREADS
        if (n_threads <= 1) {
            sealjob_t job = {n, 0, 1, ins, inlens, outs, pk, sk, -1};
            seal_open_worker(&job);
            first_fail = job.fail;
        } else {
            sealjob_t jobs[SEAL_MAX_THREADS];
            pthread_t tids[SEAL_MAX_THREADS];
            int started[SEAL_MAX_THREADS];
            for (long t = 0; t < n_threads; t++) {
                sealjob_t j = {n, t, n_threads, ins, inlens, outs, pk, sk, -1};
                jobs[t] = j;
                started[t] =
                    pthread_create(&tids[t], NULL, seal_open_worker, &jobs[t]) == 0;
                if (!started[t]) seal_open_worker(&jobs[t]); /* inline fallback */
            }
            for (long t = 0; t < n_threads; t++) {
                if (started[t]) pthread_join(tids[t], NULL);
                if (jobs[t].fail >= 0 &&
                    (first_fail < 0 || jobs[t].fail < first_fail))
                    first_fail = jobs[t].fail;
            }
        }
        Py_END_ALLOW_THREADS
        if (first_fail >= 0) {
            if (sk)
                PyErr_Format(PyExc_ValueError, "sealed box %zd failed to open",
                             first_fail);
            else
                PyErr_Format(PyExc_RuntimeError, "crypto_box_seal failed");
            goto fail;
        }
    }
    PyMem_Free(ins); PyMem_Free(inlens); PyMem_Free(outs);
    Py_DECREF(items);
    return out;
fail:
    PyMem_Free(ins); PyMem_Free(inlens); PyMem_Free(outs);
    Py_DECREF(items);
    Py_DECREF(out);
    return NULL;
}

/* seal_batch(messages: list[bytes], pk: bytes32, n_threads=1) -> list[bytes] */
static PyObject *seal_batch(PyObject *self, PyObject *args) {
    PyObject *msgs;
    Py_buffer pk;
    long n_threads = 1;
    if (!PyArg_ParseTuple(args, "O!y*|l", &PyList_Type, &msgs, &pk, &n_threads))
        return NULL;
    if (pk.len != crypto_box_PUBLICKEYBYTES) {
        PyBuffer_Release(&pk);
        return PyErr_Format(PyExc_ValueError, "public key must be 32 bytes");
    }
    PyObject *out = seal_open_batch(msgs, (const unsigned char *)pk.buf, NULL,
                                    n_threads);
    PyBuffer_Release(&pk);
    return out;
}

/* open_batch(cts: list[bytes], pk: bytes32, sk: bytes32, n_threads=1)
 * -> list[bytes]; raises ValueError naming the lowest forged index. */
static PyObject *open_batch(PyObject *self, PyObject *args) {
    PyObject *cts;
    Py_buffer pk, sk;
    long n_threads = 1;
    if (!PyArg_ParseTuple(args, "O!y*y*|l", &PyList_Type, &cts, &pk, &sk,
                          &n_threads))
        return NULL;
    if (pk.len != crypto_box_PUBLICKEYBYTES || sk.len != crypto_box_SECRETKEYBYTES) {
        PyBuffer_Release(&pk); PyBuffer_Release(&sk);
        return PyErr_Format(PyExc_ValueError, "keys must be 32 bytes");
    }
    PyObject *out = seal_open_batch(cts, (const unsigned char *)pk.buf,
                                    (const unsigned char *)sk.buf, n_threads);
    PyBuffer_Release(&pk);
    PyBuffer_Release(&sk);
    return out;
}

/* ---------------- ChaCha20 mask expansion ----------------
 *
 * Bit-identical to sda_tpu/ops/chacha.py expand_seed: classic djb
 * ChaCha20 keystream (zero nonce, 64-bit counter from 0 — libsodium's
 * crypto_stream_chacha20 layout), words consumed in order as u64 pairs
 * (w[2i] << 32) | w[2i+1], rejection-sampled below the rand-0.3
 * gen_range zone, reduced mod m. Used for the reveal hot loop: expand
 * every participant's seed and fold the masks into one running sum.
 */

#define CHACHA_CHUNK 65536 /* keystream buffer per refill; multiple of 64 */

/* expand one 32-byte key into vals[dim] (mod m), optionally accumulating
 * into acc[dim] (mod m) instead. Returns 0 on success. */
static void chacha_expand_key(const unsigned char *key, Py_ssize_t dim,
                              uint64_t m, int64_t *vals, int64_t *acc) {
    static const unsigned char nonce[8] = {0};
    unsigned char block[CHACHA_CHUNK];
    /* rand-0.3 gen_range(0, m) zone: u64::MAX - u64::MAX % m, accept
     * v < zone (ops/chacha.py rand03_zone — the Python/jnp planes use
     * the same formula; differs from 2^64 - 2^64 % m exactly when m
     * divides 2^64, where rand still rejects the top m values). */
    uint64_t u64_max = ~(uint64_t)0;
    uint64_t zone = u64_max - (u64_max % m);
    uint64_t counter = 0;
    size_t pos = 0, have = 0; /* empty buffer: first iteration refills */
    for (Py_ssize_t i = 0; i < dim;) {
        if (pos + 8 > have) {
            /* size the refill to what's left (+1 block of rejection
             * slack), not the full chunk — small dims would otherwise
             * pay for 64 KiB of keystream per key */
            size_t want = (size_t)(dim - i) * 8 + 64;
            have = want > CHACHA_CHUNK ? CHACHA_CHUNK : (want + 63) / 64 * 64;
            memset(block, 0, have);
            crypto_stream_chacha20_xor_ic(block, block, have, nonce,
                                          counter, key);
            counter += have / 64;
            pos = 0;
        }
        uint32_t w0, w1;
        memcpy(&w0, block + pos, 4); /* keystream words are little-endian */
        memcpy(&w1, block + pos + 4, 4);
        pos += 8;
        uint64_t v = ((uint64_t)w0 << 32) | (uint64_t)w1;
        if (v >= zone) continue;
        int64_t r = (int64_t)(v % m);
        if (acc) {
            acc[i] = (int64_t)(((uint64_t)acc[i] + (uint64_t)r) % m);
        } else {
            vals[i] = r;
        }
        i++;
    }
}

/* chacha_expand(key32: bytes, dim, modulus) -> bytes of int64 LE */
static PyObject *chacha_expand(PyObject *self, PyObject *args) {
    Py_buffer key;
    Py_ssize_t dim;
    unsigned long long modulus;
    if (!PyArg_ParseTuple(args, "y*nK", &key, &dim, &modulus)) return NULL;
    if (key.len != 32 || dim < 0 || modulus == 0 || modulus > (1ULL << 63)) {
        PyBuffer_Release(&key);
        return PyErr_Format(PyExc_ValueError,
                            "need 32-byte key, dim >= 0, 0 < modulus <= 2^63");
    }
    PyObject *res = PyBytes_FromStringAndSize(NULL, dim * 8);
    if (!res) { PyBuffer_Release(&key); return NULL; }
    int64_t *out = (int64_t *)PyBytes_AS_STRING(res);
    Py_BEGIN_ALLOW_THREADS
    chacha_expand_key((const unsigned char *)key.buf, dim, (uint64_t)modulus,
                      out, NULL);
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&key);
    return res;
}

/* chacha_combine(keys: bytes (n*32), dim, modulus) -> bytes of int64 LE:
 * elementwise sum mod m of every key's expanded mask. */
static PyObject *chacha_combine(PyObject *self, PyObject *args) {
    Py_buffer keys;
    Py_ssize_t dim;
    unsigned long long modulus;
    if (!PyArg_ParseTuple(args, "y*nK", &keys, &dim, &modulus)) return NULL;
    if (keys.len % 32 != 0 || dim < 0 || modulus == 0 || modulus > (1ULL << 63)) {
        PyBuffer_Release(&keys);
        return PyErr_Format(PyExc_ValueError,
                            "need n*32-byte keys, dim >= 0, 0 < modulus <= 2^63");
    }
    Py_ssize_t n = keys.len / 32;
    PyObject *res = PyBytes_FromStringAndSize(NULL, dim * 8);
    if (!res) { PyBuffer_Release(&keys); return NULL; }
    int64_t *acc = (int64_t *)PyBytes_AS_STRING(res);
    memset(acc, 0, (size_t)dim * 8);
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t s = 0; s < n; s++) {
        chacha_expand_key((const unsigned char *)keys.buf + s * 32, dim,
                          (uint64_t)modulus, NULL, acc);
    }
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&keys);
    return res;
}

static PyMethodDef methods[] = {
    {"varint_encode", varint_encode, METH_VARARGS,
     "zigzag-LEB128 encode a buffer of little-endian int64"},
    {"varint_decode", varint_decode, METH_VARARGS,
     "decode a zigzag-LEB128 stream to little-endian int64 bytes"},
    {"seal_batch", seal_batch, METH_VARARGS, "sealed-box encrypt a batch"},
    {"open_batch", open_batch, METH_VARARGS, "sealed-box decrypt a batch"},
    {"chacha_expand", chacha_expand, METH_VARARGS,
     "expand one 32-byte ChaCha20 key to int64 mask bytes mod m"},
    {"chacha_combine", chacha_combine, METH_VARARGS,
     "sum of expanded masks mod m over n concatenated 32-byte keys"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_sdanative", "native varint + sodium batch ops",
    -1, methods,
};

PyMODINIT_FUNC PyInit__sdanative(void) {
    if (sodium_init() < 0) {
        PyErr_SetString(PyExc_RuntimeError, "sodium_init failed");
        return NULL;
    }
    return PyModule_Create(&module);
}
