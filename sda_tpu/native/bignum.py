"""OpenSSL bignum bindings for the Paillier plane's modular arithmetic.

The reference's native dependencies (libsodium, the tss crate) cover its
crypto; PackedPaillier — the reference's sketched scale-up variant that we
implement — lives on modular exponentiation over 2048-bit+ moduli, where
CPython's ``pow`` is ~5-6x slower than OpenSSL's Montgomery/windowed
``BN_mod_exp`` (measured on this image: 46.8 ms vs 8.4 ms for a 4096-bit
modexp). These ctypes bindings route the hot ops through
``libcrypto.so.3`` with a pure-Python fallback, in the same spirit as
``_sdanative.c``'s libsodium bindings: link the system library the
platform already ships, never reimplement the math.

Thread safety: ``BN_CTX`` is not thread-safe; every public helper uses
thread-local scratch state (clerks/REST handlers run threaded).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading
import weakref

_local = threading.local()
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    name = ctypes.util.find_library("crypto")
    if not name:
        raise OSError("libcrypto not found")
    lib = ctypes.CDLL(name)
    lib.BN_new.restype = ctypes.c_void_p
    lib.BN_CTX_new.restype = ctypes.c_void_p
    lib.BN_bin2bn.restype = ctypes.c_void_p
    lib.BN_bin2bn.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_void_p]
    lib.BN_bn2bin.restype = ctypes.c_int
    lib.BN_bn2bin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.BN_num_bits.restype = ctypes.c_int
    lib.BN_num_bits.argtypes = [ctypes.c_void_p]
    lib.BN_mod_exp.restype = ctypes.c_int
    lib.BN_mod_exp.argtypes = [ctypes.c_void_p] * 4 + [ctypes.c_void_p]
    lib.BN_mod_mul.restype = ctypes.c_int
    lib.BN_mod_mul.argtypes = [ctypes.c_void_p] * 4 + [ctypes.c_void_p]
    lib.BN_free.restype = None
    lib.BN_free.argtypes = [ctypes.c_void_p]
    lib.BN_CTX_free.restype = None
    lib.BN_CTX_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    try:
        _load()
        return True
    except OSError:
        return False


class _Scratch:
    """Per-thread BN_CTX + four scratch BNs, reused across calls; the
    native allocations are released when the owning thread's local state
    is collected (ThreadingHTTPServer spawns a thread per request — a
    leak here would grow one BN_CTX+4BN set per request)."""

    def __init__(self, lib):
        self.lib = lib
        self.ctx = ctypes.c_void_p(lib.BN_CTX_new())
        self.bn = [ctypes.c_void_p(lib.BN_new()) for _ in range(4)]
        weakref.finalize(self, _free_scratch, lib, self.ctx, list(self.bn))

    def set(self, i: int, x: int):
        b = x.to_bytes((x.bit_length() + 7) // 8 or 1, "big")
        self.lib.BN_bin2bn(b, len(b), self.bn[i])
        return self.bn[i]

    def get(self, i: int) -> int:
        nbytes = (self.lib.BN_num_bits(self.bn[i]) + 7) // 8
        if nbytes == 0:
            return 0
        buf = ctypes.create_string_buffer(nbytes)
        self.lib.BN_bn2bin(self.bn[i], buf)
        return int.from_bytes(buf.raw, "big")


def _free_scratch(lib, ctx, bns):
    for bn in bns:
        lib.BN_free(bn)
    lib.BN_CTX_free(ctx)


def _scratch() -> _Scratch:
    s = getattr(_local, "scratch", None)
    if s is None:
        s = _local.scratch = _Scratch(_load())
    return s


def mod_exp(base: int, exp: int, mod: int) -> int:
    """``base ** exp % mod`` for nonnegative operands via BN_mod_exp."""
    if base < 0 or exp < 0 or mod <= 0:
        raise ValueError("mod_exp needs nonnegative base/exp and positive mod")
    s = _scratch()
    r = s.bn[3]
    if not s.lib.BN_mod_exp(r, s.set(0, base), s.set(1, exp), s.set(2, mod), s.ctx):
        raise ArithmeticError("BN_mod_exp failed")
    return s.get(3)


def best_mod_exp(min_bits: int = 0):
    """The fastest available ``(base, exp, mod) -> int`` modexp.

    Returns :func:`mod_exp` when libcrypto loads, builtin ``pow``
    otherwise. With ``min_bits`` set, the returned callable routes each
    call by modulus size: below the threshold the ctypes round-trip costs
    more than it saves, so small (field-modulus) operands stay on
    ``pow``. The single selection point for every caller (ops/paillier,
    ops/params)."""
    if not available():
        return pow
    if min_bits <= 0:
        return mod_exp

    def routed(base: int, exp: int, mod: int) -> int:
        if mod.bit_length() >= min_bits:
            return mod_exp(base, exp, mod)
        return pow(base, exp, mod)

    return routed


def mod_mul(a: int, b: int, mod: int) -> int:
    """``a * b % mod`` for nonnegative operands via BN_mod_mul."""
    if a < 0 or b < 0 or mod <= 0:
        raise ValueError("mod_mul needs nonnegative operands and positive mod")
    s = _scratch()
    r = s.bn[3]
    if not s.lib.BN_mod_mul(r, s.set(0, a), s.set(1, b), s.set(2, mod), s.ctx):
        raise ArithmeticError("BN_mod_mul failed")
    return s.get(3)
