"""sda_tpu.native — C acceleration layer with pure-Python fallbacks.

``available()`` reports whether the compiled extension loaded; the crypto
modules route bulk work through here either way.
"""

from __future__ import annotations

import numpy as np

try:
    from . import _sdanative as _ext
except ImportError:  # not built; fall back to the vectorized Python paths
    _ext = None


def available() -> bool:
    return _ext is not None


def varint_encode(values: np.ndarray) -> bytes:
    if _ext is not None:
        return _ext.varint_encode(np.ascontiguousarray(values, dtype="<i8").tobytes())
    from ..crypto import varint

    return varint.encode_i64(values)


def varint_decode(buf: bytes) -> np.ndarray:
    if _ext is not None:
        return np.frombuffer(_ext.varint_decode(buf), dtype="<i8")
    from ..crypto import varint

    return varint.decode_i64(buf)


def seal_batch(messages: list, public_key: bytes) -> list:
    if _ext is not None:
        return _ext.seal_batch(list(messages), public_key)
    from ..crypto import sodium

    return [sodium.seal(m, public_key) for m in messages]


def open_batch(ciphertexts: list, public_key: bytes, secret_key: bytes) -> list:
    if _ext is not None:
        return _ext.open_batch(list(ciphertexts), public_key, secret_key)
    from ..crypto import sodium

    return [sodium.seal_open(c, public_key, secret_key) for c in ciphertexts]
