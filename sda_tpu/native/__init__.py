"""sda_tpu.native — C acceleration layer with pure-Python fallbacks.

``available()`` reports whether the compiled extension loaded; the crypto
modules route bulk work through here either way. Each bulk entry point
counts its work into the telemetry plane labelled by the path actually
taken (``comb`` / ``batch`` for the C plane, ``scalar`` / ``python`` for
the fallbacks), so a scrape shows at a glance whether production traffic
is riding the accelerated plane or silently falling back.
"""

from __future__ import annotations

import sys

import numpy as np

from .. import telemetry

try:
    from . import _sdanative as _ext
except ImportError:  # not built; fall back to the vectorized Python paths
    _ext = None

if sys.byteorder != "little":
    # the C plane reads ChaCha keystream words and writes int64
    # accumulators in native byte order while Python reads the buffers
    # back as explicit little-endian ('<i8'/'<u4'); on a big-endian host
    # the two planes would silently produce different masks. No such
    # host exists in this deployment — refuse rather than risk it.
    _ext = None


def available() -> bool:
    return _ext is not None


def varint_encode(values: np.ndarray) -> bytes:
    if _ext is not None:
        return _ext.varint_encode(np.ascontiguousarray(values, dtype="<i8").tobytes())
    from ..crypto import varint

    return varint.encode_i64(values)


def varint_decode(buf: bytes) -> np.ndarray:
    if _ext is not None:
        return np.frombuffer(_ext.varint_decode(buf), dtype="<i8")
    from ..crypto import varint

    return varint.decode_i64(buf)


def _default_threads() -> int:
    """Sealed-box worker threads: ``SDA_NATIVE_THREADS`` if set, else one
    per CPU. The C plane chunks the batch across a pthread pool with the
    GIL released — results are independent of the thread count (each item
    is sealed/opened by exactly one thread)."""
    import os

    env = os.environ.get("SDA_NATIVE_THREADS")
    if env:
        return max(1, int(env))
    return os.cpu_count() or 1


def _count_seals(n: int, path: str) -> None:
    telemetry.counter(
        "sda_crypto_seals_total", "sealed boxes produced by crypto path", path=path
    ).inc(n)


def _count_opens(n: int, path: str) -> None:
    telemetry.counter(
        "sda_crypto_opens_total", "sealed boxes opened by crypto path", path=path
    ).inc(n)


def _count_chacha(n: int, path: str) -> None:
    telemetry.counter(
        "sda_crypto_chacha_expands_total",
        "ChaCha mask seeds expanded/combined by path",
        path=path,
    ).inc(n)


def seal_batch(messages: list, public_key: bytes, n_threads: int | None = None) -> list:
    if _ext is not None:
        _count_seals(len(messages), "batch")
        return _ext.seal_batch(
            list(messages), public_key, n_threads or _default_threads()
        )
    from ..crypto import sodium

    _count_seals(len(messages), "scalar")
    return [sodium.seal(m, public_key) for m in messages]


def open_batch(
    ciphertexts: list, public_key: bytes, secret_key: bytes, n_threads: int | None = None
) -> list:
    if _ext is not None:
        _count_opens(len(ciphertexts), "batch")
        return _ext.open_batch(
            list(ciphertexts), public_key, secret_key, n_threads or _default_threads()
        )
    from ..crypto import sodium

    _count_opens(len(ciphertexts), "scalar")
    return [sodium.seal_open(c, public_key, secret_key) for c in ciphertexts]


def seal_participations(
    share_matrix: list, public_keys: list, n_threads: int | None = None
) -> list:
    """Seal a ``P x C`` matrix of share messages to ``C`` clerk public keys:
    ``result[p][c]`` is ``share_matrix[p][c]`` sealed to ``public_keys[c]``.

    The C plane shares one ephemeral keypair per participant across that
    participant's ``C`` sealed boxes and amortizes the X25519 scalarmults
    with per-clerk comb tables, so large batches seal at ~(1 + 1/C)
    comb-multiplications per share instead of two Montgomery ladders.
    Every output stays a standard ``crypto_box_seal`` sealed box."""
    n = len(share_matrix) * len(public_keys)
    if _ext is not None:
        _count_seals(n, "comb")
        return _ext.seal_participations(
            [list(row) for row in share_matrix],
            list(public_keys),
            n_threads or _default_threads(),
        )
    from ..crypto import sodium

    _count_seals(n, "scalar")
    return [
        [sodium.seal(m, pk) for m, pk in zip(row, public_keys)]
        for row in share_matrix
    ]


def _chacha_keys(seed_rows: np.ndarray) -> bytes:
    """(n, <=8) u32 seed words -> n concatenated 32-byte ChaCha keys
    (little-endian words, zero-padded — the expand_seed key layout)."""
    rows = np.asarray(seed_rows, dtype=np.uint32)
    if rows.ndim == 1:
        rows = rows[None, :]
    keys = np.zeros((rows.shape[0], 8), dtype="<u4")
    keys[:, : rows.shape[1]] = rows
    return keys.tobytes()


def chacha_expand(seed_words, dim: int, modulus: int) -> np.ndarray:
    """One seed -> (dim,) int64 mask in [0, modulus); bit-identical to
    ``ops.chacha.expand_seed`` (the fallback when the extension is
    absent). Moduli above 2^63 raise in the fallback: int64 masks would
    wrap negative (no legal i64 scheme modulus reaches there)."""
    if _ext is not None and 0 < modulus <= (1 << 63):
        _count_chacha(1, "native")
        buf = _ext.chacha_expand(_chacha_keys(seed_words), int(dim), int(modulus))
        return np.frombuffer(buf, dtype="<i8").copy()
    from ..ops.chacha import expand_seed

    _count_chacha(1, "python")
    return expand_seed(np.asarray(seed_words, dtype=np.uint32), dim, modulus)


def chacha_combine(seed_rows, dim: int, modulus: int) -> np.ndarray:
    """Sum of every seed's expanded mask, elementwise mod modulus —
    the reveal hot loop, one C call for the whole cohort."""
    rows = np.asarray(seed_rows, dtype=np.uint32)
    n_seeds = int(np.prod(rows.shape[:-1])) if rows.ndim > 1 else 1
    if _ext is not None and 0 < modulus <= (1 << 63):
        _count_chacha(n_seeds, "native")
        buf = _ext.chacha_combine(_chacha_keys(rows), int(dim), int(modulus))
        return np.frombuffer(buf, dtype="<i8").copy()
    from ..ops.chacha import expand_seed

    _count_chacha(n_seeds, "python")

    # uint64 accumulate: two values each < m can exceed int64 for moduli
    # above 2^62, but their uint64 sum is < 2^64 — identical to the C path
    result = np.zeros(dim, dtype=np.uint64)
    mu = np.uint64(modulus)
    for row in rows.reshape(-1, rows.shape[-1]):
        result = (result + expand_seed(row, dim, modulus).astype(np.uint64)) % mu
    return result.astype(np.int64)
