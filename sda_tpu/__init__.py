"""sda_tpu — a TPU-native secure distributed aggregation framework.

Capabilities of snipsco/sda (reference at /root/reference), re-based on
JAX/XLA for the math plane:

- ``protocol``: the wire contract (resources, schemes, service interface).
- ``ops``: mod-p field math (NTT, Lagrange, RNG) as numpy + JAX kernels.
- ``crypto``: masking / sharing / transport-encryption / signing schemes.
- ``client``: participant / clerk / recipient role logic.
- ``server``: orchestration server, stores, snapshot pipeline.
- ``rest``: HTTP binding of the service seam (server + client proxy).
- ``parallel``: the TPU aggregation fabric (mesh sharding, collectives).
- ``cli``: ``sda`` (agent) and ``sdad`` (server daemon) command lines.

Heavy dependencies (JAX, libsodium) are imported lazily by the modules that
need them, so protocol-only use stays light.
"""

__version__ = "0.1.0"
