"""Shared worker pool for bulk-crypto sub-range dispatch.

The native seal/open kernels release the GIL (``Py_BEGIN_ALLOW_THREADS``
around every libsodium hot loop), so a plain thread pool yields true
multi-core crypto. This module owns the one process-wide pool: callers
hand :func:`map_items` a list and a kernel that processes a contiguous
sub-range, and get back the concatenated results in input order.

Sizing: ``SDA_WORKERS`` in the environment, else ``os.cpu_count()``.
``SDA_WORKERS=1`` (or a single-item batch) bypasses the pool entirely —
the kernel is invoked once on the whole list with ``n_threads=None``,
which is today's serial call, bit for bit.

Determinism: sub-ranges are contiguous and results are gathered in
submission order, so output item *i* always corresponds to input item
*i* exactly as in the serial path. Deterministic kernels (``open``) are
therefore byte-identical at any worker count; randomized kernels
(``seal`` draws an ephemeral keypair per box) differ only by that
randomness and open to identical plaintexts.

Oversubscription: the native batch entry points spawn their own
pthreads (``SDA_NATIVE_THREADS``, default cpu_count). When this pool is
active each sub-range kernel receives ``n_threads=1`` so the total
thread count stays at the pool size; the serial path passes ``None`` to
keep the native default.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, TypeVar

from .. import telemetry

T = TypeVar("T")
R = TypeVar("R")

_WORKERS_HELP = "configured crypto worker-pool size"
_TASK_HELP = "per-sub-range pool task latency, by operation"
_UTIL_HELP = "busy-time fraction of the last pooled dispatch (sum(task)/(wall*workers))"


def workers() -> int:
    """Configured pool size: ``SDA_WORKERS`` env, else ``os.cpu_count()``."""
    raw = os.environ.get("SDA_WORKERS")
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            raise ValueError(f"SDA_WORKERS must be an integer, got {raw!r}") from None
    return os.cpu_count() or 1


_pool: ThreadPoolExecutor | None = None
_pool_size = 0
_pool_lock = threading.Lock()


def _executor(size: int) -> ThreadPoolExecutor:
    """The shared executor, rebuilt if the configured size changed
    (bench sweeps flip ``SDA_WORKERS`` between configs)."""
    global _pool, _pool_size
    with _pool_lock:
        if _pool is None or _pool_size != size:
            if _pool is not None:
                _pool.shutdown(wait=False)
            _pool = ThreadPoolExecutor(max_workers=size, thread_name_prefix="sda-pool")
            _pool_size = size
        return _pool


def split_ranges(n: int, parts: int) -> List[tuple]:
    """Balanced contiguous ``[start, end)`` bounds covering ``range(n)``."""
    parts = max(1, min(parts, n))
    base, extra = divmod(n, parts)
    bounds, start = [], 0
    for i in range(parts):
        end = start + base + (1 if i < extra else 0)
        bounds.append((start, end))
        start = end
    return bounds


def map_items(
    op: str,
    items: Sequence[T],
    kernel: Callable[[Sequence[T], "int | None"], List[R]],
) -> List[R]:
    """Run ``kernel(sub_range, n_threads)`` over ``items``, pooled.

    ``kernel`` must map a contiguous sub-list to a result list of the
    same length. With one worker (or one item) it is called exactly once
    as ``kernel(items, None)`` — the unchanged serial path. Otherwise the
    list is split into at most ``workers()`` contiguous sub-ranges, each
    dispatched to the shared pool with ``n_threads=1``, and the result
    lists are concatenated in input order. The first failing sub-range's
    exception propagates.

    ``op`` is a small fixed label ("seal"/"open"/"share_matrix") for the
    ``sda_pool_task_seconds`` series — never unbounded values.
    """
    n = workers()
    telemetry.gauge("sda_pool_workers", _WORKERS_HELP).set(n)
    if n <= 1 or len(items) <= 1:
        return kernel(items, None)

    bounds = split_ranges(len(items), n)
    task_hist = telemetry.histogram("sda_pool_task_seconds", _TASK_HELP, op=op)
    busy = [0.0] * len(bounds)

    def run(ix: int, lo: int, hi: int) -> List[R]:
        t0 = time.perf_counter()
        try:
            return kernel(items[lo:hi], 1)
        finally:
            busy[ix] = time.perf_counter() - t0
            task_hist.observe(busy[ix])

    wall0 = time.perf_counter()
    pool = _executor(n)
    futures = [pool.submit(run, ix, lo, hi) for ix, (lo, hi) in enumerate(bounds)]
    out: List[R] = []
    for f in futures:  # submission order: deterministic in-order reassembly
        out.extend(f.result())
    wall = time.perf_counter() - wall0
    if wall > 0:
        telemetry.gauge("sda_pool_utilization", _UTIL_HELP).set(
            min(1.0, sum(busy) / (wall * n))
        )
    return out


@dataclass
class TaskOutcome:
    """One :func:`scatter` task's result: exactly one of ``value`` /
    ``error`` is meaningful unless the task was ``cancelled`` before it
    ran (then both stay None). ``seconds`` is the task's busy time — the
    per-lane numerator of the dispatch's overlap efficiency."""

    value: object = None
    error: Optional[BaseException] = None
    seconds: float = 0.0
    cancelled: bool = False


def scatter(
    op: str,
    tasks: Sequence[Callable[[], object]],
    width: int,
    *,
    cancel_on_error: bool = False,
) -> List[TaskOutcome]:
    """Run independent zero-arg ``tasks`` through a bounded pool of
    ``width`` threads; returns one :class:`TaskOutcome` per task, in
    task order regardless of completion order.

    Unlike :func:`map_items` (contiguous sub-ranges of one kernel), this
    is whole-task dispatch for heterogeneous work — per-node tier closes,
    per-clerk committee drains — where each task blocks on its own I/O.
    The caller's trace id is rebound into every worker, so all tasks'
    spans join the dispatching round's trace.

    ``cancel_on_error=True`` makes the first failing task cancel every
    sibling that has not started yet (queued futures are cancelled AND
    workers re-check before running); already-running siblings finish.
    Failures never raise here — the caller inspects the outcomes so it
    can keep strict re-raise / non-strict skip semantics deterministic.

    A dedicated short-lived executor is used instead of the shared
    crypto pool above: tasks routinely call back into :func:`map_items`,
    and queueing them on the pool their own sub-ranges need is a
    textbook nested-dispatch deadlock.

    ``width <= 1`` (or a single task) runs everything inline on the
    caller's thread in order — the serial path, bit for bit.
    """
    tasks = list(tasks)
    outcomes = [TaskOutcome() for _ in tasks]
    if not tasks:
        return outcomes
    width = max(1, min(width, len(tasks)))
    task_hist = telemetry.histogram("sda_pool_task_seconds", _TASK_HELP, op=op)
    stop = threading.Event()
    trace_id = telemetry.current_trace_id()

    def run(ix: int, task: Callable[[], object]) -> None:
        if cancel_on_error and stop.is_set():
            outcomes[ix].cancelled = True
            return
        if trace_id:
            telemetry.set_trace_id(trace_id)
        t0 = time.perf_counter()
        try:
            outcomes[ix].value = task()
        except BaseException as exc:  # noqa: BLE001 — surfaced via outcome
            outcomes[ix].error = exc
            if cancel_on_error:
                stop.set()
        finally:
            outcomes[ix].seconds = time.perf_counter() - t0
            task_hist.observe(outcomes[ix].seconds)

    if width <= 1 or len(tasks) <= 1:
        for ix, task in enumerate(tasks):
            run(ix, task)
            if cancel_on_error and stop.is_set():
                for rest in outcomes[ix + 1:]:
                    rest.cancelled = True
                break
        return outcomes

    wall0 = time.perf_counter()
    with ThreadPoolExecutor(
        max_workers=width, thread_name_prefix="sda-fanout"
    ) as pool:
        futures = [pool.submit(run, ix, t) for ix, t in enumerate(tasks)]
        for ix, f in enumerate(futures):
            try:
                f.result()
            except Exception:
                # a future cancelled before its worker started
                pass
            if cancel_on_error and stop.is_set():
                for rest in futures[ix + 1:]:
                    rest.cancel()
        for ix, f in enumerate(futures):
            if f.cancelled():
                outcomes[ix].cancelled = True
    wall = time.perf_counter() - wall0
    if wall > 0:
        telemetry.gauge("sda_pool_utilization", _UTIL_HELP).set(
            min(1.0, sum(o.seconds for o in outcomes) / (wall * width))
        )
    return outcomes
