"""Deterministic fault injection + retry/backoff primitives.

The churn-and-chaos plane's transport half: a seed-driven fault plane
that the REST client and server interpose to inject the failures flaky
cloud transport actually produces — dead connections, transient 5xx
with Retry-After, latency spikes, truncated response bodies — plus the
jittered exponential ``Backoff`` the hardened client and the daemon
poll loops share.

Spec grammar (``SDA_FAULTS=<spec>:<seed>``)::

    spec  := rule ("," rule)*
    rule  := [side "."] kind "=" rate ["@" param]
    side  := "client" | "server"          (default: server)
    kind  := "drop"     — kill the connection without an HTTP response
           | "e503"     — answer 503; param = Retry-After seconds (0.05)
           | "latency"  — stall before handling; param = seconds (0.05)
           | "truncate" — declare the full Content-Length but send half
           | "reset"    — send half the body then abort the connection
                          (the mid-response-body RST flaky LBs produce)
    rate  := probability in [0, 1] that a request draws this fault
    seed  := integer (default 0)

Examples::

    SDA_FAULTS=e503=0.1@0.2:42
    SDA_FAULTS=drop=0.05,latency=0.2@0.01,truncate=0.05:7
    SDA_FAULTS=client.drop=0.1,e503=0.1:3

Determinism: the fault drawn for the N-th request on a side is a pure
function of (seed, N) — ``FaultPlane.decide(n)`` — so the same spec and
seed replay the same failure sequence regardless of wall clock or PID.
Each request draws at most one fault (rules partition one uniform
draw), and the client and server sides count requests independently.

The plane is OFF unless ``SDA_FAULTS`` is set; the interposition points
check a cached module accessor (one env read) per request, so the cost
when disabled is a dict lookup.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass

from .. import telemetry

SPEC_ENV = "SDA_FAULTS"

KINDS = ("drop", "e503", "latency", "truncate", "reset")

#: default per-kind parameter (seconds: Retry-After for e503, stall for
#: latency; drop/truncate/reset take no parameter)
_DEFAULT_PARAM = {
    "drop": 0.0,
    "e503": 0.05,
    "latency": 0.05,
    "truncate": 0.0,
    "reset": 0.0,
}


@dataclass(frozen=True)
class Fault:
    kind: str
    param: float


@dataclass(frozen=True)
class Rule:
    side: str  # "client" | "server"
    kind: str
    rate: float
    param: float


def parse_spec(text: str) -> tuple[list[Rule], int]:
    """Parse ``<spec>:<seed>`` into (rules, seed). Raises ValueError on
    unknown kinds/sides, rates outside [0, 1], or per-side rates summing
    past 1 (the rules partition a single uniform draw)."""
    text = text.strip()
    if not text:
        raise ValueError("empty SDA_FAULTS spec")
    spec, seed = text, 0
    if ":" in text:
        spec, _, tail = text.rpartition(":")
        try:
            seed = int(tail)
        except ValueError:
            raise ValueError(f"SDA_FAULTS seed must be an integer, got {tail!r}")
    rules = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        lhs, eq, rhs = item.partition("=")
        if not eq:
            raise ValueError(f"SDA_FAULTS rule {item!r} is not kind=rate[@param]")
        side, dot, kind = lhs.partition(".")
        if not dot:
            side, kind = "server", lhs
        if side not in ("client", "server"):
            raise ValueError(f"SDA_FAULTS side must be client or server, got {side!r}")
        if kind not in KINDS:
            raise ValueError(f"unknown SDA_FAULTS kind {kind!r} (know {KINDS})")
        rate_text, at, param_text = rhs.partition("@")
        rate = float(rate_text)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"SDA_FAULTS rate for {kind} must be in [0,1], got {rate}")
        param = float(param_text) if at else _DEFAULT_PARAM[kind]
        if param < 0:
            raise ValueError(f"SDA_FAULTS param for {kind} must be >= 0, got {param}")
        rules.append(Rule(side=side, kind=kind, rate=rate, param=param))
    if not rules:
        raise ValueError("SDA_FAULTS spec has no rules")
    for side in ("client", "server"):
        total = sum(r.rate for r in rules if r.side == side)
        if total > 1.0 + 1e-9:
            raise ValueError(f"{side}-side SDA_FAULTS rates sum to {total} > 1")
    return rules, seed


def _unit(seed: int, index: int) -> float:
    """One uniform draw in [0, 1) as a pure function of (seed, index).
    Mersenne-Twister int seeding is stable across platforms and runs,
    so the whole failure sequence replays from the spec alone."""
    return random.Random((seed * 1_000_003 + index) & 0xFFFFFFFFFFFFFFFF).random()


class FaultPlane:
    """One side's view of a parsed spec: a thread-safe request counter
    plus the pure (seed, index) -> fault decision."""

    def __init__(self, rules: list[Rule], seed: int, side: str):
        self.rules = tuple(r for r in rules if r.side == side)
        self.seed = seed
        self.side = side
        self._lock = threading.Lock()
        self._index = 0

    def decide(self, index: int) -> Fault | None:
        """The deterministic core: walk the rules through one uniform
        draw, so a request suffers at most one fault."""
        u = _unit(self.seed, index)
        acc = 0.0
        for rule in self.rules:
            acc += rule.rate
            if u < acc:
                return Fault(rule.kind, rule.param)
        return None

    def draw(self) -> Fault | None:
        """Decide for the next request index (counted per side)."""
        with self._lock:
            index = self._index
            self._index += 1
        fault = self.decide(index)
        if fault is not None and telemetry.enabled():
            telemetry.counter(
                "sda_fault_injections_total",
                "faults injected by the SDA_FAULTS plane, by side and kind",
                side=self.side,
                kind=fault.kind,
            ).inc()
        return fault


# planes are cached per (spec text, side) so the request counter — and
# with it the deterministic failure sequence — survives across requests;
# changing the env spec mid-process starts a fresh sequence
_cache_lock = threading.Lock()
_planes: dict = {}


def plane(side: str) -> FaultPlane | None:
    text = os.environ.get(SPEC_ENV)
    if not text:
        return None
    key = (text, side)
    with _cache_lock:
        cached = _planes.get(key)
        if cached is None and key not in _planes:
            rules, seed = parse_spec(text)
            built = FaultPlane(rules, seed, side)
            cached = _planes[key] = built if built.rules else None
        return cached


def client_draw() -> Fault | None:
    p = plane("client")
    return p.draw() if p is not None else None


def server_draw() -> Fault | None:
    p = plane("server")
    return p.draw() if p is not None else None


class Backoff:
    """Jittered exponential backoff (full jitter): delay i is uniform in
    [0, min(cap, base * factor**i)], optionally floored by a server's
    Retry-After. Shared by the REST client's retry loop and the
    clerk/committee daemon poll loops — ``reset()`` after useful work so
    a busy queue drains at ``base`` cadence while an idle or stalled
    peer is probed at most every ``cap`` seconds.
    """

    def __init__(self, base: float = 0.05, factor: float = 2.0, cap: float = 2.0,
                 rng: random.Random | None = None):
        self.base = base
        self.factor = factor
        self.cap = cap
        self._attempt = 0
        self._rng = rng if rng is not None else random.Random()

    def ceiling(self) -> float:
        """The next delay's upper bound (before jitter)."""
        return min(self.cap, self.base * self.factor ** self._attempt)

    def next_delay(self, floor: float = 0.0) -> float:
        delay = self._rng.uniform(0.0, self.ceiling())
        self._attempt += 1
        return max(floor, delay)

    def sleep(self, floor: float = 0.0) -> float:
        delay = self.next_delay(floor)
        if delay > 0:
            time.sleep(delay)
        return delay

    def reset(self) -> None:
        self._attempt = 0
