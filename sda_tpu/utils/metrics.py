"""Phase metrics and tracing.

The reference has no timers or counters anywhere (SURVEY.md §5); this is a
from-scratch aux subsystem: lightweight wall-clock phase timers + counters
with a process-global registry, used by the server snapshot pipeline, the
clerk hot path, reveal, and the bench harness. ``jax_trace`` wraps the JAX
profiler for device-level traces.

Exposed over REST as ``GET /v1/metrics`` (an additive route — the reference
wire protocol is untouched otherwise).
"""

from __future__ import annotations

import contextlib
import threading
import time


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._timers: dict = {}  # name -> [count, total_s, max_s]

    def count(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                entry = self._timers.setdefault(name, [0, 0.0, 0.0])
                entry[0] += 1
                entry[1] += dt
                entry[2] = max(entry[2], dt)

    def report(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "phases": {
                    name: {
                        "count": c,
                        "total_s": round(total, 6),
                        "mean_s": round(total / c, 6) if c else 0.0,
                        "max_s": round(mx, 6),
                    }
                    for name, (c, total, mx) in self._timers.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()


_GLOBAL = Metrics()


def get_metrics() -> Metrics:
    return _GLOBAL


@contextlib.contextmanager
def jax_trace(log_dir: str):
    """Capture a JAX/XLA device profile (TensorBoard trace format)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
