"""Legacy phase-metrics facade over the telemetry plane.

The original from-scratch aux subsystem (the reference has no timers or
counters anywhere, SURVEY.md §5) kept its own locked dicts; it is now an
adapter over :mod:`sda_tpu.telemetry` so the snapshot pipeline and clerk
hot path feed the same registry everything else samples:

- ``count(name)``  -> ``sda_events_total{event=name}``
- ``phase(name)``  -> ``sda_phase_seconds{phase=name}`` plus a
  ``phase.<name>`` span, so legacy timers join trace-id correlation.

``report()`` keeps the historical shape (``counters`` + ``phases`` with
count/total/mean/max) and ``reset()`` keeps its windowing semantics by
baseline subtraction — it never wipes the process registry out from
under other consumers. One caveat survives the adaptation: ``max_s`` is
the max since process start, not since ``reset()`` (histogram cells keep
a running max, not a window). ``jax_trace`` wraps the JAX profiler for
device-level traces, as before.
"""

from __future__ import annotations

import contextlib
import time

from .. import telemetry

_EVENTS = "sda_events_total"
_PHASES = "sda_phase_seconds"


def _collect() -> tuple:
    """(counters by event, phases by name -> (count, total_s, max_s))
    from the current registry snapshot."""
    snap = telemetry.get_registry().snapshot()
    counters = {
        dict(labels)["event"]: value
        for (name, labels), value in snap["counters"].items()
        if name == _EVENTS
    }
    phases = {
        dict(labels)["phase"]: (hist["count"], hist["sum"], hist["max"])
        for (name, labels), hist in snap["histograms"].items()
        if name == _PHASES
    }
    return counters, phases


class Metrics:
    def __init__(self):
        # report() windows: totals at the last reset(), subtracted out
        self._base_counters: dict = {}
        self._base_phases: dict = {}

    def count(self, name: str, delta: int = 1) -> None:
        telemetry.counter(_EVENTS, "legacy Metrics.count events", event=name).inc(
            delta
        )

    @contextlib.contextmanager
    def phase(self, name: str):
        hist = telemetry.histogram(
            _PHASES, "legacy Metrics.phase timers", phase=name
        )
        t0 = time.perf_counter()
        with telemetry.span(f"phase.{name}"):
            try:
                yield
            finally:
                # observed even when the phase body raises (legacy semantics)
                hist.observe(time.perf_counter() - t0)

    def report(self) -> dict:
        counters, phases = _collect()
        out_counters = {}
        for name, value in counters.items():
            windowed = value - self._base_counters.get(name, 0)
            if windowed:
                out_counters[name] = windowed
        out_phases = {}
        for name, (count, total, mx) in phases.items():
            base_count, base_total = self._base_phases.get(name, (0, 0.0))
            c = count - base_count
            if not c:
                continue
            total = total - base_total
            out_phases[name] = {
                "count": c,
                "total_s": round(total, 6),
                "mean_s": round(total / c, 6),
                "max_s": round(mx, 6),
            }
        return {"counters": out_counters, "phases": out_phases}

    def reset(self) -> None:
        counters, phases = _collect()
        self._base_counters = counters
        self._base_phases = {
            name: (count, total) for name, (count, total, _) in phases.items()
        }


_GLOBAL = Metrics()


def get_metrics() -> Metrics:
    return _GLOBAL


@contextlib.contextmanager
def jax_trace(log_dir: str):
    """Capture a JAX/XLA device profile (TensorBoard trace format)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
