"""Shared atomic JSON-per-object directory store.

One ``<id>.json`` file per object with:
- atomic writes (tmp + ``os.replace``),
- private permissions (0700 dirs / 0600 files — these directories hold
  secret keys and auth tokens),
- a per-directory lock making ``create`` (get-then-put, idempotent when
  content is identical — the reference's jfs semantics,
  server/src/jfs_stores/mod.rs:79-89) safe under the threaded REST server.

Used by both the client keystore (sda_tpu/crypto/keystore.py) and the
server file store (sda_tpu/server/filestore.py).
"""

from __future__ import annotations

import json
import os
import threading


class ConflictError(Exception):
    """create() saw an existing object with different content."""


# Locks are keyed by absolute directory path, not by JsonDir instance:
# callers freely mint transient JsonDir objects for the same directory
# (e.g. the server filestore's per-aggregation subdirs), and create()'s
# get-then-put must serialize across all of them.
_LOCKS: dict = {}
_LOCKS_GUARD = threading.Lock()


def _lock_for(path: str) -> threading.RLock:
    with _LOCKS_GUARD:
        lock = _LOCKS.get(path)
        if lock is None:
            lock = _LOCKS[path] = threading.RLock()
        return lock


class JsonDir:
    def __init__(self, path):
        self.path = os.path.abspath(str(path))
        os.makedirs(self.path, mode=0o700, exist_ok=True)
        self._lock = _lock_for(self.path)

    def _file(self, id) -> str:
        name = str(id)
        if "/" in name or name.startswith("."):
            raise ValueError(f"bad id {name!r}")
        return os.path.join(self.path, name + ".json")

    def put(self, id, payload) -> None:
        with self._lock:
            self._put_locked(id, payload)

    def _put_locked(self, id, payload) -> None:
        target = self._file(id)
        tmp = target + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, target)

    def get(self, id):
        # lock-free read: writes land via tmp + os.replace, so a reader
        # always opens either the complete old file or the complete new
        # one — never a partial write. Only the get-then-put paths
        # (create/create_once) need the directory lock; decoding JSON
        # outside any lock keeps concurrent readers from convoying.
        try:
            with open(self._file(id)) as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        return json.loads(raw)

    def create(self, id, payload) -> None:
        """create-if-identical: reposting identical content is a no-op,
        differing content raises ConflictError."""
        with self._lock:
            try:
                with open(self._file(id)) as f:
                    existing = json.load(f)
            except FileNotFoundError:
                existing = None
            if existing is not None and existing != payload:
                raise ConflictError(f"object already exists: {id}")
            self._put_locked(id, payload)

    def create_once(self, id, payload) -> bool:
        """Write only if absent; returns whether this call wrote it."""
        with self._lock:
            if os.path.exists(self._file(id)):
                return False
            self._put_locked(id, payload)
            return True

    def delete(self, id) -> None:
        with self._lock:
            try:
                os.remove(self._file(id))
            except FileNotFoundError:
                pass

    def list_ids(self) -> list:
        with self._lock:
            return sorted(
                f[: -len(".json")] for f in os.listdir(self.path) if f.endswith(".json")
            )
