"""Deterministic arrival traces: diurnal ramps, bursts, churn.

The flagship campaign and the load soak need *realistic* open-loop
arrival processes — phones check in on a diurnal cycle, push
notifications produce thundering-herd bursts, and a slice of the cohort
churns (disconnects and retries late) — while staying byte-replayable:
the same spec and seed must produce the same arrival sequence on any
host, any wall clock, any PID. This module is the fault plane's
(:mod:`.faults`) sibling for *offered load* instead of injected
failure: a tiny spec grammar, pure ``(seed, index)`` draws, no global
state.

Spec grammar (``--trace <spec>[:<seed>]``)::

    spec  := rule ("," rule)*
    rule  := "base"    "=" rate            — baseline arrivals/second
           | "diurnal" "=" amp ["@" period]
                — sinusoidal day-cycle: rate multiplier
                  1 + amp*sin(2*pi*t/period); amp in [0,1],
                  period seconds (default 60 — a compressed "day"
                  so a minutes-long soak sees full cycles)
           | "burst"   "=" prob ["@" mult]
                — each 1-second slot independently becomes a burst
                  slot with probability ``prob`` (pure (seed, slot)
                  draw); during a burst the rate is multiplied by
                  ``mult`` (default 5) — the push-notification herd
           | "churn"   "=" prob
                — each arrival independently churns with probability
                  ``prob`` (pure (seed, index) draw): the caller
                  delays that participant's upload to the end of the
                  round, modelling disconnect-and-retry. Churn moves
                  *when* a phone arrives, never *whether* — reveals
                  stay exact
    seed  := integer (default 0)

Examples::

    base=20
    base=50,diurnal=0.8@30,burst=0.1@8:42
    base=10,churn=0.25:7

Determinism: the k-th inter-arrival gap is ``-ln(1-u)/rate(t_k)`` with
``u`` a pure function of (seed, k) — a seed-replayable inhomogeneous
Poisson process (rate frozen over each gap, fine at soak rates). Burst
slots and churn flags draw from disjoint index spaces of the same seed
so adding a rule never shifts another rule's sequence.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from .faults import _unit

#: disjoint (seed, index) spaces: gap draws, burst-slot draws, churn
#: draws must not consume each other's sequence
_GAP_SPACE = 0
_BURST_SPACE = 1 << 40
_CHURN_SPACE = 2 << 40

#: burst slots are drawn per whole second of trace time
_SLOT_S = 1.0


@dataclass(frozen=True)
class TraceSpec:
    base: float
    diurnal_amp: float = 0.0
    diurnal_period: float = 60.0
    burst_prob: float = 0.0
    burst_mult: float = 5.0
    churn_prob: float = 0.0
    seed: int = 0


def parse_trace(text: str) -> TraceSpec:
    """Parse ``<spec>[:<seed>]`` into a :class:`TraceSpec`. Raises
    ValueError on unknown rules, rates/probabilities out of range, or a
    missing ``base``."""
    text = text.strip()
    if not text:
        raise ValueError("empty arrival-trace spec")
    spec, seed = text, 0
    if ":" in text:
        spec, _, tail = text.rpartition(":")
        try:
            seed = int(tail)
        except ValueError:
            raise ValueError(f"trace seed must be an integer, got {tail!r}")
    fields = {"seed": seed}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        kind, eq, rhs = item.partition("=")
        if not eq:
            raise ValueError(f"trace rule {item!r} is not kind=value[@param]")
        value_text, at, param_text = rhs.partition("@")
        value = float(value_text)
        if kind == "base":
            if value <= 0:
                raise ValueError(f"trace base rate must be > 0, got {value}")
            fields["base"] = value
        elif kind == "diurnal":
            if not 0.0 <= value <= 1.0:
                raise ValueError(f"diurnal amplitude must be in [0,1], got {value}")
            fields["diurnal_amp"] = value
            if at:
                period = float(param_text)
                if period <= 0:
                    raise ValueError(f"diurnal period must be > 0, got {period}")
                fields["diurnal_period"] = period
        elif kind == "burst":
            if not 0.0 <= value <= 1.0:
                raise ValueError(f"burst probability must be in [0,1], got {value}")
            fields["burst_prob"] = value
            if at:
                mult = float(param_text)
                if mult < 1.0:
                    raise ValueError(f"burst multiplier must be >= 1, got {mult}")
                fields["burst_mult"] = mult
        elif kind == "churn":
            if not 0.0 <= value <= 1.0:
                raise ValueError(f"churn probability must be in [0,1], got {value}")
            fields["churn_prob"] = value
        else:
            raise ValueError(
                f"unknown trace rule {kind!r} (know base/diurnal/burst/churn)"
            )
    if "base" not in fields:
        raise ValueError("arrival-trace spec needs a base=<rate> rule")
    return TraceSpec(**fields)


class ArrivalTrace:
    """One parsed spec's pure arrival process.

    Everything is a function of (spec, seed, index) — two traces built
    from the same text produce identical sequences independently.
    """

    def __init__(self, spec: TraceSpec):
        self.spec = spec

    @classmethod
    def from_text(cls, text: str) -> "ArrivalTrace":
        return cls(parse_trace(text))

    def is_burst_slot(self, slot: int) -> bool:
        s = self.spec
        return s.burst_prob > 0 and _unit(s.seed, _BURST_SPACE + slot) < s.burst_prob

    def rate_at(self, t: float) -> float:
        """Instantaneous offered rate (arrivals/second) at trace time t."""
        s = self.spec
        rate = s.base
        if s.diurnal_amp > 0:
            rate *= 1.0 + s.diurnal_amp * math.sin(
                2.0 * math.pi * t / s.diurnal_period
            )
        if self.is_burst_slot(int(t // _SLOT_S)):
            rate *= s.burst_mult
        # the diurnal trough of amp=1 touches zero; floor so the gap
        # integral below always terminates
        return max(rate, s.base * 1e-3)

    def is_churned(self, index: int) -> bool:
        """Whether the index-th arrival churns (upload deferred to the
        end of the round by the caller)."""
        s = self.spec
        return s.churn_prob > 0 and _unit(s.seed, _CHURN_SPACE + index) < s.churn_prob

    def next_arrival(self, index: int, t: float) -> float:
        """Arrival time of the ``index``-th event given the previous
        arrival at trace time ``t``: an exponential gap from the pure
        (seed, index) draw, rate frozen over the gap. Callers stepping a
        live trace keep (index, t) as their cursor."""
        u = _unit(self.spec.seed, _GAP_SPACE + index)
        # u in [0,1): 1-u in (0,1], so the log is finite
        return t + -math.log(1.0 - u) / self.rate_at(t)

    def times(self, n: int, start: float = 0.0) -> list[float]:
        """The first ``n`` arrival offsets (seconds from trace start)."""
        out = []
        t = start
        for k in range(n):
            t = self.next_arrival(k, t)
            out.append(t)
        return out
