"""Consistent hashing of aggregation ids over K partitions.

One ring serves both halves of the sharded coordination plane: the
``ShardedStore`` (``server/sharded.py``) uses it to pick the backing
partition for an aggregation, and the multi-frontend REST client
(``rest/client.py``) uses it to pick a frontend for a request — both
sides hash the same key (the aggregation id as a string) so an
aggregation's traffic lands on one frontend and one partition without
any coordination between them.

Classic fixed-ring construction: each partition owns ``vnodes`` points
on a 64-bit ring (SHA-1 of ``"shard-<ix>-<vnode>"``), a key maps to the
first point clockwise from its own hash. Fully deterministic across
processes and runs — no randomness, no process-seeded hashing (never
``hash()``: PYTHONHASHSEED would split the client and server rings).
Virtual nodes keep the load split near-uniform at small K, and growing
K moves only ~1/K of the keyspace (the consistent-hashing property that
makes repartitioning cheap when a future PR makes K dynamic).
"""

from __future__ import annotations

import bisect
import hashlib


def _point(data: str) -> int:
    """A deterministic 64-bit ring position for ``data``."""
    return int.from_bytes(hashlib.sha1(data.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """A consistent-hash ring over ``shards`` partitions."""

    def __init__(self, shards: int, vnodes: int = 64):
        if shards < 1:
            raise ValueError("a hash ring needs at least one shard")
        self.shards = shards
        points = []
        for ix in range(shards):
            for v in range(vnodes):
                points.append((_point(f"shard-{ix}-{v}"), ix))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [ix for _, ix in points]

    def shard_for(self, key) -> int:
        """The partition owning ``key`` (stringified before hashing)."""
        if self.shards == 1:
            return 0
        at = bisect.bisect_right(self._points, _point(str(key)))
        return self._owners[at % len(self._owners)]

    def preference(self, key) -> list:
        """Every shard ordered by ring walk from ``key``'s point: the
        owner first, then each next-distinct shard clockwise. The client
        router uses this as its failover order so every client agrees on
        which frontend is 'next' for a given aggregation."""
        if self.shards == 1:
            return [0]
        at = bisect.bisect_right(self._points, _point(str(key)))
        order: list = []
        seen = set()
        n = len(self._owners)
        for step in range(n):
            owner = self._owners[(at + step) % n]
            if owner not in seen:
                seen.add(owner)
                order.append(owner)
                if len(order) == self.shards:
                    break
        return order
