"""Wire-format helpers: fixed byte arrays, binary blobs, Signed/Labelled.

Wire parity notes (vs the reference):
- ``B8``/``B32``/``B64`` fixed-size byte arrays serialize as standard base64
  with padding (/root/reference/protocol/src/byte_arrays.rs:3-99).
- ``Binary`` is a variable-size base64 blob (protocol/src/helpers.rs:176-216).
- ``Signed<M>`` carries ``signature``, ``signer``, ``body`` in that field
  order (helpers.rs:99-107); ``Labelled<ID, M>`` carries ``id``, ``body``
  (helpers.rs:146-152). Field order matters because the canonical signing
  bytes are defined as the compact JSON encoding of the object
  (helpers.rs:130-142) — we pin the same order and separators.
"""

from __future__ import annotations

import base64
import json


def canonical_bytes(obj) -> bytes:
    """Canonical signing bytes: the compact JSON encoding of the object.

    Matches the reference rule ``Sign::canonical = serde_json::to_vec``
    (protocol/src/helpers.rs:138-142): field order is declaration order,
    no whitespace. Accepts either a wire object (with ``to_json``) or an
    already-plain JSON value.
    """
    payload = obj.to_json() if hasattr(obj, "to_json") else obj
    return json.dumps(payload, separators=(",", ":"), ensure_ascii=False).encode("utf-8")


class FixedBytes:
    """Fixed-length byte array; wire form is padded standard base64."""

    SIZE = 0
    __slots__ = ("data",)

    def __init__(self, data: bytes | None = None):
        if data is None:
            data = bytes(self.SIZE)
        data = bytes(data)
        if len(data) != self.SIZE:
            raise ValueError(f"{type(self).__name__} expects {self.SIZE} bytes, got {len(data)}")
        self.data = data

    def to_json(self) -> str:
        return base64.b64encode(self.data).decode("ascii")

    @classmethod
    def from_json(cls, obj):
        if not isinstance(obj, str):
            raise ValueError(f"expected base64 string, got {obj!r}")
        return cls(base64.b64decode(obj, validate=True))

    def __bytes__(self) -> bytes:
        return self.data

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other.data == self.data

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.data))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.data.hex()})"


class B8(FixedBytes):
    SIZE = 8


class B32(FixedBytes):
    SIZE = 32


class B64(FixedBytes):
    SIZE = 64


class Binary:
    """Variable-length binary blob; wire form is padded standard base64."""

    __slots__ = ("data",)

    def __init__(self, data: bytes = b""):
        self.data = bytes(data)

    def to_json(self) -> str:
        return base64.b64encode(self.data).decode("ascii")

    @classmethod
    def from_json(cls, obj):
        if not isinstance(obj, str):
            raise ValueError(f"expected base64 string, got {obj!r}")
        return cls(base64.b64decode(obj, validate=True))

    def __bytes__(self) -> bytes:
        return self.data

    def __len__(self) -> int:
        return len(self.data)

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other.data == self.data

    def __hash__(self) -> int:
        return hash(("Binary", self.data))

    def __repr__(self) -> str:
        preview = self.data[:8].hex()
        return f"Binary({len(self.data)}B:{preview}...)"


class Labelled:
    """A message labelled by an identifier: ``{id, body}``."""

    __slots__ = ("id", "body")

    def __init__(self, id, body):
        self.id = id
        self.body = body

    def to_json(self):
        return {"id": self.id.to_json(), "body": self.body.to_json()}

    @classmethod
    def from_json(cls, obj, id_cls, body_cls):
        return cls(id=id_cls.from_json(obj["id"]), body=body_cls.from_json(obj["body"]))

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Labelled) and other.id == self.id and other.body == self.body
        )

    def __hash__(self) -> int:
        return hash(("Labelled", self.id, self.body))

    def __repr__(self) -> str:
        return f"Labelled(id={self.id!r}, body={self.body!r})"


class Signed:
    """A signed message with claimed signer: ``{signature, signer, body}``.

    The signature covers ``canonical_bytes(body)``.
    """

    __slots__ = ("signature", "signer", "body")

    def __init__(self, signature, signer, body):
        self.signature = signature
        self.signer = signer
        self.body = body

    def to_json(self):
        return {
            "signature": self.signature.to_json(),
            "signer": self.signer.to_json(),
            "body": self.body.to_json(),
        }

    @classmethod
    def from_json(cls, obj, body_from_json):
        from .schemes import Signature
        from .ids import AgentId

        return cls(
            signature=Signature.from_json(obj["signature"]),
            signer=AgentId.from_json(obj["signer"]),
            body=body_from_json(obj["body"]),
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Signed)
            and other.signature == self.signature
            and other.signer == self.signer
            and other.body == self.body
        )

    def __repr__(self) -> str:
        return f"Signed(signer={self.signer!r}, body={self.body!r})"
