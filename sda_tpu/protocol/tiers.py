"""Hierarchical aggregation topology — pure derivation, no IO.

The tree scheme of "Secret Sharing Sharing For Highly Scalable Secure
Aggregation" (arXiv 2201.00864): a tiered aggregation is a TREE of
ordinary aggregations, derived entirely from the ROOT record. Node ids
are uuid5 of (parent id, child index), participants hash into
sub-cohorts per node, and every node runs the unchanged flat pipeline
(committee, snapshot, clerking, reveal) over its own cohort — per-clerk
work drops from O(N) to O(N / m^(tiers-1)) because each sub-committee
only ever touches its own sub-cohort's columns.

Client and server both import these functions, so both sides compute the
SAME topology from the same root record: a participant can resolve its
leaf without asking the server, and the server can enumerate the derived
tree (tier status, delete cascade) without storing any edges.

``tiers`` counts committee LEVELS (2 = sub-committees + root committee);
``sub_cohort_size`` is the fan-out m — the number of sub-cohorts each
tiered node splits its cohort into (NOT the participants per sub-cohort).
A node's children carry ``tiers - 1``; nodes reaching 1 are plain flat
aggregations and accept real participations.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import Optional

from ..utils.hashring import HashRing
from .ids import AggregationId, ParticipationId
from .resources import Aggregation
from .schemes import AdditiveSharing, SodiumEncryptionScheme

#: uuid5 namespace for everything tier-derived (child ids, cohort hashes).
#: Fixed forever: child ids must be reproducible by any client or server
#: from the root id alone, across processes and versions.
TIER_NAMESPACE = uuid.UUID("8f3f6d2a-94b1-4dfd-b1b5-6a42a86be1a4")

#: validation bounds (server/service.py): the tree has m^(tiers-1) leaves,
#: so both knobs are capped to keep the derived fan-out enumerable
MAX_TIERS = 4
MAX_SUB_COHORTS = 64

#: how partial sums climb the tree. ``reveal`` is the PR-14 path (the
#: promoter reconstructs the sub-cohort partial and re-submits it);
#: ``reshare`` is the paper's share-promotion path (clerks re-share their
#: aggregated columns upward; nothing intermediate is ever reconstructed).
PROMOTION_REVEAL = "reveal"
PROMOTION_RESHARE = "reshare"

#: re-share epochs are tiny (0 = full committee, 1 = survivor reissue);
#: the bound keeps the deterministic id space and validation enumerable
MAX_RESHARE_EPOCHS = 16


def effective_promotion(aggregation: Aggregation) -> str:
    """The promotion path a tiered round actually runs. Explicit
    ``tier_promotion`` wins; otherwise share-promotion is the default for
    every threshold scheme and additive sharing falls back to reveal
    (additive columns are the secrets' full image — there is no Lagrange
    column to re-share by, and ``reconstruction_matrix`` has no additive
    form)."""
    if aggregation.tier_promotion is not None:
        return aggregation.tier_promotion
    if isinstance(aggregation.committee_sharing_scheme, AdditiveSharing):
        return PROMOTION_REVEAL
    return PROMOTION_RESHARE


def is_reshare_child(aggregation: Aggregation) -> bool:
    """True when ``aggregation`` is a derived tier child whose clerks must
    promote their aggregated share columns to ``tier_parent`` instead of
    sealing clerking results for a local reveal."""
    return (
        aggregation.tier_parent is not None
        and effective_promotion(aggregation) == PROMOTION_RESHARE
    )


def reshare_participation_id(
    child_id: AggregationId, epoch: int, position: Optional[int] = None
) -> ParticipationId:
    """Deterministic id for a share-promotion row: uuid5 of (child, epoch,
    committee position), or of (child,) alone for the owner's single
    mask-correction row. Retries and re-drains therefore collide on the
    stores' create-if-identical semantics instead of double-counting."""
    leaf = "reshare-mask" if position is None else f"reshare:{epoch}:{position}"
    return ParticipationId(uuid.uuid5(TIER_NAMESPACE, f"{child_id}:{leaf}"))


def tier_depth(aggregation: Aggregation) -> int:
    return aggregation.tiers or 1


def child_aggregation_id(parent_id: AggregationId, index: int) -> AggregationId:
    """Deterministic sub-aggregation id: uuid5 of (parent, child index).
    The same idiom as the snapshot pipeline's job ids — a re-provisioned
    tree derives byte-identical records, which the stores'
    create-if-identical semantics absorb."""
    return AggregationId(uuid.uuid5(TIER_NAMESPACE, f"{parent_id}:child:{index}"))


def assign_sub_cohort(node_id: AggregationId, participant_id, sub_cohorts: int) -> int:
    """Which of ``node_id``'s sub-cohorts ``participant_id`` belongs to.

    Deterministic hash, salted by the node id: the same participant lands
    in independent positions at different nodes of the tree, so one tier's
    assignment leaks nothing about another's."""
    if sub_cohorts < 1:
        raise ValueError("sub_cohorts must be >= 1")
    digest = uuid.uuid5(TIER_NAMESPACE, f"{node_id}:cohort:{participant_id}")
    return digest.int % sub_cohorts


def leaf_aggregation_id(root: Aggregation, participant_id) -> AggregationId:
    """The leaf aggregation a participant's submission routes to: walk the
    derived tree from the root, hashing into a sub-cohort per tiered
    node. Pure — every hop's id derives from the root id, so no server
    round-trips are needed to resolve the leaf."""
    node, depth = root.id, tier_depth(root)
    while depth > 1:
        ix = assign_sub_cohort(node, participant_id, root.sub_cohort_size)
        node = child_aggregation_id(node, ix)
        depth -= 1
    return node


def frontend_for(aggregation_id, frontends: int) -> int:
    """Which of ``frontends`` REST frontends serves ``aggregation_id``'s
    traffic. This is exactly the multi-root client's routing function
    (``HashRing(len(roots)).shard_for(str(key))`` — see
    ``rest/client.py``), exposed as a pure topology function so tier
    drivers can pin each node's committee daemon next to the frontend
    its requests will land on WITHOUT asking any coordinator: every
    party derives the same placement from the root id alone."""
    if frontends < 1:
        raise ValueError("placement needs at least one frontend")
    return HashRing(frontends).shard_for(str(aggregation_id))


def tier_placement(root: Aggregation, frontends: int) -> dict:
    """Deterministic tier→frontend placement for the whole derived tree:
    ``{aggregation_id: frontend_index}`` for every node of ``root``'s
    topology. A pure function of (root id, frontend count) — clients,
    committee daemons, and launchers all compute the identical map, so a
    sub-committee process can be spawned pointing at exactly the
    frontend that will serve its node's wire traffic."""
    ring = HashRing(frontends) if frontends > 1 else None
    return {
        node.aggregation_id: (
            ring.shard_for(str(node.aggregation_id)) if ring is not None else 0
        )
        for node in iter_tier_nodes(root)
    }


@dataclass(frozen=True)
class TierNode:
    """One node of the derived tree: tier 0 is the root; ``index`` is the
    position within the parent's children (0 for the root)."""

    aggregation_id: AggregationId
    tier: int
    index: int
    parent: Optional[AggregationId]

    def is_leaf_of(self, root: Aggregation) -> bool:
        return self.tier == tier_depth(root) - 1


def iter_tier_nodes(root: Aggregation) -> list:
    """The whole derived tree as a list of ``TierNode``, breadth-first,
    root first — the enumeration order tier status reports in and the
    provisioning order (parents before children) the round driver uses.
    A flat aggregation yields just its own root node."""
    nodes = [TierNode(root.id, 0, 0, None)]
    frontier = [root.id]
    m = root.sub_cohort_size or 0
    for tier in range(1, tier_depth(root)):
        next_frontier = []
        for parent in frontier:
            for ix in range(m):
                child = child_aggregation_id(parent, ix)
                nodes.append(TierNode(child, tier, ix, parent))
                next_frontier.append(child)
        frontier = next_frontier
    return nodes


def child_aggregation(
    parent: Aggregation, index: int, recipient, recipient_key
) -> Aggregation:
    """The derived sub-aggregation record for child ``index`` of
    ``parent``: same group (modulus, dimension), same masking and sharing
    schemes (so every tier gets the same dropout tolerance), one fewer
    tier. The child's recipient is its OWNER — under share-promotion it
    only ever decrypts the sub-cohort's mask sum (to submit the
    mask-correction row); under reveal-promotion it reconstructs and
    re-submits the partial. Either way the recipient encryption scheme is
    pinned to sodium sealed boxes (owner keystores hold sodium keys;
    PackedPaillier mask transport stays a root-only concern).
    ``tier_parent``/``tier_promotion`` propagate so a child record alone
    tells its clerks where and how to promote."""
    remaining = tier_depth(parent) - 1
    return Aggregation(
        id=child_aggregation_id(parent.id, index),
        title=f"{parent.title}/sub{index}",
        vector_dimension=parent.vector_dimension,
        modulus=parent.modulus,
        recipient=recipient,
        recipient_key=recipient_key,
        masking_scheme=parent.masking_scheme,
        committee_sharing_scheme=parent.committee_sharing_scheme,
        recipient_encryption_scheme=SodiumEncryptionScheme(),
        committee_encryption_scheme=parent.committee_encryption_scheme,
        sub_cohort_size=parent.sub_cohort_size if remaining > 1 else None,
        tiers=remaining if remaining > 1 else None,
        tier_parent=parent.id,
        tier_promotion=parent.tier_promotion,
    )
