"""The SDA service interface — the single seam of the whole system.

The 19 RPC methods of /root/reference/protocol/src/methods.rs as one abstract
base class. The in-process server, the REST client proxy, and any future
binding all implement this same interface, so protocol logic and tests are
written once against it (the reference's key architectural property,
SURVEY.md §1).

Every method takes ``caller`` for access control; ``get_*`` methods return
``None`` for missing resources.
"""

from __future__ import annotations

import abc
from typing import Optional


class SdaService(abc.ABC):
    """Combined SDA service: agent, aggregation, participation, clerking,
    and recipient methods (methods.rs:13-112)."""

    # -- base ---------------------------------------------------------------

    @abc.abstractmethod
    def ping(self):
        """Liveness check; returns Pong."""

    # -- agents (methods.rs:31-50) -----------------------------------------

    @abc.abstractmethod
    def create_agent(self, caller, agent) -> None:
        """Register an agent (caller must be the agent itself)."""

    @abc.abstractmethod
    def get_agent(self, caller, agent_id):
        """Fetch an agent description; public."""

    @abc.abstractmethod
    def upsert_profile(self, caller, profile) -> None:
        """Create or update the caller's public profile."""

    @abc.abstractmethod
    def get_profile(self, caller, owner_id):
        """Fetch a public profile."""

    @abc.abstractmethod
    def create_encryption_key(self, caller, signed_key) -> None:
        """Register a signed encryption key (caller must be the signer)."""

    @abc.abstractmethod
    def get_encryption_key(self, caller, key_id):
        """Fetch a signed encryption key; public."""

    # -- aggregations (methods.rs:53-64) -------------------------------------

    @abc.abstractmethod
    def list_aggregations(self, caller, filter: Optional[str] = None, recipient=None):
        """Search aggregations by title substring and/or recipient."""

    @abc.abstractmethod
    def get_aggregation(self, caller, aggregation_id):
        """Fetch an aggregation description."""

    @abc.abstractmethod
    def get_committee(self, caller, aggregation_id):
        """Fetch the committee elected for an aggregation."""

    # -- participation (methods.rs:68-73) ------------------------------------

    @abc.abstractmethod
    def create_participation(self, caller, participation) -> None:
        """Submit a participation (caller must be the participant)."""

    def create_participations(self, caller, participations) -> None:
        """Submit a batch of participations (caller must be the participant
        of every one).  Both shipped bindings (the in-process service and
        the REST client's batch route) make the batch atomic: every
        participation is accepted — idempotent replays included — or none
        is stored.  This default is only a compatibility shim for
        third-party bindings and submits sequentially, without atomicity."""
        for participation in participations:
            self.create_participation(caller, participation)

    # -- clerking (methods.rs:76-84) -----------------------------------------

    @abc.abstractmethod
    def get_clerking_job(self, caller, clerk_id):
        """Poll the durable queue for the clerk's next job, if any.

        Jobs above the server's paging threshold come back as metadata
        (``ClerkingJob.is_paged()``): ``encryptions`` empty,
        ``total_encryptions``/``chunk_size`` set, the ciphertext column
        fetched range-by-range via ``get_clerking_job_chunk``."""

    def get_clerking_job_chunk(self, caller, job_id, start: int):
        """Fetch one ciphertext range ``[start, start+server_chunk)`` of
        a paged clerking job the caller owns; returns list[Encryption]
        (empty past the end), or None for a job that doesn't exist or
        belongs to another clerk. Bindings serve this from the chunk
        route / ranged store reads; this default exists so third-party
        ``SdaService`` implementations predating paged delivery keep
        importing — but they will never hand out a paged job either, so
        reaching it means a binding/version mismatch."""
        raise NotImplementedError(
            "this SdaService binding does not support paged clerking jobs"
        )

    @abc.abstractmethod
    def create_clerking_result(self, caller, result) -> None:
        """Push the result of a finished clerking job."""

    def complete_clerking_job(self, caller, job_id) -> None:
        """Retire a clerking job the caller owns WITHOUT filing a result —
        the terminal of tier share-promotion (client/clerk.py), where the
        clerk's output left as tagged participations of the parent and no
        recipient-sealed result may exist. Idempotent on replay. Default
        shim raises so ``SdaService`` bindings predating share promotion
        keep importing; reaching it means a binding/version mismatch."""
        raise NotImplementedError(
            "this SdaService binding does not support completing a job "
            "without a clerking result"
        )

    # -- recipient (methods.rs:87-112) ----------------------------------------

    @abc.abstractmethod
    def create_aggregation(self, caller, aggregation) -> None:
        """Create an aggregation (caller must be the recipient)."""

    @abc.abstractmethod
    def delete_aggregation(self, caller, aggregation_id) -> None:
        """Delete all information regarding an aggregation."""

    @abc.abstractmethod
    def suggest_committee(self, caller, aggregation_id):
        """Propose suitable committee members; returns list[ClerkCandidate]."""

    @abc.abstractmethod
    def create_committee(self, caller, committee) -> None:
        """Elect the committee for an aggregation."""

    @abc.abstractmethod
    def get_aggregation_status(self, caller, aggregation_id):
        """Poll aggregation status (participations, snapshots, readiness)."""

    def get_tier_status(self, caller, aggregation_id):
        """Per-node readiness of a TIERED aggregation's derived tree
        (``TierStatus``, nodes in breadth-first order, root first), or
        None for a flat or unknown aggregation. Recipient-only, like
        ``get_aggregation_status``. Compatibility shim rationale as the
        paged-delivery defaults: a binding predating tiered aggregation
        never creates one, so reaching this default means a
        binding/version mismatch."""
        raise NotImplementedError(
            "this SdaService binding does not support tiered aggregations"
        )

    @abc.abstractmethod
    def create_snapshot(self, caller, snapshot) -> None:
        """Freeze a consistent subset of participations and build clerk jobs."""

    @abc.abstractmethod
    def get_snapshot_result(self, caller, aggregation_id, snapshot_id):
        """Fetch the collected clerk results + mask blob for a snapshot.

        Results above the server's paging threshold come back as metadata
        (``SnapshotResult.is_paged()``): payload lists empty,
        ``mask_encryption_count``/``clerk_result_count``/``chunk_size``
        set, both payloads fetched range-by-range via
        ``get_snapshot_result_masks`` / ``get_snapshot_result_clerks``."""

    def get_snapshot_result_masks(self, caller, aggregation_id, snapshot_id, start: int):
        """Fetch one recipient-mask-encryption range
        ``[start, start+server_chunk)`` of a paged snapshot result;
        returns list[Encryption] (empty past the end), or None for a
        snapshot that doesn't exist, doesn't belong to the aggregation,
        or stored no mask. Same compatibility shim rationale as
        ``get_clerking_job_chunk``: a binding predating paged delivery
        never hands out a paged result, so reaching this default means a
        binding/version mismatch."""
        raise NotImplementedError(
            "this SdaService binding does not support paged snapshot results"
        )

    def get_snapshot_result_clerks(self, caller, aggregation_id, snapshot_id, start: int):
        """Fetch one clerk-result range ``[start, start+server_chunk)``
        of a paged snapshot result, ordered by job id; returns
        list[ClerkingResult] (empty past the end), or None for a snapshot
        that doesn't exist or doesn't belong to the aggregation."""
        raise NotImplementedError(
            "this SdaService binding does not support paged snapshot results"
        )
