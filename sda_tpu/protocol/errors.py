"""Error types of the SDA protocol.

Mirrors the error kinds surfaced by the reference wire protocol
(/root/reference/server-http/src/lib.rs:112-117 maps them onto 401/403/400/500):
``InvalidCredentials``, ``PermissionDenied``, ``Invalid(reason)``, and a
catch-all internal error.
"""

from __future__ import annotations


class SdaError(Exception):
    """Base class for all SDA protocol errors."""


class InvalidCredentialsError(SdaError):
    """Authentication failed (wire: HTTP 401)."""


class PermissionDeniedError(SdaError):
    """Caller is authenticated but not allowed (wire: HTTP 403)."""


class InvalidRequestError(SdaError):
    """Malformed or inconsistent request (wire: HTTP 400)."""


class ServerError(SdaError):
    """Internal server failure (wire: HTTP 500)."""
