"""Protocol resources — the REST objects of the SDA wire contract.

Field names and order mirror /root/reference/protocol/src/resources.rs so the
JSON wire format (and canonical signing bytes) match the reference's serde
output byte for byte.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .helpers import Labelled, Signed
from .ids import (
    AgentId,
    AggregationId,
    ClerkingJobId,
    EncryptionKeyId,
    ParticipationId,
    SnapshotId,
    VerificationKeyId,
)
from .schemes import (
    AdditiveEncryptionScheme,
    Encryption,
    EncryptionKey,
    LinearMaskingScheme,
    LinearSecretSharingScheme,
    VerificationKey,
)


def _opt(value, f):
    return None if value is None else f(value)


@dataclass
class Agent:
    """Fundamental agent description (resources.rs:12-17)."""

    id: AgentId
    verification_key: Labelled  # Labelled[VerificationKeyId, VerificationKey]

    def to_json(self):
        return {
            "id": self.id.to_json(),
            "verification_key": self.verification_key.to_json(),
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            id=AgentId.from_json(obj["id"]),
            verification_key=Labelled.from_json(
                obj["verification_key"], VerificationKeyId, VerificationKey
            ),
        )


@dataclass
class Profile:
    """Extended public profile of an agent (resources.rs:24-35)."""

    owner: AgentId
    name: Optional[str] = None
    twitter_id: Optional[str] = None
    keybase_id: Optional[str] = None
    website: Optional[str] = None

    def to_json(self):
        return {
            "owner": self.owner.to_json(),
            "name": self.name,
            "twitter_id": self.twitter_id,
            "keybase_id": self.keybase_id,
            "website": self.website,
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            owner=AgentId.from_json(obj["owner"]),
            name=obj.get("name"),
            twitter_id=obj.get("twitter_id"),
            keybase_id=obj.get("keybase_id"),
            website=obj.get("website"),
        )


def signed_encryption_key_from_json(obj) -> Signed:
    """SignedEncryptionKey = Signed<Labelled<EncryptionKeyId, EncryptionKey>>."""
    return Signed.from_json(
        obj, lambda body: Labelled.from_json(body, EncryptionKeyId, EncryptionKey)
    )


@dataclass
class Aggregation:
    """Description of an aggregation (resources.rs:44-67).

    ``sub_cohort_size`` / ``tiers`` are the hierarchical-plane extension
    (arXiv 2201.00864): a TIERED aggregation (``tiers >= 2``) partitions
    its participants into ``sub_cohort_size`` sub-cohorts per node by
    deterministic hash, each aggregated by its own sub-committee, with
    partial sums re-shared upward until the root committee reveals the
    exact total (protocol/tiers.py derives the whole tree from this one
    record). Both fields are emitted only when set, so FLAT aggregations
    — the default — keep the original ten-key wire shape and canonical
    signing bytes, byte for byte.
    """

    id: AggregationId
    title: str
    vector_dimension: int
    modulus: int
    recipient: AgentId
    recipient_key: EncryptionKeyId
    masking_scheme: LinearMaskingScheme
    committee_sharing_scheme: LinearSecretSharingScheme
    recipient_encryption_scheme: AdditiveEncryptionScheme
    committee_encryption_scheme: AdditiveEncryptionScheme
    sub_cohort_size: Optional[int] = None  # fan-out m per tiered node
    tiers: Optional[int] = None  # committee tiers; absent/1 = flat
    tier_parent: Optional[AggregationId] = None  # set on derived children
    tier_promotion: Optional[str] = None  # "reveal" | "reshare"; absent = auto

    def is_tiered(self) -> bool:
        return (self.tiers or 1) > 1

    def to_json(self):
        obj = {
            "id": self.id.to_json(),
            "title": self.title,
            "vector_dimension": self.vector_dimension,
            "modulus": self.modulus,
            "recipient": self.recipient.to_json(),
            "recipient_key": self.recipient_key.to_json(),
            "masking_scheme": self.masking_scheme.to_json(),
            "committee_sharing_scheme": self.committee_sharing_scheme.to_json(),
            "recipient_encryption_scheme": self.recipient_encryption_scheme.to_json(),
            "committee_encryption_scheme": self.committee_encryption_scheme.to_json(),
        }
        if self.sub_cohort_size is not None:
            obj["sub_cohort_size"] = self.sub_cohort_size
        if self.tiers is not None:
            obj["tiers"] = self.tiers
        if self.tier_parent is not None:
            obj["tier_parent"] = self.tier_parent.to_json()
        if self.tier_promotion is not None:
            obj["tier_promotion"] = self.tier_promotion
        return obj

    @classmethod
    def from_json(cls, obj):
        return cls(
            id=AggregationId.from_json(obj["id"]),
            title=obj["title"],
            vector_dimension=int(obj["vector_dimension"]),
            modulus=int(obj["modulus"]),
            recipient=AgentId.from_json(obj["recipient"]),
            recipient_key=EncryptionKeyId.from_json(obj["recipient_key"]),
            masking_scheme=LinearMaskingScheme.from_json(obj["masking_scheme"]),
            committee_sharing_scheme=LinearSecretSharingScheme.from_json(
                obj["committee_sharing_scheme"]
            ),
            recipient_encryption_scheme=AdditiveEncryptionScheme.from_json(
                obj["recipient_encryption_scheme"]
            ),
            committee_encryption_scheme=AdditiveEncryptionScheme.from_json(
                obj["committee_encryption_scheme"]
            ),
            sub_cohort_size=_opt(obj.get("sub_cohort_size"), int),
            tiers=_opt(obj.get("tiers"), int),
            tier_parent=_opt(obj.get("tier_parent"), AggregationId.from_json),
            tier_promotion=obj.get("tier_promotion"),
        )


@dataclass
class ClerkCandidate:
    """Suggested clerk for an aggregation (resources.rs:74-79)."""

    id: AgentId
    keys: list  # list[EncryptionKeyId]

    def to_json(self):
        return {"id": self.id.to_json(), "keys": [k.to_json() for k in self.keys]}

    @classmethod
    def from_json(cls, obj):
        return cls(
            id=AgentId.from_json(obj["id"]),
            keys=[EncryptionKeyId.from_json(k) for k in obj["keys"]],
        )


@dataclass
class Committee:
    """Committee elected for an aggregation (resources.rs:83-88)."""

    aggregation: AggregationId
    clerks_and_keys: list  # list[tuple[AgentId, EncryptionKeyId]]

    def to_json(self):
        return {
            "aggregation": self.aggregation.to_json(),
            "clerks_and_keys": [
                [a.to_json(), k.to_json()] for (a, k) in self.clerks_and_keys
            ],
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            aggregation=AggregationId.from_json(obj["aggregation"]),
            clerks_and_keys=[
                (AgentId.from_json(a), EncryptionKeyId.from_json(k))
                for (a, k) in obj["clerks_and_keys"]
            ],
        )


@dataclass
class TierReshare:
    """Share-promotion tag on a participation climbing the tier tree
    (arXiv 2201.00864: re-share shares upward, never reveal).

    ``position`` is the submitting clerk's 0-based seat in ``child``'s
    committee for a re-shared column row, or None for the mask-correction
    row the child's owner submits (which carries only the negated mask
    sum — data-independent, no aggregate content). ``survivors`` is the
    consistent 0-based seat set the Lagrange weights of this ``epoch``
    were computed over (None on mask rows). The tagged participation is
    otherwise an ordinary one — freshly masked, shared, and sealed for
    the PARENT aggregation — so flat records and parent-side clerking
    stay byte-unchanged."""

    child: AggregationId
    epoch: int
    position: Optional[int] = None
    survivors: Optional[list] = None  # list[int], sorted

    def to_json(self):
        obj = {"child": self.child.to_json(), "epoch": self.epoch}
        if self.position is not None:
            obj["position"] = self.position
        if self.survivors is not None:
            obj["survivors"] = [int(s) for s in self.survivors]
        return obj

    @classmethod
    def from_json(cls, obj):
        survivors = obj.get("survivors")
        return cls(
            child=AggregationId.from_json(obj["child"]),
            epoch=int(obj["epoch"]),
            position=_opt(obj.get("position"), int),
            survivors=None if survivors is None else [int(s) for s in survivors],
        )


@dataclass
class Participation:
    """A participant's input to an aggregation (resources.rs:92-108).

    ``id`` is client-chosen so retries are idempotent (resources.rs:93-101).
    ``tier_reshare`` marks a share-promotion row of the hierarchical plane
    and is emitted only when set, so flat participations keep the original
    five-key wire shape byte for byte.
    """

    id: ParticipationId
    participant: AgentId
    aggregation: AggregationId
    recipient_encryption: Optional[Encryption]
    clerk_encryptions: list  # list[tuple[AgentId, Encryption]]
    tier_reshare: Optional[TierReshare] = None

    def to_json(self):
        obj = {
            "id": self.id.to_json(),
            "participant": self.participant.to_json(),
            "aggregation": self.aggregation.to_json(),
            "recipient_encryption": _opt(self.recipient_encryption, lambda e: e.to_json()),
            "clerk_encryptions": [
                [a.to_json(), e.to_json()] for (a, e) in self.clerk_encryptions
            ],
        }
        if self.tier_reshare is not None:
            obj["tier_reshare"] = self.tier_reshare.to_json()
        return obj

    @classmethod
    def from_json(cls, obj):
        return cls(
            id=ParticipationId.from_json(obj["id"]),
            participant=AgentId.from_json(obj["participant"]),
            aggregation=AggregationId.from_json(obj["aggregation"]),
            recipient_encryption=_opt(obj.get("recipient_encryption"), Encryption.from_json),
            clerk_encryptions=[
                (AgentId.from_json(a), Encryption.from_json(e))
                for (a, e) in obj["clerk_encryptions"]
            ],
            tier_reshare=_opt(obj.get("tier_reshare"), TierReshare.from_json),
        )


@dataclass
class Snapshot:
    """A consistent cut over the participation stream (resources.rs:116-121)."""

    id: SnapshotId
    aggregation: AggregationId

    def to_json(self):
        return {"id": self.id.to_json(), "aggregation": self.aggregation.to_json()}

    @classmethod
    def from_json(cls, obj):
        return cls(
            id=SnapshotId.from_json(obj["id"]),
            aggregation=AggregationId.from_json(obj["aggregation"]),
        )


@dataclass
class ClerkingJob:
    """Partial aggregation job for one clerk (resources.rs:128-139).

    Jobs above the server's paging threshold are DELIVERED as metadata:
    ``encryptions`` empty, ``total_encryptions``/``chunk_size`` set, and
    the ciphertext column fetched range-by-range via
    ``GET /v1/aggregations/implied/jobs/{id}/chunks/{start}``. Small jobs
    keep the original five-key wire shape (both paging fields are emitted
    only when set), so pre-paging clients and transcripts stay byte
    compatible.
    """

    id: ClerkingJobId
    clerk: AgentId
    aggregation: AggregationId
    snapshot: SnapshotId
    encryptions: list  # list[Encryption], one per participant
    total_encryptions: Optional[int] = None  # paged delivery only
    chunk_size: Optional[int] = None  # server's suggested fetch range

    def is_paged(self) -> bool:
        return self.total_encryptions is not None

    def to_json(self):
        obj = {
            "id": self.id.to_json(),
            "clerk": self.clerk.to_json(),
            "aggregation": self.aggregation.to_json(),
            "snapshot": self.snapshot.to_json(),
            "encryptions": [e.to_json() for e in self.encryptions],
        }
        if self.total_encryptions is not None:
            obj["total_encryptions"] = self.total_encryptions
        if self.chunk_size is not None:
            obj["chunk_size"] = self.chunk_size
        return obj

    @classmethod
    def from_json(cls, obj):
        return cls(
            id=ClerkingJobId.from_json(obj["id"]),
            clerk=AgentId.from_json(obj["clerk"]),
            aggregation=AggregationId.from_json(obj["aggregation"]),
            snapshot=SnapshotId.from_json(obj["snapshot"]),
            encryptions=[Encryption.from_json(e) for e in obj["encryptions"]],
            total_encryptions=_opt(obj.get("total_encryptions"), int),
            chunk_size=_opt(obj.get("chunk_size"), int),
        )


@dataclass
class ClerkingResult:
    """Result of a clerking job (resources.rs:146-153)."""

    job: ClerkingJobId
    clerk: AgentId
    encryption: Encryption

    def to_json(self):
        return {
            "job": self.job.to_json(),
            "clerk": self.clerk.to_json(),
            "encryption": self.encryption.to_json(),
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            job=ClerkingJobId.from_json(obj["job"]),
            clerk=AgentId.from_json(obj["clerk"]),
            encryption=Encryption.from_json(obj["encryption"]),
        )


@dataclass
class SnapshotStatus:
    """Status of a snapshot (resources.rs:168-175)."""

    id: SnapshotId
    number_of_clerking_results: int
    result_ready: bool

    def to_json(self):
        return {
            "id": self.id.to_json(),
            "number_of_clerking_results": self.number_of_clerking_results,
            "result_ready": self.result_ready,
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            id=SnapshotId.from_json(obj["id"]),
            number_of_clerking_results=int(obj["number_of_clerking_results"]),
            result_ready=bool(obj["result_ready"]),
        )


@dataclass
class AggregationStatus:
    """Status of an aggregation (resources.rs:157-164)."""

    aggregation: AggregationId
    number_of_participations: int
    snapshots: list  # list[SnapshotStatus]

    def to_json(self):
        return {
            "aggregation": self.aggregation.to_json(),
            "number_of_participations": self.number_of_participations,
            "snapshots": [s.to_json() for s in self.snapshots],
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            aggregation=AggregationId.from_json(obj["aggregation"]),
            number_of_participations=int(obj["number_of_participations"]),
            snapshots=[SnapshotStatus.from_json(s) for s in obj["snapshots"]],
        )


@dataclass
class SnapshotResult:
    """Result of a snapshot, ready for reconstruction (resources.rs:179-188).

    Results above the server's paging threshold are DELIVERED as metadata:
    ``clerk_encryptions`` empty, ``recipient_encryptions`` None, and the
    three paging fields set; the recipient then streams both payloads
    range-by-range via
    ``GET .../snapshots/{id}/result/masks/{start}`` and
    ``GET .../snapshots/{id}/result/clerks/{start}``. Small results keep
    the original four-key wire shape (paging fields are emitted only when
    set), so pre-paging clients and transcripts stay byte compatible.
    ``mask_encryption_count`` is None in a paged result iff the snapshot
    stored no recipient mask (NoMasking) — mirroring the legacy
    ``recipient_encryptions`` None/list distinction.
    """

    snapshot: SnapshotId
    number_of_participations: int
    clerk_encryptions: list  # list[ClerkingResult]
    recipient_encryptions: Optional[list]  # Optional[list[Encryption]]
    mask_encryption_count: Optional[int] = None  # paged delivery only
    clerk_result_count: Optional[int] = None  # paged delivery only
    chunk_size: Optional[int] = None  # server's suggested fetch range

    def is_paged(self) -> bool:
        return self.clerk_result_count is not None

    def to_json(self):
        obj = {
            "snapshot": self.snapshot.to_json(),
            "number_of_participations": self.number_of_participations,
            "clerk_encryptions": [c.to_json() for c in self.clerk_encryptions],
            "recipient_encryptions": _opt(
                self.recipient_encryptions, lambda es: [e.to_json() for e in es]
            ),
        }
        if self.mask_encryption_count is not None:
            obj["mask_encryption_count"] = self.mask_encryption_count
        if self.clerk_result_count is not None:
            obj["clerk_result_count"] = self.clerk_result_count
        if self.chunk_size is not None:
            obj["chunk_size"] = self.chunk_size
        return obj

    @classmethod
    def from_json(cls, obj):
        recipient = obj.get("recipient_encryptions")
        return cls(
            snapshot=SnapshotId.from_json(obj["snapshot"]),
            number_of_participations=int(obj["number_of_participations"]),
            clerk_encryptions=[ClerkingResult.from_json(c) for c in obj["clerk_encryptions"]],
            recipient_encryptions=None
            if recipient is None
            else [Encryption.from_json(e) for e in recipient],
            mask_encryption_count=_opt(obj.get("mask_encryption_count"), int),
            clerk_result_count=_opt(obj.get("clerk_result_count"), int),
            chunk_size=_opt(obj.get("chunk_size"), int),
        )


@dataclass
class TierNodeStatus:
    """Status of one node of a tiered aggregation's derived tree.

    ``exists`` is False for a node whose sub-aggregation record was never
    provisioned (the topology is derived, not stored — see
    protocol/tiers.py); counts are zero for such nodes. ``result_ready``
    means at least one of the node's snapshots has collected enough clerk
    results to reconstruct."""

    aggregation: AggregationId
    tier: int
    parent: Optional[AggregationId]
    exists: bool
    number_of_participations: int
    result_ready: bool

    def to_json(self):
        return {
            "aggregation": self.aggregation.to_json(),
            "tier": self.tier,
            "parent": _opt(self.parent, lambda p: p.to_json()),
            "exists": self.exists,
            "number_of_participations": self.number_of_participations,
            "result_ready": self.result_ready,
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            aggregation=AggregationId.from_json(obj["aggregation"]),
            tier=int(obj["tier"]),
            parent=_opt(obj.get("parent"), AggregationId.from_json),
            exists=bool(obj["exists"]),
            number_of_participations=int(obj["number_of_participations"]),
            result_ready=bool(obj["result_ready"]),
        )


@dataclass
class TierStatus:
    """Per-node readiness of a tiered aggregation's whole derived tree,
    root first in breadth-first order (additive resource, no reference
    counterpart)."""

    aggregation: AggregationId
    tiers: int
    sub_cohort_size: int
    nodes: list  # list[TierNodeStatus], BFS order, root first

    def to_json(self):
        return {
            "aggregation": self.aggregation.to_json(),
            "tiers": self.tiers,
            "sub_cohort_size": self.sub_cohort_size,
            "nodes": [n.to_json() for n in self.nodes],
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            aggregation=AggregationId.from_json(obj["aggregation"]),
            tiers=int(obj["tiers"]),
            sub_cohort_size=int(obj["sub_cohort_size"]),
            nodes=[TierNodeStatus.from_json(n) for n in obj["nodes"]],
        )


@dataclass
class Pong:
    """Return message of the ping call (methods.rs:6-10)."""

    running: bool

    def to_json(self):
        return {"running": self.running}

    @classmethod
    def from_json(cls, obj):
        return cls(running=bool(obj["running"]))
