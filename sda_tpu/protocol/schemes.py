"""Cryptographic scheme descriptors and their derived properties.

Wire parity with /root/reference/protocol/src/crypto.rs (serde externally
tagged enums):
- newtype variants: ``{"Sodium": "<base64>"}`` (Encryption, keys, Signature)
- unit variants: ``"None"`` / ``"Sodium"`` (LinearMaskingScheme::None,
  AdditiveEncryptionScheme::Sodium)
- struct variants: ``{"Full": {"modulus": 433}}`` etc.

Derived properties (input/output size, privacy/reconstruction thresholds)
mirror crypto.rs:117-155; in particular the packed-Shamir dropout-tolerance
formula ``reconstruction_threshold = privacy_threshold + secret_count``
(crypto.rs:151).
"""

from __future__ import annotations

from dataclasses import dataclass

from .helpers import B32, B64, Binary


def _tagged(tag, payload):
    return {tag: payload}


def _untag(obj, expected_tags):
    """Decode an externally tagged enum value; returns (tag, payload)."""
    if isinstance(obj, str):
        if obj not in expected_tags:
            raise ValueError(f"unknown enum variant {obj!r}, expected one of {expected_tags}")
        return obj, None
    if isinstance(obj, dict) and len(obj) == 1:
        tag, payload = next(iter(obj.items()))
        if tag not in expected_tags:
            raise ValueError(f"unknown enum variant {tag!r}, expected one of {expected_tags}")
        return tag, payload
    raise ValueError(f"malformed enum value {obj!r}")


class _SodiumNewtype:
    """Base for single-variant ``Sodium(bytes)`` enums."""

    INNER = None  # B32 / B64 / Binary
    __slots__ = ("inner",)

    def __init__(self, inner):
        if isinstance(inner, (bytes, bytearray)):
            inner = self.INNER(bytes(inner))
        if not isinstance(inner, self.INNER):
            raise TypeError(f"{type(self).__name__} expects {self.INNER.__name__}")
        self.inner = inner

    @property
    def data(self) -> bytes:
        return self.inner.data

    def to_json(self):
        return _tagged("Sodium", self.inner.to_json())

    @classmethod
    def from_json(cls, obj):
        _, payload = _untag(obj, ("Sodium",))
        return cls(cls.INNER.from_json(payload))

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other.inner == self.inner

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.inner))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.inner!r})"


class Encryption(_SodiumNewtype):
    """A ciphertext. Reference enum has one variant, ``Sodium`` (sealed
    box, crypto.rs:8-14); ``Paillier`` is our wire-compatible extension
    carrying packed-Paillier blocks, tagged so external consumers never
    misread one payload kind as the other."""

    INNER = Binary
    VARIANTS = ("Sodium", "Paillier")
    __slots__ = ("variant",)

    def __init__(self, inner, variant: str = "Sodium"):
        super().__init__(inner)
        if variant not in self.VARIANTS:
            raise ValueError(f"unknown Encryption variant {variant!r}")
        self.variant = variant

    def to_json(self):
        return _tagged(self.variant, self.inner.to_json())

    @classmethod
    def from_json(cls, obj):
        tag, payload = _untag(obj, cls.VARIANTS)
        return cls(Binary.from_json(payload), variant=tag)

    @classmethod
    def _from_wire(cls, data: bytes, variant: str):
        """Trusted bulk-decode path: wrap ciphertext bytes sliced out of a
        validated binary frame, bypassing the isinstance-dispatching
        constructors (profiled hot at thousands of ciphertexts per frame).
        Callers must pass ``bytes`` and a tag from ``VARIANTS``."""
        inner = object.__new__(Binary)
        inner.data = data
        self = object.__new__(cls)
        self.inner = inner
        self.variant = variant
        return self

    def __eq__(self, other) -> bool:
        return (
            type(other) is type(self)
            and other.inner == self.inner
            and other.variant == self.variant
        )

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.variant, self.inner))


class EncryptionKey(_SodiumNewtype):
    """Sodium box public key (32 bytes)."""

    INNER = B32

    @classmethod
    def from_json(cls, obj):
        # polymorphic: sodium keys are {"Sodium": b64}; Paillier public
        # keys (the sketched PackedPaillier extension) are
        # {"Paillier": {"n": decimal}} — both usable wherever a key goes
        tag, payload = _untag(obj, ("Sodium", "Paillier"))
        if tag == "Paillier":
            return PaillierEncryptionKey(int(payload["n"]))
        return cls(B32.from_json(payload))


@dataclass(frozen=True)
class PaillierEncryptionKey:
    """Paillier public key: the modulus n (g is fixed to n+1)."""

    n: int

    def to_json(self):
        return {"Paillier": {"n": str(self.n)}}

    @classmethod
    def from_json(cls, obj):
        _, payload = _untag(obj, ("Paillier",))
        return cls(int(payload["n"]))


class Signature(_SodiumNewtype):
    """Ed25519 detached signature (64 bytes)."""

    INNER = B64


class SigningKey(_SodiumNewtype):
    """Ed25519 signing key (64 bytes: seed || public)."""

    INNER = B64


class VerificationKey(_SodiumNewtype):
    """Ed25519 verification key (32 bytes)."""

    INNER = B32


# ---------------------------------------------------------------------------
# Masking schemes
# ---------------------------------------------------------------------------


class LinearMaskingScheme:
    """Masking scheme between recipient and committee (crypto.rs:43-74)."""

    def has_mask(self) -> bool:
        raise NotImplementedError

    @staticmethod
    def from_json(obj):
        tag, payload = _untag(obj, ("None", "Full", "ChaCha"))
        if tag == "None":
            return NoMasking()
        if tag == "Full":
            return FullMasking(modulus=int(payload["modulus"]))
        return ChaChaMasking(
            modulus=int(payload["modulus"]),
            dimension=int(payload["dimension"]),
            seed_bitsize=int(payload["seed_bitsize"]),
        )


@dataclass(frozen=True)
class NoMasking(LinearMaskingScheme):
    """No masking: secrets are shared directly to the clerks."""

    def has_mask(self) -> bool:
        return False

    def to_json(self):
        return "None"


@dataclass(frozen=True)
class FullMasking(LinearMaskingScheme):
    """Per-element uniform masking with fresh OS randomness."""

    modulus: int

    def has_mask(self) -> bool:
        return True

    def to_json(self):
        return _tagged("Full", {"modulus": self.modulus})


@dataclass(frozen=True)
class ChaChaMasking(LinearMaskingScheme):
    """Seed-compressed masking: upload a small seed, expand via ChaCha20.

    Trades upload/download size for expansion compute on both sides
    (crypto.rs:53-62).
    """

    modulus: int
    dimension: int
    seed_bitsize: int

    def has_mask(self) -> bool:
        return True

    def to_json(self):
        return _tagged(
            "ChaCha",
            {
                "modulus": self.modulus,
                "dimension": self.dimension,
                "seed_bitsize": self.seed_bitsize,
            },
        )


# ---------------------------------------------------------------------------
# Secret sharing schemes
# ---------------------------------------------------------------------------


class LinearSecretSharingScheme:
    """Sharing scheme across the clerk committee (crypto.rs:79-155).

    Derived properties are plain attributes/properties: ``input_size``
    (secrets per batch), ``output_size`` (shares produced = committee size),
    ``privacy_threshold`` (max colluding clerks tolerated), and
    ``reconstruction_threshold`` (min clerk results needed).
    """

    @staticmethod
    def from_json(obj):
        tag, payload = _untag(obj, ("Additive", "BasicShamir", "PackedShamir"))
        if tag == "Additive":
            return AdditiveSharing(
                share_count=int(payload["share_count"]), modulus=int(payload["modulus"])
            )
        if tag == "BasicShamir":
            return BasicShamirSharing(
                share_count=int(payload["share_count"]),
                privacy_threshold=int(payload["privacy_threshold"]),
                prime_modulus=int(payload["prime_modulus"]),
            )
        return PackedShamirSharing(
            secret_count=int(payload["secret_count"]),
            share_count=int(payload["share_count"]),
            privacy_threshold=int(payload["privacy_threshold"]),
            prime_modulus=int(payload["prime_modulus"]),
            omega_secrets=int(payload["omega_secrets"]),
            omega_shares=int(payload["omega_shares"]),
        )


@dataclass(frozen=True)
class AdditiveSharing(LinearSecretSharingScheme):
    """n-of-n additive sharing in Z_modulus."""

    share_count: int
    modulus: int

    @property
    def input_size(self) -> int:
        return 1

    @property
    def output_size(self) -> int:
        return self.share_count

    @property
    def privacy_threshold(self) -> int:
        return self.share_count - 1

    @property
    def reconstruction_threshold(self) -> int:
        return self.share_count

    def to_json(self):
        return _tagged(
            "Additive", {"share_count": self.share_count, "modulus": self.modulus}
        )


@dataclass(frozen=True)
class BasicShamirSharing(LinearSecretSharingScheme):
    """Classic (non-packed) Shamir over F_p: one degree-t polynomial per
    secret, shares at points 1..n, reconstruction from any t+1 shares.

    The reference sketches this variant but leaves it commented out
    (crypto.rs:89-96, same field names); here it is implemented — unlike
    packed Shamir it imposes NO radix structure on the field or committee
    (any prime, any share_count), at the cost of one polynomial per
    element instead of per k-batch.
    """

    share_count: int
    privacy_threshold: int
    prime_modulus: int

    def __post_init__(self):
        if not 0 < self.privacy_threshold < self.share_count:
            raise ValueError("need 0 < privacy_threshold < share_count")
        if self.share_count >= self.prime_modulus:
            # evaluation points 1..n must be distinct and nonzero mod p: a
            # point ≡ 0 would hand a clerk the raw secret, colliding points
            # make reveal impossible — reject at construction (incl. wire)
            raise ValueError("share_count must be below the prime modulus")

    @property
    def input_size(self) -> int:
        return 1

    @property
    def output_size(self) -> int:
        return self.share_count

    @property
    def reconstruction_threshold(self) -> int:
        return self.privacy_threshold + 1

    def to_json(self):
        return _tagged(
            "BasicShamir",
            {
                "share_count": self.share_count,
                "privacy_threshold": self.privacy_threshold,
                "prime_modulus": self.prime_modulus,
            },
        )


@dataclass(frozen=True)
class PackedShamirSharing(LinearSecretSharingScheme):
    """Packed Shamir over F_p: one degree-(t+k) polynomial hides k secrets.

    Valid parameter sets satisfy ``order(omega_secrets) ==
    secret_count + privacy_threshold + 1`` (a power of 2) and
    ``order(omega_shares) == share_count + 1`` (a power of 3), with
    ``p = 1 (mod 2^a * 3^b)``; see the verified p=433 test vector in
    /root/reference/integration-tests/tests/full_loop.rs:56-64.
    """

    secret_count: int
    share_count: int
    privacy_threshold: int
    prime_modulus: int
    omega_secrets: int
    omega_shares: int

    @property
    def input_size(self) -> int:
        return self.secret_count

    @property
    def output_size(self) -> int:
        return self.share_count

    @property
    def reconstruction_threshold(self) -> int:
        return self.privacy_threshold + self.secret_count

    def to_json(self):
        return _tagged(
            "PackedShamir",
            {
                "secret_count": self.secret_count,
                "share_count": self.share_count,
                "privacy_threshold": self.privacy_threshold,
                "prime_modulus": self.prime_modulus,
                "omega_secrets": self.omega_secrets,
                "omega_shares": self.omega_shares,
            },
        )


# ---------------------------------------------------------------------------
# Additive encryption schemes
# ---------------------------------------------------------------------------


class AdditiveEncryptionScheme:
    """Transport encryption scheme for shares/masks (crypto.rs:159-188)."""

    def batch_size(self) -> int:
        raise NotImplementedError

    @staticmethod
    def from_json(obj):
        tag, payload = _untag(obj, ("Sodium", "PackedPaillier"))
        if tag == "PackedPaillier":
            return PackedPaillierEncryptionScheme(
                component_count=int(payload["component_count"]),
                component_bitsize=int(payload["component_bitsize"]),
                max_value_bitsize=int(payload["max_value_bitsize"]),
                min_modulus_bitsize=int(payload["min_modulus_bitsize"]),
            )
        return SodiumEncryptionScheme()


@dataclass(frozen=True)
class SodiumEncryptionScheme(AdditiveEncryptionScheme):
    """Sodium sealed-box transport encryption."""

    def batch_size(self) -> int:
        return 1

    def to_json(self):
        return "Sodium"


@dataclass(frozen=True)
class PackedPaillierEncryptionScheme(AdditiveEncryptionScheme):
    """Packed Paillier transport encryption — additively homomorphic.

    The reference sketches exactly these fields (crypto.rs:164-174) and
    names Paillier as its scale-up path; here it is implemented. Masks
    encrypted under this scheme can be combined BY THE SERVER (ciphertext
    multiplication), so the recipient decrypts one ciphertext per
    component block regardless of participant count. Up to
    ``2^(component_bitsize - max_value_bitsize)`` ciphertexts may be
    combined before a component could carry into its neighbor.
    """

    component_count: int
    component_bitsize: int
    max_value_bitsize: int
    min_modulus_bitsize: int

    def __post_init__(self):
        if self.max_value_bitsize > self.component_bitsize:
            raise ValueError("component values larger than their slots")
        if self.component_bitsize > 62:
            # decrypted component sums must fit the i64 share plane
            raise ValueError("component_bitsize must be <= 62")
        if self.component_count * self.component_bitsize >= self.min_modulus_bitsize:
            raise ValueError("components do not fit the plaintext space")

    def batch_size(self) -> int:
        return self.component_count

    def to_json(self):
        return _tagged(
            "PackedPaillier",
            {
                "component_count": self.component_count,
                "component_bitsize": self.component_bitsize,
                "max_value_bitsize": self.max_value_bitsize,
                "min_modulus_bitsize": self.min_modulus_bitsize,
            },
        )
