"""Typed unique identifiers.

The reference generates one id newtype per resource via the ``uuid_id!`` macro
(/root/reference/protocol/src/helpers.rs:19-86); ids serialize as hyphenated
uuid strings. We keep one small Python class per id type so type confusion
(e.g. passing an AgentId where a SnapshotId is expected) stays a visible bug
rather than a silent one, and so the wire format is pinned.
"""

from __future__ import annotations

import uuid


class TypedId:
    """A uuid wrapper with nominal typing; wire form is the hyphenated string."""

    __slots__ = ("uuid", "_hash")

    def __init__(self, value=None):
        if value is None:
            self.uuid = uuid.uuid4()
        elif isinstance(value, uuid.UUID):
            self.uuid = value
        elif isinstance(value, TypedId):
            if type(value) is not type(self):
                raise TypeError(f"cannot build {type(self).__name__} from {type(value).__name__}")
            self.uuid = value.uuid
        elif isinstance(value, str):
            try:
                self.uuid = uuid.UUID(value)
            except ValueError:
                raise ValueError(f"unparseable uuid {value}")
        else:
            raise TypeError(f"cannot build {type(self).__name__} from {value!r}")

    @classmethod
    def random(cls):
        return cls(uuid.uuid4())

    @classmethod
    def _from_uuid_bytes(cls, raw: bytes):
        """Trusted bulk-decode path: build from 16 raw big-endian bytes,
        bypassing the dispatching constructor and ``uuid.UUID.__init__``
        (both profiled hot when a binary wire frame carries thousands of
        id columns). Callers must guarantee ``len(raw) == 16``."""
        u = object.__new__(uuid.UUID)
        object.__setattr__(u, "int", int.from_bytes(raw, "big"))
        object.__setattr__(u, "is_safe", uuid.SafeUUID.unknown)
        self = object.__new__(cls)
        self.uuid = u
        return self

    @classmethod
    def from_str(cls, s: str):
        return cls(s)

    def to_json(self) -> str:
        return str(self.uuid)

    @classmethod
    def from_json(cls, obj):
        if not isinstance(obj, str):
            raise ValueError(f"expected hyphenated uuid string, got {obj!r}")
        return cls(obj)

    def __str__(self) -> str:
        return str(self.uuid)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({str(self.uuid)!r})"

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other.uuid.int == self.uuid.int

    def __hash__(self) -> int:
        # Ids are immutable and hashed constantly as store keys; cache the
        # hash on first use rather than re-deriving the tuple each lookup.
        try:
            return self._hash
        except AttributeError:
            h = hash((type(self).__name__, self.uuid))
            self._hash = h
            return h


class AgentId(TypedId):
    """Unique agent identifier (resources.rs:19)."""


class VerificationKeyId(TypedId):
    """Unique verification key identifier (resources.rs:3)."""


class EncryptionKeyId(TypedId):
    """Unique encryption key identifier (resources.rs:37)."""


class AggregationId(TypedId):
    """Unique aggregation identifier (resources.rs:69)."""


class ParticipationId(TypedId):
    """Unique participation identifier (resources.rs:110)."""


class SnapshotId(TypedId):
    """Unique snapshot identifier (resources.rs:123)."""


class ClerkingJobId(TypedId):
    """Unique clerking job identifier (resources.rs:141)."""
