"""``sdad`` — the server daemon (and committee runner).

Parity with /root/reference/server-cli/src/bin/sdad.rs: pick a storage
backend (``--file root`` durable, ``--mem`` in-memory; the reference's
equivalents are ``--jfs``/``--mongo``), then ``httpd -b ip:port`` (default
127.0.0.1:8888).

``committee`` runs several clerk identities concurrently against a
remote server (``client.run_committee``): one worker thread per clerk,
so committee wall time approaches the slowest member instead of the
round-robin sum — the daemon shape for hosting a whole committee in one
process.
"""

from __future__ import annotations

import argparse
import logging
import sys
import time

from ..server import new_file_server, new_mem_server

log = logging.getLogger("sda.sdad")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="sdad", description="SDA server daemon")
    parser.add_argument("-v", "--verbose", action="count", default=0)
    backend = parser.add_mutually_exclusive_group()
    backend.add_argument("--file", metavar="ROOT", help="durable JSON-file store root")
    backend.add_argument("--sqlite", metavar="DB", help="sqlite database path (production)")
    backend.add_argument("--mem", action="store_true", help="in-memory store (dev)")
    parser.add_argument(
        "--shards",
        type=int,
        default=1,
        metavar="K",
        help="partition aggregation state over K store shards "
        "(file/sqlite paths become per-shard roots under the given path)",
    )
    parser.add_argument(
        "--replicas",
        type=int,
        default=None,
        metavar="R",
        help="replicate each aggregation's state over the first R shards "
        "of its ring preference (quorum writes + hinted handoff; default "
        "SDA_SHARD_REPLICAS or 1 — single-home routing). R>1 lets any "
        "one store shard die mid-round without losing the round.",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    httpd = sub.add_parser("httpd", help="run the REST server")
    httpd.add_argument("-b", "--bind", default="127.0.0.1:8888", metavar="IP:PORT")
    committee = sub.add_parser(
        "committee", help="run several clerk identities concurrently"
    )
    committee.add_argument(
        "-s",
        "--server",
        action="append",
        default=None,
        metavar="URL",
        help="SDA service URL; repeat once per frontend of a multi-frontend "
        "deployment, in frontend order (every process must agree on it — "
        "the clerks' keyed requests ring-route over the list exactly like "
        "a multi-root client). Default http://127.0.0.1:8888",
    )
    committee.add_argument(
        "-i",
        "--identity",
        action="append",
        required=True,
        metavar="DIR",
        help="clerk identity/keys directory (repeat once per clerk)",
    )
    committee.add_argument(
        "-o", "--once", action="store_true", help="drain every queue once and exit"
    )
    committee.add_argument(
        "-p",
        "--poll-seconds",
        type=float,
        default=2.0,
        metavar="SECONDS",
        help="max sleep between queue polls (jittered backoff ramps up "
        "to this after an idle pass)",
    )
    return parser


def run_committee_daemon(args) -> int:
    from pathlib import Path

    from ..client import SdaClient, run_committee
    from ..crypto import Filebased, Keystore
    from ..protocol import Agent, SdaError
    from ..rest import SdaHttpClient, TokenStore

    roots = args.server or ["http://127.0.0.1:8888"]
    clerks = []
    for d in args.identity:
        identity = Path(d)
        agent = Filebased(identity).get_aliased("agent", Agent.from_json)
        if agent is None:
            raise SystemExit(f"sdad: no agent identity under {identity}")
        clerks.append(
            SdaClient(
                agent,
                Keystore(identity / "keys"),
                SdaHttpClient(roots, TokenStore(identity)),
            )
        )
    log.info(
        "running a committee of %d clerks against %d frontend(s): %s",
        len(clerks), len(roots), " ".join(roots),
    )
    # bounded jittered backoff between polls: after a pass that found
    # work the queues are re-polled almost immediately (stragglers from
    # a snapshot land promptly); an idle or stalled server is probed at
    # most every poll_seconds, so the daemon never spins
    from ..utils.faults import Backoff

    backoff = Backoff(cap=max(args.poll_seconds, 0.001))
    while True:
        try:
            n = run_committee(clerks, -1)
        except SdaError as e:
            # a transient transport stall must not kill the daemon; the
            # next poll retries. --once runs propagate: the caller asked
            # for exactly one attempt and needs the failure.
            if args.once:
                raise
            log.warning("committee pass failed (%s); retrying next poll", e)
        else:
            if n:
                log.info("committee processed %d jobs", n)
                backoff.reset()
            if args.once:
                return 0
        time.sleep(backoff.next_delay())


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    level = [logging.INFO, logging.DEBUG][min(args.verbose, 1)]
    logging.basicConfig(level=level, stream=sys.stderr, format="%(asctime)s %(name)s %(message)s")

    if args.command == "committee":
        return run_committee_daemon(args)

    shards = max(int(args.shards or 1), 1)
    replicas = args.replicas if args.replicas is None else max(int(args.replicas), 1)
    if shards > 1:
        from ..server import new_sharded_server

        if args.file:
            service = new_sharded_server("file", shards, args.file, replicas=replicas)
            log.info("using file store at %s over %d shards", args.file, shards)
        elif args.sqlite:
            service = new_sharded_server("sqlite", shards, args.sqlite, replicas=replicas)
            log.info("using sqlite store at %s over %d shards", args.sqlite, shards)
        else:
            service = new_sharded_server("mem", shards, replicas=replicas)
            log.info("using in-memory store over %d shards", shards)
        log.info(
            "replication factor %d (quorum writes + hinted handoff)"
            if service.shard_router.replicas > 1
            else "replication factor %d (single-home routing)",
            service.shard_router.replicas,
        )
    elif args.file:
        service = new_file_server(args.file)
        log.info("using file store at %s", args.file)
    elif args.sqlite:
        from ..server import new_sqlite_server

        service = new_sqlite_server(args.sqlite)
        log.info("using sqlite store at %s", args.sqlite)
    else:
        service = new_mem_server()
        log.info("using in-memory store")

    host, _, port = args.bind.rpartition(":")
    from ..rest.server import listen

    httpd = listen((host or "127.0.0.1", int(port)), service)
    bound_host, bound_port = httpd.server_address[:2]
    # report the bound address on stdout: with ``-b ip:0`` the kernel picks
    # the port, so parent processes (tests, orchestration) parse this line
    # instead of racing a probe-socket for a "free" port
    print(f"sdad: listening on {bound_host}:{bound_port}", flush=True)
    log.info("sda REST server listening on %s:%s", bound_host, bound_port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        # keep-alive accounting: force-close live persistent connections
        # instead of waiting out their idle timeout (SDA_REST_IDLE_TIMEOUT_S)
        log.info("interrupted; closing live connections")
    finally:
        httpd.shutdown()
        httpd.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
