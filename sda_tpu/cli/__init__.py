"""sda_tpu.cli — the ``sda`` agent CLI and ``sdad`` server daemon."""
