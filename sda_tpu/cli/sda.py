"""``sda`` — the agent command line.

Subcommand parity with /root/reference/cli/src/main.rs:29-81: ``ping``,
``agent create/show``, ``agent keys create/show``, ``clerk [--once]``,
``aggregations create/begin/end/reveal``, ``participate``. Identity lives in
a directory (default ``.sda``; keys under ``keys/``), the server defaults to
``http://localhost:8888``.

One deliberate capability upgrade: ``--sharing shamir`` works here (the
reference CLI panics ``unimplemented!()`` at cli/src/main.rs:226) — packed
Shamir parameters are generated on the fly from ``--secret-count`` /
``--privacy-threshold`` and the requested modulus size.
"""

from __future__ import annotations

import argparse
import logging
import sys
import time
from pathlib import Path

from ..client import SdaClient
from ..crypto import Keystore, Filebased
from ..protocol import (
    Aggregation,
    AggregationId,
    Agent,
    AgentId,
    ChaChaMasking,
    EncryptionKeyId,
    FullMasking,
    NoMasking,
    AdditiveSharing,
    BasicShamirSharing,
    PackedShamirSharing,
    SdaError,
    SodiumEncryptionScheme,
)
from ..rest import SdaHttpClient, TokenStore

log = logging.getLogger("sda.cli")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="sda", description="SDA agent CLI")
    parser.add_argument("-s", "--server", default="http://localhost:8888", help="Server root")
    parser.add_argument("-v", "--verbose", action="count", default=0)
    parser.add_argument(
        "-i", "--identity", default=".sda", help="Storage directory for identity and keys"
    )
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("ping", help="check service availability")

    agent = sub.add_parser("agent", help="identity management")
    agent_sub = agent.add_subparsers(dest="agent_command", required=True)
    agent_sub.add_parser("show")
    create = agent_sub.add_parser("create")
    create.add_argument("-f", "--force", action="store_true", help="Overwrite any existing identity")
    keys = agent_sub.add_parser("keys")
    keys_sub = keys.add_subparsers(dest="keys_command", required=True)
    keys_sub.add_parser("create")
    keys_sub.add_parser("show")
    prof = agent_sub.add_parser(
        "profile", help="public profile (link external identities)"
    )
    prof_sub = prof.add_subparsers(dest="profile_command", required=True)
    pset = prof_sub.add_parser("set")
    pset.add_argument("--name")
    pset.add_argument("--twitter")
    pset.add_argument("--keybase")
    pset.add_argument("--website")
    pset.add_argument(
        "--clear", action="store_true",
        help="drop fields not given instead of keeping their current values",
    )
    pshow = prof_sub.add_parser("show")
    pshow.add_argument(
        "owner", nargs="?", help="agent id (default: own profile)"
    )

    clerk = sub.add_parser("clerk", help="run a clerk in a loop")
    clerk.add_argument("-o", "--once", action="store_true", help="Run just once and leave")
    clerk.add_argument(
        "--poll-seconds",
        type=float,
        default=2.0,
        help="Max sleep between queue polls (jittered backoff ramps up "
        "to this after an idle pass; the pre-backoff fixed sleep was 300)",
    )

    aggs = sub.add_parser(
        "aggregations", aliases=["agg", "aggs", "aggregation"], help="manage aggregations"
    )
    aggs_sub = aggs.add_subparsers(dest="agg_command", required=True)
    create = aggs_sub.add_parser("create")
    create.add_argument("title")
    create.add_argument("dimension", type=int)
    create.add_argument("modulus", type=int)
    create.add_argument("key", help="key to use for recipient encryption")
    create.add_argument("share_count", type=int)
    create.add_argument("--id")
    create.add_argument("--mask", choices=["none", "full", "chacha"], default="none")
    create.add_argument(
        "--sharing", choices=["add", "shamir", "basic"], default="add",
        help="add = n-of-n additive; shamir = packed Shamir (generated field); "
        "basic = classic Shamir (any prime modulus, any committee size)",
    )
    create.add_argument("--secret-count", type=int, help="shamir: secrets packed per batch")
    create.add_argument("--privacy-threshold", type=int, help="shamir: collusion tolerance")
    for name in ("begin", "end", "reveal"):
        p = aggs_sub.add_parser(name)
        p.add_argument("aggregation_id")
        if name == "begin":
            p.add_argument(
                "--clerk",
                action="append",
                dest="clerks",
                metavar="AGENT_ID",
                help="choose this agent as a committee clerk (repeat once "
                "per clerk, in committee order); default: first suggested "
                "candidates",
            )

    part = sub.add_parser("participate", help="contribute a vector to an aggregation")
    part.add_argument("id", help="aggregation id")
    part.add_argument("values", nargs="+", type=int)

    return parser


def make_client(args):
    identity = Path(args.identity)
    service = SdaHttpClient(args.server, TokenStore(identity))
    identitystore = Filebased(identity)
    keystore = Keystore(identity / "keys")
    agent = identitystore.get_aliased("agent", Agent.from_json)
    return service, identitystore, keystore, agent


def require_agent(agent):
    if agent is None:
        raise SystemExit('Agent is needed. Maybe run "sda agent create" ?')
    return agent


def _verify_sharing(scheme) -> None:
    """Rank-based privacy/reconstruction check (ops.verify_scheme) on every
    CLI-constructed Shamir scheme — committee-sized, so it is cheap."""
    from ..ops import verify_scheme

    verify_scheme(scheme)


def cmd_aggregations_create(client, args) -> None:
    modulus = args.modulus
    if args.sharing == "add":
        sharing = AdditiveSharing(share_count=args.share_count, modulus=modulus)
    elif args.sharing == "basic":
        from ..ops.params import is_prime

        if not is_prime(modulus):
            raise SystemExit(f"basic Shamir needs a prime modulus, got {modulus}")
        t = (args.share_count - 1) if args.privacy_threshold is None else args.privacy_threshold
        if not 0 < t < args.share_count:
            raise SystemExit(f"privacy threshold {t} must be in (0, share_count)")
        sharing = BasicShamirSharing(
            share_count=args.share_count, privacy_threshold=t, prime_modulus=modulus
        )
        _verify_sharing(sharing)
    else:
        from ..ops import find_packed_parameters

        k = 3 if args.secret_count is None else args.secret_count
        t = (args.share_count - k - 1) if args.privacy_threshold is None else args.privacy_threshold
        p, w2, w3 = find_packed_parameters(
            k, t, args.share_count, min_modulus_bits=min(30, max(8, modulus.bit_length()))
        )
        if p != modulus:
            log.warning("modulus %d unsuitable for packed Shamir; using prime %d", modulus, p)
            modulus = p
        sharing = PackedShamirSharing(k, args.share_count, t, p, w2, w3)
        _verify_sharing(sharing)
    mask = {
        "none": NoMasking(),
        "full": FullMasking(modulus=modulus),
        "chacha": ChaChaMasking(modulus=modulus, dimension=args.dimension, seed_bitsize=128),
    }[args.mask]
    agg = Aggregation(
        id=AggregationId(args.id) if args.id else AggregationId.random(),
        title=args.title,
        vector_dimension=args.dimension,
        modulus=modulus,
        recipient=client.agent.id,
        recipient_key=EncryptionKeyId(args.key),
        masking_scheme=mask,
        committee_sharing_scheme=sharing,
        recipient_encryption_scheme=SodiumEncryptionScheme(),
        committee_encryption_scheme=SodiumEncryptionScheme(),
    )
    client.upload_aggregation(agg)
    print(f"aggregation created. id: {agg.id}")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    level = [logging.WARNING, logging.INFO, logging.DEBUG][min(args.verbose, 2)]
    logging.basicConfig(level=level, stream=sys.stderr, format="%(asctime)s %(name)s %(message)s")

    service, identitystore, keystore, agent = make_client(args)

    if args.command == "ping":
        pong = service.ping()
        if not pong.running:
            raise SystemExit("Service may not be running")
        log.info("Service appears to be running")
        return 0

    if args.command == "agent":
        if args.agent_command == "show":
            if agent is None:
                log.warning("No local agent found")
            else:
                print(f"Local agent is {agent.id}")
            return 0
        if args.agent_command == "create":
            if agent is not None and not args.force:
                log.warning("Using existing agent; use --force to create new")
            else:
                agent = SdaClient.new_agent(keystore)
                identitystore.put_aliased("agent", agent)
                log.info("Created new agent with id %s", agent.id)
            SdaClient(agent, keystore, service).upload_agent()
            return 0
        if args.agent_command == "keys":
            client = SdaClient(require_agent(agent), keystore, service)
            if args.keys_command == "create":
                key = client.new_encryption_key()
                client.upload_encryption_key(key)
                print(f"Created and uploaded key: {key}")
                return 0
            if args.keys_command == "show":
                for key_id in keystore.list_ids():
                    print(key_id)
                return 0
        if args.agent_command == "profile":
            client = SdaClient(require_agent(agent), keystore, service)
            if args.profile_command == "set":
                # read-merge-write: flags imply field-level update, so
                # untouched fields keep their current values (pass
                # --clear to drop everything not given)
                existing = (
                    None if args.clear else client.get_profile(client.agent.id)
                )

                def merged(flag, field):
                    if flag is not None:
                        return flag
                    return getattr(existing, field) if existing else None

                profile = client.update_profile(
                    name=merged(args.name, "name"),
                    twitter_id=merged(args.twitter, "twitter_id"),
                    keybase_id=merged(args.keybase, "keybase_id"),
                    website=merged(args.website, "website"),
                )
                print(f"Profile updated for {profile.owner}")
                return 0
            if args.profile_command == "show":
                owner = AgentId(args.owner) if args.owner else client.agent.id
                profile = client.get_profile(owner)
                if profile is None:
                    log.warning("No profile for %s", owner)
                    return 1
                for field in ("name", "twitter_id", "keybase_id", "website"):
                    value = getattr(profile, field)
                    if value is not None:
                        print(f"{field}: {value}")
                return 0

    if args.command == "clerk":
        from ..utils.faults import Backoff

        client = SdaClient(require_agent(agent), keystore, service)
        service.ping()
        # bounded jittered backoff between polls: a busy queue is
        # re-polled almost immediately after draining, an idle or
        # stalled server at most every poll_seconds — so neither a hot
        # committee nor a wedged deployment makes the clerk spin
        backoff = Backoff(cap=max(args.poll_seconds, 0.001))
        while True:
            log.debug("Polling for clerking job")
            try:
                n = client.run_chores(-1)
            except SdaError as e:
                # a transient transport stall (REST timeout, connection
                # reset) must not kill a long-running clerk daemon; the
                # next poll retries. --once runs propagate: the caller
                # asked for exactly one attempt and needs the failure.
                if args.once:
                    raise
                log.warning("clerking pass failed (%s); retrying next poll", e)
            else:
                if n:
                    backoff.reset()
            if args.once:
                return 0
            time.sleep(backoff.next_delay())

    if args.command in ("aggregations", "agg", "aggs", "aggregation"):
        client = SdaClient(require_agent(agent), keystore, service)
        service.ping()
        if args.agg_command == "create":
            cmd_aggregations_create(client, args)
            return 0
        agg_id = AggregationId(args.aggregation_id)
        if args.agg_command == "begin":
            chosen = (
                [AgentId(c) for c in args.clerks] if args.clerks else None
            )
            client.begin_aggregation(agg_id, chosen_clerks=chosen)
            return 0
        if args.agg_command == "end":
            client.end_aggregation(agg_id)
            return 0
        if args.agg_command == "reveal":
            output = client.reveal_aggregation(agg_id).positive()
            print("result:", " ".join(str(v) for v in output.values))
            return 0

    if args.command == "participate":
        client = SdaClient(require_agent(agent), keystore, service)
        client.participate(args.values, AggregationId(args.id))
        return 0

    raise SystemExit(f"Unknown command {args.command}")


if __name__ == "__main__":
    sys.exit(main())
