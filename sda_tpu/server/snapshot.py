"""The snapshot pipeline — the server's orchestration heart.

Mirrors /root/reference/server/src/snapshot.rs:4-47: freeze the current
participation set, transpose the (participants x clerks) ciphertext matrix,
enqueue one durable ClerkingJob per committee member, persist the snapshot,
and (when the scheme masks) collect every participation's recipient
encryption into the snapshot mask blob.

The run is an explicit STAGE PIPELINE (``SNAPSHOT_STAGES``): freeze →
job fan-out → mask collect → commit, each stage a named function over the
same (server, aggregation, snapshot) triple. Everything before the commit
stage is idempotent — membership freeze is write-once, job ids
deterministic, mask blob a plain overwrite of identical content — so a
crashed run retried by the client replays cleanly into the stores'
create-if-identical semantics.

Hierarchical aggregations run this SAME pipeline once per node of their
derived tree (protocol/tiers.py): each sub-aggregation's snapshot fans
its sub-cohort's columns out to its own sub-committee, so per-clerk work
is O(cohort/m) instead of O(cohort). ``snapshot_dag`` exposes the
execution order — leaves first, root last, each node's snapshot
depending on its children's promotions having landed — which the client
round driver (client/tiers.py) walks bottom-up.
"""

from __future__ import annotations

import logging
import uuid

from ..protocol import ClerkingJob, ClerkingJobId, ServerError
from ..protocol import tiers as tiers_mod
from ..utils.metrics import get_metrics
from . import stores as stores_mod

log = logging.getLogger("sda.server.snapshot")

# Deterministic job ids: uuid5 of (snapshot, clerk position). A crashed
# snapshot run retried by the client re-creates byte-identical jobs, which
# the stores' create-if-identical semantics absorb — no duplicate jobs, no
# double-counted results.
_JOB_NAMESPACE = uuid.UUID("6b1b36cf-4f3a-4bca-8a3c-1d53437e8ed9")


def _job_id(snapshot_id, clerk_index: int) -> ClerkingJobId:
    return ClerkingJobId(uuid.uuid5(_JOB_NAMESPACE, f"{snapshot_id}:{clerk_index}"))


def snapshot_dag(aggregation) -> list:
    """The sub-aggregation DAG a full round of ``aggregation`` snapshots
    through, in execution order: leaves first, root last (reverse
    breadth-first over the derived tree). Each entry is a
    ``protocol.tiers.TierNode``; a node's snapshot may only be cut after
    its children's partial sums have been promoted into it, which is
    exactly the reversed-BFS order. Flat aggregations yield a
    single-node DAG — the degenerate tree."""
    return list(reversed(tiers_mod.iter_tier_nodes(aggregation)))


# -- pipeline stages ---------------------------------------------------------


def _stage_prepare_reshare(server, aggregation, snapshot) -> None:
    """Resolve share-promotion epochs BEFORE the membership freeze.

    A tiered parent's participation table may hold, per derived child,
    tier_reshare-tagged rows from several epochs (the full-committee
    epoch 0, plus a survivor reissue after a clerk death) and one
    mask-correction row. Only ONE consistent epoch per child may enter
    the frozen cut — folding two epochs would double-count the
    sub-cohort — so this stage picks, per child, the highest COMPLETE
    epoch (one consistent survivor set, a column row from every survivor,
    enough survivors to reconstruct) and discards every other tagged row
    of that child. A child with no complete epoch (or a masked child
    missing its correction row) contributes nothing: all its rows are
    dropped and the round continues exact off the surviving subtrees —
    the cross-tier threshold semantics client/tiers.py builds on.

    Runs only on tiered nodes, and only while membership is still
    unfrozen: once ``snapshot_participations`` has pinned a member list
    (a crashed earlier run), the resolution that freeze saw must stand —
    discarding a frozen member would corrupt the transpose count.
    """
    if not aggregation.is_tiered():
        return
    if (
        server.aggregation_store.count_participations_snapshot(
            snapshot.aggregation, snapshot.id
        )
        > 0
    ):
        return  # membership already frozen: resolution is pinned
    by_child: dict = {}
    for part in server.aggregation_store.iter_participations(snapshot.aggregation):
        tag = part.tier_reshare
        if tag is not None:
            by_child.setdefault(tag.child, []).append(part)
    needs_mask = aggregation.masking_scheme.has_mask()
    threshold = aggregation.committee_sharing_scheme.reconstruction_threshold
    discard = []
    for child, rows in by_child.items():
        mask_rows = [p for p in rows if p.tier_reshare.position is None]
        epochs: dict = {}
        for p in rows:
            if p.tier_reshare.position is not None:
                epochs.setdefault(p.tier_reshare.epoch, []).append(p)
        chosen = None
        for epoch in sorted(epochs, reverse=True):
            cols = epochs[epoch]
            survivor_sets = {tuple(p.tier_reshare.survivors) for p in cols}
            if len(survivor_sets) != 1:
                continue  # inconsistent weights: Lagrange columns disagree
            survivors = set(next(iter(survivor_sets)))
            positions = {p.tier_reshare.position for p in cols}
            if positions != survivors or len(survivors) < threshold:
                continue  # incomplete epoch: missing a survivor's column
            chosen = epoch
            break
        if chosen is None or (needs_mask and not mask_rows):
            discard.extend(p.id for p in rows)
            log.warning(
                "snapshot %s: child %s has no complete re-share epoch; "
                "dropping its %d promotion rows (subtree excluded)",
                snapshot.id,
                child,
                len(rows),
            )
            continue
        discard.extend(
            p.id
            for p in rows
            if p.tier_reshare.position is not None and p.tier_reshare.epoch != chosen
        )
    if discard:
        with get_metrics().phase("snapshot.prepare_reshare"):
            server.aggregation_store.discard_participations(
                snapshot.aggregation, discard
            )


def _stage_freeze(server, aggregation, snapshot) -> None:
    """Freeze the participation set: the consistent cut every later stage
    (and every retry) reads. Write-once per (aggregation, snapshot)."""
    with get_metrics().phase("snapshot.freeze"):
        server.aggregation_store.snapshot_participations(
            snapshot.aggregation, snapshot.id
        )


def _stage_fanout_jobs(server, aggregation, snapshot) -> None:
    """Transpose the frozen (participants x clerks) ciphertext matrix and
    enqueue one durable ClerkingJob per committee member."""
    metrics = get_metrics()
    committee = server.aggregation_store.get_committee(snapshot.aggregation)
    if committee is None:
        raise ServerError("lost committee")

    log.debug("snapshot %s: transposing + enqueueing clerking jobs", snapshot.id)
    with metrics.phase("snapshot.transpose"):
        # streaming backends enqueue jobs before later columns are even
        # read — malformed bodies must be rejected up front, or a
        # mid-stream failure leaves phantom durable jobs for a snapshot
        # that never commits (see AggregationsStore.validate_snapshot_clerk_jobs)
        server.aggregation_store.validate_snapshot_clerk_jobs(
            snapshot.aggregation, snapshot.id, len(committee.clerks_and_keys)
        )
        # chunked write-through: each clerk column flows to the job store
        # as an iterator of ranges, so peak memory is one chunk — not one
        # full column per clerk (the old iter_snapshot_clerk_jobs_data
        # path, still in place for callers that want whole columns)
        per_clerk = iter(
            server.aggregation_store.iter_snapshot_clerk_jobs_chunks(
                snapshot.aggregation,
                snapshot.id,
                len(committee.clerks_and_keys),
                stores_mod.job_chunk_size(),
            )
        )
    for ix, (clerk_id, _) in enumerate(committee.clerks_and_keys):
        with metrics.phase("snapshot.transpose"):
            try:
                chunks = next(per_clerk)
            except StopIteration:
                raise ServerError(
                    f"transpose yielded fewer than "
                    f"{len(committee.clerks_and_keys)} clerk columns"
                )
        # lazy backends do the column I/O as the enqueue consumes the
        # chunk iterator, so transpose and enqueue costs land in the
        # enqueue phase here (the chunked path interleaves them by design)
        with metrics.phase("snapshot.enqueue"):
            server.clerking_job_store.enqueue_clerking_job_chunked(
                ClerkingJob(
                    id=_job_id(snapshot.id, ix),
                    clerk=clerk_id,
                    aggregation=snapshot.aggregation,
                    snapshot=snapshot.id,
                    encryptions=[],
                ),
                chunks,
            )


def _stage_collect_masks(server, aggregation, snapshot) -> None:
    """Gather every frozen participation's recipient encryption into the
    snapshot mask blob (skipped entirely for non-masking schemes)."""
    if not aggregation.masking_scheme.has_mask():
        return
    log.debug("snapshot %s: collecting masking data", snapshot.id)
    recipient_encryptions = []
    for part in server.aggregation_store.iter_snapped_participations(
        snapshot.aggregation, snapshot.id
    ):
        if part.recipient_encryption is None:
            raise ServerError("participation should have had a recipient encryption")
        recipient_encryptions.append(part.recipient_encryption)
    recipient_encryptions = _maybe_combine_masks(
        server, aggregation, recipient_encryptions
    )
    server.aggregation_store.create_snapshot_mask(snapshot.id, recipient_encryptions)


def _stage_commit(server, aggregation, snapshot) -> None:
    """Persist the snapshot record — the COMMIT POINT: the retry guard in
    ``run_snapshot`` keys on it, so every earlier stage must be (and is)
    idempotent."""
    server.aggregation_store.create_snapshot(snapshot)


#: the pipeline, in order; each stage is f(server, aggregation, snapshot).
#: Every stage before the final commit is idempotent by construction.
SNAPSHOT_STAGES = (
    _stage_prepare_reshare,
    _stage_freeze,
    _stage_fanout_jobs,
    _stage_collect_masks,
    _stage_commit,
)


def run_snapshot(server, snapshot) -> None:
    aggregation = server.aggregation_store.get_aggregation(snapshot.aggregation)
    if aggregation is None:
        raise ServerError("lost aggregation")

    # Idempotent retry: the snapshot id is client-chosen; re-submitting an
    # existing snapshot must not enqueue a second set of clerking jobs
    # (duplicate results would double-count toward result_ready).
    if server.aggregation_store.get_snapshot(snapshot.aggregation, snapshot.id) is not None:
        log.debug("snapshot %s: already exists, retry is a no-op", snapshot.id)
        return

    get_metrics().count("snapshots")
    log.debug("snapshot %s: freezing participations", snapshot.id)
    for stage in SNAPSHOT_STAGES:
        stage(server, aggregation, snapshot)
    log.debug("snapshot %s: done", snapshot.id)


def _maybe_combine_masks(server, aggregation, recipient_encryptions):
    """Homomorphic server-side mask combine (the Paillier scale-up path,
    reference README "Doing more"): when masks are PackedPaillier-encrypted,
    multiply all participants' ciphertexts into ONE — the recipient then
    decrypts O(dim) data regardless of participant count. Public-key only;
    the untrusted server learns nothing. Falls back to the uncombined list
    (recipient combines after decrypting, still correct) if the cohort
    exceeds the packing's addition capacity or the key is unavailable.
    """
    from ..protocol import PackedPaillierEncryptionScheme

    scheme = aggregation.recipient_encryption_scheme
    if not isinstance(scheme, PackedPaillierEncryptionScheme):
        return recipient_encryptions
    if len(recipient_encryptions) < 2:
        return recipient_encryptions
    from ..ops.paillier import Packing

    capacity = Packing(
        scheme.component_count, scheme.component_bitsize, scheme.max_value_bitsize
    ).additions_capacity
    if len(recipient_encryptions) > capacity:
        log.warning(
            "snapshot: %d participations exceed Paillier addition capacity %d; "
            "leaving masks uncombined",
            len(recipient_encryptions),
            capacity,
        )
        return recipient_encryptions
    signed = server.agents_store.get_encryption_key(aggregation.recipient_key)
    if signed is None:
        log.warning("snapshot: recipient key unavailable; leaving masks uncombined")
        return recipient_encryptions
    from ..crypto.encryption import combine_encryptions

    try:
        with get_metrics().phase("snapshot.paillier_combine"):
            combined = combine_encryptions(
                signed.body.body, scheme, recipient_encryptions
            )
    except Exception:
        # one malformed participant upload must not wedge the snapshot
        # forever (retries would re-read the same stored participations):
        # the uncombined list is always a correct fallback — the recipient
        # decrypts and combines client-side.
        log.warning(
            "snapshot: homomorphic mask combine failed; leaving masks "
            "uncombined",
            exc_info=True,
        )
        return recipient_encryptions
    return [combined]
