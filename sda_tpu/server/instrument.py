"""Telemetry wrapper for store backends.

``instrument_store(inner, store)`` returns a proxy that times every
public store method into ``sda_store_op_seconds{store,op}``, counts rows
on write ops into ``sda_store_rows_written_total{store,op}``, and records
a ``store.<op>`` span carrying the current trace id — the server-side end
of the ``X-SDA-Trace`` propagation chain. One wrapper serves all three
backends (mem/file/sqlite): instrumentation lives at the interface seam,
not in each backend, so new backends inherit it for free.

The proxy is attribute-transparent: non-callable and dunder attributes
pass through, and wrapped methods are cached on the proxy instance so
steady-state dispatch is one instance-dict hit. Exceptions count in the
latency histogram too (a failing store op is still an op) and re-raise
unchanged.
"""

from __future__ import annotations

import functools
import time

from .. import telemetry

#: ops whose first argument is a batch — rows written = len(arg)
_BATCH_OPS = frozenset({"create_participations"})

#: op-name prefixes that count as writes (rows_written series)
_WRITE_PREFIXES = (
    "create_",
    "upsert_",
    "register_",
    "enqueue_",
    "delete_",
    "snapshot_",
)


class InstrumentedStore:
    """Timing/span proxy around one store backend instance."""

    def __init__(self, inner, store: str):
        self._inner = inner
        self._store = store

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name.startswith("_") or not callable(attr):
            return attr
        wrapped = self._wrap(name, attr)
        # cache: later lookups skip __getattr__ entirely
        object.__setattr__(self, name, wrapped)
        return wrapped

    def _wrap(self, op: str, fn):
        store = self._store
        latency = telemetry.histogram(
            "sda_store_op_seconds",
            "store operation latency by backend and op",
            store=store,
            op=op,
        )
        rows = None
        if op.startswith(_WRITE_PREFIXES):
            rows = telemetry.counter(
                "sda_store_rows_written_total",
                "rows written to a store backend",
                store=store,
                op=op,
            )
        batch = op in _BATCH_OPS
        span_name = f"store.{op}"

        @functools.wraps(fn)
        def instrumented(*args, **kwargs):
            if not telemetry.enabled():
                return fn(*args, **kwargs)
            with telemetry.span(span_name, store=store):
                t0 = time.perf_counter()
                try:
                    result = fn(*args, **kwargs)
                finally:
                    latency.observe(time.perf_counter() - t0)
                if rows is not None:
                    n = len(args[0]) if batch and args else 1
                    rows.inc(n)
                return result

        return instrumented


def instrument_store(inner, store: str) -> InstrumentedStore:
    """Wrap one backend instance for the given store label (mem/file/sqlite)."""
    return InstrumentedStore(inner, store)
