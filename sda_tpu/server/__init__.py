"""sda_tpu.server — orchestration server, stores, snapshot pipeline.

Every server constructor wraps its stores with the telemetry proxy
(:mod:`.instrument`): op latency, rows written, and ``store.<op>`` spans
come for free on all backends, labelled mem/file/sqlite.
"""

from __future__ import annotations

from .instrument import instrument_store
from .memstore import (
    MemAgentsStore,
    MemAggregationsStore,
    MemAuthTokensStore,
    MemClerkingJobsStore,
)
from .service import SdaServer, SdaServerService
from .stores import (
    AggregationsStore,
    AgentsStore,
    AuthToken,
    AuthTokensStore,
    BaseStore,
    ClerkingJobsStore,
)


def _server(store: str, agents, auths, aggs, jobs) -> SdaServerService:
    return SdaServerService(
        SdaServer(
            agents_store=instrument_store(agents, store),
            auth_tokens_store=instrument_store(auths, store),
            aggregation_store=instrument_store(aggs, store),
            clerking_job_store=instrument_store(jobs, store),
        )
    )


def new_mem_server() -> SdaServerService:
    """In-memory server (tests / dev)."""
    return _server(
        "mem",
        MemAgentsStore(),
        MemAuthTokensStore(),
        MemAggregationsStore(),
        MemClerkingJobsStore(),
    )


def new_file_server(path) -> SdaServerService:
    """Durable JSON-file-backed server (the reference's jfs equivalent)."""
    from .filestore import (
        FileAgentsStore,
        FileAggregationsStore,
        FileAuthTokensStore,
        FileClerkingJobsStore,
    )

    import os

    return _server(
        "file",
        FileAgentsStore(os.path.join(path, "agents")),
        FileAuthTokensStore(os.path.join(path, "auths")),
        FileAggregationsStore(os.path.join(path, "agg")),
        FileClerkingJobsStore(os.path.join(path, "jobs")),
    )


def new_sqlite_server(path) -> SdaServerService:
    """Production sqlite-backed server (the reference's mongo equivalent)."""
    from .sqlstore import (
        SqliteAgentsStore,
        SqliteAggregationsStore,
        SqliteAuthTokensStore,
        SqliteBackend,
        SqliteClerkingJobsStore,
    )

    backend = SqliteBackend(path)
    return _server(
        "sqlite",
        SqliteAgentsStore(backend),
        SqliteAuthTokensStore(backend),
        SqliteAggregationsStore(backend),
        SqliteClerkingJobsStore(backend),
    )


__all__ = [
    "SdaServer",
    "SdaServerService",
    "instrument_store",
    "new_mem_server",
    "new_file_server",
    "new_sqlite_server",
    "BaseStore",
    "AuthToken",
    "AuthTokensStore",
    "AgentsStore",
    "AggregationsStore",
    "ClerkingJobsStore",
    "MemAgentsStore",
    "MemAuthTokensStore",
    "MemAggregationsStore",
    "MemClerkingJobsStore",
]
