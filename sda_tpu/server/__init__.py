"""sda_tpu.server — orchestration server, stores, snapshot pipeline.

Every server constructor wraps its stores with the telemetry proxy
(:mod:`.instrument`): op latency, rows written, and ``store.<op>`` spans
come for free on all backends, labelled mem/file/sqlite.
"""

from __future__ import annotations

from .instrument import instrument_store
from .memstore import (
    MemAgentsStore,
    MemAggregationsStore,
    MemAuthTokensStore,
    MemClerkingJobsStore,
)
from .service import SdaServer, SdaServerService
from .stores import (
    AggregationsStore,
    AgentsStore,
    AuthToken,
    AuthTokensStore,
    BaseStore,
    ClerkingJobsStore,
)


def _server(store: str, agents, auths, aggs, jobs) -> SdaServerService:
    return SdaServerService(
        SdaServer(
            agents_store=instrument_store(agents, store),
            auth_tokens_store=instrument_store(auths, store),
            aggregation_store=instrument_store(aggs, store),
            clerking_job_store=instrument_store(jobs, store),
        )
    )


def new_mem_server() -> SdaServerService:
    """In-memory server (tests / dev)."""
    return _server(
        "mem",
        MemAgentsStore(),
        MemAuthTokensStore(),
        MemAggregationsStore(),
        MemClerkingJobsStore(),
    )


def new_file_server(path) -> SdaServerService:
    """Durable JSON-file-backed server (the reference's jfs equivalent)."""
    from .filestore import (
        FileAgentsStore,
        FileAggregationsStore,
        FileAuthTokensStore,
        FileClerkingJobsStore,
    )

    import os

    return _server(
        "file",
        FileAgentsStore(os.path.join(path, "agents")),
        FileAuthTokensStore(os.path.join(path, "auths")),
        FileAggregationsStore(os.path.join(path, "agg")),
        FileClerkingJobsStore(os.path.join(path, "jobs")),
    )


def new_sharded_server(
    kind: str, shards: int, path=None, replicas=None
) -> SdaServerService:
    """Server over K store partitions routed by aggregation id.

    ``kind`` picks the backend for every partition (``mem`` / ``file`` /
    ``sqlite``; the latter two lay partitions out under ``path`` as
    ``shard-NN`` dirs / ``shard-NN.db`` files). Agents and auth tokens —
    the small global tables — are pinned to partition 0; the
    aggregation-keyed tables are consistent-hashed over all K. With
    ``shards == 1`` this is behaviourally identical to the plain
    constructors (one partition owns the whole ring).

    ``replicas`` (default: ``SDA_SHARD_REPLICAS``, 1) writes each
    aggregation's state to the first R shards of its ring preference
    with quorum + hinted handoff, so any one partition can die mid-round
    without losing the round (see ``server/sharded.py``). R > 1 starts
    the background handoff-repair thread; the router is exposed as
    ``service.shard_router`` for operability (wedge/heal hooks, hint
    depth, deterministic drains in tests).
    """
    from .sharded import (
        ShardedAggregationsStore,
        ShardedClerkingJobsStore,
        ShardRouter,
    )

    import os

    def _partition(ix: int):
        if kind == "mem":
            return (
                MemAgentsStore(),
                MemAuthTokensStore(),
                MemAggregationsStore(),
                MemClerkingJobsStore(),
            )
        if kind == "file":
            from .filestore import (
                FileAgentsStore,
                FileAggregationsStore,
                FileAuthTokensStore,
                FileClerkingJobsStore,
            )

            root = os.path.join(path, f"shard-{ix:02d}")
            return (
                FileAgentsStore(os.path.join(root, "agents")),
                FileAuthTokensStore(os.path.join(root, "auths")),
                FileAggregationsStore(os.path.join(root, "agg")),
                FileClerkingJobsStore(os.path.join(root, "jobs")),
            )
        if kind == "sqlite":
            from .sqlstore import (
                SqliteAgentsStore,
                SqliteAggregationsStore,
                SqliteAuthTokensStore,
                SqliteBackend,
                SqliteClerkingJobsStore,
            )

            backend = SqliteBackend(os.path.join(path, f"shard-{ix:02d}.db"))
            return (
                SqliteAgentsStore(backend),
                SqliteAuthTokensStore(backend),
                SqliteAggregationsStore(backend),
                SqliteClerkingJobsStore(backend),
            )
        raise ValueError(f"unknown sharded store kind: {kind!r}")

    if kind in ("file", "sqlite") and path is None:
        raise ValueError(f"sharded {kind} store needs a path")
    if replicas is None:
        replicas = int(os.environ.get("SDA_SHARD_REPLICAS", "1") or 1)

    router = ShardRouter(shards, replicas=replicas, root=path)
    parts = [_partition(ix) for ix in range(shards)]
    # each partition's stores get the usual telemetry proxy, so per-op
    # store metrics stay labelled by backend kind exactly as before
    aggs = [instrument_store(p[2], kind) for p in parts]
    jobs = [instrument_store(p[3], kind) for p in parts]
    service = SdaServerService(
        SdaServer(
            agents_store=instrument_store(parts[0][0], kind),
            auth_tokens_store=instrument_store(parts[0][1], kind),
            aggregation_store=ShardedAggregationsStore(aggs, router),
            clerking_job_store=ShardedClerkingJobsStore(jobs, router),
        )
    )
    # elastic scale-out seam: router.add_shard() builds partition K
    # through the same factory (and telemetry proxy) the initial layout
    # used, so a grown shard is indistinguishable from a seeded one
    def _grow_partition(ix: int):
        p = _partition(ix)
        return instrument_store(p[2], kind), instrument_store(p[3], kind)

    router.new_partition = _grow_partition
    service.shard_router = router
    if router.replicas > 1:
        router.start_repair()
    return service


def new_sqlite_server(path) -> SdaServerService:
    """Production sqlite-backed server (the reference's mongo equivalent)."""
    from .sqlstore import (
        SqliteAgentsStore,
        SqliteAggregationsStore,
        SqliteAuthTokensStore,
        SqliteBackend,
        SqliteClerkingJobsStore,
    )

    backend = SqliteBackend(path)
    return _server(
        "sqlite",
        SqliteAgentsStore(backend),
        SqliteAuthTokensStore(backend),
        SqliteAggregationsStore(backend),
        SqliteClerkingJobsStore(backend),
    )


__all__ = [
    "SdaServer",
    "SdaServerService",
    "instrument_store",
    "new_mem_server",
    "new_file_server",
    "new_sqlite_server",
    "new_sharded_server",
    "BaseStore",
    "AuthToken",
    "AuthTokensStore",
    "AgentsStore",
    "AggregationsStore",
    "ClerkingJobsStore",
    "MemAgentsStore",
    "MemAuthTokensStore",
    "MemAggregationsStore",
    "MemClerkingJobsStore",
]
