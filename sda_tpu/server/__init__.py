"""sda_tpu.server — orchestration server, stores, snapshot pipeline."""

from __future__ import annotations

from .memstore import (
    MemAgentsStore,
    MemAggregationsStore,
    MemAuthTokensStore,
    MemClerkingJobsStore,
)
from .service import SdaServer, SdaServerService
from .stores import (
    AggregationsStore,
    AgentsStore,
    AuthToken,
    AuthTokensStore,
    BaseStore,
    ClerkingJobsStore,
)


def new_mem_server() -> SdaServerService:
    """In-memory server (tests / dev)."""
    return SdaServerService(
        SdaServer(
            agents_store=MemAgentsStore(),
            auth_tokens_store=MemAuthTokensStore(),
            aggregation_store=MemAggregationsStore(),
            clerking_job_store=MemClerkingJobsStore(),
        )
    )


def new_file_server(path) -> SdaServerService:
    """Durable JSON-file-backed server (the reference's jfs equivalent)."""
    from .filestore import (
        FileAgentsStore,
        FileAggregationsStore,
        FileAuthTokensStore,
        FileClerkingJobsStore,
    )

    import os

    return SdaServerService(
        SdaServer(
            agents_store=FileAgentsStore(os.path.join(path, "agents")),
            auth_tokens_store=FileAuthTokensStore(os.path.join(path, "auths")),
            aggregation_store=FileAggregationsStore(os.path.join(path, "agg")),
            clerking_job_store=FileClerkingJobsStore(os.path.join(path, "jobs")),
        )
    )


def new_sqlite_server(path) -> SdaServerService:
    """Production sqlite-backed server (the reference's mongo equivalent)."""
    from .sqlstore import (
        SqliteAgentsStore,
        SqliteAggregationsStore,
        SqliteAuthTokensStore,
        SqliteBackend,
        SqliteClerkingJobsStore,
    )

    backend = SqliteBackend(path)
    return SdaServerService(
        SdaServer(
            agents_store=SqliteAgentsStore(backend),
            auth_tokens_store=SqliteAuthTokensStore(backend),
            aggregation_store=SqliteAggregationsStore(backend),
            clerking_job_store=SqliteClerkingJobsStore(backend),
        )
    )


__all__ = [
    "SdaServer",
    "SdaServerService",
    "new_mem_server",
    "new_file_server",
    "new_sqlite_server",
    "BaseStore",
    "AuthToken",
    "AuthTokensStore",
    "AgentsStore",
    "AggregationsStore",
    "ClerkingJobsStore",
    "MemAgentsStore",
    "MemAuthTokensStore",
    "MemAggregationsStore",
    "MemClerkingJobsStore",
]
