"""Durable JSON-file store backend.

Equivalent of the reference's jfs stores (server/src/jfs_stores/): one JSON
file per object, idempotent create-if-identical semantics (mod.rs:79-89),
per-aggregation participation directories (aggregations.rs:47-50), and
durable per-clerk job queues laid out as ``queue/<clerk>/``,
``results/<snapshot>/``, ``done/<clerk>/`` with move-after-result
(clerking_jobs.rs:36-59) — a crashed clerk re-polls the same job.

Everything is written atomically (tmp + rename) so a crashed server restarts
from consistent state; durability-by-construction is the reference's
checkpoint/resume story (SURVEY.md §5) and it is preserved here.
"""

from __future__ import annotations

import json
import os
import struct

from ..protocol import (
    Agent,
    ClerkCandidate,
    ClerkingJob,
    ClerkingResult,
    Committee,
    Aggregation,
    Encryption,
    InvalidRequestError,
    Labelled,
    Participation,
    Profile,
    ServerError,
    Snapshot,
    signed_encryption_key_from_json,
)
from ..protocol.ids import (
    AgentId,
    ClerkingJobId,
    SnapshotId,
)
from ..utils.jsondir import ConflictError, JsonDir
from .stores import (
    AggregationsStore,
    AgentsStore,
    AuthTokensStore,
    ClerkingJobsStore,
    job_chunk_size,
    job_page_threshold,
    result_page_threshold,
    split_small_column,
)


def _create(jdir: JsonDir, id, payload) -> None:
    """create-if-identical, mapped onto the server error type."""
    try:
        jdir.create(id, payload)
    except ConflictError as e:
        raise ServerError(str(e))


class FileAuthTokensStore(AuthTokensStore):
    def __init__(self, path):
        self.dir = JsonDir(str(path))

    def upsert_auth_token(self, token) -> None:
        self.dir.put(token.id, {"id": str(token.id), "body": token.body})

    def register_auth_token(self, token) -> bool:
        # JsonDir.create is atomic under the per-directory lock
        try:
            self.dir.create(token.id, {"id": str(token.id), "body": token.body})
            return True
        except ConflictError:
            return False

    def get_auth_token(self, agent_id):
        payload = self.dir.get(agent_id)
        if payload is None:
            return None
        return Labelled(AgentId(payload["id"]), payload["body"])

    def delete_auth_token(self, agent_id) -> None:
        self.dir.delete(agent_id)


class FileAgentsStore(AgentsStore):
    def __init__(self, path):
        path = str(path)
        self.agents = JsonDir(os.path.join(path, "agents"))
        self.profiles = JsonDir(os.path.join(path, "profiles"))
        self.keys = JsonDir(os.path.join(path, "keys"))

    def create_agent(self, agent) -> None:
        _create(self.agents, agent.id, agent.to_json())

    def get_agent(self, agent_id):
        payload = self.agents.get(agent_id)
        return None if payload is None else Agent.from_json(payload)

    def upsert_profile(self, profile) -> None:
        self.profiles.put(profile.owner, profile.to_json())

    def get_profile(self, owner_id):
        payload = self.profiles.get(owner_id)
        return None if payload is None else Profile.from_json(payload)

    def create_encryption_key(self, signed_key) -> None:
        _create(self.keys, signed_key.body.id, signed_key.to_json())

    def get_encryption_key(self, key_id):
        payload = self.keys.get(key_id)
        return None if payload is None else signed_encryption_key_from_json(payload)

    def suggest_committee(self) -> list:
        by_signer: dict = {}
        for key_id in self.keys.list_ids():
            signed = signed_encryption_key_from_json(self.keys.get(key_id))
            by_signer.setdefault(signed.signer, []).append(signed.body.id)
        return [
            ClerkCandidate(id=signer, keys=keys)
            for signer, keys in by_signer.items()
            if self.agents.get(signer) is not None
        ]


class FileAggregationsStore(AggregationsStore):
    def __init__(self, path):
        self.root = str(path)
        self.aggregations = JsonDir(os.path.join(self.root, "aggregations"))
        self.committees = JsonDir(os.path.join(self.root, "committees"))
        self.members = JsonDir(os.path.join(self.root, "snapshot_members"))
        self.masks = JsonDir(os.path.join(self.root, "snapshot_masks"))

    def _participations(self, aggregation_id) -> JsonDir:
        return JsonDir(os.path.join(self.root, "participations", str(aggregation_id)))

    def _snapshots(self, aggregation_id) -> JsonDir:
        return JsonDir(os.path.join(self.root, "snapshots", str(aggregation_id)))

    def list_aggregations(self, filter, recipient) -> list:
        out = []
        for agg_id in self.aggregations.list_ids():
            agg = Aggregation.from_json(self.aggregations.get(agg_id))
            if filter is not None and filter not in agg.title:
                continue
            if recipient is not None and agg.recipient != recipient:
                continue
            out.append(agg.id)
        return out

    def create_aggregation(self, aggregation) -> None:
        _create(self.aggregations, aggregation.id, aggregation.to_json())

    def get_aggregation(self, aggregation_id):
        payload = self.aggregations.get(aggregation_id)
        return None if payload is None else Aggregation.from_json(payload)

    def delete_aggregation(self, aggregation_id) -> None:
        import shutil

        for snap_id in self._snapshots(aggregation_id).list_ids():
            self.members.delete(snap_id)
            self.masks.delete(snap_id)
            for path in self._mask_paths(snap_id):
                if os.path.exists(path):
                    os.unlink(path)
        self.aggregations.delete(aggregation_id)
        self.committees.delete(aggregation_id)
        for sub in ("participations", "snapshots"):
            path = os.path.join(self.root, sub, str(aggregation_id))
            shutil.rmtree(path, ignore_errors=True)

    def get_committee(self, aggregation_id):
        payload = self.committees.get(aggregation_id)
        return None if payload is None else Committee.from_json(payload)

    def create_committee(self, committee) -> None:
        _create(self.committees, committee.aggregation, committee.to_json())

    def create_participation(self, participation) -> None:
        if self.aggregations.get(participation.aggregation) is None:
            raise InvalidRequestError(f"no aggregation {participation.aggregation}")
        _create(
            self._participations(participation.aggregation),
            participation.id,
            participation.to_json(),
        )

    def create_participations(self, participations) -> None:
        # validate the whole batch (aggregation existence + conflicts)
        # before the first write, so a mid-batch reject leaves no partial
        # state from *this* batch. File-per-object gives no multi-file
        # transaction: a crash mid-loop can still persist a prefix, which
        # is exactly the durability model of N single uploads (each
        # already-written file is a valid, idempotently replayable row).
        participations = list(participations)
        staged: dict = {}
        dirs: dict = {}
        for p in participations:
            if p.aggregation not in dirs:
                if self.aggregations.get(p.aggregation) is None:
                    raise InvalidRequestError(f"no aggregation {p.aggregation}")
                dirs[p.aggregation] = self._participations(p.aggregation)
            payload = p.to_json()
            prev = staged.get(p.id)
            if prev is not None and prev[1] != payload:
                raise ServerError(f"object already exists: {p.id}")
            existing = dirs[p.aggregation].get(p.id)
            if existing is not None and existing != payload:
                raise ServerError(f"object already exists: {p.id}")
            staged[p.id] = (p.aggregation, payload)
        for pid, (agg, payload) in staged.items():
            # _create (not put): keeps the per-directory lock's conflict
            # check against writers racing this batch
            _create(dirs[agg], pid, payload)

    def create_snapshot(self, snapshot) -> None:
        _create(self._snapshots(snapshot.aggregation), snapshot.id, snapshot.to_json())

    def list_snapshots(self, aggregation_id) -> list:
        return [SnapshotId(s) for s in self._snapshots(aggregation_id).list_ids()]

    def get_snapshot(self, aggregation_id, snapshot_id):
        payload = self._snapshots(aggregation_id).get(snapshot_id)
        return None if payload is None else Snapshot.from_json(payload)

    def count_participations(self, aggregation_id) -> int:
        return len(self._participations(aggregation_id).list_ids())

    def iter_participations(self, aggregation_id):
        table = self._participations(aggregation_id)
        for pid in sorted(table.list_ids(), key=str):
            payload = table.get(pid)
            if payload is None:
                continue  # raced a concurrent delete — nothing to copy
            yield Participation.from_json(payload)

    def discard_participations(self, aggregation_id, participation_ids) -> None:
        table = self._participations(aggregation_id)
        for pid in participation_ids:
            table.delete(pid)

    def snapshot_participations(self, aggregation_id, snapshot_id) -> None:
        # write-once: a retry after a partial snapshot must not re-freeze a
        # different membership (participations may have arrived in between)
        members = self._participations(aggregation_id).list_ids()
        self.members.create_once(snapshot_id, members)

    def iter_snapped_participations(self, aggregation_id, snapshot_id):
        members = self.members.get(snapshot_id) or []
        table = self._participations(aggregation_id)
        for pid in members:
            payload = table.get(pid)
            if payload is None:
                # the frozen member list IS the count the transpose and
                # number_of_participations report; silently skipping a
                # missing payload (partial write, manual cleanup) would
                # let the count and the rows actually transposed diverge
                raise ServerError(
                    f"snapshot {snapshot_id}: snapped participation "
                    f"{pid} has no payload on disk — store corrupted?"
                )
            yield Participation.from_json(payload)

    def count_participations_snapshot(self, aggregation_id, snapshot_id) -> int:
        # the default parses every member's JSON just to count; the
        # frozen id list already knows (a snapped member whose payload
        # later goes missing makes iter_snapped_participations raise, so
        # this count can never silently disagree with the rows iterated)
        return len(self.members.get(snapshot_id) or [])

    #: above this many snapped participations the transpose switches from
    #: the one-pass in-memory default to per-clerk column scans
    TRANSPOSE_STREAM_THRESHOLD = 10_000

    def validate_snapshot_clerk_jobs(
        self, aggregation_id, snapshot_id, clerks_number: int
    ) -> None:
        """Streaming cohorts only: one validation pass over the snapped
        bodies before the pipeline enqueues anything (the eager
        below-threshold path is safe by construction — see the base
        docstring). Also surfaces missing payload files up front via
        iter_snapped_participations' loud-raise, narrowing the window in
        which a mid-column-scan disappearance could strand phantom jobs.
        Cost: one extra directory scan on top of the ``clerks`` column
        scans (~1/clerks overhead)."""
        n = self.count_participations_snapshot(aggregation_id, snapshot_id)
        if n <= self.TRANSPOSE_STREAM_THRESHOLD:
            return
        for p in self.iter_snapped_participations(aggregation_id, snapshot_id):
            if len(p.clerk_encryptions) != clerks_number:
                raise ServerError(
                    f"snapshot {snapshot_id}: participation {p.id} has "
                    f"{len(p.clerk_encryptions)} clerk encryptions, "
                    f"expected {clerks_number} — refusing to enqueue a "
                    "partial transpose"
                )

    def iter_snapshot_clerk_jobs_data(
        self, aggregation_id, snapshot_id, clerks_number: int
    ):
        """Memory-bounded transpose for large cohorts (SURVEY hard part
        #6: the reference's jfs path materializes every ciphertext at
        once, stores.rs:86-101; its mongo path spills to disk instead).

        Below the threshold: the default single-pass transpose (reads
        each participation file once). Above it: one pass per clerk,
        yielding a single clerk's ciphertext column at a time — the
        snapshot pipeline enqueues each job before the next column is
        built, so peak memory is one column (1/clerks of the cohort)
        plus one serialized job, at the cost of ``clerks`` directory
        scans."""
        n = self.count_participations_snapshot(aggregation_id, snapshot_id)
        if n <= self.TRANSPOSE_STREAM_THRESHOLD:
            return super().iter_snapshot_clerk_jobs_data(
                aggregation_id, snapshot_id, clerks_number
            )

        def columns():
            for ix in range(clerks_number):
                yield [
                    p.clerk_encryptions[ix][1]
                    for p in self.iter_snapped_participations(
                        aggregation_id, snapshot_id
                    )
                ]

        return columns()

    def iter_snapshot_clerk_jobs_chunks(
        self, aggregation_id, snapshot_id, clerks_number: int, chunk_size: int
    ):
        """Chunked transpose for large cohorts: each chunk re-reads only
        its own slice of the frozen member list, so peak memory per clerk
        is one chunk of ciphertexts instead of one column. Below the
        threshold the default (re-chunked eager transpose) is cheaper —
        one file read per participation instead of ``clerks``."""
        n = self.count_participations_snapshot(aggregation_id, snapshot_id)
        if n <= self.TRANSPOSE_STREAM_THRESHOLD:
            return super().iter_snapshot_clerk_jobs_chunks(
                aggregation_id, snapshot_id, clerks_number, chunk_size
            )
        members = self.members.get(snapshot_id) or []
        table = self._participations(aggregation_id)

        def column_chunks(ix: int):
            for lo in range(0, len(members), chunk_size):
                block = []
                for pid in members[lo : lo + chunk_size]:
                    payload = table.get(pid)
                    if payload is None:
                        raise ServerError(
                            f"snapshot {snapshot_id}: snapped participation "
                            f"{pid} has no payload on disk — store corrupted?"
                        )
                    block.append(
                        Participation.from_json(payload).clerk_encryptions[ix][1]
                    )
                yield block

        return (column_chunks(ix) for ix in range(clerks_number))

    # -- snapshot masks ------------------------------------------------------
    # Two layouts, mirroring FileClerkingJobsStore's columns: small masks
    # stay a single JSON list in the masks JsonDir; masks above
    # result_page_threshold() are EXTERNALIZED — the JsonDir payload
    # becomes the marker ``{"externalized": n}`` and the encryptions live
    # in ``mask_columns/<snapshot>.jsonl`` with an n+1 little-endian
    # uint64 byte-offset sidecar, so a range read is two seeks, never a
    # blob parse. Layout is decided at WRITE time; the wire shape is
    # decided per call in the service, so either layout serves both.

    def _mask_paths(self, snapshot_id):
        d = os.path.join(self.root, "mask_columns")
        os.makedirs(d, exist_ok=True)
        return (
            os.path.join(d, f"{snapshot_id}.jsonl"),
            os.path.join(d, f"{snapshot_id}.idx"),
        )

    def _read_mask_range(self, snapshot_id, start: int, end: int) -> list:
        # lock-free like _read_column_range: idx + jsonl are immutable
        # once the snapshot-mask metadata is visible
        if end <= start:
            return []
        data_path, idx_path = self._mask_paths(snapshot_id)
        with open(idx_path, "rb") as xf:
            xf.seek(start * 8)
            raw = xf.read((end - start + 1) * 8)
        offs = struct.unpack(f"<{len(raw) // 8}Q", raw)
        if len(offs) < 2:
            return []
        with open(data_path, "rb") as df:
            df.seek(offs[0])
            blob = df.read(offs[-1] - offs[0])
        return [Encryption.from_json(json.loads(line)) for line in blob.splitlines()]

    def create_snapshot_mask(self, snapshot_id, mask) -> None:
        mask = list(mask)
        if len(mask) <= result_page_threshold():
            self.masks.put(snapshot_id, [e.to_json() for e in mask])
            return
        # externalized: column files land atomically first, the marker —
        # the blob's visibility point — last, so a crash mid-write leaves
        # the mask absent and the snapshot pipeline's retry rewrites it
        data_path, idx_path = self._mask_paths(snapshot_id)
        tmp_data, tmp_idx = data_path + ".tmp", idx_path + ".tmp"
        try:
            with open(tmp_data, "wb") as df, open(tmp_idx, "wb") as xf:
                off = 0
                xf.write(struct.pack("<Q", 0))
                for e in mask:
                    line = json.dumps(e.to_json()).encode("utf-8") + b"\n"
                    df.write(line)
                    off += len(line)
                    xf.write(struct.pack("<Q", off))
            os.replace(tmp_data, data_path)
            os.replace(tmp_idx, idx_path)
        finally:
            for tmp in (tmp_data, tmp_idx):
                if os.path.exists(tmp):
                    os.unlink(tmp)
        self.masks.put(snapshot_id, {"externalized": len(mask)})

    def get_snapshot_mask(self, snapshot_id):
        payload = self.masks.get(snapshot_id)
        if payload is None:
            return None
        if isinstance(payload, dict):
            return self._read_mask_range(snapshot_id, 0, int(payload["externalized"]))
        return [Encryption.from_json(e) for e in payload]

    def count_snapshot_mask(self, snapshot_id):
        payload = self.masks.get(snapshot_id)
        if payload is None:
            return None
        if isinstance(payload, dict):
            return int(payload["externalized"])
        return len(payload)

    def get_snapshot_mask_range(self, snapshot_id, start, count):
        payload = self.masks.get(snapshot_id)
        if payload is None:
            return None
        if start < 0 or count < 0:
            return []
        if isinstance(payload, dict):
            end = min(start + count, int(payload["externalized"]))
            return self._read_mask_range(snapshot_id, start, end)
        return [Encryption.from_json(e) for e in payload[start : start + count]]


class FileClerkingJobsStore(ClerkingJobsStore):
    """Two column layouts, mirroring the sqlite backend:

    - INLINE (legacy / small jobs): the full job JSON in the queue dir.
    - EXTERNALIZED: the queue JSON is metadata only
      (``total_encryptions`` set) and the ciphertext column lives in
      ``columns/<job-id>.jsonl`` (one encryption per line) with a
      sidecar ``columns/<job-id>.idx`` of n+1 little-endian uint64 byte
      offsets — a chunk read is two seeks, never a column parse.
    """

    def __init__(self, path):
        self.root = str(path)

    def _queue(self, clerk_id) -> JsonDir:
        return JsonDir(os.path.join(self.root, "queue", str(clerk_id)))

    def _done(self, clerk_id) -> JsonDir:
        return JsonDir(os.path.join(self.root, "done", str(clerk_id)))

    def _results(self, snapshot_id) -> JsonDir:
        return JsonDir(os.path.join(self.root, "results", str(snapshot_id)))

    def _column_paths(self, job_id):
        d = os.path.join(self.root, "columns")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{job_id}.jsonl"), os.path.join(d, f"{job_id}.idx")

    def _read_column_range(self, job_id, start: int, end: int) -> list:
        """Ciphertexts [start, end) via the offset sidecar: seek into the
        idx for the bounding offsets, then one ranged read of the jsonl.

        Deliberately lock-free: both files are written whole before the
        job metadata lands (tmp + os.replace) and are immutable after,
        so concurrent chunk readers never contend on a store lock."""
        if end <= start:
            return []
        data_path, idx_path = self._column_paths(job_id)
        with open(idx_path, "rb") as xf:
            xf.seek(start * 8)
            raw = xf.read((end - start + 1) * 8)
        offs = struct.unpack(f"<{len(raw) // 8}Q", raw)
        if len(offs) < 2:
            return []
        with open(data_path, "rb") as df:
            df.seek(offs[0])
            blob = df.read(offs[-1] - offs[0])
        return [Encryption.from_json(json.loads(line)) for line in blob.splitlines()]

    def _deliver(self, payload):
        """Stored payload -> wire body under the current paging threshold."""
        job = ClerkingJob.from_json(payload)
        total = (
            job.total_encryptions
            if job.total_encryptions is not None
            else len(job.encryptions)
        )
        if total > job_page_threshold():
            return ClerkingJob(
                id=job.id,
                clerk=job.clerk,
                aggregation=job.aggregation,
                snapshot=job.snapshot,
                encryptions=[],
                total_encryptions=total,
                chunk_size=job_chunk_size(),
            )
        if job.total_encryptions is None:
            return job  # inline + small: original shape, untouched
        # externalized + small: reassemble the monolithic wire body
        return ClerkingJob(
            id=job.id,
            clerk=job.clerk,
            aggregation=job.aggregation,
            snapshot=job.snapshot,
            encryptions=self._read_column_range(job.id, 0, total),
        )

    def enqueue_clerking_job(self, job) -> None:
        # idempotent under snapshot retries (job ids are deterministic): a
        # job already queued or already completed is not enqueued again
        if len(job.encryptions) > job_page_threshold():
            self.enqueue_clerking_job_chunked(
                ClerkingJob(
                    id=job.id,
                    clerk=job.clerk,
                    aggregation=job.aggregation,
                    snapshot=job.snapshot,
                    encryptions=[],
                ),
                [job.encryptions],
            )
            return
        if self._done(job.clerk).get(job.id) is not None:
            return
        _create(self._queue(job.clerk), job.id, job.to_json())

    def enqueue_clerking_job_chunked(self, job, chunks) -> None:
        """Streaming enqueue into the externalized layout: column ranges
        append to tmp files (one chunk in memory at a time), both files
        land atomically via os.replace, and the queue metadata JSON —
        the job's visibility point — is written last, so a crash
        mid-column leaves no pollable job and the deterministic-id retry
        rewrites the orphaned tmp/column files from scratch."""
        if (
            self._done(job.clerk).get(job.id) is not None
            or self._queue(job.clerk).get(job.id) is not None
        ):
            return  # idempotent: don't consume the iterator either
        column, chunks = split_small_column(chunks, job_page_threshold())
        if column is not None:
            # small column: keep the legacy inline layout
            job.encryptions = column
            _create(self._queue(job.clerk), job.id, job.to_json())
            return
        data_path, idx_path = self._column_paths(job.id)
        tmp_data, tmp_idx = data_path + ".tmp", idx_path + ".tmp"
        total = 0
        try:
            with open(tmp_data, "wb") as df, open(tmp_idx, "wb") as xf:
                off = 0
                xf.write(struct.pack("<Q", 0))
                for block in chunks:
                    lines = [
                        json.dumps(e.to_json()).encode("utf-8") + b"\n"
                        for e in block
                    ]
                    df.write(b"".join(lines))
                    for line in lines:
                        off += len(line)
                        xf.write(struct.pack("<Q", off))
                    total += len(block)
            os.replace(tmp_data, data_path)
            os.replace(tmp_idx, idx_path)
        finally:
            for tmp in (tmp_data, tmp_idx):
                if os.path.exists(tmp):
                    os.unlink(tmp)
        meta = ClerkingJob(
            id=job.id,
            clerk=job.clerk,
            aggregation=job.aggregation,
            snapshot=job.snapshot,
            encryptions=[],
            total_encryptions=total,
        )
        _create(self._queue(job.clerk), job.id, meta.to_json())

    def poll_clerking_job(self, clerk_id):
        queue = self._queue(clerk_id)
        ids = queue.list_ids()
        if not ids:
            return None
        return self._deliver(queue.get(ids[0]))

    def get_clerking_job(self, clerk_id, job_id):
        payload = self._queue(clerk_id).get(job_id) or self._done(clerk_id).get(job_id)
        return None if payload is None else self._deliver(payload)

    def get_clerking_job_chunk(self, clerk_id, job_id, start, count):
        payload = self._queue(clerk_id).get(job_id) or self._done(clerk_id).get(job_id)
        if payload is None:
            return None
        if start < 0 or count < 0:
            return []
        job = ClerkingJob.from_json(payload)
        if job.total_encryptions is None:
            return job.encryptions[start : start + count]  # inline layout
        end = min(start + count, job.total_encryptions)
        return self._read_column_range(job.id, start, end)

    def create_clerking_result(self, result) -> None:
        # raw stored payload, not the delivered view: the done-dir copy
        # must keep the stored layout (meta for externalized jobs) so the
        # column file stays addressable after completion
        payload = self._queue(result.clerk).get(result.job) or self._done(
            result.clerk
        ).get(result.job)
        if payload is None:
            raise InvalidRequestError(f"no job {result.job}")
        job = ClerkingJob.from_json(payload)
        self._results(job.snapshot).put(job.id, result.to_json())
        # move queue -> done so the job is no longer pollable but stays auditable
        self._done(job.clerk).put(job.id, payload)
        self._queue(job.clerk).delete(job.id)

    def complete_clerking_job(self, clerk_id, job_id) -> None:
        payload = self._queue(clerk_id).get(job_id)
        if payload is None:
            if self._done(clerk_id).get(job_id) is not None:
                return  # already retired — idempotent replay
            raise InvalidRequestError(f"no job {job_id}")
        self._done(clerk_id).put(job_id, payload)
        self._queue(clerk_id).delete(job_id)

    def list_results(self, snapshot_id) -> list:
        return [ClerkingJobId(j) for j in self._results(snapshot_id).list_ids()]

    def get_result(self, snapshot_id, job_id):
        payload = self._results(snapshot_id).get(job_id)
        return None if payload is None else ClerkingResult.from_json(payload)

    def get_results(self, snapshot_id) -> list:
        # one directory scan in list_ids order (canonical str sort)
        results = self._results(snapshot_id)
        out = []
        for job_id in results.list_ids():
            payload = results.get(job_id)
            if payload is None:
                raise ServerError("inconsistent storage")
            out.append(ClerkingResult.from_json(payload))
        return out

    def count_results(self, snapshot_id) -> int:
        return len(self._results(snapshot_id).list_ids())

    def get_results_range(self, snapshot_id, start, count) -> list:
        # file-per-result: the range is an id-list slice, reading only
        # the requested files (list_ids is already the canonical order)
        if start < 0 or count < 0:
            return []
        results = self._results(snapshot_id)
        out = []
        for job_id in results.list_ids()[start : start + count]:
            payload = results.get(job_id)
            if payload is None:
                raise ServerError("inconsistent storage")
            out.append(ClerkingResult.from_json(payload))
        return out
