"""Storage abstraction of the orchestration server.

Four store interfaces mirroring /root/reference/server/src/stores.rs: agents,
auth tokens, aggregations (incl. participations/snapshots/masks), and
clerking jobs (durable per-clerk pull queues). The server core only talks to
these interfaces; backends plug in underneath (memory, file, sqlite).

``iter_snapshot_clerk_jobs_data`` is the server's one nontrivial computation:
transposing the (participants x clerks) ciphertext matrix into per-clerk job
payloads (stores.rs:86-101). Backends may override it with something
smarter (the reference's mongo store runs it as an aggregation pipeline with
disk spill; the TPU fabric does it as an all_to_all when tensor-resident).
"""

from __future__ import annotations

import abc
import os
from typing import Iterable, Iterator, Optional

from ..protocol import Labelled, ServerError

# AuthToken = Labelled[AgentId, str] (stores.rs:8)
AuthToken = Labelled


def job_page_threshold() -> int:
    """Encryption count above which ``poll_clerking_job`` delivers paged
    metadata instead of the monolithic body. Read per call so tests (and
    operators) can flip it without rebuilding stores; <= 0 pages every
    job."""
    return int(os.environ.get("SDA_JOB_PAGE_THRESHOLD", "8192"))


def job_chunk_size() -> int:
    """Server-suggested chunk length for paged delivery and for the
    chunked transpose write-through. Clamped to >= 1."""
    return max(1, int(os.environ.get("SDA_JOB_CHUNK_SIZE", "4096")))


def result_page_threshold() -> int:
    """Payload-item count (mask encryptions + clerk results) above which
    ``get_snapshot_result`` delivers paged metadata instead of the
    monolithic body. Read per call, like ``job_page_threshold``; <= 0
    pages every result."""
    return int(os.environ.get("SDA_RESULT_PAGE_THRESHOLD", "8192"))


def result_chunk_size() -> int:
    """Server-suggested range length for paged snapshot-result delivery.
    Clamped to >= 1."""
    return max(1, int(os.environ.get("SDA_RESULT_CHUNK_SIZE", "4096")))


def split_small_column(chunks, threshold: int):
    """Consume ``chunks`` just far enough to learn whether the column
    fits within ``threshold`` ciphertexts. Returns ``(column, None)``
    with the full materialized column when it does — small jobs keep the
    legacy inline layout — or ``(None, iterator)`` where the iterator
    replays the buffered prefix and then the remaining ranges. Peak
    memory is one threshold's worth either way."""
    import itertools

    buffered: list = []
    total = 0
    it = iter(chunks)
    for block in it:
        buffered.append(block)
        total += len(block)
        if total > threshold:
            return None, itertools.chain(buffered, it)
    return [enc for block in buffered for enc in block], None


def paged_job_view(job):
    """The wire view of a job under paged delivery: metadata only, the
    ciphertext column left behind for ``get_clerking_job_chunk``. Small
    jobs pass through untouched so the original wire shape survives."""
    total = len(job.encryptions) if job.total_encryptions is None else job.total_encryptions
    if total <= job_page_threshold():
        return job
    return type(job)(
        id=job.id,
        clerk=job.clerk,
        aggregation=job.aggregation,
        snapshot=job.snapshot,
        encryptions=[],
        total_encryptions=total,
        chunk_size=job_chunk_size(),
    )


class BaseStore(abc.ABC):
    def ping(self) -> None:
        """Raise if the backend is unhealthy."""


class AuthTokensStore(BaseStore):
    @abc.abstractmethod
    def upsert_auth_token(self, token: AuthToken) -> None: ...

    @abc.abstractmethod
    def register_auth_token(self, token: AuthToken) -> bool:
        """Atomic trust-on-first-use registration: record the token if the
        agent id has none yet; return whether the presented token is now
        the valid one (existing identical token also returns True).
        Check-and-write must be one atomic operation — two concurrent first
        registrations must not last-writer-win."""

    @abc.abstractmethod
    def get_auth_token(self, agent_id) -> Optional[AuthToken]: ...

    @abc.abstractmethod
    def delete_auth_token(self, agent_id) -> None: ...


class AgentsStore(BaseStore):
    @abc.abstractmethod
    def create_agent(self, agent) -> None: ...

    @abc.abstractmethod
    def get_agent(self, agent_id): ...

    @abc.abstractmethod
    def upsert_profile(self, profile) -> None: ...

    @abc.abstractmethod
    def get_profile(self, owner_id): ...

    @abc.abstractmethod
    def create_encryption_key(self, signed_key) -> None: ...

    @abc.abstractmethod
    def get_encryption_key(self, key_id): ...

    @abc.abstractmethod
    def suggest_committee(self) -> list:
        """All agents holding at least one registered key, as ClerkCandidates
        (reference jfs impl groups signed keys by signer, agents.rs:66-83)."""


class AggregationsStore(BaseStore):
    @abc.abstractmethod
    def list_aggregations(self, filter: Optional[str], recipient) -> list: ...

    @abc.abstractmethod
    def create_aggregation(self, aggregation) -> None: ...

    @abc.abstractmethod
    def get_aggregation(self, aggregation_id): ...

    @abc.abstractmethod
    def delete_aggregation(self, aggregation_id) -> None: ...

    @abc.abstractmethod
    def get_committee(self, aggregation_id): ...

    @abc.abstractmethod
    def create_committee(self, committee) -> None: ...

    @abc.abstractmethod
    def create_participation(self, participation) -> None: ...

    @abc.abstractmethod
    def iter_participations(self, aggregation_id):
        """Every stored participation of ``aggregation_id``, in a stable
        (id-sorted) order. Snapshot-independent — this is the raw table
        scan the shard-migration copier replays onto a new partition,
        not the frozen-membership iteration the transpose uses."""
        ...

    def create_participations(self, participations) -> None:
        """Bulk write of pre-validated participations — the storage half of
        the batched ingest pipeline.

        Contract: ATOMIC with the same create-if-identical idempotence as
        singles.  If any participation conflicts (same id, different body)
        or its aggregation is missing, the whole batch must be rejected
        with no partial state.  Backends override with a real bulk write
        (sqlite: one BEGIN IMMEDIATE + executemany); this default serves
        backends whose single create is already an in-memory mutation that
        the caller serializes (and is made atomic there by pre-checking)."""
        for participation in participations:
            self.create_participation(participation)

    def discard_participations(self, aggregation_id, participation_ids) -> None:
        """Remove the given participation rows before any snapshot freezes
        them — the share-promotion prepare stage drops incomplete re-share
        epochs here (server/snapshot.py). Missing ids are ignored; rows
        already frozen into a snapshot must never be passed (the pipeline
        guards on frozen membership before resolving)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support discard_participations"
        )

    @abc.abstractmethod
    def create_snapshot(self, snapshot) -> None: ...

    @abc.abstractmethod
    def list_snapshots(self, aggregation_id) -> list: ...

    @abc.abstractmethod
    def get_snapshot(self, aggregation_id, snapshot_id): ...

    @abc.abstractmethod
    def count_participations(self, aggregation_id) -> int: ...

    @abc.abstractmethod
    def snapshot_participations(self, aggregation_id, snapshot_id) -> None:
        """Freeze the current participation set as the snapshot's members."""

    @abc.abstractmethod
    def iter_snapped_participations(self, aggregation_id, snapshot_id) -> Iterator: ...

    def count_participations_snapshot(self, aggregation_id, snapshot_id) -> int:
        return sum(1 for _ in self.iter_snapped_participations(aggregation_id, snapshot_id))

    def validate_snapshot_clerk_jobs(
        self, aggregation_id, snapshot_id, clerks_number: int
    ) -> None:
        """Reject malformed snapped bodies BEFORE the transpose starts.

        Streaming backends yield columns lazily, after the snapshot
        pipeline has begun durably enqueueing clerk jobs — a mid-stream
        failure would leave clerks 0..k-1 holding jobs for a snapshot
        whose commit point never runs. The pipeline calls this first; a
        backend whose transpose can fail mid-stream must override it to
        raise here instead (sqlite: indexed COUNT; file store: one
        validation pass). The default is a no-op because the base
        transpose is eager — it materializes every column before the
        caller sees the first one, so a malformed body raises before any
        enqueue. (The service layer validates shape at participation
        creation; this guards direct store writes and corruption.)"""

    def iter_snapshot_clerk_jobs_data(
        self, aggregation_id, snapshot_id, clerks_number: int
    ) -> Iterable:
        """Transpose participations x clerks -> per-clerk ciphertext columns.

        Contract: an ITERABLE of ``clerks_number`` columns, consumed once
        in committee order (column ix = the clerk's committee position;
        participations carry clerk encryptions in committee order).
        Backends may return a lazy single-use generator (sqlite, file
        store above its threshold) — callers must not index, len(), or
        iterate twice. This default is the reference's eager in-memory
        transpose (stores.rs:86-101).
        """
        shares: list = [[] for _ in range(clerks_number)]
        for participation in self.iter_snapped_participations(aggregation_id, snapshot_id):
            for ix, (_, enc) in enumerate(participation.clerk_encryptions):
                shares[ix].append(enc)
        return shares

    def iter_snapshot_clerk_jobs_chunks(
        self, aggregation_id, snapshot_id, clerks_number: int, chunk_size: int
    ) -> Iterable:
        """Chunked transpose: an iterable of ``clerks_number`` column
        iterators, each yielding ``chunk_size``-long ciphertext ranges in
        participant order. Same single-use, committee-order contract as
        ``iter_snapshot_clerk_jobs_data``; this is what keeps snapshot
        enqueue memory at one chunk instead of one full column per clerk.
        The default re-chunks the column transpose (eager backends gain
        nothing, which is fine: they already hold everything in memory);
        sqlite and the file store override with genuinely ranged reads.
        """

        def chunks_of(column):
            it = iter(column)
            while True:
                block = []
                for enc in it:
                    block.append(enc)
                    if len(block) >= chunk_size:
                        break
                if not block:
                    return
                yield block

        for column in self.iter_snapshot_clerk_jobs_data(
            aggregation_id, snapshot_id, clerks_number
        ):
            yield chunks_of(column)

    @abc.abstractmethod
    def create_snapshot_mask(self, snapshot_id, mask: list) -> None: ...

    @abc.abstractmethod
    def get_snapshot_mask(self, snapshot_id): ...

    def count_snapshot_mask(self, snapshot_id) -> Optional[int]:
        """Length of the stored recipient-mask blob, or None when the
        snapshot stored no mask — the paged-delivery decision input.
        Backends with an externalized mask layout override to answer from
        metadata without materializing the blob."""
        mask = self.get_snapshot_mask(snapshot_id)
        return None if mask is None else len(mask)

    def get_snapshot_mask_range(self, snapshot_id, start: int, count: int) -> Optional[list]:
        """Mask encryptions ``[start, start+count)`` in stored order, or
        None when no mask exists. Ranges past the end return the
        (possibly empty) tail, like ``get_clerking_job_chunk``. Backends
        override to read ONLY the requested range (sqlite: indexed
        position rows; file store: byte-offset seek); this default slices
        the materialized blob for in-memory layouts."""
        mask = self.get_snapshot_mask(snapshot_id)
        if mask is None:
            return None
        if start < 0 or count < 0:
            return []
        return mask[start : start + count]


class ClerkingJobsStore(BaseStore):
    @abc.abstractmethod
    def enqueue_clerking_job(self, job) -> None: ...

    def enqueue_clerking_job_chunked(self, job, chunks: Iterable) -> None:
        """Enqueue ``job`` (its ``encryptions`` empty) with the ciphertext
        column supplied as an iterator of ranges, in participant order.

        The streaming half of the chunked transpose: backends with an
        external column representation (sqlite rows, file-store column
        files) write ranges through without ever holding the full column;
        this default materializes for purely in-memory backends, which
        hold the whole queue anyway. Must keep ``enqueue_clerking_job``'s
        idempotence: re-enqueueing an existing job id is a no-op."""
        encryptions = []
        for block in chunks:
            encryptions.extend(block)
        job.encryptions = encryptions
        self.enqueue_clerking_job(job)

    @abc.abstractmethod
    def poll_clerking_job(self, clerk_id):
        """First not-yet-done job for the clerk; jobs stay queued until a
        result is posted, so a crashed clerk re-polls the same job
        (jfs_stores/clerking_jobs.rs:40-59). Jobs above
        ``job_page_threshold()`` are returned as paged metadata (see
        ``paged_job_view``); the column is then read range-by-range via
        ``get_clerking_job_chunk``."""

    @abc.abstractmethod
    def get_clerking_job(self, clerk_id, job_id): ...

    def get_clerking_job_chunk(
        self, clerk_id, job_id, start: int, count: int
    ) -> Optional[list]:
        """Ciphertexts ``[start, start+count)`` of the job's column, or
        None when the job doesn't exist / isn't the clerk's. Ranges past
        the end return the (possibly empty) tail — polling clients stop
        on their own count, and an empty list is a valid answer. Backends
        override to read ONLY the requested range (sqlite: indexed
        position rows; file store: byte-offset seek); this default slices
        the materialized job for in-memory layouts."""
        job = self.get_clerking_job(clerk_id, job_id)
        if job is None:
            return None
        if start < 0 or count < 0:
            return []
        return job.encryptions[start : start + count]

    @abc.abstractmethod
    def create_clerking_result(self, result) -> None: ...

    def complete_clerking_job(self, clerk_id, job_id) -> None:
        """Retire a job WITHOUT filing a clerking result — the terminal of
        tier share-promotion (the clerk's output left as tagged
        participations of the parent aggregation, so no recipient-sealed
        result may exist). Must be idempotent: completing an already-done
        job is a no-op; an unknown/foreign job raises. Backends that
        predate share-promotion inherit this raising default."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement complete_clerking_job"
        )

    @abc.abstractmethod
    def list_results(self, snapshot_id) -> list: ...

    @abc.abstractmethod
    def get_result(self, snapshot_id, job_id): ...

    def get_results(self, snapshot_id) -> list:
        """All ClerkingResults for the snapshot in ``list_results`` order
        (sorted by str(job_id) — canonical across backends). Bulk
        replacement for the get_result-per-job loop; backends override
        with a single scan/query."""
        results = []
        for job_id in self.list_results(snapshot_id):
            result = self.get_result(snapshot_id, job_id)
            if result is None:
                raise ServerError("inconsistent storage")
            results.append(result)
        return results

    def count_results(self, snapshot_id) -> int:
        """Number of posted ClerkingResults for the snapshot — the other
        paged-delivery decision input. Backends override with an indexed
        COUNT where one exists."""
        return len(self.list_results(snapshot_id))

    def get_results_range(self, snapshot_id, start: int, count: int) -> list:
        """ClerkingResults ``[start, start+count)`` in ``get_results``
        order (sorted by str(job_id) — the canonical cross-backend order,
        so a paged reader sees exactly the monolithic sequence). Ranges
        past the end return the (possibly empty) tail. Committee results
        are small next to mask columns, but paging them through the same
        discipline keeps one reveal-side code path."""
        if start < 0 or count < 0:
            return []
        return self.get_results(snapshot_id)[start : start + count]
