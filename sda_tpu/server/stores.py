"""Storage abstraction of the orchestration server.

Four store interfaces mirroring /root/reference/server/src/stores.rs: agents,
auth tokens, aggregations (incl. participations/snapshots/masks), and
clerking jobs (durable per-clerk pull queues). The server core only talks to
these interfaces; backends plug in underneath (memory, file, sqlite).

``iter_snapshot_clerk_jobs_data`` is the server's one nontrivial computation:
transposing the (participants x clerks) ciphertext matrix into per-clerk job
payloads (stores.rs:86-101). Backends may override it with something
smarter (the reference's mongo store runs it as an aggregation pipeline with
disk spill; the TPU fabric does it as an all_to_all when tensor-resident).
"""

from __future__ import annotations

import abc
from typing import Iterable, Iterator, Optional

from ..protocol import Labelled

# AuthToken = Labelled[AgentId, str] (stores.rs:8)
AuthToken = Labelled


class BaseStore(abc.ABC):
    def ping(self) -> None:
        """Raise if the backend is unhealthy."""


class AuthTokensStore(BaseStore):
    @abc.abstractmethod
    def upsert_auth_token(self, token: AuthToken) -> None: ...

    @abc.abstractmethod
    def register_auth_token(self, token: AuthToken) -> bool:
        """Atomic trust-on-first-use registration: record the token if the
        agent id has none yet; return whether the presented token is now
        the valid one (existing identical token also returns True).
        Check-and-write must be one atomic operation — two concurrent first
        registrations must not last-writer-win."""

    @abc.abstractmethod
    def get_auth_token(self, agent_id) -> Optional[AuthToken]: ...

    @abc.abstractmethod
    def delete_auth_token(self, agent_id) -> None: ...


class AgentsStore(BaseStore):
    @abc.abstractmethod
    def create_agent(self, agent) -> None: ...

    @abc.abstractmethod
    def get_agent(self, agent_id): ...

    @abc.abstractmethod
    def upsert_profile(self, profile) -> None: ...

    @abc.abstractmethod
    def get_profile(self, owner_id): ...

    @abc.abstractmethod
    def create_encryption_key(self, signed_key) -> None: ...

    @abc.abstractmethod
    def get_encryption_key(self, key_id): ...

    @abc.abstractmethod
    def suggest_committee(self) -> list:
        """All agents holding at least one registered key, as ClerkCandidates
        (reference jfs impl groups signed keys by signer, agents.rs:66-83)."""


class AggregationsStore(BaseStore):
    @abc.abstractmethod
    def list_aggregations(self, filter: Optional[str], recipient) -> list: ...

    @abc.abstractmethod
    def create_aggregation(self, aggregation) -> None: ...

    @abc.abstractmethod
    def get_aggregation(self, aggregation_id): ...

    @abc.abstractmethod
    def delete_aggregation(self, aggregation_id) -> None: ...

    @abc.abstractmethod
    def get_committee(self, aggregation_id): ...

    @abc.abstractmethod
    def create_committee(self, committee) -> None: ...

    @abc.abstractmethod
    def create_participation(self, participation) -> None: ...

    def create_participations(self, participations) -> None:
        """Bulk write of pre-validated participations — the storage half of
        the batched ingest pipeline.

        Contract: ATOMIC with the same create-if-identical idempotence as
        singles.  If any participation conflicts (same id, different body)
        or its aggregation is missing, the whole batch must be rejected
        with no partial state.  Backends override with a real bulk write
        (sqlite: one BEGIN IMMEDIATE + executemany); this default serves
        backends whose single create is already an in-memory mutation that
        the caller serializes (and is made atomic there by pre-checking)."""
        for participation in participations:
            self.create_participation(participation)

    @abc.abstractmethod
    def create_snapshot(self, snapshot) -> None: ...

    @abc.abstractmethod
    def list_snapshots(self, aggregation_id) -> list: ...

    @abc.abstractmethod
    def get_snapshot(self, aggregation_id, snapshot_id): ...

    @abc.abstractmethod
    def count_participations(self, aggregation_id) -> int: ...

    @abc.abstractmethod
    def snapshot_participations(self, aggregation_id, snapshot_id) -> None:
        """Freeze the current participation set as the snapshot's members."""

    @abc.abstractmethod
    def iter_snapped_participations(self, aggregation_id, snapshot_id) -> Iterator: ...

    def count_participations_snapshot(self, aggregation_id, snapshot_id) -> int:
        return sum(1 for _ in self.iter_snapped_participations(aggregation_id, snapshot_id))

    def validate_snapshot_clerk_jobs(
        self, aggregation_id, snapshot_id, clerks_number: int
    ) -> None:
        """Reject malformed snapped bodies BEFORE the transpose starts.

        Streaming backends yield columns lazily, after the snapshot
        pipeline has begun durably enqueueing clerk jobs — a mid-stream
        failure would leave clerks 0..k-1 holding jobs for a snapshot
        whose commit point never runs. The pipeline calls this first; a
        backend whose transpose can fail mid-stream must override it to
        raise here instead (sqlite: indexed COUNT; file store: one
        validation pass). The default is a no-op because the base
        transpose is eager — it materializes every column before the
        caller sees the first one, so a malformed body raises before any
        enqueue. (The service layer validates shape at participation
        creation; this guards direct store writes and corruption.)"""

    def iter_snapshot_clerk_jobs_data(
        self, aggregation_id, snapshot_id, clerks_number: int
    ) -> Iterable:
        """Transpose participations x clerks -> per-clerk ciphertext columns.

        Contract: an ITERABLE of ``clerks_number`` columns, consumed once
        in committee order (column ix = the clerk's committee position;
        participations carry clerk encryptions in committee order).
        Backends may return a lazy single-use generator (sqlite, file
        store above its threshold) — callers must not index, len(), or
        iterate twice. This default is the reference's eager in-memory
        transpose (stores.rs:86-101).
        """
        shares: list = [[] for _ in range(clerks_number)]
        for participation in self.iter_snapped_participations(aggregation_id, snapshot_id):
            for ix, (_, enc) in enumerate(participation.clerk_encryptions):
                shares[ix].append(enc)
        return shares

    @abc.abstractmethod
    def create_snapshot_mask(self, snapshot_id, mask: list) -> None: ...

    @abc.abstractmethod
    def get_snapshot_mask(self, snapshot_id): ...


class ClerkingJobsStore(BaseStore):
    @abc.abstractmethod
    def enqueue_clerking_job(self, job) -> None: ...

    @abc.abstractmethod
    def poll_clerking_job(self, clerk_id):
        """First not-yet-done job for the clerk; jobs stay queued until a
        result is posted, so a crashed clerk re-polls the same job
        (jfs_stores/clerking_jobs.rs:40-59)."""

    @abc.abstractmethod
    def get_clerking_job(self, clerk_id, job_id): ...

    @abc.abstractmethod
    def create_clerking_result(self, result) -> None: ...

    @abc.abstractmethod
    def list_results(self, snapshot_id) -> list: ...

    @abc.abstractmethod
    def get_result(self, snapshot_id, job_id): ...
