"""In-memory store backend.

The semantic reference implementation of the store interfaces: dict-backed,
thread-safe via a single lock, with the same create/upsert semantics as the
reference's jfs stores (idempotent create-if-identical,
server/src/jfs_stores/mod.rs:79-89). Used by tests and as the in-process
dev server; the file/sqlite backends mirror its behavior durably.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

from ..protocol import InvalidRequestError, ServerError
from .stores import (
    AggregationsStore,
    AgentsStore,
    AuthTokensStore,
    ClerkingJobsStore,
    paged_job_view,
)


def _create_if_identical(table: dict, key, value) -> None:
    """Reference jfs create semantics: re-creating with identical content is
    a no-op; differing content is an error (jfs_stores/mod.rs:79-89)."""
    if key in table and table[key] != value:
        raise ServerError(f"object already exists: {key}")
    table[key] = value


class MemAuthTokensStore(AuthTokensStore):
    def __init__(self):
        self._lock = threading.RLock()
        self._tokens: dict = {}

    def upsert_auth_token(self, token) -> None:
        with self._lock:
            self._tokens[token.id] = token

    def register_auth_token(self, token) -> bool:
        with self._lock:
            existing = self._tokens.get(token.id)
            if existing is None:
                self._tokens[token.id] = token
                return True
            return existing == token

    def get_auth_token(self, agent_id):
        with self._lock:
            return self._tokens.get(agent_id)

    def delete_auth_token(self, agent_id) -> None:
        with self._lock:
            self._tokens.pop(agent_id, None)


class MemAgentsStore(AgentsStore):
    def __init__(self):
        self._lock = threading.RLock()
        self._agents: dict = {}
        self._profiles: dict = {}
        self._keys: dict = {}  # EncryptionKeyId -> SignedEncryptionKey

    def create_agent(self, agent) -> None:
        with self._lock:
            _create_if_identical(self._agents, agent.id, agent)

    def get_agent(self, agent_id):
        with self._lock:
            return self._agents.get(agent_id)

    def upsert_profile(self, profile) -> None:
        with self._lock:
            self._profiles[profile.owner] = profile

    def get_profile(self, owner_id):
        with self._lock:
            return self._profiles.get(owner_id)

    def create_encryption_key(self, signed_key) -> None:
        with self._lock:
            _create_if_identical(self._keys, signed_key.body.id, signed_key)

    def get_encryption_key(self, key_id):
        with self._lock:
            return self._keys.get(key_id)

    def suggest_committee(self) -> list:
        from ..protocol import ClerkCandidate

        with self._lock:
            by_signer: dict = {}
            for signed in self._keys.values():
                by_signer.setdefault(signed.signer, []).append(signed.body.id)
            return [
                ClerkCandidate(id=signer, keys=keys)
                for signer, keys in by_signer.items()
                if signer in self._agents
            ]


class MemAggregationsStore(AggregationsStore):
    def __init__(self):
        self._lock = threading.RLock()
        self._aggregations: dict = {}
        self._committees: dict = {}  # AggregationId -> Committee
        self._participations: dict = {}  # AggregationId -> {ParticipationId: Participation}
        self._snapshots: dict = {}  # AggregationId -> {SnapshotId: Snapshot}
        self._snapshot_members: dict = {}  # SnapshotId -> [ParticipationId]
        self._snapshot_masks: dict = {}  # SnapshotId -> [Encryption]

    def list_aggregations(self, filter: Optional[str], recipient) -> list:
        with self._lock:
            out = []
            for agg in self._aggregations.values():
                if filter is not None and filter not in agg.title:
                    continue
                if recipient is not None and agg.recipient != recipient:
                    continue
                out.append(agg.id)
            return out

    def create_aggregation(self, aggregation) -> None:
        with self._lock:
            _create_if_identical(self._aggregations, aggregation.id, aggregation)
            self._participations.setdefault(aggregation.id, {})
            self._snapshots.setdefault(aggregation.id, {})

    def get_aggregation(self, aggregation_id):
        with self._lock:
            return self._aggregations.get(aggregation_id)

    def delete_aggregation(self, aggregation_id) -> None:
        with self._lock:
            self._aggregations.pop(aggregation_id, None)
            self._committees.pop(aggregation_id, None)
            self._participations.pop(aggregation_id, None)
            for snap_id in self._snapshots.pop(aggregation_id, {}):
                self._snapshot_members.pop(snap_id, None)
                self._snapshot_masks.pop(snap_id, None)

    def get_committee(self, aggregation_id):
        with self._lock:
            return self._committees.get(aggregation_id)

    def create_committee(self, committee) -> None:
        with self._lock:
            _create_if_identical(self._committees, committee.aggregation, committee)

    def create_participation(self, participation) -> None:
        with self._lock:
            agg = participation.aggregation
            if agg not in self._aggregations:
                raise InvalidRequestError(f"no aggregation {agg}")
            _create_if_identical(self._participations[agg], participation.id, participation)

    def create_participations(self, participations) -> None:
        # atomic batch: validate everything under the lock, then commit —
        # a mid-batch conflict/missing aggregation leaves no partial state
        participations = list(participations)
        with self._lock:
            staged: dict = {}
            for p in participations:
                if p.aggregation not in self._aggregations:
                    raise InvalidRequestError(f"no aggregation {p.aggregation}")
                prev = staged.get(p.id)
                if prev is not None and prev != p:
                    raise ServerError(f"object already exists: {p.id}")
                existing = self._participations[p.aggregation].get(p.id)
                if existing is not None and existing != p:
                    raise ServerError(f"object already exists: {p.id}")
                staged[p.id] = p
            for p in staged.values():
                self._participations[p.aggregation][p.id] = p

    def create_snapshot(self, snapshot) -> None:
        with self._lock:
            self._snapshots.setdefault(snapshot.aggregation, {})
            _create_if_identical(self._snapshots[snapshot.aggregation], snapshot.id, snapshot)

    def list_snapshots(self, aggregation_id) -> list:
        with self._lock:
            return list(self._snapshots.get(aggregation_id, {}).keys())

    def get_snapshot(self, aggregation_id, snapshot_id):
        with self._lock:
            return self._snapshots.get(aggregation_id, {}).get(snapshot_id)

    def count_participations(self, aggregation_id) -> int:
        with self._lock:
            return len(self._participations.get(aggregation_id, {}))

    def iter_participations(self, aggregation_id):
        with self._lock:
            table = self._participations.get(aggregation_id, {})
            return iter(sorted(table.values(), key=lambda p: str(p.id)))

    def discard_participations(self, aggregation_id, participation_ids) -> None:
        with self._lock:
            table = self._participations.get(aggregation_id)
            if table is None:
                return
            for pid in participation_ids:
                table.pop(pid, None)

    def snapshot_participations(self, aggregation_id, snapshot_id) -> None:
        with self._lock:
            # write-once: retries must not re-freeze a different membership
            if snapshot_id in self._snapshot_members:
                return
            members = list(self._participations.get(aggregation_id, {}).keys())
            self._snapshot_members[snapshot_id] = members

    def iter_snapped_participations(self, aggregation_id, snapshot_id):
        with self._lock:
            members = self._snapshot_members.get(snapshot_id, [])
            table = self._participations.get(aggregation_id, {})
            return iter([table[pid] for pid in members if pid in table])

    def create_snapshot_mask(self, snapshot_id, mask: list) -> None:
        with self._lock:
            self._snapshot_masks[snapshot_id] = list(mask)

    def get_snapshot_mask(self, snapshot_id):
        with self._lock:
            return self._snapshot_masks.get(snapshot_id)

    def count_snapshot_mask(self, snapshot_id):
        with self._lock:
            mask = self._snapshot_masks.get(snapshot_id)
            return None if mask is None else len(mask)

    def get_snapshot_mask_range(self, snapshot_id, start, count):
        # grab the reference under the lock, slice outside: the mask list
        # is replaced whole by create_snapshot_mask, never mutated in
        # place, so concurrent range readers don't convoy on the lock
        with self._lock:
            mask = self._snapshot_masks.get(snapshot_id)
        if mask is None:
            return None
        if start < 0 or count < 0:
            return []
        return mask[start : start + count]


class MemClerkingJobsStore(ClerkingJobsStore):
    def __init__(self):
        self._lock = threading.RLock()
        # per-clerk FIFO of pending job ids: poll peeks the head in O(1)
        # instead of rebuilding/scanning a job list (done jobs are lazily
        # popped off the head on the next poll)
        self._queues: dict = {}  # AgentId -> deque[ClerkingJobId]
        self._jobs: dict = {}  # ClerkingJobId -> ClerkingJob
        self._done: set = set()  # ClerkingJobIds with a posted result
        self._results: dict = {}  # SnapshotId -> {ClerkingJobId: ClerkingResult}

    def enqueue_clerking_job(self, job) -> None:
        with self._lock:
            # idempotent under snapshot retries (job ids are deterministic)
            if job.id in self._jobs:
                return
            self._jobs[job.id] = job
            self._queues.setdefault(job.clerk, collections.deque()).append(job.id)

    def poll_clerking_job(self, clerk_id):
        with self._lock:
            queue = self._queues.get(clerk_id)
            while queue:
                job_id = queue[0]
                if job_id in self._done:
                    queue.popleft()  # amortized O(1): each id pops once
                    continue
                return paged_job_view(self._jobs[job_id])
            return None

    def get_clerking_job(self, clerk_id, job_id):
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.clerk != clerk_id:
                return None
            return job

    def get_clerking_job_chunk(self, clerk_id, job_id, start, count):
        # grab the job under the lock, slice outside: the encryption
        # column is immutable after enqueue, so concurrent chunk readers
        # (prefetch pipelines, many clerks) don't convoy on the lock
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None or job.clerk != clerk_id:
            return None
        if start < 0 or count < 0:
            return []
        return job.encryptions[start : start + count]

    def create_clerking_result(self, result) -> None:
        with self._lock:
            job = self._jobs.get(result.job)
            if job is None:
                raise InvalidRequestError(f"no job {result.job}")
            self._results.setdefault(job.snapshot, {})[job.id] = result
            self._done.add(job.id)

    def complete_clerking_job(self, clerk_id, job_id) -> None:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.clerk != clerk_id:
                raise InvalidRequestError(f"no job {job_id}")
            self._done.add(job_id)

    def list_results(self, snapshot_id) -> list:
        # job-id order: every store returns the same canonical ordering
        # (sqlite's ORDER BY job), so snapshot-result bodies are
        # byte-stable across backends (asserted by test_replay_interop)
        with self._lock:
            keys = list(self._results.get(snapshot_id, {}).keys())
        return sorted(keys, key=str)  # O(n log n) outside the lock

    def get_result(self, snapshot_id, job_id):
        with self._lock:
            return self._results.get(snapshot_id, {}).get(job_id)

    def get_results(self, snapshot_id) -> list:
        # copy the table under the lock, sort + build outside
        with self._lock:
            table = dict(self._results.get(snapshot_id, {}))
        return [table[job_id] for job_id in sorted(table.keys(), key=str)]

    def count_results(self, snapshot_id) -> int:
        with self._lock:
            return len(self._results.get(snapshot_id, {}))

    def get_results_range(self, snapshot_id, start, count) -> list:
        if start < 0 or count < 0:
            return []
        with self._lock:
            table = dict(self._results.get(snapshot_id, {}))
        ordered = sorted(table.keys(), key=str)[start : start + count]
        return [table[job_id] for job_id in ordered]
