"""Production store backend: sqlite.

Fills the role of the reference's MongoDB backend (server-store-mongodb/):
durable, indexed, and — the scalability-critical part — a **streaming
server-side transpose**. The reference runs the (participants x clerks)
ciphertext transpose as a Mongo aggregation pipeline with disk spill
($unwind/$group, aggregations.rs:164-195); here each clerk's column is
extracted by the SQL engine with ``json_extract`` over an indexed snapshot
scan, one streaming pass per clerk, so no participation set is ever
materialized in RAM (contrast the generic in-memory transpose,
stores.iter_snapshot_clerk_jobs_data).

Job documents carry a ``done`` flag instead of queue-file moves, matching
the mongo store's shape (clerking_jobs.rs:36-76).

Multi-process sharing: like the reference's mongo backend — where any
number of server processes serve one datastore (server-store-mongodb/
src/lib.rs:64-84, unique-index upsert Daos at lib.rs:86-151) — one
sqlite file may back several ``sdad`` processes at once. WAL keeps
readers unblocked by the (single) writer, ``busy_timeout`` turns
cross-process write contention into bounded waiting instead of
``database is locked`` errors, and every check-then-act sequence runs
inside ``BEGIN IMMEDIATE`` so the read half of a read-modify-write
holds the write lock — two processes racing create-if-identical or the
job-done flip serialize instead of interleaving. Verified end-to-end
by tests/test_shared_store.py (two REST server processes, one file,
full protocol + contention).
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from contextlib import contextmanager
from typing import Optional

from ..protocol import (
    Agent,
    Aggregation,
    ClerkCandidate,
    ClerkingJob,
    ClerkingResult,
    Committee,
    Encryption,
    InvalidRequestError,
    Labelled,
    Participation,
    Profile,
    ServerError,
    Snapshot,
    signed_encryption_key_from_json,
)
from ..protocol.ids import AgentId, AggregationId, ClerkingJobId, SnapshotId
from .stores import (
    AggregationsStore,
    AgentsStore,
    AuthTokensStore,
    ClerkingJobsStore,
    job_chunk_size,
    job_page_threshold,
    result_page_threshold,
    split_small_column,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS agents (id TEXT PRIMARY KEY, body TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS profiles (owner TEXT PRIMARY KEY, body TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS enc_keys (
    id TEXT PRIMARY KEY, signer TEXT NOT NULL, body TEXT NOT NULL);
CREATE INDEX IF NOT EXISTS enc_keys_signer ON enc_keys (signer);
CREATE TABLE IF NOT EXISTS auth_tokens (agent TEXT PRIMARY KEY, token TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS aggregations (
    id TEXT PRIMARY KEY, title TEXT NOT NULL, recipient TEXT NOT NULL,
    body TEXT NOT NULL);
CREATE INDEX IF NOT EXISTS aggregations_recipient ON aggregations (recipient);
CREATE TABLE IF NOT EXISTS committees (aggregation TEXT PRIMARY KEY, body TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS participations (
    id TEXT PRIMARY KEY, aggregation TEXT NOT NULL, body TEXT NOT NULL);
CREATE INDEX IF NOT EXISTS participations_agg ON participations (aggregation);
CREATE TABLE IF NOT EXISTS snapshots (
    id TEXT PRIMARY KEY, aggregation TEXT NOT NULL, body TEXT NOT NULL);
CREATE INDEX IF NOT EXISTS snapshots_agg ON snapshots (aggregation);
CREATE TABLE IF NOT EXISTS snapshot_members (
    snapshot TEXT NOT NULL, ord INTEGER NOT NULL, participation TEXT NOT NULL,
    PRIMARY KEY (snapshot, ord));
CREATE TABLE IF NOT EXISTS snapshot_masks (snapshot TEXT PRIMARY KEY, body TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS mask_encs (
    snapshot TEXT NOT NULL, pos INTEGER NOT NULL, body TEXT NOT NULL,
    PRIMARY KEY (snapshot, pos)) WITHOUT ROWID;
CREATE TABLE IF NOT EXISTS jobs (
    id TEXT PRIMARY KEY, clerk TEXT NOT NULL, snapshot TEXT NOT NULL,
    done INTEGER NOT NULL DEFAULT 0, body TEXT NOT NULL);
CREATE INDEX IF NOT EXISTS jobs_clerk ON jobs (clerk, done);
CREATE TABLE IF NOT EXISTS job_encs (
    job TEXT NOT NULL, pos INTEGER NOT NULL, body TEXT NOT NULL,
    PRIMARY KEY (job, pos)) WITHOUT ROWID;
CREATE TABLE IF NOT EXISTS results (
    job TEXT PRIMARY KEY, snapshot TEXT NOT NULL, body TEXT NOT NULL);
CREATE INDEX IF NOT EXISTS results_snapshot ON results (snapshot);
"""


#: cross-process write-contention wait bound (seconds). Long enough to
#: ride out another process's streaming transpose commit; short enough
#: that a wedged writer surfaces as an error rather than a silent hang.
BUSY_TIMEOUT_S = 30.0


class SqliteBackend:
    """Shared write connection + lock, per-thread read connections.

    ``self.lock`` serializes *threads* of one process on the shared
    write connection; ``transaction()`` (BEGIN IMMEDIATE) serializes
    *processes* on the shared file — both are needed: the thread lock
    cannot see other processes, and sqlite's write lock cannot protect
    a Python check-then-act unless the check runs inside an immediate
    transaction.

    Reads take neither lock: each reading thread gets its own
    connection (``threading.local``), and WAL lets any number of
    readers run concurrently with the single writer — so
    ThreadingHTTPServer's per-request threads actually serve chunk
    range-reads in parallel instead of convoying on one shared read
    connection. Thread-local connections are reclaimed when their
    thread dies (thread-per-request server) or at interpreter exit.
    """

    def __init__(self, path):
        path = str(path)
        if path != ":memory:":
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

        def connect():
            # autocommit mode: transaction boundaries are explicit (BEGIN
            # IMMEDIATE in transaction()); Python's implicit deferred
            # transactions would take the write lock only at the first
            # write, after the check half of check-then-act already ran.
            # timeout=0 so the PRAGMA below is the one place the busy
            # wait is configured.
            conn = sqlite3.connect(
                path, check_same_thread=False, timeout=0, isolation_level=None
            )
            conn.execute(f"PRAGMA busy_timeout={int(BUSY_TIMEOUT_S * 1000)}")
            # the rollback->WAL transition takes an exclusive lock through
            # a path that does NOT invoke the busy handler (observed: two
            # sdad processes booting on one fresh file -> "database is
            # locked" despite the busy_timeout above; scripts/crash_soak.py
            # seed 20002), so the wait has to live here in a retry loop
            deadline = time.monotonic() + BUSY_TIMEOUT_S
            while True:
                try:
                    conn.execute("PRAGMA journal_mode=WAL")
                    break
                except sqlite3.OperationalError as exc:
                    if "locked" not in str(exc) or time.monotonic() >= deadline:
                        raise
                    time.sleep(0.05)
            return conn

        self.conn = connect()
        self.lock = threading.RLock()
        with self.lock:
            self.conn.executescript(_SCHEMA)
        # reads go through per-thread connections: WAL lets readers run
        # concurrently with the (single) writer, so neither a thread
        # stuck in BEGIN IMMEDIATE's busy wait nor another reader's
        # range scan can stall this thread's polls/status reads.
        # ":memory:" has no shared file — a second connection would be a
        # different database — so reads alias the write connection
        # (under self.lock) there.
        self._memory = path == ":memory:"
        self._connect = connect
        self._readers = threading.local()

    def _read_conn(self):
        """This thread's read connection, created on first use."""
        conn = getattr(self._readers, "conn", None)
        if conn is None:
            conn = self._readers.conn = self._connect()
        return conn

    @contextmanager
    def transaction(self):
        """Thread lock + BEGIN IMMEDIATE: the write lock is taken up
        front, so reads inside the block see a state no other process
        can change before our writes commit."""
        with self.lock:
            self.conn.execute("BEGIN IMMEDIATE")
            try:
                yield self.conn
                self.conn.execute("COMMIT")
            except BaseException:
                # a failed COMMIT must roll back too, or the shared
                # connection stays inside a dead transaction and every
                # later BEGIN fails ("cannot start a transaction within
                # a transaction"). Guarded: some COMMIT failures
                # (SQLITE_FULL/IOERR) auto-roll-back, and a bare
                # ROLLBACK there would mask the real error
                if self.conn.in_transaction:
                    self.conn.execute("ROLLBACK")
                raise

    def execute(self, sql, params=()):
        with self.lock:
            # single-statement writes are atomic on their own; autocommit
            # applies them immediately (no explicit transaction needed)
            return self.conn.execute(sql, params)

    def query_one(self, sql, params=()):
        if self._memory:
            with self.lock:
                return self.conn.execute(sql, params).fetchone()
        return self._read_conn().execute(sql, params).fetchone()

    def query_all(self, sql, params=()):
        if self._memory:
            with self.lock:
                return self.conn.execute(sql, params).fetchall()
        return self._read_conn().execute(sql, params).fetchall()

    def create_row(self, table, id_col, id_val, cols: dict):
        """create-if-identical semantics via INSERT OR conflict check."""
        with self.transaction() as conn:
            row = conn.execute(
                f"SELECT body FROM {table} WHERE {id_col} = ?", (id_val,)
            ).fetchone()
            if row is not None:
                if row[0] != cols["body"]:
                    raise ServerError(f"object already exists: {id_val}")
                return
            names = ", ".join([id_col] + list(cols))
            marks = ", ".join("?" * (1 + len(cols)))
            conn.execute(
                f"INSERT INTO {table} ({names}) VALUES ({marks})",
                (id_val, *cols.values()),
            )


class SqliteAuthTokensStore(AuthTokensStore):
    def __init__(self, backend: SqliteBackend):
        self.db = backend

    def upsert_auth_token(self, token) -> None:
        self.db.execute(
            "INSERT INTO auth_tokens (agent, token) VALUES (?, ?) "
            "ON CONFLICT(agent) DO UPDATE SET token = excluded.token",
            (str(token.id), token.body),
        )

    def register_auth_token(self, token) -> bool:
        with self.db.transaction() as conn:
            row = conn.execute(
                "SELECT token FROM auth_tokens WHERE agent = ?", (str(token.id),)
            ).fetchone()
            if row is None:
                conn.execute(
                    "INSERT INTO auth_tokens (agent, token) VALUES (?, ?)",
                    (str(token.id), token.body),
                )
                return True
            return row[0] == token.body

    def get_auth_token(self, agent_id):
        row = self.db.query_one(
            "SELECT token FROM auth_tokens WHERE agent = ?", (str(agent_id),)
        )
        return None if row is None else Labelled(agent_id, row[0])

    def delete_auth_token(self, agent_id) -> None:
        self.db.execute("DELETE FROM auth_tokens WHERE agent = ?", (str(agent_id),))


class SqliteAgentsStore(AgentsStore):
    def __init__(self, backend: SqliteBackend):
        self.db = backend

    def create_agent(self, agent) -> None:
        self.db.create_row(
            "agents", "id", str(agent.id), {"body": json.dumps(agent.to_json())}
        )

    def get_agent(self, agent_id):
        row = self.db.query_one("SELECT body FROM agents WHERE id = ?", (str(agent_id),))
        return None if row is None else Agent.from_json(json.loads(row[0]))

    def upsert_profile(self, profile) -> None:
        self.db.execute(
            "INSERT INTO profiles (owner, body) VALUES (?, ?) "
            "ON CONFLICT(owner) DO UPDATE SET body = excluded.body",
            (str(profile.owner), json.dumps(profile.to_json())),
        )

    def get_profile(self, owner_id):
        row = self.db.query_one(
            "SELECT body FROM profiles WHERE owner = ?", (str(owner_id),)
        )
        return None if row is None else Profile.from_json(json.loads(row[0]))

    def create_encryption_key(self, signed_key) -> None:
        self.db.create_row(
            "enc_keys",
            "id",
            str(signed_key.body.id),
            {"signer": str(signed_key.signer), "body": json.dumps(signed_key.to_json())},
        )

    def get_encryption_key(self, key_id):
        row = self.db.query_one("SELECT body FROM enc_keys WHERE id = ?", (str(key_id),))
        return None if row is None else signed_encryption_key_from_json(json.loads(row[0]))

    def suggest_committee(self) -> list:
        rows = self.db.query_all(
            "SELECT k.signer, k.id FROM enc_keys k JOIN agents a ON a.id = k.signer "
            "ORDER BY k.signer, k.id"
        )
        out: dict = {}
        for signer, key_id in rows:
            out.setdefault(signer, []).append(key_id)
        from ..protocol.ids import EncryptionKeyId

        return [
            ClerkCandidate(id=AgentId(s), keys=[EncryptionKeyId(k) for k in keys])
            for s, keys in out.items()
        ]


class SqliteAggregationsStore(AggregationsStore):
    def __init__(self, backend: SqliteBackend):
        self.db = backend

    def list_aggregations(self, filter: Optional[str], recipient) -> list:
        sql = "SELECT id, title, recipient FROM aggregations"
        rows = self.db.query_all(sql)
        out = []
        for id_, title, rec in rows:
            if filter is not None and filter not in title:
                continue
            if recipient is not None and rec != str(recipient):
                continue
            out.append(AggregationId(id_))
        return out

    def create_aggregation(self, aggregation) -> None:
        self.db.create_row(
            "aggregations",
            "id",
            str(aggregation.id),
            {
                "title": aggregation.title,
                "recipient": str(aggregation.recipient),
                "body": json.dumps(aggregation.to_json()),
            },
        )

    def get_aggregation(self, aggregation_id):
        row = self.db.query_one(
            "SELECT body FROM aggregations WHERE id = ?", (str(aggregation_id),)
        )
        return None if row is None else Aggregation.from_json(json.loads(row[0]))

    def delete_aggregation(self, aggregation_id) -> None:
        a = str(aggregation_id)
        with self.db.transaction() as conn:
            snaps = [
                r[0]
                for r in conn.execute(
                    "SELECT id FROM snapshots WHERE aggregation = ?", (a,)
                ).fetchall()
            ]
            for s in snaps:
                conn.execute("DELETE FROM snapshot_members WHERE snapshot = ?", (s,))
                conn.execute("DELETE FROM snapshot_masks WHERE snapshot = ?", (s,))
                conn.execute("DELETE FROM mask_encs WHERE snapshot = ?", (s,))
            conn.execute("DELETE FROM snapshots WHERE aggregation = ?", (a,))
            conn.execute("DELETE FROM participations WHERE aggregation = ?", (a,))
            conn.execute("DELETE FROM committees WHERE aggregation = ?", (a,))
            conn.execute("DELETE FROM aggregations WHERE id = ?", (a,))

    def get_committee(self, aggregation_id):
        row = self.db.query_one(
            "SELECT body FROM committees WHERE aggregation = ?", (str(aggregation_id),)
        )
        return None if row is None else Committee.from_json(json.loads(row[0]))

    def create_committee(self, committee) -> None:
        self.db.create_row(
            "committees",
            "aggregation",
            str(committee.aggregation),
            {"body": json.dumps(committee.to_json())},
        )

    def create_participation(self, participation) -> None:
        # existence check + insert are NOT one transaction: a concurrent
        # delete_aggregation can strand this row, which the snapshot
        # freeze scopes out (it selects by aggregation id); matching the
        # reference's non-transactional Mongo Daos
        if self.get_aggregation(participation.aggregation) is None:
            raise InvalidRequestError(f"no aggregation {participation.aggregation}")
        self.db.create_row(
            "participations",
            "id",
            str(participation.id),
            {
                "aggregation": str(participation.aggregation),
                "body": json.dumps(participation.to_json()),
            },
        )

    def create_participations(self, participations) -> None:
        """Bulk ingest: ONE write transaction for the whole batch.

        The single-row path pays a BEGIN IMMEDIATE + existence probe +
        SELECT + INSERT per participation; here the batch shares one
        transaction, one aggregation probe per distinct aggregation, a
        chunked IN() duplicate scan, and one executemany (sqlite3 reuses
        the prepared INSERT across the whole sequence). Semantics match
        N singles: identical replays no-op, a same-id-different-body
        conflict or missing aggregation raises and the transaction's
        rollback discards every row of the batch."""
        participations = list(participations)
        if not participations:
            return
        # canonicalize + intra-batch dedup before taking the write lock
        rows: dict = {}
        for p in participations:
            key = str(p.id)
            body = json.dumps(p.to_json())
            prev = rows.get(key)
            if prev is not None and prev[2] != body:
                raise ServerError(f"object already exists: {key}")
            rows[key] = (key, str(p.aggregation), body)
        with self.db.transaction() as conn:
            for agg in sorted({r[1] for r in rows.values()}):
                if (
                    conn.execute(
                        "SELECT 1 FROM aggregations WHERE id = ?", (agg,)
                    ).fetchone()
                    is None
                ):
                    raise InvalidRequestError(f"no aggregation {agg}")
            fresh = dict(rows)
            ids = list(rows)
            chunk = 500  # stay under SQLITE_MAX_VARIABLE_NUMBER (999 legacy)
            for lo in range(0, len(ids), chunk):
                part = ids[lo : lo + chunk]
                marks = ",".join("?" * len(part))
                for id_, body in conn.execute(
                    f"SELECT id, body FROM participations WHERE id IN ({marks})",
                    part,
                ):
                    if body != rows[id_][2]:
                        raise ServerError(f"object already exists: {id_}")
                    fresh.pop(id_, None)  # identical replay: no-op
            if fresh:
                conn.executemany(
                    "INSERT INTO participations (id, aggregation, body) "
                    "VALUES (?, ?, ?)",
                    list(fresh.values()),
                )

    def create_snapshot(self, snapshot) -> None:
        self.db.create_row(
            "snapshots",
            "id",
            str(snapshot.id),
            {
                "aggregation": str(snapshot.aggregation),
                "body": json.dumps(snapshot.to_json()),
            },
        )

    def list_snapshots(self, aggregation_id) -> list:
        rows = self.db.query_all(
            "SELECT id FROM snapshots WHERE aggregation = ? ORDER BY id",
            (str(aggregation_id),),
        )
        return [SnapshotId(r[0]) for r in rows]

    def get_snapshot(self, aggregation_id, snapshot_id):
        row = self.db.query_one(
            "SELECT body FROM snapshots WHERE id = ? AND aggregation = ?",
            (str(snapshot_id), str(aggregation_id)),
        )
        return None if row is None else Snapshot.from_json(json.loads(row[0]))

    def count_participations(self, aggregation_id) -> int:
        row = self.db.query_one(
            "SELECT COUNT(*) FROM participations WHERE aggregation = ?",
            (str(aggregation_id),),
        )
        return row[0]

    def iter_participations(self, aggregation_id):
        # ordered full scan for the shard-migration copier: id-keyed
        # batches keep memory bounded like iter_snapped_participations
        a = str(aggregation_id)
        last = ""
        batch = 1024
        while True:
            rows = self.db.query_all(
                "SELECT id, body FROM participations "
                "WHERE aggregation = ? AND id > ? ORDER BY id LIMIT ?",
                (a, last, batch),
            )
            if not rows:
                return
            for pid, body in rows:
                yield Participation.from_json(json.loads(body))
            last = rows[-1][0]

    def discard_participations(self, aggregation_id, participation_ids) -> None:
        ids = [str(pid) for pid in participation_ids]
        if not ids:
            return
        a = str(aggregation_id)
        chunk = 500  # stay under SQLITE_MAX_VARIABLE_NUMBER (999 legacy)
        with self.db.transaction() as conn:
            for lo in range(0, len(ids), chunk):
                part = ids[lo : lo + chunk]
                marks = ",".join("?" * len(part))
                conn.execute(
                    f"DELETE FROM participations "
                    f"WHERE aggregation = ? AND id IN ({marks})",
                    [a] + part,
                )

    def snapshot_participations(self, aggregation_id, snapshot_id) -> None:
        s = str(snapshot_id)
        with self.db.transaction() as conn:
            existing = conn.execute(
                "SELECT COUNT(*) FROM snapshot_members WHERE snapshot = ?", (s,)
            ).fetchone()[0]
            if existing:
                return  # write-once freeze (retry safety)
            conn.execute(
                "INSERT INTO snapshot_members (snapshot, ord, participation) "
                "SELECT ?, ROW_NUMBER() OVER (ORDER BY id) - 1, id "
                "FROM participations WHERE aggregation = ?",
                (s, str(aggregation_id)),
            )

    def iter_snapped_participations(self, aggregation_id, snapshot_id):
        # streaming: indexed ord-range batches, memory bounded to one
        # batch (a fetchall would materialize every raw body for the
        # whole cohort — the exact RAM ceiling this backend exists to
        # avoid). Each batch is a COMPLETE query on the read connection —
        # never an open cursor held across lock releases, whose row
        # visibility under same-connection writes (e.g.
        # delete_aggregation) is undefined in sqlite. ord is dense
        # 0..n-1 at freeze time, so a short batch means rows were
        # deleted mid-scan: raise loudly rather than silently yield a
        # partial cohort.
        s = str(snapshot_id)
        total = self.db.query_one(
            "SELECT COUNT(*) FROM snapshot_members WHERE snapshot = ?", (s,)
        )[0]
        batch = 1024
        for lo in range(0, total, batch):
            want = min(batch, total - lo)
            rows = self.db.query_all(
                "SELECT p.body FROM snapshot_members m "
                "JOIN participations p ON p.id = m.participation "
                "WHERE m.snapshot = ? AND m.ord >= ? AND m.ord < ? "
                "ORDER BY m.ord",
                (s, lo, lo + batch),
            )
            if len(rows) != want:
                raise ServerError(
                    f"snapshot {snapshot_id}: snapped rows vanished "
                    f"mid-scan (ord [{lo},{lo + batch}) returned "
                    f"{len(rows)}/{want}) — store mutated during iteration?"
                )
            for (body,) in rows:
                yield Participation.from_json(json.loads(body))

    def count_participations_snapshot(self, aggregation_id, snapshot_id) -> int:
        row = self.db.query_one(
            "SELECT COUNT(*) FROM snapshot_members WHERE snapshot = ?",
            (str(snapshot_id),),
        )
        return row[0]

    def validate_snapshot_clerk_jobs(
        self, aggregation_id, snapshot_id, clerks_number: int
    ) -> None:
        """One indexed COUNT validates every snapped body's
        clerk_encryptions shape before the pipeline enqueues anything —
        constant memory, no phantom jobs (see the base docstring)."""
        bad = self.db.query_one(
            "SELECT COUNT(*) FROM snapshot_members m "
            "JOIN participations p ON p.id = m.participation "
            "WHERE m.snapshot = ? AND ("
            "  json_array_length(p.body, '$.clerk_encryptions') IS NULL"
            "  OR json_array_length(p.body, '$.clerk_encryptions') != ?)",
            (str(snapshot_id), clerks_number),
        )[0]
        if bad:
            raise ServerError(
                f"snapshot {snapshot_id}: {bad} snapped participation(s) "
                f"lack exactly {clerks_number} clerk encryptions — "
                "refusing to enqueue a partial transpose"
            )

    def iter_snapshot_clerk_jobs_data(
        self, aggregation_id, snapshot_id, clerks_number: int
    ):
        """The streaming transpose: the SQL engine extracts clerk ``ix``'s
        ciphertext column with json_extract, one indexed pass per clerk —
        the sqlite analog of the reference's $unwind/$group disk-spilling
        pipeline (server-store-mongodb/src/aggregations.rs:164-195).

        Returns a GENERATOR of columns: the snapshot pipeline enqueues
        each clerk's job before pulling the next column, so peak memory
        is one column (1/clerks of the cohort) — a list of columns here
        would materialize the entire ciphertext matrix and erase the
        point of streaming (asserted by the 100K flat-memory stress,
        tests/test_scale_stress.py).

        Malformed bodies are rejected up front by
        ``validate_snapshot_clerk_jobs`` (called by the snapshot
        pipeline before the first yield)."""

        def column(ix: int):
            rows = self.db.query_all(
                "SELECT json_extract(p.body, '$.clerk_encryptions[' || ? || '][1]') "
                "FROM snapshot_members m "
                "JOIN participations p ON p.id = m.participation "
                "WHERE m.snapshot = ? ORDER BY m.ord",
                (ix, str(snapshot_id)),
            )
            return [Encryption.from_json(json.loads(r[0])) for r in rows]

        return (column(ix) for ix in range(clerks_number))

    def iter_snapshot_clerk_jobs_chunks(
        self, aggregation_id, snapshot_id, clerks_number: int, chunk_size: int
    ):
        """Chunked streaming transpose: same json_extract column pull as
        ``iter_snapshot_clerk_jobs_data``, but each chunk is its own
        ord-range query, so peak memory per clerk drops from one column
        to one chunk. Same complete-query-per-batch and loud short-batch
        rules as ``iter_snapped_participations``."""
        s = str(snapshot_id)
        total = self.count_participations_snapshot(aggregation_id, snapshot_id)

        def column_chunks(ix: int):
            for lo in range(0, total, chunk_size):
                want = min(chunk_size, total - lo)
                rows = self.db.query_all(
                    "SELECT json_extract(p.body, '$.clerk_encryptions[' || ? || '][1]') "
                    "FROM snapshot_members m "
                    "JOIN participations p ON p.id = m.participation "
                    "WHERE m.snapshot = ? AND m.ord >= ? AND m.ord < ? "
                    "ORDER BY m.ord",
                    (ix, s, lo, lo + chunk_size),
                )
                if len(rows) != want:
                    raise ServerError(
                        f"snapshot {snapshot_id}: snapped rows vanished "
                        f"mid-transpose (ord [{lo},{lo + chunk_size}) returned "
                        f"{len(rows)}/{want}) — store mutated during iteration?"
                    )
                yield [Encryption.from_json(json.loads(r[0])) for r in rows]

        return (column_chunks(ix) for ix in range(clerks_number))

    # -- snapshot masks ------------------------------------------------------
    # Two layouts, mirroring job_encs: small masks stay one JSON blob in
    # snapshot_masks.body; masks above result_page_threshold() are
    # EXTERNALIZED — the blob becomes the marker ``{"externalized": n}``
    # and the encryptions live as one ``mask_encs`` row per ciphertext,
    # keyed (snapshot, pos), so a range read is an indexed scan. Layout
    # is decided at write time; the wire shape per call in the service.

    def create_snapshot_mask(self, snapshot_id, mask: list) -> None:
        mask = list(mask)
        s = str(snapshot_id)
        with self.db.transaction() as conn:
            # stale rows from a different-threshold rewrite must not
            # survive a layout switch (the snapshot retry path overwrites)
            conn.execute("DELETE FROM mask_encs WHERE snapshot = ?", (s,))
            if len(mask) <= result_page_threshold():
                body = json.dumps([e.to_json() for e in mask])
            else:
                conn.executemany(
                    "INSERT INTO mask_encs (snapshot, pos, body) VALUES (?, ?, ?)",
                    (
                        (s, pos, json.dumps(e.to_json()))
                        for pos, e in enumerate(mask)
                    ),
                )
                body = json.dumps({"externalized": len(mask)})
            conn.execute(
                "INSERT INTO snapshot_masks (snapshot, body) VALUES (?, ?) "
                "ON CONFLICT(snapshot) DO UPDATE SET body = excluded.body",
                (s, body),
            )

    def _mask_marker(self, snapshot_id):
        """(payload, total) — payload is the parsed blob (list for the
        inline layout, dict marker for externalized), total its length."""
        row = self.db.query_one(
            "SELECT body FROM snapshot_masks WHERE snapshot = ?", (str(snapshot_id),)
        )
        if row is None:
            return None, None
        payload = json.loads(row[0])
        if isinstance(payload, dict):
            return payload, int(payload["externalized"])
        return payload, len(payload)

    def get_snapshot_mask(self, snapshot_id):
        payload, total = self._mask_marker(snapshot_id)
        if payload is None:
            return None
        if isinstance(payload, dict):
            return self._read_mask_range(snapshot_id, 0, total)
        return [Encryption.from_json(e) for e in payload]

    def count_snapshot_mask(self, snapshot_id):
        _, total = self._mask_marker(snapshot_id)
        return total

    def get_snapshot_mask_range(self, snapshot_id, start, count):
        payload, total = self._mask_marker(snapshot_id)
        if payload is None:
            return None
        if start < 0 or count < 0:
            return []
        if isinstance(payload, dict):
            return self._read_mask_range(snapshot_id, start, min(start + count, total))
        return [Encryption.from_json(e) for e in payload[start : start + count]]

    def _read_mask_range(self, snapshot_id, start: int, end: int) -> list:
        if end <= start:
            return []
        rows = self.db.query_all(
            "SELECT body FROM mask_encs WHERE snapshot = ? AND pos >= ? AND pos < ? "
            "ORDER BY pos",
            (str(snapshot_id), start, end),
        )
        return [Encryption.from_json(json.loads(r[0])) for r in rows]


class SqliteClerkingJobsStore(ClerkingJobsStore):
    """Two column layouts coexist:

    - INLINE (legacy / small jobs): the full ciphertext column lives in
      ``jobs.body`` — the original wire shape, parsed and sliced on
      demand.
    - EXTERNALIZED (chunked enqueue, or plain enqueue above the paging
      threshold): ``jobs.body`` is the metadata-only job
      (``total_encryptions`` set, ``encryptions`` empty) and the column
      lives as one ``job_encs`` row per ciphertext, keyed (job, pos), so
      a chunk read is an indexed range scan and never materializes the
      column.

    Delivery shape is decided at poll time from the CURRENT paging
    threshold: small externalized jobs are reassembled into the
    monolithic wire body (byte-identical to inline — both re-serialize
    through the same dataclasses), large inline jobs are paged by view.
    """

    def __init__(self, backend: SqliteBackend):
        self.db = backend

    def enqueue_clerking_job(self, job) -> None:
        if len(job.encryptions) > job_page_threshold():
            self.enqueue_clerking_job_chunked(
                ClerkingJob(
                    id=job.id,
                    clerk=job.clerk,
                    aggregation=job.aggregation,
                    snapshot=job.snapshot,
                    encryptions=[],
                ),
                [job.encryptions],
            )
            return
        with self.db.transaction() as conn:
            row = conn.execute(
                "SELECT id FROM jobs WHERE id = ?", (str(job.id),)
            ).fetchone()
            if row is not None:
                return  # idempotent under deterministic snapshot retries
            conn.execute(
                "INSERT INTO jobs (id, clerk, snapshot, done, body) VALUES (?, ?, ?, 0, ?)",
                (str(job.id), str(job.clerk), str(job.snapshot), json.dumps(job.to_json())),
            )

    def enqueue_clerking_job_chunked(self, job, chunks) -> None:
        """Streaming enqueue: small columns (within the paging threshold)
        keep the legacy inline layout; larger ones land externalized in
        one write transaction, one executemany per range, never more
        than one range of the column in memory. The jobs row (with the
        final total) lands last, inside the same transaction, so a crash
        mid-column leaves no visible job and the deterministic-id retry
        rewrites from scratch."""
        job_key = str(job.id)
        if (
            self.db.query_one("SELECT id FROM jobs WHERE id = ?", (job_key,))
            is not None
        ):
            return  # idempotent: don't consume the iterator either
        column, chunks = split_small_column(chunks, job_page_threshold())
        if column is not None:
            job.encryptions = column
            self.enqueue_clerking_job(job)
            return
        with self.db.transaction() as conn:
            row = conn.execute(
                "SELECT id FROM jobs WHERE id = ?", (job_key,)
            ).fetchone()
            if row is not None:
                return  # lost a race to a concurrent retry: same bytes
            # defensive: an aborted prior transaction can't leave rows
            # (transactional), but a stale manual write could
            conn.execute("DELETE FROM job_encs WHERE job = ?", (job_key,))
            pos = 0
            for block in chunks:
                conn.executemany(
                    "INSERT INTO job_encs (job, pos, body) VALUES (?, ?, ?)",
                    [
                        (job_key, pos + i, json.dumps(enc.to_json()))
                        for i, enc in enumerate(block)
                    ],
                )
                pos += len(block)
            meta = ClerkingJob(
                id=job.id,
                clerk=job.clerk,
                aggregation=job.aggregation,
                snapshot=job.snapshot,
                encryptions=[],
                total_encryptions=pos,
            )
            conn.execute(
                "INSERT INTO jobs (id, clerk, snapshot, done, body) VALUES (?, ?, ?, 0, ?)",
                (job_key, str(job.clerk), str(job.snapshot), json.dumps(meta.to_json())),
            )

    def _deliver(self, job):
        """Stored body -> wire body under the current paging threshold."""
        total = (
            job.total_encryptions
            if job.total_encryptions is not None
            else len(job.encryptions)
        )
        if total > job_page_threshold():
            return ClerkingJob(
                id=job.id,
                clerk=job.clerk,
                aggregation=job.aggregation,
                snapshot=job.snapshot,
                encryptions=[],
                total_encryptions=total,
                chunk_size=job_chunk_size(),
            )
        if job.total_encryptions is None:
            return job  # inline + small: original shape, untouched
        # externalized + small: reassemble the monolithic wire body
        rows = self.db.query_all(
            "SELECT body FROM job_encs WHERE job = ? ORDER BY pos", (str(job.id),)
        )
        return ClerkingJob(
            id=job.id,
            clerk=job.clerk,
            aggregation=job.aggregation,
            snapshot=job.snapshot,
            encryptions=[Encryption.from_json(json.loads(r[0])) for r in rows],
        )

    def poll_clerking_job(self, clerk_id):
        row = self.db.query_one(
            "SELECT body FROM jobs WHERE clerk = ? AND done = 0 ORDER BY id LIMIT 1",
            (str(clerk_id),),
        )
        if row is None:
            return None
        return self._deliver(ClerkingJob.from_json(json.loads(row[0])))

    def get_clerking_job(self, clerk_id, job_id):
        row = self.db.query_one(
            "SELECT body FROM jobs WHERE id = ? AND clerk = ?",
            (str(job_id), str(clerk_id)),
        )
        if row is None:
            return None
        return self._deliver(ClerkingJob.from_json(json.loads(row[0])))

    def get_clerking_job_chunk(self, clerk_id, job_id, start, count):
        row = self.db.query_one(
            "SELECT body FROM jobs WHERE id = ? AND clerk = ?",
            (str(job_id), str(clerk_id)),
        )
        if row is None:
            return None
        if start < 0 or count < 0:
            return []
        job = ClerkingJob.from_json(json.loads(row[0]))
        if job.total_encryptions is None:
            return job.encryptions[start : start + count]  # inline layout
        # externalized: indexed (job, pos) range scan — reads ONLY the
        # requested rows, the whole point of the layout
        rows = self.db.query_all(
            "SELECT body FROM job_encs WHERE job = ? AND pos >= ? AND pos < ? "
            "ORDER BY pos",
            (str(job_id), start, start + count),
        )
        return [Encryption.from_json(json.loads(r[0])) for r in rows]

    def create_clerking_result(self, result) -> None:
        with self.db.transaction() as conn:
            row = conn.execute(
                "SELECT snapshot FROM jobs WHERE id = ?", (str(result.job),)
            ).fetchone()
            if row is None:
                raise InvalidRequestError(f"no job {result.job}")
            conn.execute(
                "INSERT INTO results (job, snapshot, body) VALUES (?, ?, ?) "
                "ON CONFLICT(job) DO UPDATE SET body = excluded.body",
                (str(result.job), row[0], json.dumps(result.to_json())),
            )
            conn.execute(
                "UPDATE jobs SET done = 1 WHERE id = ?", (str(result.job),)
            )

    def complete_clerking_job(self, clerk_id, job_id) -> None:
        with self.db.transaction() as conn:
            row = conn.execute(
                "SELECT clerk FROM jobs WHERE id = ?", (str(job_id),)
            ).fetchone()
            if row is None or row[0] != str(clerk_id):
                raise InvalidRequestError(f"no job {job_id}")
            conn.execute("UPDATE jobs SET done = 1 WHERE id = ?", (str(job_id),))

    def list_results(self, snapshot_id) -> list:
        rows = self.db.query_all(
            "SELECT job FROM results WHERE snapshot = ? ORDER BY job", (str(snapshot_id),)
        )
        return [ClerkingJobId(r[0]) for r in rows]

    def get_result(self, snapshot_id, job_id):
        row = self.db.query_one(
            "SELECT body FROM results WHERE job = ? AND snapshot = ?",
            (str(job_id), str(snapshot_id)),
        )
        return None if row is None else ClerkingResult.from_json(json.loads(row[0]))

    def get_results(self, snapshot_id) -> list:
        # one indexed scan replaces the list_results + get_result-per-job
        # N+1; ORDER BY job keeps the canonical cross-backend ordering
        rows = self.db.query_all(
            "SELECT body FROM results WHERE snapshot = ? ORDER BY job",
            (str(snapshot_id),),
        )
        return [ClerkingResult.from_json(json.loads(r[0])) for r in rows]

    def count_results(self, snapshot_id) -> int:
        row = self.db.query_one(
            "SELECT COUNT(*) FROM results WHERE snapshot = ?", (str(snapshot_id),)
        )
        return int(row[0])

    def get_results_range(self, snapshot_id, start, count) -> list:
        if start < 0 or count < 0:
            return []
        rows = self.db.query_all(
            "SELECT body FROM results WHERE snapshot = ? ORDER BY job "
            "LIMIT ? OFFSET ?",
            (str(snapshot_id), count, start),
        )
        return [ClerkingResult.from_json(json.loads(r[0])) for r in rows]
