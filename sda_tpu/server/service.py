"""SdaServer core and its ACL-enforcing service wrapper.

``SdaServer`` delegates every RPC to the four stores (reference:
server/src/server.rs:23-191); ``SdaServerService`` implements the protocol's
``SdaService`` interface on top, adding per-route access control exactly as
server.rs:193-361: recipient-only guards on all recipient routes, caller ==
subject on create/upsert routes, and the clerk-job ownership double check on
result submission.
"""

from __future__ import annotations

from typing import Optional

from .. import telemetry
from ..protocol import (
    AggregationStatus,
    InvalidCredentialsError,
    InvalidRequestError,
    PackedPaillierEncryptionScheme,
    PermissionDeniedError,
    Pong,
    SdaService,
    ServerError,
    SnapshotResult,
    SnapshotStatus,
    TierNodeStatus,
    TierStatus,
)
from ..protocol import tiers as tiers_mod
from . import snapshot as snapshot_mod
from . import stores


class SdaServer:
    def __init__(self, agents_store, auth_tokens_store, aggregation_store, clerking_job_store):
        self.agents_store = agents_store
        self.auth_tokens_store = auth_tokens_store
        self.aggregation_store = aggregation_store
        self.clerking_job_store = clerking_job_store

    # -- base --------------------------------------------------------------

    def ping(self) -> Pong:
        self.agents_store.ping()
        return Pong(running=True)

    # -- agents ------------------------------------------------------------

    def create_agent(self, agent) -> None:
        self.agents_store.create_agent(agent)

    def get_agent(self, agent_id):
        return self.agents_store.get_agent(agent_id)

    def upsert_profile(self, profile) -> None:
        self.agents_store.upsert_profile(profile)

    def get_profile(self, agent_id):
        return self.agents_store.get_profile(agent_id)

    def create_encryption_key(self, key) -> None:
        self.agents_store.create_encryption_key(key)

    def get_encryption_key(self, key_id):
        return self.agents_store.get_encryption_key(key_id)

    # -- aggregations --------------------------------------------------------

    def list_aggregations(self, filter, recipient):
        return self.aggregation_store.list_aggregations(filter, recipient)

    def get_aggregation(self, aggregation_id):
        return self.aggregation_store.get_aggregation(aggregation_id)

    def get_committee(self, aggregation_id):
        return self.aggregation_store.get_committee(aggregation_id)

    def create_aggregation(self, aggregation) -> None:
        from ..ops.modular import WIDE_MAX_MODULUS
        from ..protocol import ChaChaMasking

        if not 0 < aggregation.modulus < WIDE_MAX_MODULUS:
            raise InvalidRequestError(
                f"modulus {aggregation.modulus} outside (0, 2^62): beyond the "
                "exactness bound of the wide math plane"
            )
        # the math plane computes with the SCHEME-embedded moduli, so they
        # must match the aggregation's group (and obey the same bound) —
        # a mismatch silently corrupts the revealed aggregate
        sharing = aggregation.committee_sharing_scheme
        scheme_modulus = getattr(sharing, "modulus", None) or getattr(
            sharing, "prime_modulus", None
        )
        if scheme_modulus != aggregation.modulus:
            raise InvalidRequestError(
                "committee sharing scheme modulus differs from aggregation modulus"
            )
        masking = aggregation.masking_scheme
        mask_modulus = getattr(masking, "modulus", None)
        if mask_modulus is not None and mask_modulus != aggregation.modulus:
            raise InvalidRequestError(
                "masking scheme modulus differs from aggregation modulus"
            )
        if (
            isinstance(masking, ChaChaMasking)
            and masking.dimension != aggregation.vector_dimension
        ):
            raise InvalidRequestError(
                "ChaCha masking dimension differs from aggregation vector dimension"
            )
        from ..protocol import FullMasking, PackedPaillierEncryptionScheme

        if isinstance(
            aggregation.committee_encryption_scheme, PackedPaillierEncryptionScheme
        ):
            # shares are signed residues (truncated-remainder semantics);
            # Paillier packing is nonnegative-only, so clerk transport
            # stays on sodium sealed boxes
            raise InvalidRequestError(
                "PackedPaillier applies to recipient encryption only"
            )
        if isinstance(
            aggregation.recipient_encryption_scheme, PackedPaillierEncryptionScheme
        ):
            pscheme = aggregation.recipient_encryption_scheme
            if not isinstance(masking, (FullMasking,)) and masking.has_mask():
                # ChaCha uploads SEEDS as masks — summing seeds
                # homomorphically would corrupt the unmask silently
                raise InvalidRequestError(
                    "PackedPaillier recipient encryption requires Full masking"
                )
            if aggregation.modulus.bit_length() > pscheme.max_value_bitsize:
                raise InvalidRequestError(
                    "mask values would not fit the Paillier component bound"
                )
        # hierarchical knobs travel together: tiers counts committee levels
        # (so 1 is just "flat" and must be spelled as absence — the fields
        # are omitted from wire/signing bytes when unset, and an explicit
        # tiers=1 would make two byte-encodings of the same flat semantics)
        if aggregation.tiers is not None or aggregation.sub_cohort_size is not None:
            t, m = aggregation.tiers, aggregation.sub_cohort_size
            if t is None or m is None:
                raise InvalidRequestError(
                    "tiers and sub_cohort_size must be set together"
                )
            if not 2 <= t <= tiers_mod.MAX_TIERS:
                raise InvalidRequestError(
                    f"tiers must be in [2, {tiers_mod.MAX_TIERS}] "
                    "(flat aggregations omit the field)"
                )
            if not 2 <= m <= tiers_mod.MAX_SUB_COHORTS:
                raise InvalidRequestError(
                    f"sub_cohort_size must be in [2, {tiers_mod.MAX_SUB_COHORTS}]"
                )
            telemetry.gauge(
                "sda_tier_depth",
                "committee levels of the most recently created tiered aggregation",
            ).set(t)
        if aggregation.tier_promotion is not None:
            if aggregation.tier_promotion not in (
                tiers_mod.PROMOTION_REVEAL,
                tiers_mod.PROMOTION_RESHARE,
            ):
                raise InvalidRequestError(
                    f"tier_promotion must be "
                    f"{tiers_mod.PROMOTION_REVEAL!r} or "
                    f"{tiers_mod.PROMOTION_RESHARE!r}"
                )
            # the knob only means something on the hierarchical plane: a
            # root (tiers set) or a derived child (tier_parent set — leaves
            # carry tiers=None but still promote)
            if aggregation.tiers is None and aggregation.tier_parent is None:
                raise InvalidRequestError(
                    "tier_promotion requires a tiered aggregation"
                )
            from ..protocol import AdditiveSharing

            if aggregation.tier_promotion == tiers_mod.PROMOTION_RESHARE and isinstance(
                aggregation.committee_sharing_scheme, AdditiveSharing
            ):
                # an additive clerk column has no Lagrange weight to
                # re-share by — there is no share-promotion linear map
                raise InvalidRequestError(
                    "share-promotion requires a threshold (Shamir-family) "
                    "committee sharing scheme; additive sharing promotes "
                    "by reveal only"
                )
        if aggregation.tier_parent is not None:
            parent = self.aggregation_store.get_aggregation(aggregation.tier_parent)
            if parent is None or not parent.is_tiered():
                raise InvalidRequestError(
                    "tier_parent must name an existing tiered aggregation"
                )
            children = {
                tiers_mod.child_aggregation_id(parent.id, ix)
                for ix in range(parent.sub_cohort_size)
            }
            if aggregation.id not in children:
                raise InvalidRequestError(
                    "aggregation is not a derived child of its tier_parent"
                )
        self.aggregation_store.create_aggregation(aggregation)

    def delete_aggregation(self, aggregation_id) -> None:
        # a tiered root's sub-aggregations are DERIVED state of the root
        # record (protocol/tiers.py), so deleting the root cascades over
        # every provisioned node of its tree — orphaned sub-aggregations
        # would otherwise hold participations no one can ever reveal
        agg = self.aggregation_store.get_aggregation(aggregation_id)
        if agg is not None and agg.is_tiered():
            for node in tiers_mod.iter_tier_nodes(agg):
                if node.parent is None:
                    continue
                if self.aggregation_store.get_aggregation(node.aggregation_id) is not None:
                    self.aggregation_store.delete_aggregation(node.aggregation_id)
        self.aggregation_store.delete_aggregation(aggregation_id)

    def _sodium_key_of(self, key_id, owner):
        """The registered sodium box key ``key_id`` signed by ``owner``, or
        None. The single definition of "usable clerk key": clerk transport
        is sodium sealed boxes (a Paillier key would crash participants at
        share-sealing time), and participants verify signer == clerk
        client-side (participate.py), so a key signed by anyone else
        dead-ends the aggregation just the same."""
        from ..protocol import EncryptionKey

        signed = self.agents_store.get_encryption_key(key_id)
        if (
            signed is not None
            and signed.signer == owner
            and isinstance(signed.body.body, EncryptionKey)
        ):
            return signed
        return None

    def suggest_committee(self, aggregation_id):
        if self.aggregation_store.get_aggregation(aggregation_id) is None:
            raise ServerError("aggregation not found")
        # offer only keys a participant could actually seal shares to
        # (and drop agents left with none)
        candidates = []
        for cand in self.agents_store.suggest_committee():
            usable = [k for k in cand.keys if self._sodium_key_of(k, cand.id)]
            if usable:
                candidates.append(type(cand)(id=cand.id, keys=usable))
        return candidates

    def create_committee(self, committee) -> None:
        agg = self.aggregation_store.get_aggregation(committee.aggregation)
        if agg is None:
            raise ServerError("aggregation not found")
        expected = agg.committee_sharing_scheme.output_size
        if expected != len(committee.clerks_and_keys):
            raise InvalidRequestError(
                f"Expected {expected} clerks in the committee, "
                f"found {len(committee.clerks_and_keys)} instead"
            )
        # a clerk appearing twice would map two share columns onto one
        # reconstruction index, making the aggregation unrevealable
        clerk_ids = [c for (c, _) in committee.clerks_and_keys]
        if len(set(clerk_ids)) != len(clerk_ids):
            raise InvalidRequestError("committee contains duplicate clerks")
        # suggest_committee already filters to usable keys, but the
        # invariant must hold for committees built by any client, so
        # enforce it at the accept point too (see _sodium_key_of).
        for clerk_id, key_id in committee.clerks_and_keys:
            if self._sodium_key_of(key_id, clerk_id) is None:
                raise InvalidRequestError(
                    f"committee key {key_id} of clerk {clerk_id} is not a "
                    "registered sodium box key signed by that clerk"
                )
        self.aggregation_store.create_committee(committee)

    def _validate_participation(self, participation, committee, agg, expected=None) -> None:
        # Validate the clerk-encryption list against the committee: the
        # snapshot transpose routes ciphertexts to clerks *by position*
        # (stores.iter_snapshot_clerk_jobs_data), so a short/long/misordered
        # list would crash snapshotting or silently corrupt the aggregate.
        # (The reference accepts these unchecked — a deliberate hardening.)
        # ``expected`` lets batched ingest hoist the committee's clerk list
        # out of the per-item loop; it must equal the list derived here.
        if committee is None:
            raise InvalidRequestError("no committee for aggregation")
        if expected is None:
            expected = [clerk for (clerk, _) in committee.clerks_and_keys]
        ce = participation.clerk_encryptions
        if len(ce) != len(expected):
            raise InvalidRequestError(
                "participation clerk encryptions do not match the committee"
            )
        # one pass over the row: order against the committee, and clerk
        # transport is sodium — a mis-tagged ciphertext would only surface
        # as an opaque clerk-side decrypt failure later
        for (clerk, e), want in zip(ce, expected):
            if clerk != want:
                raise InvalidRequestError(
                    "participation clerk encryptions do not match the committee"
                )
            if e.variant != "Sodium":
                raise InvalidRequestError(
                    "clerk encryptions must be sodium sealed boxes"
                )
        self._validate_recipient_encryption(participation, agg)
        if participation.tier_reshare is not None:
            self._validate_tier_reshare(participation, agg)

    def _validate_tier_reshare(self, participation, agg) -> None:
        """Gate share-promotion rows at the door: a tagged row must target
        a tiered parent, name one of its derived children, carry a sane
        epoch/position/survivor set, and be submitted by the identity the
        tag claims (the child's clerk at ``position``, or the child's
        owner for the mask-correction row). Late rows — arriving after the
        parent froze a snapshot — are rejected so the prepare stage's
        epoch resolution stays pinned."""
        tag = participation.tier_reshare
        if agg is None:
            return  # the store write will surface the missing aggregation
        if not agg.is_tiered():
            raise InvalidRequestError(
                "tier_reshare rows may only target tiered aggregations"
            )
        children = {
            tiers_mod.child_aggregation_id(agg.id, ix)
            for ix in range(agg.sub_cohort_size)
        }
        if tag.child not in children:
            raise InvalidRequestError(
                "tier_reshare child is not a derived child of the aggregation"
            )
        if not 0 <= tag.epoch < tiers_mod.MAX_RESHARE_EPOCHS:
            raise InvalidRequestError(
                f"tier_reshare epoch must be in [0, {tiers_mod.MAX_RESHARE_EPOCHS})"
            )
        child = self.aggregation_store.get_aggregation(tag.child)
        if child is None:
            raise InvalidRequestError(
                "tier_reshare child aggregation is not provisioned"
            )
        if tag.position is None:
            # mask-correction row: the child's owner cancels its
            # sub-cohort's mask sum one tier up
            if tag.survivors is not None:
                raise InvalidRequestError(
                    "tier_reshare mask rows carry no survivor set"
                )
            if not agg.masking_scheme.has_mask():
                raise InvalidRequestError(
                    "tier_reshare mask row for a maskless aggregation"
                )
            if participation.participant != child.recipient:
                raise InvalidRequestError(
                    "tier_reshare mask row must come from the child's owner"
                )
        else:
            n = child.committee_sharing_scheme.output_size
            threshold = child.committee_sharing_scheme.reconstruction_threshold
            survivors = tag.survivors
            if survivors is None:
                raise InvalidRequestError(
                    "tier_reshare column rows must carry their survivor set"
                )
            if len(set(survivors)) != len(survivors) or any(
                not 0 <= s < n for s in survivors
            ):
                raise InvalidRequestError(
                    "tier_reshare survivors must be distinct committee positions"
                )
            if len(survivors) < threshold:
                raise InvalidRequestError(
                    f"tier_reshare survivor set below the reconstruction "
                    f"threshold {threshold}"
                )
            if tag.position not in survivors:
                raise InvalidRequestError(
                    "tier_reshare position must be among the survivors"
                )
            child_committee = self.aggregation_store.get_committee(tag.child)
            if child_committee is None:
                raise InvalidRequestError(
                    "tier_reshare child has no committee"
                )
            clerk, _ = child_committee.clerks_and_keys[tag.position]
            if participation.participant != clerk:
                raise InvalidRequestError(
                    "tier_reshare column row must come from the child's "
                    "clerk at the claimed position"
                )
        if self.aggregation_store.list_snapshots(participation.aggregation):
            raise InvalidRequestError(
                "tier_reshare row arrived after the aggregation snapshotted"
            )

    def create_participation(self, participation) -> None:
        committee = self.aggregation_store.get_committee(participation.aggregation)
        agg = self.aggregation_store.get_aggregation(participation.aggregation)
        self._validate_participation(participation, committee, agg)
        self.aggregation_store.create_participation(participation)
        self._count_promotion(agg, [participation])

    def create_participations(self, participations) -> None:
        """Batched ingest: every item passes the exact single-item checks
        (committee order, sodium variants, recipient-ciphertext shape),
        with committee/aggregation lookups amortized per aggregation, then
        ONE bulk store write — which rejects atomically, so one invalid
        participation stores nothing from the batch."""
        participations = list(participations)
        committees: dict = {}
        aggs: dict = {}
        expected: dict = {}
        for p in participations:
            a = p.aggregation
            if a not in committees:
                committees[a] = self.aggregation_store.get_committee(a)
                aggs[a] = self.aggregation_store.get_aggregation(a)
                if committees[a] is not None:
                    expected[a] = [clerk for (clerk, _) in committees[a].clerks_and_keys]
            self._validate_participation(p, committees[a], aggs[a], expected.get(a))
        self.aggregation_store.create_participations(participations)
        for a, agg in aggs.items():
            self._count_promotion(agg, [p for p in participations if p.aggregation == a])

    @staticmethod
    def _count_promotion(agg, participations) -> None:
        """Every participation accepted into a TIERED aggregation is a
        promotion by construction: real participants route to leaf
        sub-aggregations (which are flat), so anything landing on a node
        with tiers > 1 is a sub-cohort's partial climbing one level
        (client/tiers.py). ``path`` distinguishes the PR-14 reveal rows
        (untagged re-submissions of a reconstructed partial) from
        share-promotion rows (tier_reshare-tagged columns + mask
        corrections)."""
        if agg is None or not agg.is_tiered():
            return
        counts: dict = {}
        for p in participations:
            path = "reshare" if p.tier_reshare is not None else "reveal"
            counts[path] = counts.get(path, 0) + 1
        for path, n in counts.items():
            telemetry.counter(
                "sda_tier_promotions_total",
                "partial-sum promotions accepted into parent-tier aggregations",
                tier=str(agg.tiers),
                path=path,
            ).inc(n)

    def _validate_recipient_encryption(self, participation, agg) -> None:
        """Shape-check the recipient (mask) ciphertext at the door. For
        Paillier the wire format is public, so a garbage blob — which would
        otherwise surface only at snapshot-combine or recipient-decrypt
        time, after the participant's shares are in the aggregate — is
        rejected here. Sodium sealed boxes are opaque; only the variant tag
        can be checked."""
        enc = participation.recipient_encryption
        if enc is None:
            return
        if agg is None:
            return  # caller's store write will surface the missing aggregation
        scheme = agg.recipient_encryption_scheme
        if not isinstance(scheme, PackedPaillierEncryptionScheme):
            if enc.variant != "Sodium":
                raise InvalidRequestError(
                    "recipient encryption must be a sodium sealed box"
                )
            return
        from ..crypto.encryption import paillier_ciphertext_well_formed

        signed = self.agents_store.get_encryption_key(agg.recipient_key)
        if signed is None:
            return  # can't check without the key; combine falls back safely
        if not paillier_ciphertext_well_formed(
            enc, signed.body.body, scheme, agg.vector_dimension
        ):
            raise InvalidRequestError("malformed Paillier recipient encryption")

    def get_aggregation_status(self, aggregation_id) -> Optional[AggregationStatus]:
        agg = self.aggregation_store.get_aggregation(aggregation_id)
        if agg is None:
            return None
        snapshots = []
        for snap_id in self.aggregation_store.list_snapshots(aggregation_id):
            results_count = len(self.clerking_job_store.list_results(snap_id))
            snapshots.append(
                SnapshotStatus(
                    id=snap_id,
                    number_of_clerking_results=results_count,
                    result_ready=results_count
                    >= agg.committee_sharing_scheme.reconstruction_threshold,
                )
            )
        return AggregationStatus(
            aggregation=aggregation_id,
            number_of_participations=self.aggregation_store.count_participations(
                aggregation_id
            ),
            snapshots=snapshots,
        )

    def get_tier_status(self, aggregation_id) -> Optional[TierStatus]:
        """Readiness of every node of a tiered aggregation's derived tree,
        BFS order root first — the recipient's one-call view of how far the
        bottom-up round has climbed. None for flat/unknown aggregations.
        The tree is enumerated from the root record alone (protocol/
        tiers.py); nodes the round driver has not provisioned yet report
        ``exists=False``."""
        agg = self.aggregation_store.get_aggregation(aggregation_id)
        if agg is None or not agg.is_tiered():
            return None
        nodes = []
        for node in tiers_mod.iter_tier_nodes(agg):
            st = self.get_aggregation_status(node.aggregation_id)
            nodes.append(
                TierNodeStatus(
                    aggregation=node.aggregation_id,
                    tier=node.tier,
                    parent=node.parent,
                    exists=st is not None,
                    number_of_participations=0
                    if st is None
                    else st.number_of_participations,
                    result_ready=st is not None
                    and any(s.result_ready for s in st.snapshots),
                )
            )
        return TierStatus(
            aggregation=aggregation_id,
            tiers=agg.tiers,
            sub_cohort_size=agg.sub_cohort_size,
            nodes=nodes,
        )

    def create_snapshot(self, snapshot) -> None:
        snapshot_mod.run_snapshot(self, snapshot)

    # -- clerking ------------------------------------------------------------

    def poll_clerking_job(self, clerk_id):
        return self.clerking_job_store.poll_clerking_job(clerk_id)

    def get_clerking_job(self, clerk_id, job_id):
        return self.clerking_job_store.get_clerking_job(clerk_id, job_id)

    def get_clerking_job_chunk(self, clerk_id, job_id, start, count):
        return self.clerking_job_store.get_clerking_job_chunk(
            clerk_id, job_id, start, count
        )

    def create_clerking_result(self, result) -> None:
        self.clerking_job_store.create_clerking_result(result)

    def complete_clerking_job(self, clerk_id, job_id) -> None:
        self.clerking_job_store.complete_clerking_job(clerk_id, job_id)

    def get_snapshot_result(self, aggregation_id, snapshot_id) -> Optional[SnapshotResult]:
        # The snapshot must exist AND belong to this aggregation — otherwise
        # a recipient could read another aggregation's results through their
        # own ACL check (the reference marks this hole "FIXME no
        # aggregation/snapshot spoofing", server.rs:324; fixed here).
        if self.aggregation_store.get_snapshot(aggregation_id, snapshot_id) is None:
            return None
        number_of_participations = self.aggregation_store.count_participations_snapshot(
            aggregation_id, snapshot_id
        )
        # wire shape decided per CALL from the current threshold (the
        # stored layout was decided at write time; either serves both):
        # above it, answer metadata only and let the recipient stream the
        # two payloads through the range routes
        mask_count = self.aggregation_store.count_snapshot_mask(snapshot_id)
        clerk_count = self.clerking_job_store.count_results(snapshot_id)
        if (mask_count or 0) + clerk_count > stores.result_page_threshold():
            return SnapshotResult(
                snapshot=snapshot_id,
                number_of_participations=number_of_participations,
                clerk_encryptions=[],
                recipient_encryptions=None,
                mask_encryption_count=mask_count,
                clerk_result_count=clerk_count,
                chunk_size=stores.result_chunk_size(),
            )
        # one bulk read (backends: single query/scan) — the old
        # list_results + get_result-per-job loop was an N+1
        results = self.clerking_job_store.get_results(snapshot_id)
        return SnapshotResult(
            snapshot=snapshot_id,
            number_of_participations=number_of_participations,
            clerk_encryptions=results,
            recipient_encryptions=self.aggregation_store.get_snapshot_mask(snapshot_id),
        )

    def get_snapshot_result_masks(self, aggregation_id, snapshot_id, start, count):
        # same anti-spoofing gate as get_snapshot_result
        if self.aggregation_store.get_snapshot(aggregation_id, snapshot_id) is None:
            return None
        return self.aggregation_store.get_snapshot_mask_range(snapshot_id, start, count)

    def get_snapshot_result_clerks(self, aggregation_id, snapshot_id, start, count):
        if self.aggregation_store.get_snapshot(aggregation_id, snapshot_id) is None:
            return None
        return self.clerking_job_store.get_results_range(snapshot_id, start, count)

    # -- auth ----------------------------------------------------------------

    def upsert_auth_token(self, token) -> None:
        self.auth_tokens_store.upsert_auth_token(token)

    def register_auth_token(self, token) -> None:
        """Trust-on-first-use registration: the first token presented for an
        agent id sticks; later attempts with a different token are rejected
        (otherwise anyone could re-post a public Agent object and hijack the
        account by overwriting its token). Delegated to the store as one
        atomic check-and-write."""
        if not self.auth_tokens_store.register_auth_token(token):
            _count_rejection("auth_token")
            raise InvalidCredentialsError("agent already registered")

    def check_auth_token(self, token):
        import hmac

        stored = self.auth_tokens_store.get_auth_token(token.id)
        # constant-time secret compare (VERDICT r4 #7): a `==` on the token
        # body leaks a prefix-length timing oracle on a network-facing auth
        # path. The reference itself compares with == (server.rs:174-186);
        # this is a deliberate hardening deviation (docs/security.md).
        # Compared as the body's canonical BYTES: a str() coercion would
        # make any non-string body with a matching repr authenticate (e.g.
        # a list whose repr equals the stored secret), and would diverge
        # from what register_auth_token actually persisted.
        if stored is not None and hmac.compare_digest(
            _token_body_bytes(stored.body), _token_body_bytes(token.body)
        ):
            agent = self.agents_store.get_agent(token.id)
            if agent is None:
                _count_rejection("auth_token")
                raise InvalidCredentialsError("Agent not found")
            return agent
        _count_rejection("auth_token")
        raise InvalidCredentialsError("invalid token")

    def delete_auth_token(self, agent_id) -> None:
        self.auth_tokens_store.delete_auth_token(agent_id)


def _token_body_bytes(body) -> bytes:
    """Canonical byte encoding of an auth-token secret. Only the two wire
    shapes are comparable; anything else fails closed as a bad credential
    rather than being repr()-flattened into something comparable."""
    if isinstance(body, bytes):
        return bytes(body)
    if isinstance(body, str):
        return body.encode("utf-8")
    raise InvalidCredentialsError("malformed auth token")


def _count_rejection(check: str) -> None:
    telemetry.counter(
        "sda_acl_rejections_total", "denied service calls by ACL check", check=check
    ).inc()


def _acl_agent_is(caller, agent_id) -> None:
    if caller.id != agent_id:
        _count_rejection("agent_is")
        raise PermissionDeniedError(f"caller {caller.id} is not {agent_id}")


class SdaServerService(SdaService):
    """ACL wrapper: the in-process implementation of the service seam."""

    def __init__(self, server: SdaServer):
        self.server = server

    def ping(self):
        return self.server.ping()

    # -- agents (ACL: caller must be the subject on writes) -------------------

    def create_agent(self, caller, agent) -> None:
        _acl_agent_is(caller, agent.id)
        self.server.create_agent(agent)

    def get_agent(self, caller, agent_id):
        return self.server.get_agent(agent_id)

    def upsert_profile(self, caller, profile) -> None:
        _acl_agent_is(caller, profile.owner)
        self.server.upsert_profile(profile)

    def get_profile(self, caller, owner_id):
        return self.server.get_profile(owner_id)

    def create_encryption_key(self, caller, signed_key) -> None:
        _acl_agent_is(caller, signed_key.signer)
        self.server.create_encryption_key(signed_key)

    def get_encryption_key(self, caller, key_id):
        return self.server.get_encryption_key(key_id)

    # -- aggregations (public reads) ------------------------------------------

    def list_aggregations(self, caller, filter=None, recipient=None):
        return self.server.list_aggregations(filter, recipient)

    def get_aggregation(self, caller, aggregation_id):
        return self.server.get_aggregation(aggregation_id)

    def get_committee(self, caller, aggregation_id):
        return self.server.get_committee(aggregation_id)

    # -- recipient routes (ACL: caller must be the recipient) ------------------

    def _acl_recipient(self, caller, aggregation_id):
        agg = self.server.get_aggregation(aggregation_id)
        if agg is None:
            raise ServerError("No aggregation found")
        _acl_agent_is(caller, agg.recipient)
        return agg

    def create_aggregation(self, caller, aggregation) -> None:
        _acl_agent_is(caller, aggregation.recipient)
        self.server.create_aggregation(aggregation)

    def delete_aggregation(self, caller, aggregation_id) -> None:
        self._acl_recipient(caller, aggregation_id)
        self.server.delete_aggregation(aggregation_id)

    def suggest_committee(self, caller, aggregation_id):
        self._acl_recipient(caller, aggregation_id)
        return self.server.suggest_committee(aggregation_id)

    def create_committee(self, caller, committee) -> None:
        self._acl_recipient(caller, committee.aggregation)
        self.server.create_committee(committee)

    def get_aggregation_status(self, caller, aggregation_id):
        self._acl_recipient(caller, aggregation_id)
        return self.server.get_aggregation_status(aggregation_id)

    def get_tier_status(self, caller, aggregation_id):
        self._acl_recipient(caller, aggregation_id)
        return self.server.get_tier_status(aggregation_id)

    def create_snapshot(self, caller, snapshot) -> None:
        self._acl_recipient(caller, snapshot.aggregation)
        self.server.create_snapshot(snapshot)

    def get_snapshot_result(self, caller, aggregation_id, snapshot_id):
        self._acl_recipient(caller, aggregation_id)
        return self.server.get_snapshot_result(aggregation_id, snapshot_id)

    def get_snapshot_result_masks(self, caller, aggregation_id, snapshot_id, start):
        self._acl_recipient(caller, aggregation_id)
        count = stores.result_chunk_size()
        return self.server.get_snapshot_result_masks(
            aggregation_id, snapshot_id, start, count
        )

    def get_snapshot_result_clerks(self, caller, aggregation_id, snapshot_id, start):
        self._acl_recipient(caller, aggregation_id)
        count = stores.result_chunk_size()
        return self.server.get_snapshot_result_clerks(
            aggregation_id, snapshot_id, start, count
        )

    # -- participation ---------------------------------------------------------

    def create_participation(self, caller, participation) -> None:
        _acl_agent_is(caller, participation.participant)
        self.server.create_participation(participation)

    def create_participations(self, caller, participations) -> None:
        # the same ACL gate as singles, applied to EVERY item before any
        # validation or storage work happens
        participations = list(participations)
        for p in participations:
            _acl_agent_is(caller, p.participant)
        self.server.create_participations(participations)

    # -- clerking --------------------------------------------------------------

    def get_clerking_job(self, caller, clerk_id):
        _acl_agent_is(caller, clerk_id)
        return self.server.poll_clerking_job(clerk_id)

    def get_clerking_job_chunk(self, caller, job_id, start):
        # ownership is implied: the store's chunk lookup is keyed by
        # (clerk, job) and answers None unless the CALLER owns the job —
        # another clerk's job id reads as not-found, never as data
        count = stores.job_chunk_size()
        return self.server.get_clerking_job_chunk(caller.id, job_id, start, count)

    def create_clerking_result(self, caller, result) -> None:
        # double check the job really belongs to the caller (server.rs:351-360)
        job = self.server.get_clerking_job(result.clerk, result.job)
        if job is None:
            raise ServerError("Job not found")
        _acl_agent_is(caller, job.clerk)
        self.server.create_clerking_result(result)

    def complete_clerking_job(self, caller, job_id) -> None:
        # same ownership check as create_clerking_result: the job must
        # exist and belong to the caller before it can be retired
        job = self.server.get_clerking_job(caller.id, job_id)
        if job is None:
            raise ServerError("Job not found")
        _acl_agent_is(caller, job.clerk)
        self.server.complete_clerking_job(job.clerk, job_id)
