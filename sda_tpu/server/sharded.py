"""Partitioned store seam: K backing store partitions behind the one
store interface the server core already speaks.

The routable half of the sharded coordination plane (ROADMAP item 2,
SSNet's service-plane shape): aggregation-keyed state — the hot,
unbounded tables — is consistent-hashed over K complete backing store
partitions (mem, file, or sqlite; ``HashRing`` in ``utils/hashring.py``),
while the small global tables (agents, auth tokens, encryption keys) are
pinned to shard 0 by the factory (``new_sharded_server``). ``service.py``,
the snapshot pipeline, paged delivery, and every bulk read work
unchanged: the sharded classes implement the exact ``AggregationsStore``
/ ``ClerkingJobsStore`` interfaces and delegate each call to the owning
partition, so a backend's smarter overrides (sqlite's indexed counts,
the file store's ranged reads) are still the code that runs.

Routing rules:

- anything keyed by aggregation id hashes to its home partition;
- clerking jobs ride their ``job.aggregation`` at enqueue, and lookups
  keyed only by job id or snapshot id consult in-process routing maps
  recorded at enqueue/snapshot time, falling back to a partition fan-out
  (first partition that answers) so a fresh process over durable
  partitions still resolves everything;
- ``poll_clerking_job`` fans out in shard order — a clerk serves
  whichever aggregations hashed anywhere;
- snapshot-scoped result reads are single-partition by construction
  (every job of a snapshot lives with its aggregation), so the fan-out
  merge path is exact whenever the map is cold.

Every partition access ticks ``sda_shard_requests_total{shard}`` so the
split is observable (fan-out ops tick each partition they touch); the
time-series sampler derives a per-shard rate column from the deltas.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional

from .. import telemetry
from ..protocol import ServerError
from ..utils.hashring import HashRing
from . import stores


class ShardRouter:
    """Shared routing state for one sharded deployment: the ring plus
    the job-id/snapshot-id maps both sharded stores consult."""

    def __init__(self, shards: int):
        self.shards = shards
        self.ring = HashRing(shards)
        # in-process routing hints only — correctness never depends on
        # them (every reader has a fan-out fallback), so a fresh process
        # over durable partitions starts cold and warms as it routes
        self._snapshot_shard: dict = {}
        self._job_shard: dict = {}

    def touch(self, ix: int) -> None:
        if telemetry.enabled():
            telemetry.counter(
                "sda_shard_requests_total",
                "store requests routed per shard (fan-outs tick each "
                "partition touched)",
                shard=str(ix),
            ).inc()

    def aggregation_shard(self, aggregation_id) -> int:
        return self.ring.shard_for(str(aggregation_id))

    def note_snapshot(self, snapshot_id, ix: int) -> None:
        self._snapshot_shard[str(snapshot_id)] = ix

    def snapshot_shard(self, snapshot_id) -> Optional[int]:
        return self._snapshot_shard.get(str(snapshot_id))

    def note_job(self, job_id, ix: int) -> None:
        self._job_shard[str(job_id)] = ix

    def job_shard(self, job_id) -> Optional[int]:
        return self._job_shard.get(str(job_id))


class ShardedAggregationsStore(stores.AggregationsStore):
    """K ``AggregationsStore`` partitions routed by aggregation id."""

    def __init__(self, partitions: list, router: ShardRouter):
        self._parts = partitions
        self._router = router

    def ping(self) -> None:
        for part in self._parts:
            part.ping()

    def _home(self, aggregation_id):
        ix = self._router.aggregation_shard(aggregation_id)
        self._router.touch(ix)
        return self._parts[ix]

    def _snap_home(self, aggregation_id, snapshot_id):
        """Route by the aggregation AND warm the snapshot map — these
        calls are the only ones that carry both ids, and the snapshot
        pipeline issues several of them before the first snapshot-only
        lookup (mask writes happen before the snapshot record commits)."""
        ix = self._router.aggregation_shard(aggregation_id)
        self._router.note_snapshot(snapshot_id, ix)
        self._router.touch(ix)
        return self._parts[ix]

    # -- aggregations --------------------------------------------------------

    def list_aggregations(self, filter: Optional[str], recipient) -> list:
        out: list = []
        for ix, part in enumerate(self._parts):
            self._router.touch(ix)
            out.extend(part.list_aggregations(filter, recipient))
        return out

    def create_aggregation(self, aggregation) -> None:
        self._home(aggregation.id).create_aggregation(aggregation)

    def get_aggregation(self, aggregation_id):
        return self._home(aggregation_id).get_aggregation(aggregation_id)

    def delete_aggregation(self, aggregation_id) -> None:
        self._home(aggregation_id).delete_aggregation(aggregation_id)

    def get_committee(self, aggregation_id):
        return self._home(aggregation_id).get_committee(aggregation_id)

    def create_committee(self, committee) -> None:
        self._home(committee.aggregation).create_committee(committee)

    # -- participations ------------------------------------------------------

    def create_participation(self, participation) -> None:
        self._home(participation.aggregation).create_participation(participation)

    def create_participations(self, participations) -> None:
        """Bulk write grouped by home partition. Atomicity holds within
        each partition (the backend's contract); a batch spanning
        aggregations on different shards commits per-shard — the service
        layer submits per-aggregation batches, so in practice this is
        one partition's single atomic write."""
        by_shard: dict = {}
        for participation in participations:
            ix = self._router.aggregation_shard(participation.aggregation)
            by_shard.setdefault(ix, []).append(participation)
        for ix, group in sorted(by_shard.items()):
            self._router.touch(ix)
            self._parts[ix].create_participations(group)

    def count_participations(self, aggregation_id) -> int:
        return self._home(aggregation_id).count_participations(aggregation_id)

    # -- snapshots -----------------------------------------------------------

    def create_snapshot(self, snapshot) -> None:
        ix = self._router.aggregation_shard(snapshot.aggregation)
        self._router.note_snapshot(snapshot.id, ix)
        self._router.touch(ix)
        self._parts[ix].create_snapshot(snapshot)

    def list_snapshots(self, aggregation_id) -> list:
        return self._home(aggregation_id).list_snapshots(aggregation_id)

    def get_snapshot(self, aggregation_id, snapshot_id):
        return self._snap_home(aggregation_id, snapshot_id).get_snapshot(
            aggregation_id, snapshot_id
        )

    def snapshot_participations(self, aggregation_id, snapshot_id) -> None:
        self._snap_home(aggregation_id, snapshot_id).snapshot_participations(
            aggregation_id, snapshot_id
        )

    def iter_snapped_participations(self, aggregation_id, snapshot_id) -> Iterator:
        return self._snap_home(aggregation_id, snapshot_id).iter_snapped_participations(
            aggregation_id, snapshot_id
        )

    def count_participations_snapshot(self, aggregation_id, snapshot_id) -> int:
        return self._snap_home(
            aggregation_id, snapshot_id
        ).count_participations_snapshot(aggregation_id, snapshot_id)

    def validate_snapshot_clerk_jobs(
        self, aggregation_id, snapshot_id, clerks_number: int
    ) -> None:
        self._snap_home(aggregation_id, snapshot_id).validate_snapshot_clerk_jobs(
            aggregation_id, snapshot_id, clerks_number
        )

    def iter_snapshot_clerk_jobs_data(
        self, aggregation_id, snapshot_id, clerks_number: int
    ) -> Iterable:
        return self._snap_home(
            aggregation_id, snapshot_id
        ).iter_snapshot_clerk_jobs_data(aggregation_id, snapshot_id, clerks_number)

    def iter_snapshot_clerk_jobs_chunks(
        self, aggregation_id, snapshot_id, clerks_number: int, chunk_size: int
    ) -> Iterable:
        return self._snap_home(
            aggregation_id, snapshot_id
        ).iter_snapshot_clerk_jobs_chunks(
            aggregation_id, snapshot_id, clerks_number, chunk_size
        )

    # -- snapshot masks (snapshot-id-keyed) ----------------------------------

    def create_snapshot_mask(self, snapshot_id, mask: list) -> None:
        ix = self._router.snapshot_shard(snapshot_id)
        if ix is None:
            # unreachable through the snapshot pipeline (it routes
            # several (aggregation, snapshot)-keyed calls first); a
            # direct write with a cold map has no home to resolve
            raise ServerError(f"unroutable snapshot mask: {snapshot_id}")
        self._router.touch(ix)
        self._parts[ix].create_snapshot_mask(snapshot_id, mask)

    def _mask_read(self, snapshot_id, op, *args):
        ix = self._router.snapshot_shard(snapshot_id)
        if ix is not None:
            self._router.touch(ix)
            return getattr(self._parts[ix], op)(snapshot_id, *args)
        for ix, part in enumerate(self._parts):
            self._router.touch(ix)
            out = getattr(part, op)(snapshot_id, *args)
            if out is not None:
                self._router.note_snapshot(snapshot_id, ix)
                return out
        return None

    def get_snapshot_mask(self, snapshot_id):
        return self._mask_read(snapshot_id, "get_snapshot_mask")

    def count_snapshot_mask(self, snapshot_id) -> Optional[int]:
        return self._mask_read(snapshot_id, "count_snapshot_mask")

    def get_snapshot_mask_range(
        self, snapshot_id, start: int, count: int
    ) -> Optional[list]:
        return self._mask_read(snapshot_id, "get_snapshot_mask_range", start, count)


class ShardedClerkingJobsStore(stores.ClerkingJobsStore):
    """K ``ClerkingJobsStore`` partitions; jobs live with their
    aggregation's shard, polls fan out across all partitions."""

    def __init__(self, partitions: list, router: ShardRouter):
        self._parts = partitions
        self._router = router

    def ping(self) -> None:
        for part in self._parts:
            part.ping()

    def _enqueue_shard(self, job) -> int:
        ix = self._router.aggregation_shard(job.aggregation)
        self._router.note_job(job.id, ix)
        if job.snapshot is not None:
            self._router.note_snapshot(job.snapshot, ix)
        self._router.touch(ix)
        return ix

    def enqueue_clerking_job(self, job) -> None:
        self._parts[self._enqueue_shard(job)].enqueue_clerking_job(job)

    def enqueue_clerking_job_chunked(self, job, chunks: Iterable) -> None:
        self._parts[self._enqueue_shard(job)].enqueue_clerking_job_chunked(job, chunks)

    def poll_clerking_job(self, clerk_id):
        for ix, part in enumerate(self._parts):
            self._router.touch(ix)
            job = part.poll_clerking_job(clerk_id)
            if job is not None:
                self._router.note_job(job.id, ix)
                return job
        return None

    def _job_read(self, job_id, op, *args):
        ix = self._router.job_shard(job_id)
        if ix is not None:
            self._router.touch(ix)
            return getattr(self._parts[ix], op)(*args)
        for ix, part in enumerate(self._parts):
            self._router.touch(ix)
            out = getattr(part, op)(*args)
            if out is not None:
                self._router.note_job(job_id, ix)
                return out
        return None

    def get_clerking_job(self, clerk_id, job_id):
        return self._job_read(job_id, "get_clerking_job", clerk_id, job_id)

    def get_clerking_job_chunk(
        self, clerk_id, job_id, start: int, count: int
    ) -> Optional[list]:
        return self._job_read(
            job_id, "get_clerking_job_chunk", clerk_id, job_id, start, count
        )

    def create_clerking_result(self, result) -> None:
        ix = self._router.job_shard(result.job)
        if ix is None:
            # cold map (fresh process): locate the job by owner probe —
            # the result carries its clerk, and job ids are unique
            for probe, part in enumerate(self._parts):
                self._router.touch(probe)
                if part.get_clerking_job(result.clerk, result.job) is not None:
                    self._router.note_job(result.job, probe)
                    ix = probe
                    break
        if ix is None:
            raise ServerError(f"unroutable clerking result: job {result.job}")
        self._router.touch(ix)
        self._parts[ix].create_clerking_result(result)

    # -- snapshot-scoped result reads ---------------------------------------
    # Every job of a snapshot lives on one partition (its aggregation's),
    # so the cold-map fan-out merges are exact: K-1 partitions contribute
    # nothing and the canonical sort matches the single-store order.

    def _snap_part(self, snapshot_id):
        ix = self._router.snapshot_shard(snapshot_id)
        if ix is None:
            return None
        self._router.touch(ix)
        return self._parts[ix]

    def list_results(self, snapshot_id) -> list:
        part = self._snap_part(snapshot_id)
        if part is not None:
            return part.list_results(snapshot_id)
        out: list = []
        for ix, part in enumerate(self._parts):
            self._router.touch(ix)
            out.extend(part.list_results(snapshot_id))
        return sorted(out, key=str)

    def get_result(self, snapshot_id, job_id):
        part = self._snap_part(snapshot_id)
        if part is not None:
            return part.get_result(snapshot_id, job_id)
        return self._job_read(job_id, "get_result", snapshot_id, job_id)

    def get_results(self, snapshot_id) -> list:
        part = self._snap_part(snapshot_id)
        if part is not None:
            return part.get_results(snapshot_id)
        out: list = []
        for ix, part in enumerate(self._parts):
            self._router.touch(ix)
            out.extend(part.get_results(snapshot_id))
        return sorted(out, key=lambda r: str(r.job))

    def count_results(self, snapshot_id) -> int:
        part = self._snap_part(snapshot_id)
        if part is not None:
            return part.count_results(snapshot_id)
        total = 0
        for ix, part in enumerate(self._parts):
            self._router.touch(ix)
            total += part.count_results(snapshot_id)
        return total

    def get_results_range(self, snapshot_id, start: int, count: int) -> list:
        part = self._snap_part(snapshot_id)
        if part is not None:
            return part.get_results_range(snapshot_id, start, count)
        if start < 0 or count < 0:
            return []
        return self.get_results(snapshot_id)[start : start + count]
