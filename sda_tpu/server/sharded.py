"""Partitioned store seam: K backing store partitions behind the one
store interface the server core already speaks — now with R-way
replication so losing any one partition mid-round is a non-event.

The routable half of the sharded coordination plane (ROADMAP item 2,
SSNet's service-plane shape): aggregation-keyed state — the hot,
unbounded tables — is consistent-hashed over K complete backing store
partitions (mem, file, or sqlite; ``HashRing`` in ``utils/hashring.py``),
while the small global tables (agents, auth tokens, encryption keys) are
pinned to shard 0 by the factory (``new_sharded_server``). ``service.py``,
the snapshot pipeline, paged delivery, and every bulk read work
unchanged: the sharded classes implement the exact ``AggregationsStore``
/ ``ClerkingJobsStore`` interfaces and delegate each call to the owning
partition(s), so a backend's smarter overrides (sqlite's indexed counts,
the file store's ranged reads) are still the code that runs.

Routing rules:

- anything keyed by aggregation id hashes to its home partition; with
  ``replicas = R > 1`` the write set is the first R shards of the ring's
  ``preference()`` walk — a fixed, deterministic prefix, so replicas of
  one aggregation are self-consistent (parent rows always precede child
  rows on every replica);
- clerking jobs ride their ``job.aggregation`` at enqueue, and lookups
  keyed only by job id or snapshot id consult in-process routing maps
  recorded at enqueue/snapshot time, falling back to a partition fan-out
  (first partition that answers) so a fresh process over durable
  partitions still resolves everything;
- ``poll_clerking_job`` fans out in shard order — a clerk serves
  whichever aggregations hashed anywhere;
- snapshot-scoped result reads land on the aggregation's replica set by
  construction (every job of a snapshot lives with its aggregation), so
  the fan-out merge path is exact whenever the map is cold (with a
  replica-aware dedupe when R > 1).

Replication model (``SDA_SHARD_REPLICAS``, default 1 = the PR-12
single-home plane, bit for bit):

- **writes** fan out to all R target shards. A write needs a quorum of
  ``ceil((R+1)/2)`` acknowledgements, where a replica that is down (the
  wedge hook, a dead sqlite file, any transport-class error) is
  acknowledged *as a hint*: the op is queued in the coordinator and
  replayed by the background repair thread once the shard returns. At
  least one real (non-hinted) replica must accept, so the hard floor is
  one surviving copy — lose-any-one-shard survival at R=2, lose-any-two
  best effort at R=3. Logical rejections (``SdaError``: conflicts,
  missing parents, bad requests) are deterministic across replicas and
  propagate immediately — they are never hinted.
- **hinted handoff**: hints replay in FIFO order (program order per
  shard, so causality holds: ``create_aggregation`` replays before the
  participations that reference it). A hint whose shard is reachable but
  keeps rejecting is dropped after ``SDA_SHARD_HANDOFF_ATTEMPTS``
  tries (every store write is idempotent create-if-identical, so
  replays and client retries never double-apply).
- **reads** walk the target shards in preference order. Record reads
  (``get_*`` returning ``None`` on miss) take the first hit and
  *read-repair* any earlier replica that was up but missing the record;
  set/count/iterator reads are answered by the first reachable replica
  (replicas converge once the handoff queue drains — the drain window
  is the documented staleness bound, see docs/robustness.md).

The deterministic shard-fault hook has two faces: in-process
``router.wedge(ix)`` / ``heal(ix)``, and — for wedging a shard inside a
live ``sdad`` from another process — a ``shard-NN.down`` marker file in
the deployment root (``ShardRouter.down_marker``). Both make every
access to that partition fail with ``ShardDownError`` until healed.

Every partition access ticks ``sda_shard_requests_total{shard}`` so the
split is observable (fan-out ops tick each partition they touch); the
replica plane adds ``sda_shard_replica_writes_total{shard,outcome}``
(outcome ok / hinted / handoff / abandoned), the
``sda_shard_handoff_queue`` depth gauge, and
``sda_shard_read_repairs_total``.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
from typing import Iterable, Iterator, Optional

from .. import telemetry
from ..protocol import SdaError, ServerError
from ..utils.hashring import HashRing
from . import stores

log = logging.getLogger("sda.shard")


class ShardDownError(Exception):
    """A partition is wedged or unreachable.

    Deliberately *not* an ``SdaError``: the replicated paths classify
    ``SdaError`` as a deterministic logical rejection (propagate) and
    everything else as a transport-class replica failure (hint and
    carry on). Reaching the REST layer it maps to a retryable 500.
    """


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class ShardRouter:
    """Shared routing state for one sharded deployment: the ring, the
    replica count, the job-id/snapshot-id target maps both sharded
    stores consult, the shard-fault hook, and the hinted-handoff queue
    with its background repair thread."""

    def __init__(self, shards: int, replicas: int = 1, root=None):
        self.shards = shards
        self.replicas = max(1, min(int(replicas), shards))
        self.ring = HashRing(shards)
        #: deployment root for cross-process ``shard-NN.down`` markers
        #: (None for mem partitions — wedge in-process instead)
        self.root = root
        # in-process routing hints only — correctness never depends on
        # them (every reader has a fan-out fallback), so a fresh process
        # over durable partitions starts cold and warms as it routes.
        # Values are tuples of target shard indexes (length R).
        self._snapshot_targets: dict = {}
        self._job_targets: dict = {}
        # -- shard-fault hook + hinted handoff ----------------------------
        self._down: set = set()
        self._hints: collections.deque = collections.deque()
        self._hints_lock = threading.Lock()
        self._stores: dict = {}  # "agg"/"jobs" -> partition list (attach())
        self._repair_stop: Optional[threading.Event] = None
        self._repair_thread: Optional[threading.Thread] = None
        # -- elastic scale-out (add_shard / finish_add_shard) -------------
        #: shards mid-migration: writes hint to them, reads skip them
        self._warming: set = set()
        #: warming shards whose bulk copy has not landed yet — the
        #: handoff drain must hold off (hints replay AFTER the base copy)
        self._copying: set = set()
        #: the grown ring while a migration is in flight (targets() adds
        #: its preference prefix to the current ring's, old shards first)
        self._next_ring: Optional[HashRing] = None
        #: factory building partition ``ix`` on demand, attached by
        #: ``new_sharded_server`` — ``None`` means this deployment
        #: cannot grow (hand-assembled partition lists)
        self.new_partition = None
        # write gate: finish_add_shard's flip drains in-flight writes,
        # swaps the ring, and releases — the only moment writes pause
        self._gate = threading.Condition()
        self._inflight = 0
        self._paused = False

    # -- telemetry ---------------------------------------------------------

    def touch(self, ix: int) -> None:
        if telemetry.enabled():
            telemetry.counter(
                "sda_shard_requests_total",
                "store requests routed per shard (fan-outs tick each "
                "partition touched)",
                shard=str(ix),
            ).inc()

    def tick_replica(self, ix: int, outcome: str) -> None:
        if telemetry.enabled():
            telemetry.counter(
                "sda_shard_replica_writes_total",
                "replicated write attempts per shard: ok (replica "
                "acked), hinted (replica down, queued for handoff), "
                "handoff (hint replayed), abandoned (hint dropped)",
                shard=str(ix),
                outcome=outcome,
            ).inc()

    def tick_read_repair(self) -> None:
        if telemetry.enabled():
            telemetry.counter(
                "sda_shard_read_repairs_total",
                "records written back to a live replica that was "
                "missing them",
            ).inc()

    def _update_hint_gauge(self) -> None:
        if telemetry.enabled():
            telemetry.gauge(
                "sda_shard_handoff_queue",
                "writes queued for replay onto a down shard",
            ).set(float(len(self._hints)))

    # -- routing -----------------------------------------------------------

    def aggregation_shard(self, aggregation_id) -> int:
        return self.ring.shard_for(str(aggregation_id))

    def targets(self, key) -> tuple:
        """The write/read set for ``key``: the first R shards of the
        ring's preference walk (just the home shard when R == 1).

        While a shard add is migrating, keys the grown ring moves get
        the UNION of both rings' prefixes, old shards first: reads stay
        authoritative on the current home (the new shard is skipped as
        warming anyway), while every write is also queued for the future
        home as a hinted handoff — so by flip time the new shard holds
        base copy + replayed deltas and nothing is lost."""
        next_ring = self._next_ring
        if next_ring is None and self.replicas == 1:
            return (self.aggregation_shard(key),)
        out = tuple(self.ring.preference(str(key))[: self.replicas])
        if next_ring is not None:
            grown = [
                ix
                for ix in next_ring.preference(str(key))[: self.replicas]
                if ix not in out
            ]
            out = out + tuple(grown)
        return out

    def note_snapshot(self, snapshot_id, ixs) -> None:
        self._snapshot_targets[str(snapshot_id)] = (
            (ixs,) if isinstance(ixs, int) else tuple(ixs)
        )

    def snapshot_targets(self, snapshot_id) -> Optional[tuple]:
        return self._snapshot_targets.get(str(snapshot_id))

    def note_job(self, job_id, ixs) -> None:
        self._job_targets[str(job_id)] = (
            (ixs,) if isinstance(ixs, int) else tuple(ixs)
        )

    def job_targets(self, job_id) -> Optional[tuple]:
        return self._job_targets.get(str(job_id))

    # -- deterministic shard-fault hook ------------------------------------

    @staticmethod
    def down_marker(root, ix: int) -> str:
        """Path of the cross-process wedge marker for partition ``ix``:
        touch it to take the shard down inside a live server, remove it
        to bring the shard back. Scenarios and the soak use this to
        murder partitions inside a running ``sdad``."""
        return os.path.join(root, f"shard-{ix:02d}.down")

    def wedge(self, ix: int) -> None:
        """Take partition ``ix`` down (in-process hook)."""
        self._down.add(ix)

    def heal(self, ix: int) -> None:
        self._down.discard(ix)

    def shard_down(self, ix: int) -> bool:
        if ix in self._down:
            return True
        if self.root is not None:
            return os.path.exists(self.down_marker(self.root, ix))
        return False

    def shard_warming(self, ix: int) -> bool:
        """True while ``ix`` is a mid-migration shard: its contents are
        a partial copy, so reads must not treat it as authoritative."""
        return ix in self._warming

    def check_up(self, ix: int) -> None:
        if self.shard_down(ix):
            raise ShardDownError(f"shard {ix} is down")
        if ix in self._warming:
            # writes treat a warming shard exactly like a down one:
            # they queue as hints, which replay (in order, after the
            # bulk copy) instead of racing the copier
            raise ShardDownError(f"shard {ix} is warming")

    # -- hinted handoff ----------------------------------------------------

    def attach(self, kind: str, partitions: list) -> None:
        """Register a partition list ("agg" / "jobs") so the repair
        thread can replay hints onto it."""
        self._stores[kind] = partitions

    def add_hint(self, kind: str, ix: int, op: str, args: tuple) -> None:
        with self._hints_lock:
            self._hints.append([kind, ix, op, args, 0])
        self._update_hint_gauge()

    def hint_depth(self) -> int:
        return len(self._hints)

    def drain_hints_once(self) -> int:
        """One repair pass: replay queued writes onto shards that came
        back, in FIFO order (per-shard program order — causality).
        Returns the number of hints applied. A shard that is still down
        keeps its hints (attempts are free while waiting); a shard that
        is up but rejects a hint gets ``SDA_SHARD_HANDOFF_ATTEMPTS``
        tries before the hint is dropped as ``abandoned``."""
        with self._hints_lock:
            pending = list(self._hints)
            self._hints.clear()
        max_attempts = _env_int("SDA_SHARD_HANDOFF_ATTEMPTS", 8)
        applied = 0
        requeue = []
        blocked: set = set()  # shards that must keep FIFO order this pass
        for hint in pending:
            kind, ix, op, args, attempts = hint
            if ix in blocked or ix in self._copying or self.shard_down(ix):
                blocked.add(ix)
                requeue.append(hint)
                continue
            try:
                getattr(self._stores[kind][ix], op)(*args)
            except Exception as exc:
                hint[4] = attempts + 1
                if hint[4] >= max_attempts:
                    self.tick_replica(ix, "abandoned")
                    log.error(
                        "handoff hint %s to shard %d abandoned after %d "
                        "attempts: %r", op, ix, hint[4], exc
                    )
                else:
                    blocked.add(ix)
                    requeue.append(hint)
                continue
            applied += 1
            self.tick_replica(ix, "handoff")
        if requeue:
            with self._hints_lock:
                self._hints.extendleft(reversed(requeue))
        self._update_hint_gauge()
        return applied

    def start_repair(self, interval: Optional[float] = None) -> None:
        """Start the background repair thread (idempotent). The factory
        calls this when R > 1; tests may instead call
        ``drain_hints_once`` directly for deterministic stepping."""
        if self._repair_stop is not None:
            return
        if interval is None:
            interval = _env_float("SDA_SHARD_HANDOFF_S", 0.5)
        stop = threading.Event()
        self._repair_stop = stop

        def _loop():
            while not stop.wait(interval):
                try:
                    self.drain_hints_once()
                except Exception:
                    pass  # the repair loop must survive anything

        self._repair_thread = threading.Thread(
            target=_loop, name="sda-shard-repair", daemon=True
        )
        self._repair_thread.start()

    def stop_repair(self) -> None:
        if self._repair_stop is None:
            return
        self._repair_stop.set()
        if self._repair_thread is not None:
            self._repair_thread.join(timeout=2.0)
        self._repair_stop = None
        self._repair_thread = None

    # -- write gate (used by the grow flip) --------------------------------

    def write_begin(self) -> None:
        with self._gate:
            while self._paused:
                self._gate.wait()
            self._inflight += 1

    def write_end(self) -> None:
        with self._gate:
            self._inflight -= 1
            self._gate.notify_all()

    # -- elastic scale-out -------------------------------------------------

    def add_shard(self) -> int:
        """Begin a live scale-out to K+1 shards. Builds partition K via
        the attached factory, registers it with both sharded stores
        (``attach`` shares the list objects, so the append is visible
        everywhere), marks it warming+copying, and installs the grown
        ring as ``_next_ring`` — from this moment every write to a key
        the grown ring moves is ALSO queued for the new shard as a
        hinted handoff. Returns the new shard's index. The shard serves
        nothing until ``finish_add_shard`` flips the ring."""
        if self.new_partition is None:
            raise ServerError(
                "this deployment has no partition factory; cannot grow"
            )
        if self._next_ring is not None:
            raise ServerError("a shard add is already in progress")
        ix = self.shards
        agg_part, jobs_part = self.new_partition(ix)
        # warming/copying BEFORE the partitions become reachable: no
        # reader may ever treat the empty partition as authoritative
        self._warming.add(ix)
        self._copying.add(ix)
        self._stores["agg"].append(agg_part)
        self._stores["jobs"].append(jobs_part)
        self._next_ring = HashRing(self.shards + 1)
        return ix

    def moved_aggregations(self) -> list:
        """Every (aggregation id, old targets, new targets) whose target
        set the in-flight grow changes — the bulk-copy work list,
        enumerated from the old partitions' own tables (no separate
        catalog exists or is needed)."""
        if self._next_ring is None:
            return []
        seen: set = set()
        moved = []
        for src_ix in range(self.shards):
            part = self._stores["agg"][src_ix]
            if self.shard_down(src_ix):
                continue
            try:
                ids = part.list_aggregations(None, None)
            except Exception:
                continue  # a down replica's rows live on its peers
            for agg_id in ids:
                key = str(agg_id)
                if key in seen:
                    continue
                seen.add(key)
                old = tuple(self.ring.preference(key)[: self.replicas])
                new = tuple(self._next_ring.preference(key)[: self.replicas])
                if old != new:
                    moved.append((agg_id, old, new))
        return moved

    def _copy_aggregation(self, agg_id, src_ixs, dst_ix) -> None:
        """Copy one aggregation's full state from its current replica
        set onto the warming shard, in dependency order. Every store
        write is create-if-identical, so re-copies and later hint
        replays of the same rows are absorbed.

        Frozen snapshot membership is reproduced by construction: only
        the SNAPPED participations are copied before the membership
        freeze is replayed, so the destination freezes exactly the
        source's member set (the mask list is copied verbatim — nothing
        pairs masks and members positionally, reveals sum both)."""
        parts = self._stores["agg"]
        dst = parts[dst_ix]
        src = None
        for ix in src_ixs:
            if not self.shard_down(ix):
                src = parts[ix]
                break
        if src is None:
            raise ShardDownError(f"no live replica to copy {agg_id} from")
        agg = src.get_aggregation(agg_id)
        if agg is None:
            return  # deleted while the work list was being walked
        dst.create_aggregation(agg)
        committee = src.get_committee(agg_id)
        if committee is not None:
            dst.create_committee(committee)
        for snap_id in src.list_snapshots(agg_id):
            snapshot = src.get_snapshot(agg_id, snap_id)
            if snapshot is None:
                continue
            for p in src.iter_snapped_participations(agg_id, snap_id):
                dst.create_participation(p)
            dst.create_snapshot(snapshot)
            dst.snapshot_participations(agg_id, snap_id)
            self.note_snapshot(snap_id, self.targets(agg_id))
            mask = src.get_snapshot_mask(snap_id)
            if mask is not None:
                dst.create_snapshot_mask(snap_id, mask)
        for p in src.iter_participations(agg_id):
            dst.create_participation(p)

    def migrate_once(self) -> int:
        """One bulk-copy pass of the in-flight grow: copy every moved
        aggregation onto the warming shard, then open the shard to the
        handoff drain (hints replay the writes that raced the copy).
        Returns the number of aggregations copied. Idempotent."""
        if self._next_ring is None:
            return 0
        new_ix = self.shards  # the warming shard
        copied = 0
        for agg_id, old, new in self.moved_aggregations():
            if new_ix not in new:
                continue  # moved between old shards cannot happen; guard anyway
            self._copy_aggregation(agg_id, old, new_ix)
            copied += 1
        # base copy landed: let the repair thread replay queued deltas
        self._copying.discard(new_ix)
        return copied

    def finish_add_shard(self, timeout: float = 30.0) -> None:
        """Complete the grow: wait for the handoff queue to drain onto
        the (now copied) warming shard, briefly pause writes, drain the
        residual hints, atomically flip to the grown ring, and resume.
        After the flip the new shard is a full member: reads for moved
        keys land on it first and the old copies are plain garbage that
        replicated merges dedupe away."""
        import time as _time

        if self._next_ring is None:
            raise ServerError("no shard add in progress")
        new_ix = self.shards
        if new_ix in self._copying:
            self.migrate_once()
        deadline = _time.monotonic() + timeout
        while self.hint_depth() and _time.monotonic() < deadline:
            self.drain_hints_once()
            if self.hint_depth():
                _time.sleep(0.02)
        # flip under the write gate: no write may straddle the ring swap
        with self._gate:
            self._paused = True
            while self._inflight:
                if not self._gate.wait(timeout=timeout):
                    break
            try:
                # residual hints enqueued by the last in-flight writes
                while self.hint_depth():
                    if self.drain_hints_once() == 0:
                        break
                if self.hint_depth():
                    raise ServerError(
                        "grow flip aborted: handoff queue did not drain "
                        f"({self.hint_depth()} hints pending)"
                    )
                self.ring = self._next_ring
                self.shards += 1
                self._next_ring = None
                self._warming.discard(new_ix)
                self._copying.discard(new_ix)
            finally:
                self._paused = False
                self._gate.notify_all()

    def grow(self, timeout: float = 30.0) -> int:
        """Convenience one-call scale-out: add a shard, bulk-copy the
        moved keys, drain, flip. Returns the new shard index."""
        ix = self.add_shard()
        self.migrate_once()
        self.finish_add_shard(timeout=timeout)
        return ix


class _ReplicatedPartitions:
    """Shared read/write machinery over a partition list. ``_kind``
    names the partition list in the router's handoff registry."""

    _kind = ""

    def __init__(self, partitions: list, router: ShardRouter):
        self._parts = partitions
        self._router = router
        router.attach(self._kind, partitions)

    # -- write -------------------------------------------------------------

    def _write(self, op: str, args: tuple, targets) -> None:
        """Replicated write over ``targets`` (a tuple of shard indexes).

        Quorum ``ceil((R+1)/2)`` where a down replica's queued hint
        counts as a (durable-intent) ack; at least one replica must
        really accept. Logical rejections propagate untouched.

        ``targets`` may exceed R while a shard add is migrating (the
        union set); the extra warming shard is not a quorum participant
        — its write always queues as a hint — so the quorum math stays
        a function of R alone."""
        router = self._router
        router.write_begin()
        try:
            if len(targets) == 1:
                ix = targets[0]
                router.touch(ix)
                getattr(self._parts[ix], op)(*args)
                return
            quorum = (router.replicas + 2) // 2
            acks = 0
            hinted = []
            first_err = None
            for ix in targets:
                router.touch(ix)
                try:
                    router.check_up(ix)
                    getattr(self._parts[ix], op)(*args)
                except SdaError:
                    raise  # deterministic logical rejection, same everywhere
                except Exception as exc:
                    router.tick_replica(ix, "hinted")
                    log.warning(
                        "replica write %s to shard %d hinted: %r", op, ix, exc
                    )
                    hinted.append(ix)
                    if first_err is None:
                        first_err = exc
                    continue
                router.tick_replica(ix, "ok")
                acks += 1
            if acks == 0 or acks + len(hinted) < quorum:
                raise first_err if first_err is not None else ServerError(
                    f"write quorum failed: {op}"
                )
            for ix in hinted:
                router.add_hint(self._kind, ix, op, args)
        finally:
            router.write_end()

    # -- reads -------------------------------------------------------------

    def _read_record(self, op: str, args: tuple, targets, repair=None):
        """Record read (``None`` means miss): first replica with the
        record answers; earlier live-but-missing replicas get the record
        written back when ``repair(part, out)`` is provided."""
        router = self._router
        if len(targets) == 1:
            ix = targets[0]
            router.touch(ix)
            return getattr(self._parts[ix], op)(*args)
        first_err = None
        behind = []  # replicas that answered but were missing the record
        for ix in targets:
            router.touch(ix)
            try:
                router.check_up(ix)
                out = getattr(self._parts[ix], op)(*args)
            except SdaError:
                raise
            except Exception as exc:
                if first_err is None:
                    first_err = exc
                continue
            if out is None:
                behind.append(ix)
                continue
            if repair is not None:
                for b in behind:
                    try:
                        repair(self._parts[b], out)
                    except Exception:
                        continue
                    router.tick_read_repair()
            return out
        if behind:
            return None  # at least one replica answered: a genuine miss
        if first_err is not None:
            raise first_err
        return None

    def _read_any(self, op: str, args: tuple, targets):
        """Set/count/iterator read: the first reachable replica is
        authoritative (``None``/``0``/``[]`` are valid answers here, so
        there is no miss-walk — replicas converge once the handoff
        queue drains)."""
        router = self._router
        if len(targets) == 1:
            ix = targets[0]
            router.touch(ix)
            return getattr(self._parts[ix], op)(*args)
        first_err = None
        for ix in targets:
            router.touch(ix)
            try:
                router.check_up(ix)
            except ShardDownError as exc:
                if first_err is None:
                    first_err = exc
                continue
            try:
                return getattr(self._parts[ix], op)(*args)
            except SdaError:
                raise
            except Exception as exc:
                if first_err is None:
                    first_err = exc
                continue
        raise first_err if first_err is not None else ShardDownError(
            f"no replica answered {op}"
        )

    def _live_parts(self):
        """Fan-out iteration; when R > 1 a down partition is skipped
        (its rows live on R-1 other replicas). A warming partition —
        the target of an in-flight shard add — is always skipped: its
        contents are a partial copy of state that still lives, in
        full, on the old shards."""
        for ix, part in enumerate(self._parts):
            if self._router.shard_warming(ix):
                continue
            if self._router.replicas > 1 and self._router.shard_down(ix):
                continue
            yield ix, part


class ShardedAggregationsStore(_ReplicatedPartitions, stores.AggregationsStore):
    """K ``AggregationsStore`` partitions routed by aggregation id,
    replicated over the first R shards of the preference walk."""

    _kind = "agg"

    def ping(self) -> None:
        for part in self._parts:
            part.ping()

    def _home(self, aggregation_id):
        ix = self._router.aggregation_shard(aggregation_id)
        self._router.touch(ix)
        return self._parts[ix]

    def _snap_targets(self, aggregation_id, snapshot_id) -> tuple:
        """Route by the aggregation AND warm the snapshot map — these
        calls are the only ones that carry both ids, and the snapshot
        pipeline issues several of them before the first snapshot-only
        lookup (mask writes happen before the snapshot record commits)."""
        targets = self._router.targets(aggregation_id)
        self._router.note_snapshot(snapshot_id, targets)
        return targets

    # -- aggregations --------------------------------------------------------

    def list_aggregations(self, filter: Optional[str], recipient) -> list:
        # first-seen dedupe in every mode: with R > 1 each aggregation
        # appears on R shards, and after a grow a moved key's absorbed
        # copy lingers on its former home until garbage-collected
        router = self._router
        out: list = []
        seen: set = set()
        for ix, part in self._live_parts():
            router.touch(ix)
            try:
                rows = part.list_aggregations(filter, recipient)
            except SdaError:
                raise
            except Exception:
                if router.replicas == 1:
                    raise  # single-copy plane: a dead partition is fatal
                continue
            for row in rows:
                key = str(row)
                if key not in seen:
                    seen.add(key)
                    out.append(row)
        return out

    def create_aggregation(self, aggregation) -> None:
        self._write(
            "create_aggregation",
            (aggregation,),
            self._router.targets(aggregation.id),
        )

    def get_aggregation(self, aggregation_id):
        return self._read_record(
            "get_aggregation",
            (aggregation_id,),
            self._router.targets(aggregation_id),
            repair=lambda part, out: part.create_aggregation(out),
        )

    def delete_aggregation(self, aggregation_id) -> None:
        self._write(
            "delete_aggregation",
            (aggregation_id,),
            self._router.targets(aggregation_id),
        )

    def get_committee(self, aggregation_id):
        return self._read_record(
            "get_committee",
            (aggregation_id,),
            self._router.targets(aggregation_id),
            repair=lambda part, out: part.create_committee(out),
        )

    def create_committee(self, committee) -> None:
        self._write(
            "create_committee",
            (committee,),
            self._router.targets(committee.aggregation),
        )

    # -- participations ------------------------------------------------------

    def create_participation(self, participation) -> None:
        self._write(
            "create_participation",
            (participation,),
            self._router.targets(participation.aggregation),
        )

    def create_participations(self, participations) -> None:
        """Bulk write grouped by target set. Atomicity holds within
        each partition (the backend's contract); a batch spanning
        aggregations on different shards commits per-shard — the service
        layer submits per-aggregation batches, so in practice this is
        one replica set's write."""
        by_targets: dict = {}
        for participation in participations:
            targets = self._router.targets(participation.aggregation)
            by_targets.setdefault(targets, []).append(participation)
        for targets, group in sorted(by_targets.items()):
            self._write("create_participations", (group,), targets)

    def count_participations(self, aggregation_id) -> int:
        return self._read_any(
            "count_participations",
            (aggregation_id,),
            self._router.targets(aggregation_id),
        )

    def iter_participations(self, aggregation_id):
        return self._read_any(
            "iter_participations",
            (aggregation_id,),
            self._router.targets(aggregation_id),
        )

    def discard_participations(self, aggregation_id, participation_ids) -> None:
        self._write(
            "discard_participations",
            (aggregation_id, list(participation_ids)),
            self._router.targets(aggregation_id),
        )

    # -- snapshots -----------------------------------------------------------

    def create_snapshot(self, snapshot) -> None:
        targets = self._router.targets(snapshot.aggregation)
        self._router.note_snapshot(snapshot.id, targets)
        self._write("create_snapshot", (snapshot,), targets)

    def list_snapshots(self, aggregation_id) -> list:
        return self._read_any(
            "list_snapshots",
            (aggregation_id,),
            self._router.targets(aggregation_id),
        )

    def get_snapshot(self, aggregation_id, snapshot_id):
        return self._read_record(
            "get_snapshot",
            (aggregation_id, snapshot_id),
            self._snap_targets(aggregation_id, snapshot_id),
            repair=lambda part, out: part.create_snapshot(out),
        )

    def snapshot_participations(self, aggregation_id, snapshot_id) -> None:
        self._write(
            "snapshot_participations",
            (aggregation_id, snapshot_id),
            self._snap_targets(aggregation_id, snapshot_id),
        )

    def iter_snapped_participations(self, aggregation_id, snapshot_id) -> Iterator:
        return self._read_any(
            "iter_snapped_participations",
            (aggregation_id, snapshot_id),
            self._snap_targets(aggregation_id, snapshot_id),
        )

    def count_participations_snapshot(self, aggregation_id, snapshot_id) -> int:
        return self._read_any(
            "count_participations_snapshot",
            (aggregation_id, snapshot_id),
            self._snap_targets(aggregation_id, snapshot_id),
        )

    def validate_snapshot_clerk_jobs(
        self, aggregation_id, snapshot_id, clerks_number: int
    ) -> None:
        return self._read_any(
            "validate_snapshot_clerk_jobs",
            (aggregation_id, snapshot_id, clerks_number),
            self._snap_targets(aggregation_id, snapshot_id),
        )

    def iter_snapshot_clerk_jobs_data(
        self, aggregation_id, snapshot_id, clerks_number: int
    ) -> Iterable:
        return self._read_any(
            "iter_snapshot_clerk_jobs_data",
            (aggregation_id, snapshot_id, clerks_number),
            self._snap_targets(aggregation_id, snapshot_id),
        )

    def iter_snapshot_clerk_jobs_chunks(
        self, aggregation_id, snapshot_id, clerks_number: int, chunk_size: int
    ) -> Iterable:
        return self._read_any(
            "iter_snapshot_clerk_jobs_chunks",
            (aggregation_id, snapshot_id, clerks_number, chunk_size),
            self._snap_targets(aggregation_id, snapshot_id),
        )

    # -- snapshot masks (snapshot-id-keyed) ----------------------------------

    def create_snapshot_mask(self, snapshot_id, mask: list) -> None:
        targets = self._router.snapshot_targets(snapshot_id)
        if targets is None:
            # unreachable through the snapshot pipeline (it routes
            # several (aggregation, snapshot)-keyed calls first); a
            # direct write with a cold map has no home to resolve
            raise ServerError(f"unroutable snapshot mask: {snapshot_id}")
        self._write("create_snapshot_mask", (snapshot_id, mask), targets)

    def _mask_read(self, snapshot_id, op, *args, repair=None):
        targets = self._router.snapshot_targets(snapshot_id)
        if targets is not None:
            return self._read_record(op, (snapshot_id,) + args, targets, repair=repair)
        for ix, part in self._live_parts():
            self._router.touch(ix)
            try:
                out = getattr(part, op)(snapshot_id, *args)
            except SdaError:
                raise
            except Exception:
                if self._router.replicas == 1:
                    raise
                continue
            if out is not None:
                self._router.note_snapshot(snapshot_id, ix)
                return out
        return None

    def get_snapshot_mask(self, snapshot_id):
        return self._mask_read(
            snapshot_id,
            "get_snapshot_mask",
            repair=lambda part, out: part.create_snapshot_mask(snapshot_id, out),
        )

    def count_snapshot_mask(self, snapshot_id) -> Optional[int]:
        return self._mask_read(snapshot_id, "count_snapshot_mask")

    def get_snapshot_mask_range(
        self, snapshot_id, start: int, count: int
    ) -> Optional[list]:
        return self._mask_read(snapshot_id, "get_snapshot_mask_range", start, count)


class ShardedClerkingJobsStore(_ReplicatedPartitions, stores.ClerkingJobsStore):
    """K ``ClerkingJobsStore`` partitions; jobs live with their
    aggregation's replica set, polls fan out across all partitions."""

    _kind = "jobs"

    def ping(self) -> None:
        for part in self._parts:
            part.ping()

    def _enqueue_targets(self, job) -> tuple:
        targets = self._router.targets(job.aggregation)
        self._router.note_job(job.id, targets)
        if job.snapshot is not None:
            self._router.note_snapshot(job.snapshot, targets)
        return targets

    def enqueue_clerking_job(self, job) -> None:
        self._write("enqueue_clerking_job", (job,), self._enqueue_targets(job))

    def enqueue_clerking_job_chunked(self, job, chunks: Iterable) -> None:
        targets = self._enqueue_targets(job)
        if len(targets) > 1:
            # the chunk stream is single-use: materialize so the write
            # can replay across replicas (and later from a hint) — the
            # union write set of an in-flight shard grow needs this even
            # at R=1, or the hint would replay an exhausted iterator
            # (and, via the default chunked enqueue's job mutation,
            # blank the column the first shard already stored). The
            # replication trade: peak memory goes from one chunk to one
            # job column while the write is in flight.
            chunks = list(chunks)
        self._write("enqueue_clerking_job_chunked", (job, chunks), targets)

    def poll_clerking_job(self, clerk_id):
        for ix, part in self._live_parts():
            self._router.touch(ix)
            try:
                job = part.poll_clerking_job(clerk_id)
            except SdaError:
                raise
            except Exception:
                if self._router.replicas == 1:
                    raise
                continue
            if job is not None:
                # never clobber the entry recorded at enqueue time: a
                # job enqueued before a shard grow lives with its
                # aggregation's FORMER replica set, and the current
                # ring's derivation would point result writes at shards
                # that never saw the job
                if self._router.job_targets(job.id) is None:
                    targets = self._router.targets(job.aggregation)
                    self._router.note_job(
                        job.id, targets if ix in targets else (ix,)
                    )
                return job
        return None

    def _job_read(self, job_id, op, *args):
        targets = self._router.job_targets(job_id)
        if targets is not None:
            return self._read_record(op, args, targets)
        for ix, part in self._live_parts():
            self._router.touch(ix)
            try:
                out = getattr(part, op)(*args)
            except SdaError:
                raise
            except Exception:
                if self._router.replicas == 1:
                    raise
                continue
            if out is not None:
                # cache routing only when the record lets us derive the
                # FULL replica set (a job carries its aggregation). A
                # bare probe index must never land in the map: writes
                # trust it, so caching one replica here would silently
                # degrade the later result write to a single-replica
                # write — no quorum, no hint, and a round that hangs on
                # whichever replica the status read happens to consult.
                agg = getattr(out, "aggregation", None)
                if agg is not None:
                    targets = self._router.targets(agg)
                    self._router.note_job(
                        job_id, targets if ix in targets else (ix,)
                    )
                return out
        return None

    def get_clerking_job(self, clerk_id, job_id):
        return self._job_read(job_id, "get_clerking_job", clerk_id, job_id)

    def get_clerking_job_chunk(
        self, clerk_id, job_id, start: int, count: int
    ) -> Optional[list]:
        return self._job_read(
            job_id, "get_clerking_job_chunk", clerk_id, job_id, start, count
        )

    def create_clerking_result(self, result) -> None:
        targets = self._router.job_targets(result.job)
        if targets is None:
            # cold map (fresh process): locate the job by owner probe —
            # the result carries its clerk, and job ids are unique. The
            # job record carries its aggregation, which re-derives the
            # full replica set.
            for probe, part in self._live_parts():
                self._router.touch(probe)
                try:
                    job = part.get_clerking_job(result.clerk, result.job)
                except SdaError:
                    raise
                except Exception:
                    if self._router.replicas == 1:
                        raise
                    continue
                if job is not None:
                    targets = self._router.targets(job.aggregation)
                    if probe not in targets:
                        # the job predates a shard grow: it lives with
                        # its aggregation's former replica set, so write
                        # where the job actually is
                        targets = (probe,)
                    self._router.note_job(result.job, targets)
                    break
        if targets is None:
            raise ServerError(f"unroutable clerking result: job {result.job}")
        self._write("create_clerking_result", (result,), targets)

    def complete_clerking_job(self, clerk_id, job_id) -> None:
        targets = self._router.job_targets(job_id)
        if targets is None:
            # same cold-map probe as create_clerking_result: the caller
            # owns the job, and job ids are unique across partitions
            for probe, part in self._live_parts():
                self._router.touch(probe)
                try:
                    job = part.get_clerking_job(clerk_id, job_id)
                except SdaError:
                    raise
                except Exception:
                    if self._router.replicas == 1:
                        raise
                    continue
                if job is not None:
                    targets = self._router.targets(job.aggregation)
                    if probe not in targets:
                        targets = (probe,)
                    self._router.note_job(job_id, targets)
                    break
        if targets is None:
            raise ServerError(f"unroutable clerking job: {job_id}")
        self._write("complete_clerking_job", (clerk_id, job_id), targets)

    # -- snapshot-scoped result reads ---------------------------------------
    # Every job of a snapshot lives on one replica set (its
    # aggregation's), so the cold-map fan-out merges are exact: the
    # other partitions contribute nothing and the canonical sort (plus
    # a replica dedupe when R > 1) matches the single-store order.

    def _snap_read(self, snapshot_id, op, *args):
        targets = self._router.snapshot_targets(snapshot_id)
        if targets is None:
            return None, False
        out = self._read_any(op, (snapshot_id,) + args, targets)
        if not out:
            # an EMPTY routed answer is not authoritative here: after a
            # shard grow the map re-warms to the aggregation's new home
            # while job rows enqueued before the grow stay behind on the
            # former home — re-answer with the fan-out merge (exact: a
            # snapshot's jobs all live somewhere, and the merge dedupes)
            return None, False
        return out, True

    def list_results(self, snapshot_id) -> list:
        out, routed = self._snap_read(snapshot_id, "list_results")
        if routed:
            return out
        merged: list = []
        seen: set = set()
        for ix, part in self._live_parts():
            self._router.touch(ix)
            try:
                rows = part.list_results(snapshot_id)
            except SdaError:
                raise
            except Exception:
                if self._router.replicas == 1:
                    raise
                continue
            for row in rows:
                key = str(row)
                if key not in seen:
                    seen.add(key)
                    merged.append(row)
        return sorted(merged, key=str)

    def get_result(self, snapshot_id, job_id):
        targets = self._router.snapshot_targets(snapshot_id)
        if targets is not None:
            out = self._read_record("get_result", (snapshot_id, job_id), targets)
            if out is not None:
                return out
            # routed miss: the result may live with the job's pre-grow
            # home rather than the snapshot's current one
        return self._job_read(job_id, "get_result", snapshot_id, job_id)

    def get_results(self, snapshot_id) -> list:
        out, routed = self._snap_read(snapshot_id, "get_results")
        if routed:
            return out
        merged = []
        seen: set = set()
        for ix, part in self._live_parts():
            self._router.touch(ix)
            try:
                rows = part.get_results(snapshot_id)
            except SdaError:
                raise
            except Exception:
                if self._router.replicas == 1:
                    raise
                continue
            for row in rows:
                key = str(row.job)
                if key not in seen:
                    seen.add(key)
                    merged.append(row)
        return sorted(merged, key=lambda r: str(r.job))

    def count_results(self, snapshot_id) -> int:
        out, routed = self._snap_read(snapshot_id, "count_results")
        if routed:
            return out
        # merged count in every mode: a plain per-partition sum would
        # double-count rows that exist on both a moved key's former and
        # current home after a shard grow
        return len(self.list_results(snapshot_id))

    def get_results_range(self, snapshot_id, start: int, count: int) -> list:
        out, routed = self._snap_read(
            snapshot_id, "get_results_range", start, count
        )
        if routed:
            return out
        if start < 0 or count < 0:
            return []
        return self.get_results(snapshot_id)[start : start + count]
