"""Federated averaging of JAX model pytrees over secure aggregation.

The protocol plane aggregates integer vectors mod p; models are float
pytrees. This module is the bridge, in three layers:

1. **Pytree <-> flat vector**: ``flatten_pytree`` / ``unflatten_pytree``
   give a stable leaf order (jax tree flattening) so every participant
   quantizes the same coordinate layout.
2. **Fixed-point field encoding**: ``QuantizationSpec`` maps floats to
   the prime field symmetrically — ``q = round(x * 2^frac_bits) mod p``,
   negative values as high residues. The field must hold the *sum* of
   all participants' values without wrapping, so the spec checks
   ``n_participants * 2^frac_bits * clip < p / 2`` — the same
   "values must fit" discipline the reference documents for its i64
   plane (client/src/crypto/sharing/additive.rs:37-39), promoted to a
   hard precondition instead of a comment.
3. **Round driver**: ``FederatedAveraging`` runs one FedAvg round
   end-to-end over any ``SdaService``: the recipient opens an
   aggregation sized to the flattened model; each participant uploads
   its quantized update through the full crypto pipeline (mask, share,
   seal — client/participate.py); reveal returns the *mean* update,
   dequantized back into the original pytree structure. No party —
   server, clerks, or recipient — ever sees an individual model.

The aggregate is exact in the field: quantization is the only lossy
step, and its error is bounded by ``n / 2^(frac_bits+1)`` per
coordinate of the sum. Everything downstream (sharing, clerking,
reconstruction) is bit-exact integer math.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..ops.modular import positive


def _leaf_size(shape) -> int:
    return int(np.prod(shape, dtype=np.int64)) if shape else 1


def flatten_pytree(tree):
    """pytree of arrays -> ((dim,) float64 vector, treedef, shapes)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrs = [np.asarray(leaf, dtype=np.float64) for leaf in leaves]
    shapes = [a.shape for a in arrs]
    flat = (
        np.concatenate([a.reshape(-1) for a in arrs])
        if arrs
        else np.empty(0, dtype=np.float64)
    )
    return flat, treedef, shapes


def tree_layout(tree):
    """(treedef, shapes, total size) without materializing a flat copy."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [np.shape(leaf) for leaf in leaves]
    return treedef, shapes, sum(_leaf_size(s) for s in shapes)


def unflatten_pytree(flat, treedef, shapes):
    """Inverse of ``flatten_pytree`` (float64 leaves)."""
    import jax

    leaves = []
    offset = 0
    for shape in shapes:
        size = _leaf_size(shape)
        leaves.append(np.asarray(flat[offset : offset + size]).reshape(shape))
        offset += size
    return jax.tree_util.tree_unflatten(treedef, leaves)


@dataclass(frozen=True)
class QuantizationSpec:
    """Symmetric fixed-point encoding of floats into the prime field.

    ``frac_bits`` fractional bits; ``clip`` bounds each coordinate's
    magnitude (values are clamped); ``n_participants`` is the maximum
    number of summed updates the field must hold without wraparound.
    """

    modulus: int
    frac_bits: int
    clip: float
    n_participants: int

    def __post_init__(self):
        bound = self.n_participants * self.scale * self.clip
        if not bound < (self.modulus - 1) // 2:
            raise ValueError(
                f"field too small: {self.n_participants} participants x "
                f"2^{self.frac_bits} x clip={self.clip} needs modulus > "
                f"{int(2 * bound) + 1}, have {self.modulus}"
            )

    @property
    def scale(self) -> int:
        return 1 << self.frac_bits

    @classmethod
    def fitted(
        cls,
        frac_bits: int,
        clip: float,
        n_participants: int,
        *,
        secret_count: int = 5,
        privacy_threshold: int = 2,
        share_count: int = 8,
    ):
        """Generate a field just large enough plus its sharing scheme.

        Returns ``(spec, PackedShamirSharing)``: the prime is found with
        ``find_packed_parameters`` at the minimal bit width that holds
        ``n_participants`` summed updates without wraparound, so the two
        halves (quantization and sharing) are guaranteed consistent.
        """
        import math

        from ..ops import find_packed_parameters
        from ..protocol import PackedShamirSharing

        need = 2.0 * n_participants * (1 << frac_bits) * clip
        bits = max(16, math.ceil(math.log2(need)) + 1)
        if bits > 61:
            raise ValueError(f"required field width {bits} bits exceeds 61")
        p, w2, w3 = find_packed_parameters(
            secret_count, privacy_threshold, share_count, min_modulus_bits=bits
        )
        scheme = PackedShamirSharing(
            secret_count=secret_count,
            share_count=share_count,
            privacy_threshold=privacy_threshold,
            prime_modulus=p,
            omega_secrets=w2,
            omega_shares=w3,
        )
        from ..ops import verify_scheme

        verify_scheme(scheme)  # rank-based t-privacy + reconstruction proof
        return cls(p, frac_bits, clip, n_participants), scheme

    def quantize(self, flat: np.ndarray) -> np.ndarray:
        """float vector -> field elements in [0, p): round-to-nearest
        fixed point, negatives as high residues. Non-finite values are
        rejected (NaN/inf would encode as garbage residues and silently
        corrupt every aggregate sharing the coordinate)."""
        flat = np.asarray(flat, dtype=np.float64)
        if not np.isfinite(flat).all():
            raise ValueError("update contains non-finite values (NaN/inf)")
        clipped = np.clip(flat, -self.clip, self.clip)
        q = np.rint(clipped * self.scale).astype(np.int64)
        return positive(q, self.modulus)

    def dequantize_sum(self, field_sum: np.ndarray) -> np.ndarray:
        """Revealed field sum -> float vector of the *sum* of updates.

        Centered lift: residues above p/2 are the negative range. Valid
        because the precondition bounds |sum| < p/2."""
        v = np.asarray(field_sum, dtype=np.int64)
        half = self.modulus // 2
        centered = np.where(v > half, v - self.modulus, v)
        return centered.astype(np.float64) / self.scale


def quantize_update(tree, spec: QuantizationSpec):
    """Model pytree -> (field vector, treedef, shapes) for participation."""
    flat, treedef, shapes = flatten_pytree(tree)
    return spec.quantize(flat), treedef, shapes


def dequantize_mean(field_sum, n: int, spec: QuantizationSpec, treedef, shapes):
    """Revealed field sum of n updates -> mean-update pytree."""
    return unflatten_pytree(spec.dequantize_sum(field_sum) / n, treedef, shapes)


class FederatedAveraging:
    """One secure FedAvg round over any ``SdaService``.

    The recipient side (``open_round`` / ``finish_round``) and the
    participant side (``submit_update``) are separate methods because in
    a real deployment they run on different machines; the only shared
    state is the aggregation id on the wire. ``spec.n_participants`` is
    the *capacity* bound (wraparound safety); fewer may actually submit
    — the mean divides by the real count.
    """

    def __init__(self, spec: QuantizationSpec, template_tree):
        # layout only — no flat copy of a possibly-large template model
        treedef, shapes, dim = tree_layout(template_tree)
        self.spec = spec
        self.treedef = treedef
        self.shapes = shapes
        self.dim = dim

    @property
    def wire_dimension(self) -> int:
        """Aggregation vector length on the wire; subclasses that append
        extra channels (e.g. a weight coordinate) override this."""
        return self.dim

    def open_round(
        self,
        recipient,
        recipient_key,
        committee_sharing_scheme,
        *,
        title: str = "federated-round",
        masking_scheme=None,
    ):
        """Recipient: create + begin an aggregation sized to the model.

        ``committee_sharing_scheme`` comes from ``QuantizationSpec.fitted``
        (which guarantees its field matches ``spec``) or is hand-built;
        a modulus mismatch with the spec is rejected. Default masking is
        ChaCha (seed-compressed). Returns the aggregation id.
        """
        from ..protocol import (
            Aggregation,
            AggregationId,
            ChaChaMasking,
            SodiumEncryptionScheme,
        )

        scheme_mod = getattr(
            committee_sharing_scheme, "prime_modulus", None
        ) or getattr(committee_sharing_scheme, "modulus", None)
        if scheme_mod != self.spec.modulus:
            raise ValueError(
                f"sharing scheme field {scheme_mod} != quantization field "
                f"{self.spec.modulus}"
            )
        if masking_scheme is None:
            masking_scheme = ChaChaMasking(
                modulus=self.spec.modulus, dimension=self.wire_dimension,
                seed_bitsize=128
            )
        agg = Aggregation(
            id=AggregationId.random(),
            title=title,
            vector_dimension=self.wire_dimension,
            modulus=self.spec.modulus,
            recipient=recipient.agent.id,
            recipient_key=recipient_key,
            masking_scheme=masking_scheme,
            committee_sharing_scheme=committee_sharing_scheme,
            recipient_encryption_scheme=SodiumEncryptionScheme(),
            committee_encryption_scheme=SodiumEncryptionScheme(),
        )
        recipient.upload_aggregation(agg)
        recipient.begin_aggregation(agg.id)
        return agg.id

    def _validated_flat(self, update_tree) -> np.ndarray:
        """Flatten an update and verify it matches the template layout."""
        flat, treedef, shapes = flatten_pytree(update_tree)
        if treedef != self.treedef:
            raise ValueError("update pytree structure differs from template")
        if shapes != self.shapes:
            # same treedef + same total size can still misalign coordinates
            # (e.g. a transposed weight matrix) — reject, don't corrupt
            raise ValueError(
                f"update leaf shapes {shapes} differ from template {self.shapes}"
            )
        return flat

    def submit_update(self, participant, aggregation_id, update_tree):
        """Participant: quantize a local update and run full participation."""
        flat = self._validated_flat(update_tree)
        # pass the int64 ndarray straight through — participate() takes
        # array-likes; a .tolist() round-trip would allocate one Python
        # int per model parameter
        participant.participate(self.spec.quantize(flat), aggregation_id)

    def close_round(self, recipient, aggregation_id):
        """Recipient: freeze participations + enqueue clerking jobs."""
        recipient.end_aggregation(aggregation_id)

    def reveal_field_sum(self, recipient, aggregation_id, n_submitted: int):
        """Recipient: reveal and return the raw ``(dim,)`` int64 field sum.

        Call after ``close_round`` and after enough clerks drained their
        queues; raises if no snapshot is ``result_ready`` yet, if nothing
        was submitted (there is no meaningful sum), or if more updates were
        summed than the field was sized for (the revealed sum would have
        wrapped — unrecoverable, so fail loudly). Exact integer consumers
        (e.g. histograms) use this directly; ``finish_round`` dequantizes.
        """
        if n_submitted <= 0:
            raise ValueError("no updates were submitted; nothing to reveal")
        status = recipient.service.get_aggregation_status(
            recipient.agent, aggregation_id
        )
        actual = status.number_of_participations if status is not None else n_submitted
        if max(n_submitted, actual) > self.spec.n_participants:
            raise ValueError(
                f"{max(n_submitted, actual)} updates summed but the field only "
                f"holds {self.spec.n_participants} without wraparound; re-run "
                f"the round with a spec fitted for the larger cohort"
            )
        output = recipient.reveal_aggregation(aggregation_id)
        return np.asarray(output.positive().values, dtype=np.int64)

    def finish_round(self, recipient, aggregation_id, n_submitted: int):
        """Recipient: reveal (after clerking) and return the mean pytree."""
        field_sum = self.reveal_field_sum(recipient, aggregation_id, n_submitted)
        return dequantize_mean(
            field_sum, n_submitted, self.spec, self.treedef, self.shapes
        )


class WeightedFederatedAveraging(FederatedAveraging):
    """FedAvg with per-participant weights — the actual FedAvg algorithm
    (weight each update by its local sample count), as one secure round.

    Each participant submits ``(w·update, w)`` concatenated into a single
    field vector; the revealed sums give ``Σw·x / Σw`` — the weighted
    mean — without revealing any individual's weight or update. The
    weight rides as one extra coordinate, so it gets the same masking /
    sharing / sealing as the update itself.

    ``clip`` bounds each |update coordinate| and ``max_weight`` bounds
    the weight, so the product channel needs ``clip·max_weight`` of
    per-coordinate headroom — ``fitted`` sizes the field for exactly
    that. Weights are commonly integer sample counts; fractional weights
    quantize at the spec's ``frac_bits`` like everything else.
    """

    def __init__(self, spec: QuantizationSpec, template_tree, clip: float,
                 max_weight: float):
        super().__init__(spec, template_tree)
        if clip <= 0 or max_weight <= 0:
            raise ValueError("clip and max_weight must be positive")
        if clip * max_weight > spec.clip or max_weight > spec.clip:
            raise ValueError(
                f"field bound {spec.clip} below the w*x channel "
                f"({clip}*{max_weight}); build with .fitted"
            )
        self.clip = float(clip)
        self.max_weight = float(max_weight)

    @classmethod
    def fitted(cls, frac_bits: int, clip: float, max_weight: float,
               n_participants: int, template_tree, **shamir_kw):
        """(driver, sharing) with the field sized for the w·x channel."""
        bound = max(clip * max_weight, max_weight)
        spec, sharing = QuantizationSpec.fitted(
            frac_bits, bound, n_participants, **shamir_kw
        )
        return cls(spec, template_tree, clip, max_weight), sharing

    @property
    def wire_dimension(self) -> int:
        return self.dim + 1  # update coordinates + the weight

    def open_round(self, recipient, recipient_key, committee_sharing_scheme,
                   *, title: str = "weighted-federated-round",
                   masking_scheme=None):
        return super().open_round(
            recipient, recipient_key, committee_sharing_scheme,
            title=title, masking_scheme=masking_scheme,
        )

    def _quantized_wire(self, update_tree, weight: float) -> np.ndarray:
        """Validate and build the quantized ``(w·x, w)`` field vector —
        shared by the plain and DP submit paths."""
        if not 0 < weight <= self.max_weight:
            raise ValueError(
                f"weight {weight} outside (0, {self.max_weight}]"
            )
        flat = self._validated_flat(update_tree)
        if np.abs(flat).max(initial=0.0) > self.clip:
            raise ValueError(
                f"update coordinates exceed the clip bound {self.clip}"
            )
        wire = np.concatenate([flat * weight, [float(weight)]])
        return self.spec.quantize(wire)

    def submit_update(self, participant, aggregation_id, update_tree,
                      weight: float):
        # validate/build before touching `participant` (attribute lookup
        # on the call target happens before argument evaluation)
        wire = self._quantized_wire(update_tree, weight)
        participant.participate(wire, aggregation_id)

    def finish_round(self, recipient, aggregation_id, n_submitted: int):
        """-> (weighted-mean pytree, total weight)."""
        field_sum = self.reveal_field_sum(recipient, aggregation_id, n_submitted)
        sums = self.spec.dequantize_sum(field_sum)
        total_weight = float(sums[-1])
        mean = unflatten_pytree(
            self._weighted_flat(sums, total_weight), self.treedef, self.shapes
        )
        return mean, total_weight

    def _weighted_flat(self, sums, total_weight: float) -> np.ndarray:
        """Policy hook: the flat mean given the revealed sums and total.
        Noise-free weights are sums of positive submissions, so a
        non-positive total means something is deeply wrong — fail. The
        DP subclass overrides this (a noisy total can dip <= 0)."""
        if total_weight <= 0:
            raise ValueError("revealed total weight is not positive")
        return sums[: self.dim] / total_weight
