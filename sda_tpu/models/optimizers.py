"""Server-side federated optimizers for the multi-round trainer.

Plain FedAvg applies the revealed mean update directly. The standard
improvements (Reddi et al. 2021, "Adaptive Federated Optimization")
treat the mean update as a pseudo-gradient and run a server optimizer
over it: momentum (FedAvgM) and Adam (FedAdam). Both are stateful, so
they expose ``state()``/``load_state()`` and the trainer persists the
state inside its round checkpoints — a resumed coordinator continues
with the same momentum/moment estimates, not a cold restart.

All state lives as flat float64 vectors in the same coordinate layout
the wire path uses (federated.flatten_pytree), so checkpoints stay
plain ``.npz`` files.
"""

from __future__ import annotations

import numpy as np

from .federated import flatten_pytree, unflatten_pytree


class ServerOptimizer:
    """Interface: ``apply(global_model, mean_update) -> new model``.

    Optimizers are callables, so a plain function still works wherever
    a ``ServerOptimizer`` is accepted (the trainer duck-types both).
    """

    def __call__(self, global_model, mean_update):
        raise NotImplementedError

    def state(self) -> dict:
        """numpy-array state for checkpointing (empty when stateless)."""
        return {}

    def load_state(self, state: dict) -> None:
        pass


class FedAvgM(ServerOptimizer):
    """Server momentum: ``v = momentum·v + Δ̄;  w += lr·v``."""

    def __init__(self, momentum: float = 0.9, lr: float = 1.0):
        if not 0.0 <= momentum < 1.0:
            raise ValueError("momentum must be in [0, 1)")
        self.momentum = float(momentum)
        self.lr = float(lr)
        self._v = None

    def __call__(self, global_model, mean_update):
        flat_w, treedef, shapes = flatten_pytree(global_model)
        flat_u, _, _ = flatten_pytree(mean_update)
        if self._v is None:
            self._v = np.zeros_like(flat_w)
        self._v = self.momentum * self._v + flat_u
        return unflatten_pytree(flat_w + self.lr * self._v, treedef, shapes)

    def state(self) -> dict:
        return {} if self._v is None else {"v": self._v}

    def load_state(self, state: dict) -> None:
        if "v" in state:
            self._v = np.asarray(state["v"], dtype=np.float64)


class FedAdam(ServerOptimizer):
    """Server Adam over the pseudo-gradient Δ̄ (Reddi et al. 2021, Alg. 2).

    ``tau`` is the adaptivity floor (their ε): larger values make the
    update closer to plain FedAvg scaled by ``lr``.
    """

    def __init__(self, lr: float = 0.1, beta1: float = 0.9,
                 beta2: float = 0.99, tau: float = 1e-3):
        if not (0 <= beta1 < 1 and 0 <= beta2 < 1):
            raise ValueError("betas must be in [0, 1)")
        if tau <= 0:
            raise ValueError("tau must be positive")
        self.lr, self.beta1, self.beta2, self.tau = (
            float(lr), float(beta1), float(beta2), float(tau),
        )
        self._m = None
        self._v = None
        self._t = 0

    def __call__(self, global_model, mean_update):
        flat_w, treedef, shapes = flatten_pytree(global_model)
        g, _, _ = flatten_pytree(mean_update)
        if self._m is None:
            self._m = np.zeros_like(flat_w)
            self._v = np.zeros_like(flat_w)
        self._t += 1
        self._m = self.beta1 * self._m + (1 - self.beta1) * g
        self._v = self.beta2 * self._v + (1 - self.beta2) * g * g
        # bias correction keeps early rounds from undershooting
        m_hat = self._m / (1 - self.beta1 ** self._t)
        v_hat = self._v / (1 - self.beta2 ** self._t)
        step = self.lr * m_hat / (np.sqrt(v_hat) + self.tau)
        return unflatten_pytree(flat_w + step, treedef, shapes)

    def state(self) -> dict:
        if self._m is None:
            return {}
        return {"m": self._m, "v": self._v, "t": np.int64(self._t)}

    def load_state(self, state: dict) -> None:
        if "m" in state:
            self._m = np.asarray(state["m"], dtype=np.float64)
            self._v = np.asarray(state["v"], dtype=np.float64)
            self._t = int(state["t"])
