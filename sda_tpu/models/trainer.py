"""Multi-round federated training driver with durable checkpoints.

One ``FederatedAveraging`` round aggregates a single cohort of updates;
real federated learning iterates: broadcast the global model, collect a
secure mean update, apply it, repeat. This driver owns that loop and its
durability. Matching the reference's checkpoint philosophy — everything
durable-by-construction, resume by re-reading state (SURVEY.md §5) — the
trainer persists the global model + round counter after every apply, so
a crashed coordinator resumes from its last completed round. The rerun
opens a *fresh* aggregation (ids are minted per round), which is what
makes it safe: the crashed round's aggregation is simply abandoned
server-side — ``delete_aggregation`` can garbage-collect it — and a
double-apply is impossible because save happens only after apply.

Checkpoints are plain ``.npz`` files of the flattened model plus layout
metadata — no format dependencies, loadable anywhere numpy exists. The
flatten layout is the same one the wire path uses (federated.py), so a
checkpoint is also a spec-compatible record of what was broadcast.
"""

from __future__ import annotations

import json
import os
import tempfile

import numpy as np

from .federated import FederatedAveraging, flatten_pytree, unflatten_pytree


class FederatedTrainer:
    """Iterated secure FedAvg over any ``SdaService``.

    ``apply_update`` defaults to plain FedAvg (add the mean update to the
    global model); pass a ``ServerOptimizer`` (optimizers.FedAvgM /
    FedAdam) or any callable for server-side learning rates or momentum.
    Stateful optimizers' state rides inside the checkpoints (``opt_*``
    keys), so resume continues the momentum/moment estimates.
    ``checkpoint_dir=None`` disables persistence.
    """

    def __init__(
        self,
        fed: FederatedAveraging,
        global_model,
        *,
        checkpoint_dir: str | None = None,
        apply_update=None,
        keep_checkpoints: int = 3,
    ):
        self.fed = fed
        self.global_model = global_model
        self.round_index = 0
        self.checkpoint_dir = checkpoint_dir
        self.apply_update = apply_update or self._fedavg_apply
        self.keep_checkpoints = max(1, keep_checkpoints)
        # privacy ledger: per-round zCDP rho (filled when `fed` is a DP
        # driver); persisted with checkpoints so a resumed coordinator
        # keeps its spent budget
        self.round_rhos: list = []
        self.privacy_delta: float = 0.0

    @staticmethod
    def _fedavg_apply(global_model, mean_update):
        import jax

        return jax.tree_util.tree_map(
            lambda g, u: np.asarray(g, dtype=np.float64) + np.asarray(u),
            global_model,
            mean_update,
        )

    # -- persistence ---------------------------------------------------------

    def _ckpt_path(self) -> str:
        return os.path.join(self.checkpoint_dir, f"round_{self.round_index:06d}.npz")

    @staticmethod
    def _ckpt_round(filename: str) -> int:
        return int(filename[len("round_") : -len(".npz")])

    def save(self) -> str:
        """Write the current global model + round counter; atomic rename
        (same write-then-rename discipline as the file store). Keeps the
        last ``keep_checkpoints`` files and prunes older ones."""
        if self.checkpoint_dir is None:
            raise ValueError("trainer has no checkpoint_dir")
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        flat, treedef, shapes = flatten_pytree(self.global_model)
        if treedef != self.fed.treedef:
            # a custom apply_update drifted the model's structure — fail at
            # save time, not as silent cross-mapping at restore time
            raise ValueError(
                f"global model structure {treedef} differs from the "
                f"aggregation template {self.fed.treedef}"
            )
        path = self._ckpt_path()
        fd, tmp = tempfile.mkstemp(dir=self.checkpoint_dir, suffix=".tmp")
        try:
            state_fn = getattr(self.apply_update, "state", None)
            opt_state = (
                {f"opt_{k}": v for k, v in state_fn().items()}
                if callable(state_fn)
                else {}
            )
            if opt_state:
                # tag the state with its optimizer class: resuming with a
                # different optimizer must fail loudly, not install (say)
                # Adam's second moments as a momentum buffer
                opt_state["opt_type"] = type(self.apply_update).__name__
            with os.fdopen(fd, "wb") as fh:
                np.savez(
                    fh,
                    flat=flat,
                    round_index=self.round_index,
                    shapes=json.dumps([list(s) for s in shapes]),
                    treedef=str(self.fed.treedef),
                    privacy_rhos=np.asarray(self.round_rhos, dtype=np.float64),
                    privacy_delta=self.privacy_delta,
                    **opt_state,
                )
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        for old in self._checkpoints()[: -self.keep_checkpoints]:
            os.unlink(os.path.join(self.checkpoint_dir, old))
        return path

    def _checkpoints(self) -> list:
        """Checkpoint filenames, oldest first (numeric round order — a
        lexicographic sort would misorder once rounds outgrow the name's
        zero padding). Foreign files (e.g. an operator's round_best.npz
        copy) are ignored, never touched by pruning."""
        found = []
        for f in os.listdir(self.checkpoint_dir):
            if f.startswith("round_") and f.endswith(".npz"):
                try:
                    found.append((self._ckpt_round(f), f))
                except ValueError:
                    continue
        return [f for _, f in sorted(found)]

    def restore_latest(self) -> bool:
        """Load the newest checkpoint, if any. Returns whether one loaded."""
        if self.checkpoint_dir is None or not os.path.isdir(self.checkpoint_dir):
            return False
        ckpts = self._checkpoints()
        if not ckpts:
            return False
        with np.load(os.path.join(self.checkpoint_dir, ckpts[-1])) as data:
            shapes = [tuple(s) for s in json.loads(str(data["shapes"]))]
            # both structure and shapes must match — equal shape lists with
            # different treedefs would silently cross-map parameters
            if "treedef" in data and str(data["treedef"]) != str(self.fed.treedef):
                raise ValueError(
                    "checkpoint layout differs from the template model (treedef)"
                )
            if shapes != [tuple(s) for s in self.fed.shapes]:
                raise ValueError("checkpoint layout differs from the template model")
            self.global_model = unflatten_pytree(
                data["flat"], self.fed.treedef, self.fed.shapes
            )
            self.round_index = int(data["round_index"])
            if "privacy_rhos" in data:  # absent in pre-ledger checkpoints
                self.round_rhos = [float(r) for r in data["privacy_rhos"]]
                self.privacy_delta = float(data["privacy_delta"])
            saved_type = (
                str(data["opt_type"]) if "opt_type" in data.files else None
            )
            if saved_type is not None:
                current = type(self.apply_update).__name__
                if saved_type != current:
                    raise ValueError(
                        f"checkpoint carries {saved_type} optimizer state "
                        f"but the trainer was built with {current}; resume "
                        "with the matching optimizer (or delete the "
                        "checkpoints to restart server optimization cold)"
                    )
                self.apply_update.load_state({
                    k[len("opt_"):]: data[k]
                    for k in data.files
                    if k.startswith("opt_") and k != "opt_type"
                })
        return True

    # -- the round loop ------------------------------------------------------

    def run_round(self, recipient, recipient_key, sharing_scheme, submitters,
                  workers, *, parallel_submit: int = 0):
        """One full secure round: open, collect, clerk, reveal, apply, save.

        ``submitters``: list of ``(client, update_fn)`` — ``update_fn``
        receives the current global model and returns an update pytree
        (e.g. local SGD delta); each client runs full participation.
        ``workers``: clients that drain clerking queues (committee
        members among them do the clerking). ``parallel_submit``: >0 runs
        participations in that many threads — each participant is its own
        client and the server handles concurrent uploads (the concurrency
        suite covers this), so simulated cohorts collect ~Nx faster. A DP
        driver's shared numpy Generator is NOT thread-safe, so when the
        fed object carries one, each submitter gets its own spawned child
        generator (deterministic given submitter order).
        """
        agg_id = self.fed.open_round(
            recipient,
            recipient_key,
            sharing_scheme,
            title=f"federated-round-{self.round_index}",
        )

        def submit_one(client, update_fn, child_rng=None):
            update = update_fn(self.global_model)
            if child_rng is None:
                self.fed.submit_update(client, agg_id, update)
            else:
                self.fed.submit_update(client, agg_id, update, rng=child_rng)

        if parallel_submit > 0:
            from concurrent.futures import ThreadPoolExecutor

            shared_rng = getattr(self.fed, "_rng", None)
            rngs = (
                shared_rng.spawn(len(submitters))
                if shared_rng is not None
                else [None] * len(submitters)
            )
            with ThreadPoolExecutor(max_workers=parallel_submit) as pool:
                # list() propagates the first worker exception
                list(
                    pool.map(
                        lambda args: submit_one(args[0][0], args[0][1], args[1]),
                        zip(submitters, rngs),
                    )
                )
        else:
            for client, update_fn in submitters:
                submit_one(client, update_fn)
        self.fed.close_round(recipient, agg_id)
        for worker in workers:
            worker.run_chores(-1)
        # charge the ledger BEFORE the release: reveal irreversibly spends
        # privacy, so a crash between reveal and the post-apply checkpoint
        # must never lose the charge (over-counting on a crash-before-
        # reveal rerun is the safe direction). The pre-reveal save rewrites
        # this round's checkpoint file with the old model + the new rho.
        privacy = getattr(self.fed, "privacy", None)
        if privacy is not None:
            try:
                acct = privacy(len(submitters))
                rho, delta = acct.rho, acct.delta
            except NotImplementedError:
                # no implemented accounting for this mechanism (Skellam):
                # ledger the release as unbounded rather than crash or omit
                rho, delta = float("inf"), 0.0
            self.round_rhos.append(rho)
            self.privacy_delta = max(self.privacy_delta, delta)
            if self.checkpoint_dir is not None:
                self.save()
        mean_update = self.fed.finish_round(recipient, agg_id, len(submitters))
        self.global_model = self.apply_update(self.global_model, mean_update)
        self.round_index += 1
        if self.checkpoint_dir is not None:
            self.save()
        return self.global_model

    def cumulative_privacy(self, delta: float | None = None):
        """Total (ε, δ) spent across all completed DP rounds (zCDP adds;
        one tight conversion). None when no DP rounds have run — e.g. a
        plain ``FederatedAveraging`` trainer."""
        if not self.round_rhos:
            return None
        from .dp import compose_rhos

        return compose_rhos(
            self.round_rhos, self.privacy_delta if delta is None else delta
        )
