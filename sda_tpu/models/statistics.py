"""Secure statistics over the aggregation protocol: mean, variance,
histograms — computed across participants without revealing any
individual's data.

These are the classic federated-analytics queries; like model averaging
(federated.py) they reduce to secure sums:

- **mean / variance**: each participant submits ``[x, x**2]`` per
  coordinate; the revealed sums give ``E[x]`` and ``E[x**2]``, hence
  ``Var[x] = E[x**2] - E[x]**2``. The protocol is exact in the field, so
  the only error is fixed-point quantization.
- **histogram**: each participant one-hot encodes its values into bin
  counts; the revealed sum IS the cohort histogram. Counts are integers
  (``frac_bits=0``), so results are exact.

Both ride the ``FederatedAveraging`` round driver (open / submit /
close / finish) — a statistics query is just a FedAvg round over a
derived "model" — and therefore inherit masking, packed-Shamir sharing,
sealed transport, dropout tolerance, and the rank-verified schemes.
"""

from __future__ import annotations

import numpy as np

from .federated import FederatedAveraging, QuantizationSpec


def canonical_item_bytes(item) -> bytes:
    """Type-tagged canonical encoding of one hashable item.

    Shared by every workload that hashes participant items
    (``SecureCountDistinct`` here, the whole ``sda_tpu.sketches`` plane):
    a cross-participant sum of hashed structures is only correct when
    equal logical items hash identically on *every* participant — and
    ``repr`` is not that (numpy scalar reprs differ across numpy
    versions, e.g. ``np.int64(3)`` vs ``3``). Accepted types: str,
    bytes, int/bool, float and their numpy scalar equivalents; anything
    else raises. Cross-type equality follows Python set semantics
    (``{1, 1.0, True}`` is one element), so integral floats and bools
    encode as their int.
    """
    if isinstance(item, bytes):
        return b"b" + item
    if isinstance(item, str):
        return b"s" + item.encode("utf-8")
    if isinstance(item, (bool, np.bool_, int, np.integer)):
        return b"i" + str(int(item)).encode("ascii")
    if isinstance(item, (float, np.floating)):
        f = float(item)
        if f.is_integer():
            return b"i" + str(int(f)).encode("ascii")
        return b"f" + repr(f).encode("ascii")
    raise TypeError(
        f"hashed items must be str, bytes, int, or float "
        f"(got {type(item).__name__}); hash-stable canonical encoding "
        "is required for the cross-participant union"
    )


def _validate_vector(values, dim: int, clip: float) -> np.ndarray:
    """Shared submission check: shape ``(dim,)``, |coordinate| ≤ clip."""
    values = np.asarray(values, dtype=np.float64)
    if values.shape != (dim,):
        raise ValueError(f"expected ({dim},) values, got {values.shape}")
    if np.abs(values).max(initial=0.0) > clip:
        raise ValueError(f"values exceed clip bound {clip}")
    return values


class SecureStatistics:
    """Cohort mean + variance of ``(dim,)`` float vectors, privately.

    ``clip`` bounds each |coordinate|; squares are bounded by ``clip**2``,
    so the shared quantization spec is fitted to ``max(clip, clip**2)``.
    """

    def __init__(self, dim: int, clip: float, n_participants: int, frac_bits: int = 16):
        self.dim = dim
        self.clip = clip
        bound = max(clip, clip * clip)
        self.spec, self.sharing = QuantizationSpec.fitted(
            frac_bits, bound, n_participants
        )
        template = {"sum": np.zeros(dim), "sumsq": np.zeros(dim)}
        self.fed = FederatedAveraging(self.spec, template)

    def open_round(self, recipient, recipient_key):
        return self.fed.open_round(
            recipient, recipient_key, self.sharing, title="secure-statistics"
        )

    def _checked_tree(self, values) -> dict:
        """Validate one submission and build its ``[x, x²]`` channel."""
        values = _validate_vector(values, self.dim, self.clip)
        return {"sum": values, "sumsq": values * values}

    def submit(self, participant, aggregation_id, values) -> None:
        self.fed.submit_update(
            participant, aggregation_id, self._checked_tree(values)
        )

    def close_round(self, recipient, aggregation_id) -> None:
        self.fed.close_round(recipient, aggregation_id)

    def finish(self, recipient, aggregation_id, n_submitted: int) -> dict:
        """-> {"count", "mean", "variance"} (population variance)."""
        means = self.fed.finish_round(recipient, aggregation_id, n_submitted)
        mean = means["sum"]
        variance = np.maximum(means["sumsq"] - mean * mean, 0.0)
        return {"count": n_submitted, "mean": mean, "variance": variance}


class SecureCovariance:
    """Cohort covariance (and correlation) of ``(dim,)`` vectors, privately.

    Each participant submits ``[x, vech(x xᵀ)]`` — its vector plus the
    upper triangle of its outer product (``d(d+1)/2`` extra
    coordinates). The revealed sums give ``E[x]`` and ``E[x xᵀ]``, hence
    ``Cov = E[x xᵀ] − E[x]E[x]ᵀ`` — the population covariance across
    participants, exact in the field up to quantization. The covariance
    matrix is the input to federated PCA / correlation analysis; no
    party ever sees an individual's vector.

    ``clip`` bounds each |coordinate|, so products are bounded by
    ``clip²`` and the field is fitted to ``max(clip, clip²)`` — the same
    discipline as ``SecureStatistics``.
    """

    def __init__(self, dim: int, clip: float, n_participants: int,
                 frac_bits: int = 16):
        if dim < 1:
            raise ValueError("dim must be >= 1")
        self.dim = dim
        self.clip = float(clip)
        bound = max(clip, clip * clip)
        self.spec, self.sharing = QuantizationSpec.fitted(
            frac_bits, bound, n_participants
        )
        self._triu = np.triu_indices(dim)
        template = {
            "sum": np.zeros(dim),
            "outer": np.zeros(dim * (dim + 1) // 2),
        }
        self.fed = FederatedAveraging(self.spec, template)

    def open_round(self, recipient, recipient_key):
        return self.fed.open_round(
            recipient, recipient_key, self.sharing, title="secure-covariance"
        )

    def _checked_tree(self, values) -> dict:
        """Validate one submission and build its ``[x, vech(x xᵀ)]`` channel."""
        values = _validate_vector(values, self.dim, self.clip)
        return {"sum": values, "outer": np.outer(values, values)[self._triu]}

    def submit(self, participant, aggregation_id, values) -> None:
        self.fed.submit_update(
            participant, aggregation_id, self._checked_tree(values)
        )

    def close_round(self, recipient, aggregation_id) -> None:
        self.fed.close_round(recipient, aggregation_id)

    def finish(self, recipient, aggregation_id, n_submitted: int) -> dict:
        """-> {"count", "mean", "covariance"} (population covariance,
        PSD up to quantization error)."""
        means = self.fed.finish_round(recipient, aggregation_id, n_submitted)
        mean = means["sum"]
        m2 = np.zeros((self.dim, self.dim))
        m2[self._triu] = means["outer"]
        m2 = m2 + m2.T - np.diag(np.diag(m2))  # mirror the upper triangle
        cov = m2 - np.outer(mean, mean)
        # quantization can push a near-constant coordinate's variance a
        # hair negative; clamp so sqrt(diag) downstream stays finite
        np.fill_diagonal(cov, np.maximum(np.diag(cov), 0.0))
        return {"count": n_submitted, "mean": mean, "covariance": cov}

    @staticmethod
    def correlation_from_covariance(cov: np.ndarray) -> np.ndarray:
        """Correlation matrix; zero-variance coordinates yield zero
        off-diagonals and a unit diagonal."""
        std = np.sqrt(np.maximum(np.diag(cov), 0.0))
        denom = np.outer(std, std)
        corr = np.divide(
            cov, denom, out=np.zeros_like(np.asarray(cov, dtype=np.float64)),
            where=denom > 0,
        )
        np.fill_diagonal(corr, 1.0)
        return np.clip(corr, -1.0, 1.0)

    def finish_correlation(self, recipient, aggregation_id, n_submitted: int) -> dict:
        """Like ``finish`` plus the correlation matrix."""
        result = self.finish(recipient, aggregation_id, n_submitted)
        result["correlation"] = self.correlation_from_covariance(
            result["covariance"]
        )
        return result

    @staticmethod
    def principal_components(cov: np.ndarray, k: int):
        """Top-``k`` eigenpairs of a (revealed) covariance matrix —
        federated PCA is exactly this post-processing: the only
        cross-party computation was the secure covariance itself.

        Returns ``(eigenvalues, components)``: eigenvalues descending
        (clamped at 0 — a noisy/quantized matrix can dip negative),
        components as ``(k, dim)`` rows, deterministically signed (the
        largest-|coordinate| entry of each component is positive).
        """
        cov = np.asarray(cov, dtype=np.float64)
        if cov.ndim != 2 or cov.shape[0] != cov.shape[1]:
            raise ValueError("covariance must be square")
        if not 1 <= k <= cov.shape[0]:
            raise ValueError(f"k must be in [1, {cov.shape[0]}]")
        eigvals, eigvecs = np.linalg.eigh((cov + cov.T) / 2.0)
        order = np.argsort(eigvals)[::-1][:k]
        values = np.maximum(eigvals[order], 0.0)
        components = eigvecs[:, order].T
        for row in components:  # deterministic sign convention
            pivot = np.argmax(np.abs(row))
            if row[pivot] < 0:
                row *= -1.0
        return values, components


class SecureHistogram:
    """Cohort histogram over ``bins`` equal-width bins of ``[lo, hi)``.

    Each participant may contribute many values; it submits its *local*
    bin counts (integers, ``frac_bits=0`` — exact), bounded by
    ``max_values_per_participant``. Out-of-range values clamp to the edge
    bins (the usual federated-analytics convention, and it keeps the
    submitted count equal to the number of values).
    """

    def __init__(
        self,
        bins: int,
        lo: float,
        hi: float,
        n_participants: int,
        max_values_per_participant: int = 1 << 20,
    ):
        self._init_geometry(bins, lo, hi, max_values_per_participant)
        self.spec, self.sharing = QuantizationSpec.fitted(
            0, float(max_values_per_participant), n_participants
        )
        self.fed = FederatedAveraging(self.spec, {"counts": np.zeros(bins)})

    def _init_geometry(self, bins, lo, hi, max_values):
        """Bin geometry shared with subclasses that build their own field
        (DPSecureHistogram fits a noise-headroom spec instead of ours)."""
        if not (bins > 0 and hi > lo):
            raise ValueError("need bins > 0 and hi > lo")
        self.bins = bins
        self.lo, self.hi = float(lo), float(hi)
        self.max_values = max_values

    def local_counts(self, values) -> np.ndarray:
        values = np.asarray(values, dtype=np.float64).reshape(-1)
        if len(values) > self.max_values:
            raise ValueError(f"more than {self.max_values} values")
        if not np.isfinite(values).all():
            raise ValueError("values contain non-finite entries (NaN/inf)")
        ixf = np.floor((values - self.lo) / (self.hi - self.lo) * self.bins)
        # clamp BEFORE the int cast: a huge float would overflow int64 to
        # INT64_MIN and land a value above hi in the LOWEST bin
        ix = np.clip(ixf, 0, self.bins - 1).astype(np.int64)
        return np.bincount(ix, minlength=self.bins).astype(np.float64)

    def open_round(self, recipient, recipient_key):
        return self.fed.open_round(
            recipient, recipient_key, self.sharing, title="secure-histogram"
        )

    def submit(self, participant, aggregation_id, values) -> None:
        self.fed.submit_update(
            participant, aggregation_id, {"counts": self.local_counts(values)}
        )

    def close_round(self, recipient, aggregation_id) -> None:
        self.fed.close_round(recipient, aggregation_id)

    def finish(self, recipient, aggregation_id, n_submitted: int) -> np.ndarray:
        """-> (bins,) int64 exact cohort counts.

        Counts are read straight off the integer field sum (frac_bits=0,
        counts nonnegative and wraparound-guarded, so the residues ARE the
        counts) — no float round trip, exact for any permitted cohort."""
        return self.fed.reveal_field_sum(recipient, aggregation_id, n_submitted)


class SecureGroupedMean:
    """Per-category cohort means ("mean latency by region"), privately.

    Each participant holds observations ``(category, value-vector)`` with
    categories in ``{0, …, groups-1}`` and ``|value coordinate| ≤ clip``.
    It submits a scatter: a ``(groups, dim)`` matrix of its per-category
    value sums plus a ``(groups,)`` count vector — zeros everywhere it
    has no data. The revealed sums give exact per-category totals and
    counts, hence per-category means, without revealing which categories
    any participant contributed to (the zero rows are masked/shared like
    everything else).

    ``max_values_per_participant`` bounds one participant's observation
    count (the field is sized for ``n · max_values · clip`` per
    coordinate — all of one participant's mass can land in one cell).
    """

    def __init__(self, groups: int, dim: int, clip: float,
                 n_participants: int, *, frac_bits: int = 16,
                 max_values_per_participant: int = 1 << 10):
        if groups < 1 or dim < 1:
            raise ValueError("groups and dim must be >= 1")
        if clip <= 0:
            raise ValueError("clip must be positive")
        self.groups = groups
        self.dim = dim
        self.clip = float(clip)
        self.max_values = max_values_per_participant
        bound = max(clip, 1.0) * max_values_per_participant
        self.spec, self.sharing = QuantizationSpec.fitted(
            frac_bits, bound, n_participants
        )
        template = {
            "sums": np.zeros((groups, dim)),
            "counts": np.zeros(groups),
        }
        self.fed = FederatedAveraging(self.spec, template)

    def local_scatter(self, observations) -> dict:
        """``[(category, value-vector), …]`` -> this participant's
        {"sums", "counts"} contribution."""
        sums = np.zeros((self.groups, self.dim))
        counts = np.zeros(self.groups)
        observations = list(observations)
        if len(observations) > self.max_values:
            raise ValueError(f"more than {self.max_values} observations")
        for cat, vec in observations:
            cat = int(cat)
            if not 0 <= cat < self.groups:
                raise ValueError(f"category {cat} outside [0, {self.groups})")
            vec = _validate_vector(vec, self.dim, self.clip)
            sums[cat] += vec
            counts[cat] += 1
        return {"sums": sums, "counts": counts}

    def open_round(self, recipient, recipient_key):
        return self.fed.open_round(
            recipient, recipient_key, self.sharing, title="secure-grouped-mean"
        )

    def submit(self, participant, aggregation_id, observations) -> None:
        self.fed.submit_update(
            participant, aggregation_id, self.local_scatter(observations)
        )

    def close_round(self, recipient, aggregation_id) -> None:
        self.fed.close_round(recipient, aggregation_id)

    def finish(self, recipient, aggregation_id, n_submitted: int) -> dict:
        """-> {"counts": (groups,) int64, "means": (groups, dim) float64,
        NaN rows for categories nobody contributed to}."""
        from .federated import unflatten_pytree

        raw = self.fed.reveal_field_sum(recipient, aggregation_id, n_submitted)
        # decode by name through the stored layout — no dependence on the
        # pytree's key ordering
        tree = unflatten_pytree(
            self.spec.dequantize_sum(raw), self.fed.treedef, self.fed.shapes
        )
        counts = np.rint(tree["counts"]).astype(np.int64)
        totals = tree["sums"]
        g, d = self.groups, self.dim
        means = np.full((g, d), np.nan)
        nonzero = counts > 0
        means[nonzero] = totals[nonzero] / counts[nonzero, None]
        return {"counts": counts, "means": means}


def quantiles_from_histogram(counts, lo: float, hi: float, qs) -> np.ndarray:
    """Quantile estimates from equal-width bin ``counts`` over ``[lo, hi)``.

    Standard federated-analytics quantile sketch: the exact cohort
    histogram (SecureHistogram) determines each quantile to within one
    bin width; linear interpolation inside the containing bin gives the
    conventional point estimate. No individual values are ever revealed —
    only the (secure-summed) counts enter.

    ``qs`` in [0, 1]; returns float64 estimates, one per q. Empty cohorts
    raise (no data, no quantiles).
    """
    counts = np.asarray(counts, dtype=np.float64).reshape(-1)
    if counts.sum() <= 0:
        raise ValueError("empty histogram: no quantiles")
    qs = np.asarray(list(qs), dtype=np.float64)  # materialize: qs may be an iterator
    bins = len(counts)
    width = (hi - lo) / bins
    cum = np.cumsum(counts)
    total = cum[-1]
    out = np.empty(len(qs), dtype=np.float64)
    for i, q in enumerate(qs):
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        target = q * total
        b = int(np.searchsorted(cum, target, side="left"))
        b = min(b, bins - 1)
        # searchsorted lands on the leading cum==0 plateau for q=0 (and on
        # any empty-bin boundary): advance to the bin that actually holds
        # the target's mass so the one-bin-width error bound holds
        while counts[b] == 0 and b < bins - 1 and cum[b] < total:
            b += 1
        prev = cum[b - 1] if b > 0 else 0.0
        inbin = counts[b]
        frac = 0.0 if inbin == 0 else (target - prev) / inbin
        out[i] = lo + (b + min(max(frac, 0.0), 1.0)) * width
    return out


class SecureQuantiles(SecureHistogram):
    """Cohort quantiles (median, p95, ...) via the exact secure histogram.

    Same round protocol as SecureHistogram; ``finish_quantiles`` returns
    interpolated estimates with error bounded by one bin width
    ``(hi - lo) / bins`` — tighten by raising ``bins`` (cost is O(bins)
    vector length, not participant data)."""

    def finish_quantiles(self, recipient, aggregation_id, n_submitted, qs):
        counts = self.finish(recipient, aggregation_id, n_submitted)
        return quantiles_from_histogram(counts, self.lo, self.hi, qs)


class SecureFrequency(SecureHistogram):
    """Exact cohort frequency counts over a categorical domain
    ``{0, …, domain_size−1}`` — the federated heavy-hitters query for
    known domains. A category IS its bin (unit-width histogram), so counts
    are exact; ``finish_top_k`` returns the k most frequent categories
    with their counts, revealing only cohort totals."""

    def __init__(self, domain_size: int, n_participants: int, **kw):
        super().__init__(
            bins=domain_size, lo=0.0, hi=float(domain_size),
            n_participants=n_participants, **kw,
        )

    def local_counts(self, values) -> np.ndarray:
        values = np.asarray(values).reshape(-1)
        if values.size and (
            not np.issubdtype(values.dtype, np.integer)
            or values.min() < 0
            or values.max() >= self.bins
        ):
            raise ValueError(
                f"categories must be integers in [0, {self.bins})"
            )
        if values.size > self.max_values:
            raise ValueError(f"more than {self.max_values} values")
        # direct bincount on the validated integers: the parent's float
        # bin formula floor(v/D*D) can round BELOW v (e.g. v=1, D=49)
        # and silently credit the wrong category
        return np.bincount(values, minlength=self.bins).astype(np.float64)

    def finish_top_k(self, recipient, aggregation_id, n_submitted, k):
        """-> list of (category, count), k most frequent, count-descending
        (ties broken by category id for determinism)."""
        counts = self.finish(recipient, aggregation_id, n_submitted)
        order = np.lexsort((np.arange(len(counts)), -counts))[:k]
        return [(int(c), int(counts[c])) for c in order]


class SecureCountDistinct(SecureHistogram):
    """Cohort count-distinct over an *unknown or huge* item domain.

    The known-domain case is exact via ``SecureFrequency`` (one bin per
    category); when the domain is unbounded (URLs, tokens, user ids),
    each participant instead hashes its locally-distinct items into an
    ``m``-bin counting sketch (0/1 per bin after local dedupe) and the
    protocol sums the sketches. The union's distinct count is estimated
    from the number of untouched bins by linear counting
    (Whang–Vander-Zanden–Taylor 1990): ``n̂ = -m·ln(z/m)`` with ``z``
    zero bins — standard error ≈ ``sqrt(m·(exp(n/m) - n/m - 1))/n``,
    under ~1% for ``m ≥ 2n``. Only the summed sketch is revealed; items
    never leave a participant, and the hash (BLAKE2b, keyed by an
    explicit round salt all participants share) is one-way.
    """

    def __init__(self, m: int, n_participants: int, *, salt: str = "",
                 max_values_per_participant: int = 1 << 20):
        self._init_geometry(m, 0.0, float(m), max_values_per_participant)
        # sketch coordinates are 0/1 per participant (deduped), so the
        # per-bin sum is at most n_participants — fit the minimal field,
        # not the histogram default of clip=max_values
        self.spec, self.sharing = QuantizationSpec.fitted(0, 1.0, n_participants)
        self.fed = FederatedAveraging(self.spec, {"counts": np.zeros(m)})
        self.salt = salt

    # the shared canonical encoding, kept as a staticmethod for callers
    # that reached it through the class
    _canonical_bytes = staticmethod(canonical_item_bytes)

    def _bin_of(self, item) -> int:
        import hashlib

        # the salt is mixed into the hashed message (blake2b's salt param
        # silently truncates at 16 bytes, which would alias long salts
        # sharing a prefix and re-link sketches across rounds)
        h = hashlib.blake2b(
            self.salt.encode() + b"\x00" + self._canonical_bytes(item),
            digest_size=8,
        )
        return int.from_bytes(h.digest(), "big") % self.bins

    def local_counts(self, items) -> np.ndarray:
        """Locally-deduped 0/1 sketch of this participant's items."""
        distinct = set(items)
        if len(distinct) > self.max_values:
            raise ValueError(f"more than {self.max_values} values")
        out = np.zeros(self.bins, dtype=np.float64)
        out[list({self._bin_of(x) for x in distinct})] = 1.0
        return out

    @staticmethod
    def estimate_from_counts(counts) -> float:
        """Linear-counting estimate off the revealed summed sketch."""
        counts = np.asarray(counts)
        m = len(counts)
        zeros = int(np.count_nonzero(counts == 0))
        if zeros == 0:
            # sketch saturated: no unbiased estimate; report the coupon-
            # collector-style upper limit loudly rather than a number
            raise ValueError(
                f"sketch saturated (0 of {m} bins empty): raise m beyond "
                "~2x the expected distinct count and re-run"
            )
        return float(-m * np.log(zeros / m))

    def finish_estimate(self, recipient, aggregation_id, n_submitted) -> float:
        """-> estimated number of distinct items across the cohort."""
        counts = self.finish(recipient, aggregation_id, n_submitted)
        return self.estimate_from_counts(counts)
