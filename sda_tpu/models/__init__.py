"""Model-facing layer: federated learning + analytics over secure aggregation.

The reference's stated purpose is combining locally trained ML models
from phones into one global model without revealing any individual model
(reference README.md:5-15) — but it ships only the integer-vector
protocol and leaves the model plumbing to the application. This package
closes that gap:

- **learning**: pytree flattening + fixed-point field quantization,
  plain and sample-count-weighted FedAvg round drivers over any
  ``SdaService``, server optimizers (FedAvgM/FedAdam), a multi-round
  trainer with checkpoint/resume, and secure model evaluation;
- **analytics**: mean/variance, covariance/correlation (+ federated
  PCA), exact histograms, quantiles, frequency/heavy-hitters, grouped
  means, count-distinct sketches;
- **privacy**: opt-in distributed differential privacy for all of the
  above (discrete-Gaussian field noise, zCDP accounting, a persisted
  multi-round composition ledger).
"""

from .dp import (
    ComposedPrivacy,
    compose_accounts,
    compose_rhos,
    DPConfig,
    DPFederatedAveraging,
    DPSecureCovariance,
    DPSecureGroupedMean,
    DPSecureHistogram,
    DPSecureStatistics,
    DPWeightedFederatedAveraging,
    PrivacyAccount,
    eps_from_zcdp,
    noise_multiplier_for,
    sample_discrete_gaussian,
    sample_skellam,
)
from .federated import (
    FederatedAveraging,
    QuantizationSpec,
    WeightedFederatedAveraging,
    dequantize_mean,
    flatten_pytree,
    quantize_update,
    unflatten_pytree,
)
from .statistics import (
    canonical_item_bytes,
    SecureCountDistinct,
    SecureCovariance,
    SecureFrequency,
    SecureGroupedMean,
    SecureHistogram,
    SecureQuantiles,
    SecureStatistics,
    quantiles_from_histogram,
)
from .evaluation import DPSecureEvaluation, SecureEvaluation
from .optimizers import FedAdam, FedAvgM, ServerOptimizer
from .trainer import FederatedTrainer

__all__ = [
    "ComposedPrivacy",
    "compose_accounts",
    "compose_rhos",
    "DPConfig",
    "DPFederatedAveraging",
    "DPSecureCovariance",
    "DPSecureEvaluation",
    "DPSecureGroupedMean",
    "DPSecureHistogram",
    "DPSecureStatistics",
    "DPWeightedFederatedAveraging",
    "PrivacyAccount",
    "eps_from_zcdp",
    "noise_multiplier_for",
    "sample_discrete_gaussian",
    "sample_skellam",
    "FedAdam",
    "FedAvgM",
    "FederatedAveraging",
    "FederatedTrainer",
    "ServerOptimizer",
    "QuantizationSpec",
    "SecureCountDistinct",
    "SecureCovariance",
    "SecureEvaluation",
    "SecureGroupedMean",
    "WeightedFederatedAveraging",
    "SecureFrequency",
    "SecureHistogram",
    "SecureQuantiles",
    "SecureStatistics",
    "quantiles_from_histogram",
    "canonical_item_bytes",
    "dequantize_mean",
    "flatten_pytree",
    "quantize_update",
    "unflatten_pytree",
]
