"""Distributed differential privacy over secure aggregation.

The protocol reveals only the cohort sum — but the *sum itself* can leak
(a cohort of one, differencing attacks across rounds). This module adds
the standard remedy for the untrusted-server setting: **distributed
noise** — every participant adds a small amount of integer noise to its
quantized contribution *before* sharing, so the revealed aggregate
carries central-DP-calibrated noise that no single party (server,
clerks, recipient, or any sub-threshold coalition) can subtract.

This is an extension beyond the reference (no DP exists anywhere in
/root/reference — SURVEY.md §5), built from the published mechanisms the
federated-analytics literature settled on:

- **Discrete Gaussian** noise (Canonne–Kamath–Steinke 2020, "The
  Discrete Gaussian for Differential Privacy"): integer-valued, exactly
  (Δ₂²/2σ²)-zCDP, sampled by their rejection scheme from a discrete
  Laplace proposal. Each of n participants adds noise with parameter
  σ_party = σ_total/√n; the aggregate noise has variance σ_total² and is
  treated as a discrete Gaussian for accounting — the standard
  distributed-DP approximation (Kairouz–Liu–Steinke 2021), accurate when
  σ_party ≳ 1, which ``min_party_sigma`` enforces.
- **Skellam** noise (Agarwal–Kairouz–Liu 2021): Poisson(μ/2)−Poisson(μ/2),
  *exactly* closed under summation (n parties with μ/n each ⇒ total
  Skellam with variance μ, for any surviving subset). Provided as an
  alternative sampler; formal RDP accounting for Skellam is not
  implemented here — ``PrivacyAccount`` is only produced for the
  discrete-Gaussian mechanism.

Accounting: ρ-zCDP with ρ = Δ₂²/(2σ_total²), converted to (ε, δ)-DP by
the tight numeric Rényi conversion (δ(ε) minimized over the Rényi order)
with the classic ε = ρ + 2·sqrt(ρ·ln(1/δ)) closed form as a ceiling.

Field-plane details that make this *exact* over the protocol:

- Noise is added in **integer field space** (mod p), after quantization:
  float paths cannot represent 61-bit residues, integer paths can.
- Sensitivity is measured in field units: an L2-clipped update (norm
  ≤ C) quantizes to an integer vector of norm ≤ C·2^f + √d/2 (each
  coordinate rounds by ≤ 1/2) — the √d/2 rounding slack is included,
  deterministically, instead of the conditional-rounding machinery.
- Wraparound headroom: the field is sized for the data sum *plus* a
  ``NOISE_TAIL_SIGMAS``·σ_total margin. Discrete Gaussians are
  σ-sub-Gaussian, so the per-coordinate overflow probability is below
  exp(-TAIL²/2) ≈ 5e-32 at the default 12σ.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from .federated import (
    FederatedAveraging,
    QuantizationSpec,
    WeightedFederatedAveraging,
)
from .statistics import (
    SecureCovariance,
    SecureGroupedMean,
    SecureHistogram,
    SecureStatistics,
)

# Field headroom reserved for aggregate noise, in units of sigma_total.
# Sub-Gaussian tail: P(|noise| > k*sigma) <= 2*exp(-k^2/2) ~ 5e-32 at 12.
NOISE_TAIL_SIGMAS = 12.0


# ---------------------------------------------------------------------------
# Samplers (integer-valued, numpy Generator based)
# ---------------------------------------------------------------------------


def sample_discrete_laplace(t: float, size, rng) -> np.ndarray:
    """Discrete Laplace with scale ``t``: P(x) ∝ exp(-|x|/t) on Z.

    Difference of two iid geometrics on {0,1,...} with q = exp(-1/t).
    """
    if t <= 0:
        raise ValueError("scale t must be positive")
    p = -math.expm1(-1.0 / t)  # 1 - exp(-1/t), accurately for large t
    g1 = rng.geometric(p, size=size).astype(np.int64) - 1
    g2 = rng.geometric(p, size=size).astype(np.int64) - 1
    return g1 - g2


def sample_discrete_gaussian(sigma: float, size, rng) -> np.ndarray:
    """Discrete Gaussian N_Z(0, σ²): P(x) ∝ exp(-x²/2σ²) on Z.

    Canonne–Kamath–Steinke rejection sampler: propose from discrete
    Laplace with t = ⌊σ⌋+1, accept with exp(-(|y| - σ²/t)²/(2σ²)).
    Acceptance probabilities use float64 (the standard engineering
    deviation from the paper's exact rational arithmetic; error is at
    the 1e-16 level, far below the δ budgets in use).
    """
    if sigma <= 0:
        raise ValueError("sigma must be positive")
    shape = (size,) if np.isscalar(size) else tuple(size)
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    t = math.floor(sigma) + 1
    two_var = 2.0 * sigma * sigma
    shift = sigma * sigma / t
    out = np.empty(n, dtype=np.int64)
    filled = 0
    while filled < n:
        m = max(int((n - filled) * 2.5) + 16, 32)
        y = sample_discrete_laplace(t, m, rng)
        dev = np.abs(y).astype(np.float64) - shift
        accept = rng.random(m) < np.exp(-(dev * dev) / two_var)
        got = y[accept]
        k = min(got.size, n - filled)
        out[filled : filled + k] = got[:k]
        filled += k
    return out.reshape(shape)


def sample_skellam(mu: float, size, rng) -> np.ndarray:
    """Skellam(μ/2, μ/2): Poisson(μ/2) − Poisson(μ/2); variance μ.

    Exactly closed under addition: n parties each adding Skellam(μ/n)
    noise yields total Skellam(μ) noise — for any surviving subset.
    """
    if mu <= 0:
        raise ValueError("mu must be positive")
    a = rng.poisson(mu / 2.0, size=size).astype(np.int64)
    b = rng.poisson(mu / 2.0, size=size).astype(np.int64)
    return a - b


# ---------------------------------------------------------------------------
# Accounting: zCDP for the (distributed) discrete Gaussian
# ---------------------------------------------------------------------------


def zcdp_rho(l2_sensitivity: float, sigma_total: float) -> float:
    """ρ of ρ-zCDP for discrete Gaussian noise N_Z(0, σ²) per coordinate
    against integer shifts of L2 norm ≤ Δ₂ (CKS 2020, Thm 14)."""
    if sigma_total <= 0:
        raise ValueError("sigma must be positive")
    return (l2_sensitivity * l2_sensitivity) / (2.0 * sigma_total * sigma_total)


def delta_from_zcdp(rho: float, eps: float) -> float:
    """Tight δ(ε) for a ρ-zCDP mechanism (RDP curve ε(α) = ρα).

    δ = min_{α>1} exp((α−1)(ρα − ε)) · (1 − 1/α)^α / (α − 1)
    (Canonne–Kamath–Steinke 2020, Prop. 12). The unconstrained optimum
    α* = (ε + ρ)/(2ρ) is refined by a local grid to absorb the
    (1−1/α)^α/(α−1) correction terms.
    """
    if rho <= 0:
        return 0.0 if eps >= 0 else 1.0
    a_star = max((eps + rho) / (2.0 * rho), 1.0 + 1e-9)
    grid = np.concatenate(
        [
            np.linspace(1.0 + 1e-6, 2.0, 64),
            a_star * np.geomspace(0.25, 4.0, 129),
        ]
    )
    g = grid[grid > 1.0]
    dlog = (g - 1.0) * (rho * g - eps) + g * np.log1p(-1.0 / g) - np.log(g - 1.0)
    return float(min(1.0, math.exp(dlog.min())))


def eps_from_zcdp(rho: float, delta: float) -> float:
    """Tight ε for ρ-zCDP at a target δ (bisection on ``delta_from_zcdp``),
    never exceeding the classic ρ + 2·sqrt(ρ·ln(1/δ)) closed form."""
    if not 0.0 < delta < 1.0:
        raise ValueError("delta must be in (0, 1)")
    if rho <= 0:
        return 0.0
    classic = rho + 2.0 * math.sqrt(rho * math.log(1.0 / delta))
    lo, hi = 0.0, classic
    for _ in range(80):
        mid = 0.5 * (lo + hi)
        if delta_from_zcdp(rho, mid) > delta:
            lo = mid
        else:
            hi = mid
    return hi


def noise_multiplier_for(eps: float, delta: float) -> float:
    """Smallest z = σ_total/Δ₂ achieving (ε, δ)-DP (bisection)."""
    if eps <= 0:
        raise ValueError("eps must be positive")
    lo, hi = 1e-4, 1.0
    while eps_from_zcdp(zcdp_rho(1.0, hi), delta) > eps:
        hi *= 2.0
        if hi > 1e8:
            raise ValueError("unreachable privacy target")
    for _ in range(60):
        mid = 0.5 * (lo + hi)
        if eps_from_zcdp(zcdp_rho(1.0, mid), delta) > eps:
            lo = mid
        else:
            hi = mid
    return hi


@dataclass(frozen=True)
class PrivacyAccount:
    """Realized guarantee of one revealed aggregate."""

    epsilon: float
    delta: float
    rho: float
    sigma_total: float  # field units
    l2_sensitivity: float  # field units
    n_parties: int


@dataclass(frozen=True)
class ComposedPrivacy:
    """Cumulative guarantee over a sequence of releases (zCDP ledger)."""

    epsilon: float
    delta: float
    rho: float
    rounds: int


def compose_rhos(rhos, delta: float) -> ComposedPrivacy:
    """zCDP composition: ρ adds across releases; one tight (ε, δ)
    conversion at the end — strictly better than summing per-round ε."""
    rhos = [float(r) for r in rhos]
    rho = sum(rhos)
    if math.isinf(rho):
        # a release without implemented accounting (e.g. Skellam) enters
        # the ledger as rho=inf: the composed guarantee is honestly
        # "unbounded", never silently understated
        return ComposedPrivacy(epsilon=math.inf, delta=delta, rho=rho,
                               rounds=len(rhos))
    return ComposedPrivacy(
        epsilon=eps_from_zcdp(rho, delta), delta=delta, rho=rho,
        rounds=len(rhos),
    )


def compose_accounts(accounts, delta: float | None = None) -> ComposedPrivacy:
    """Compose per-release ``PrivacyAccount``s; δ defaults to the loosest
    (largest) per-release δ, which upper-bounds the composition's."""
    accounts = list(accounts)
    if not accounts:
        raise ValueError("nothing to compose")
    if delta is None:
        delta = max(a.delta for a in accounts)
    return compose_rhos([a.rho for a in accounts], delta)


# ---------------------------------------------------------------------------
# Mechanism configuration
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DPConfig:
    """Distributed-noise configuration.

    ``l2_clip`` bounds each participant's update L2 norm (real units);
    ``noise_multiplier`` z sets σ_total = z · Δ₂ (in field units, where
    Δ₂ is the quantized sensitivity); ``expected_participants`` n splits
    the noise: each party adds σ_party = σ_total/√n. ``min_party_sigma``
    guards the distributed≈central approximation (and keeps per-party
    noise meaningful against a colluding rest-of-cohort).
    """

    l2_clip: float
    noise_multiplier: float
    expected_participants: int
    delta: float = 1e-6
    mechanism: str = "dgauss"  # "dgauss" | "skellam"
    min_party_sigma: float = 1.0

    def __post_init__(self):
        if self.l2_clip <= 0:
            raise ValueError("l2_clip must be positive")
        if self.noise_multiplier <= 0:
            raise ValueError("noise_multiplier must be positive")
        if self.expected_participants < 1:
            raise ValueError("need at least one participant")
        if self.mechanism not in ("dgauss", "skellam"):
            raise ValueError(f"unknown mechanism {self.mechanism!r}")

    def sensitivity_field(self, scale: int, dim: int) -> float:
        """Quantized L2 sensitivity: C·2^f plus the √d/2 rounding slack."""
        return self.l2_clip * scale + 0.5 * math.sqrt(dim)

    def sigma_total_field(self, scale: int, dim: int) -> float:
        return self.noise_multiplier * self.sensitivity_field(scale, dim)

    def sigma_party_field(self, scale: int, dim: int) -> float:
        return self.sigma_total_field(scale, dim) / math.sqrt(
            self.expected_participants
        )

    def field_need(self, scale: int, dim: int,
                   per_coordinate_bound: float | None = None) -> float:
        """Per-coordinate magnitude the field must hold without wrapping:
        the data sum plus the NOISE_TAIL_SIGMAS aggregate-noise margin.
        Single source of truth for builders (``fitted_spec`` /
        ``fitted_dp``), the construction-time guards, and the tests.

        ``per_coordinate_bound`` defaults to ``l2_clip`` (a valid, if
        conservative, coordinate bound); channels with a tighter known
        per-coordinate bound (e.g. the weighted channel's
        ``clip·max_weight``) pass it to avoid a ~sqrt(d)-oversized field.
        """
        bound = self.l2_clip if per_coordinate_bound is None else per_coordinate_bound
        return (
            self.expected_participants * scale * bound
            + NOISE_TAIL_SIGMAS * self.sigma_total_field(scale, dim)
        )

    def account(self, scale: int, dim: int, n_actual: int | None = None) -> PrivacyAccount:
        """Guarantee realized with ``n_actual`` submitters (dropout makes
        the realized σ_total smaller than configured: noise variance is
        n_actual·σ_party², so ε grows as parties drop out)."""
        if self.mechanism != "dgauss":
            raise NotImplementedError(
                "formal accounting is implemented for the discrete-Gaussian "
                "mechanism only (Skellam RDP: Agarwal et al. 2021)"
            )
        n = self.expected_participants if n_actual is None else n_actual
        if n < 1:
            raise ValueError("need at least one submitter")
        sens = self.sensitivity_field(scale, dim)
        sigma = self.sigma_party_field(scale, dim) * math.sqrt(n)
        rho = zcdp_rho(sens, sigma)
        return PrivacyAccount(
            epsilon=eps_from_zcdp(rho, self.delta),
            delta=self.delta,
            rho=rho,
            sigma_total=sigma,
            l2_sensitivity=sens,
            n_parties=n,
        )

    def party_noise(self, scale: int, dim: int, rng=None) -> np.ndarray:
        """One participant's ``(dim,)`` int64 noise draw (field units)."""
        rng = np.random.default_rng() if rng is None else rng
        sigma = self.sigma_party_field(scale, dim)
        if sigma < self.min_party_sigma:
            raise ValueError(
                f"per-party sigma {sigma:.3f} < min_party_sigma "
                f"{self.min_party_sigma}: the distributed-noise "
                "approximation needs ~1 field unit of noise per party — "
                "raise noise_multiplier or frac_bits, or lower "
                "expected_participants"
            )
        if self.mechanism == "dgauss":
            return sample_discrete_gaussian(sigma, dim, rng)
        return sample_skellam(sigma * sigma, dim, rng)


def l2_clip_vector(flat: np.ndarray, clip: float) -> np.ndarray:
    """Scale ``flat`` down to L2 norm ≤ clip (no-op when already inside)."""
    flat = np.asarray(flat, dtype=np.float64)
    norm = float(np.linalg.norm(flat))
    if norm > clip:
        flat = flat * (clip / norm)
    return flat


# ---------------------------------------------------------------------------
# Protocol integration
# ---------------------------------------------------------------------------


class _DPRoundMixin:
    """Shared DP-round plumbing for drivers over a (possibly widened)
    field vector: the per-party sigma feasibility + noise-headroom
    guards, the revealed-cohort memo, and realized-privacy accounting.
    Hosts must set ``self.spec``/``self.dp`` before calling
    ``_check_dp_feasible`` and expose ``wire_dimension``.
    """

    def _check_dp_feasible(self, per_coordinate_bound: float | None = None,
                           builder: str = ".fitted_spec") -> None:
        sigma = self.dp.sigma_party_field(self.spec.scale, self.wire_dimension)
        if sigma < self.dp.min_party_sigma:
            raise ValueError(
                f"per-party sigma {sigma:.3f} < min_party_sigma "
                f"{self.dp.min_party_sigma}; raise noise_multiplier or "
                "frac_bits"
            )
        # a data-only-fitted field accepts the data sum but wraps under
        # aggregate noise — require the NOISE_TAIL_SIGMAS margin the
        # mechanism was accounted with
        need = self.dp.field_need(
            self.spec.scale, self.wire_dimension, per_coordinate_bound
        )
        if not need < (self.spec.modulus - 1) // 2:
            raise ValueError(
                f"field {self.spec.modulus} lacks noise headroom: data + "
                f"{NOISE_TAIL_SIGMAS:g}sigma needs > {int(2 * need) + 1}; "
                f"build the spec with {builder}"
            )

    def reveal_field_sum(self, recipient, aggregation_id, n_submitted: int):
        out = super().reveal_field_sum(recipient, aggregation_id, n_submitted)
        # remember the realized cohort so privacy() reports the guarantee
        # the revealed aggregate actually has (dropout shrinks the total
        # noise: realized sigma_total = sqrt(n_actual) * sigma_party)
        self._revealed_n = n_submitted
        return out

    def privacy(self, n_actual: int | None = None) -> PrivacyAccount:
        """Realized guarantee. Defaults to the submitter count of the last
        reveal when one happened; before any reveal it reports the
        configured target (``expected_participants``)."""
        if n_actual is None:
            n_actual = getattr(self, "_revealed_n", None)
        return self.dp.account(self.spec.scale, self.wire_dimension, n_actual)


class DPFederatedAveraging(_DPRoundMixin, FederatedAveraging):
    """FedAvg round with distributed-DP noise on every update.

    Participants L2-clip to ``dp.l2_clip`` (scaling down, not rejecting:
    a DP mechanism must accept any input), quantize, and add per-party
    integer noise in field space before the normal mask/share/seal
    pipeline. Use ``fitted_spec`` to build a field with noise headroom.
    """

    def __init__(self, spec: QuantizationSpec, template_tree, dp: DPConfig,
                 rng=None, *, per_coordinate_bound: float | None = None):
        super().__init__(spec, template_tree)
        self.dp = dp
        self._rng = np.random.default_rng() if rng is None else rng
        # fail at construction, not first submit. Channels with a known
        # tighter per-coordinate bound than l2_clip pass it here AND to
        # fitted_spec, keeping builder and guard on one formula.
        self._check_dp_feasible(
            per_coordinate_bound, builder="DPFederatedAveraging.fitted_spec"
        )

    @classmethod
    def fitted_spec(cls, frac_bits: int, dp: DPConfig, dim: int,
                    per_coordinate_bound: float | None = None, **shamir_kw):
        """(spec, sharing) sized for data sum + NOISE_TAIL_SIGMAS·σ_total.

        Mirrors ``QuantizationSpec.fitted`` with the per-coordinate bound
        inflated so n·2^f·clip_eff equals ``DPConfig.field_need``."""
        scale = 1 << frac_bits
        n = dp.expected_participants
        clip_eff = dp.field_need(scale, dim, per_coordinate_bound) / (n * scale)
        return QuantizationSpec.fitted(frac_bits, clip_eff, n, **shamir_kw)

    def submit_update(self, participant, aggregation_id, update_tree, *, rng=None):
        flat = l2_clip_vector(self._validated_flat(update_tree), self.dp.l2_clip)
        q = self.spec.quantize(flat).astype(np.int64)
        noise = self.dp.party_noise(
            self.spec.scale, self.dim, self._rng if rng is None else rng
        )
        # full reduction, not just a negative-lift: q + noise ranges over
        # (-|noise|, p + |noise|); numpy % with a positive modulus is the
        # canonical [0, p) representative either side of zero
        participant.participate((q + noise) % self.spec.modulus, aggregation_id)


class DPSecureStatistics(SecureStatistics):
    """Cohort mean + variance under distributed DP.

    ``SecureStatistics`` (participants submit ``[x, x²]`` per
    coordinate) over a ``DPFederatedAveraging`` round; validation,
    round flow, and the variance computation are inherited — only the
    field fitting (noise headroom) and noise threading differ. The
    concatenated channel has a deterministic L2 bound for
    per-coordinate ``|x| ≤ c``: ``||(x, x²)||₂ ≤ sqrt(d·(c² + c⁴))`` —
    used as the DP clip, so in-bounds submissions are never scaled and
    the accounted sensitivity is tight for worst-case inputs. Both
    revealed sums carry noise of std σ_total/2^f per coordinate; the
    variance estimate inherits it (clamped at 0 by the parent).
    """

    def __init__(self, dim: int, clip: float, n_participants: int, *,
                 noise_multiplier: float, delta: float = 1e-6,
                 frac_bits: int = 16, mechanism: str = "dgauss", rng=None):
        if clip <= 0:
            raise ValueError("clip must be positive")
        self.dim = dim
        self.clip = float(clip)
        l2 = math.sqrt(dim * (clip * clip + clip ** 4))
        self.dp = DPConfig(
            l2_clip=l2, noise_multiplier=noise_multiplier,
            expected_participants=n_participants, delta=delta,
            mechanism=mechanism,
        )
        self.spec, self.sharing = DPFederatedAveraging.fitted_spec(
            frac_bits, self.dp, 2 * dim
        )
        template = {"sum": np.zeros(dim), "sumsq": np.zeros(dim)}
        self.fed = DPFederatedAveraging(self.spec, template, self.dp, rng=rng)

    def submit(self, participant, aggregation_id, values, *, rng=None) -> None:
        self.fed.submit_update(
            participant, aggregation_id, self._checked_tree(values), rng=rng
        )

    def privacy(self, n_actual: int | None = None) -> PrivacyAccount:
        return self.fed.privacy(n_actual)


class DPWeightedFederatedAveraging(_DPRoundMixin, WeightedFederatedAveraging):
    """Weighted FedAvg under distributed DP — noise covers updates AND
    weights (a site's exact sample count is itself sensitive).

    The wire channel is ``(w·x, w)`` with ``|x_i| ≤ clip`` (L∞) and
    ``w ≤ max_weight``, so its L2 bound is
    ``max_weight·sqrt(clip²·d + 1)`` — the DP clip; in-bounds
    submissions are never rescaled. ``finish_round`` divides the noisy
    weighted sum by the noisy total weight: the ratio's noise scale is
    ``σ_total/(Σw·2^f)`` per coordinate plus a relative error of
    ``σ_total/(Σw·2^f)`` from the denominator — keep ``Σw`` well above
    the noise (e.g. n·E[w] ≫ σ_total/2^f) or widen ε.
    """

    def __init__(self, spec: QuantizationSpec, template_tree, clip: float,
                 max_weight: float, dp: DPConfig, rng=None):
        super().__init__(spec, template_tree, clip, max_weight)
        self.dp = dp
        self._rng = np.random.default_rng() if rng is None else rng
        # per-coordinate bound is max(clip*max_weight, max_weight), NOT the
        # channel L2 (the default would demand a ~sqrt(d)-too-large field)
        self._check_dp_feasible(
            per_coordinate_bound=max(self.clip * self.max_weight,
                                     self.max_weight),
            builder=".fitted_dp",
        )

    @classmethod
    def fitted_dp(cls, frac_bits: int, clip: float, max_weight: float,
                  n_participants: int, template_tree, *,
                  noise_multiplier: float, delta: float = 1e-6,
                  mechanism: str = "dgauss", rng=None, **shamir_kw):
        """(driver, sharing) with the channel's tight DP clip and a field
        holding data + noise tail."""
        from .federated import tree_layout

        _, _, dim = tree_layout(template_tree)
        l2 = max_weight * math.sqrt(clip * clip * dim + 1.0)
        dp = DPConfig(
            l2_clip=l2, noise_multiplier=noise_multiplier,
            expected_participants=n_participants, delta=delta,
            mechanism=mechanism,
        )
        wire = dim + 1
        # per-coordinate bound for the field: clip*max_weight (w*x channel)
        bound = max(clip * max_weight, max_weight)
        spec, sharing = DPFederatedAveraging.fitted_spec(
            frac_bits, dp, wire, per_coordinate_bound=bound, **shamir_kw
        )
        return cls(spec, template_tree, clip, max_weight, dp, rng=rng), sharing

    def submit_update(self, participant, aggregation_id, update_tree,
                      weight: float, *, rng=None):
        q = self._quantized_wire(update_tree, weight).astype(np.int64)
        noise = self.dp.party_noise(
            self.spec.scale, self.wire_dimension,
            self._rng if rng is None else rng,
        )
        participant.participate((q + noise) % self.spec.modulus, aggregation_id)

    def _weighted_flat(self, sums, total_weight: float) -> np.ndarray:
        """Unlike the noise-free base (which raises on a non-positive
        total), a noisy denominator can legitimately dip ≤ 0 for small
        cohorts — and by reveal time the privacy budget is already
        spent, so failing hard would waste it. NaN mean + the noisy
        total let the caller judge usability, mirroring
        ``DPSecureGroupedMean``'s noisy-count handling."""
        if total_weight > 0:
            return sums[: self.dim] / total_weight
        return np.full(self.dim, np.nan)


class DPSecureGroupedMean(SecureGroupedMean):
    """Per-category cohort means under distributed DP.

    The scatter channel (``(groups, dim)`` per-category sums + a
    ``(groups,)`` count vector) has, for one participant with at most
    ``m = max_values`` observations of ``|coordinate| ≤ c``, the L2
    bound ``m·sqrt(c²·d + 1)`` — all observations in one category is
    the worst case (the sums row reaches ``m·c`` per coordinate and the
    count cell ``m``; splitting mass across categories only lowers the
    norm). Noisy counts come back as floats (may dip negative); means
    divide by them only where the noisy count is ≥ 1.
    """

    def __init__(self, groups: int, dim: int, clip: float,
                 n_participants: int, *, noise_multiplier: float,
                 delta: float = 1e-6, frac_bits: int = 16,
                 max_values_per_participant: int = 1 << 10,
                 mechanism: str = "dgauss", rng=None):
        if groups < 1 or dim < 1:
            raise ValueError("groups and dim must be >= 1")
        if clip <= 0:
            raise ValueError("clip must be positive")
        self.groups = groups
        self.dim = dim
        self.clip = float(clip)
        self.max_values = max_values_per_participant
        m = max_values_per_participant
        l2 = m * math.sqrt(clip * clip * dim + 1.0)
        wire = groups * dim + groups
        self.dp = DPConfig(
            l2_clip=l2, noise_multiplier=noise_multiplier,
            expected_participants=n_participants, delta=delta,
            mechanism=mechanism,
        )
        bound = max(clip, 1.0) * m  # true per-coordinate bound
        self.spec, self.sharing = DPFederatedAveraging.fitted_spec(
            frac_bits, self.dp, wire, per_coordinate_bound=bound
        )
        template = {
            "sums": np.zeros((groups, dim)),
            "counts": np.zeros(groups),
        }
        self.fed = DPFederatedAveraging(
            self.spec, template, self.dp, rng=rng, per_coordinate_bound=bound
        )

    def submit(self, participant, aggregation_id, observations, *,
               rng=None) -> None:
        self.fed.submit_update(
            participant, aggregation_id, self.local_scatter(observations),
            rng=rng,
        )

    def finish(self, recipient, aggregation_id, n_submitted: int) -> dict:
        """-> {"counts": (groups,) float64 noisy counts, "means":
        (groups, dim) float64 — NaN where the noisy count is < 1}."""
        from .federated import unflatten_pytree

        raw = self.fed.reveal_field_sum(recipient, aggregation_id, n_submitted)
        tree = unflatten_pytree(
            self.spec.dequantize_sum(raw), self.fed.treedef, self.fed.shapes
        )
        counts = np.asarray(tree["counts"], dtype=np.float64)
        means = np.full((self.groups, self.dim), np.nan)
        usable = counts >= 1.0
        means[usable] = tree["sums"][usable] / counts[usable, None]
        return {"counts": counts, "means": means}

    def privacy(self, n_actual: int | None = None) -> PrivacyAccount:
        return self.fed.privacy(n_actual)


class DPSecureCovariance(SecureCovariance):
    """Cohort covariance/correlation under distributed DP.

    ``SecureCovariance`` (participants submit ``[x, vech(x xᵀ)]``) over
    a ``DPFederatedAveraging`` round. For per-coordinate ``|x| ≤ c`` the
    channel's L2 bound is ``sqrt(d·c² + d(d+1)/2·c⁴)``
    (``||vech(xxᵀ)||₂² = Σ_{i≤j}(x_i x_j)² ≤ d(d+1)/2·c⁴``, each
    off-diagonal product counted once) — the DP clip, tight at
    x = (c,…,c), so in-bounds submissions are never rescaled. The noisy
    covariance is symmetric by construction but only approximately PSD;
    its diagonal still clamps at 0 (parent ``finish``).
    """

    def __init__(self, dim: int, clip: float, n_participants: int, *,
                 noise_multiplier: float, delta: float = 1e-6,
                 frac_bits: int = 16, mechanism: str = "dgauss", rng=None):
        if dim < 1:
            raise ValueError("dim must be >= 1")
        if clip <= 0:
            raise ValueError("clip must be positive")
        self.dim = dim
        self.clip = float(clip)
        self._triu = np.triu_indices(dim)
        wire = dim + dim * (dim + 1) // 2
        l2 = math.sqrt(
            dim * clip * clip + dim * (dim + 1) / 2.0 * clip ** 4
        )
        self.dp = DPConfig(
            l2_clip=l2, noise_multiplier=noise_multiplier,
            expected_participants=n_participants, delta=delta,
            mechanism=mechanism,
        )
        self.spec, self.sharing = DPFederatedAveraging.fitted_spec(
            frac_bits, self.dp, wire
        )
        template = {
            "sum": np.zeros(dim),
            "outer": np.zeros(dim * (dim + 1) // 2),
        }
        self.fed = DPFederatedAveraging(self.spec, template, self.dp, rng=rng)

    def submit(self, participant, aggregation_id, values, *, rng=None) -> None:
        self.fed.submit_update(
            participant, aggregation_id, self._checked_tree(values), rng=rng
        )

    def privacy(self, n_actual: int | None = None) -> PrivacyAccount:
        return self.fed.privacy(n_actual)


class DPSecureHistogram(SecureHistogram):
    """Cohort histogram with distributed-DP noise on the counts.

    One participant's counts vector has L1 = #values ≤ ``max_values``
    and L2 ≤ L1 (all values in one bin), so the real-unit L2 clip is
    ``max_values`` and the clip inside ``DPFederatedAveraging`` is a
    no-op — the noise mechanism is the whole point of the composition.

    Counts are scaled by ``2^frac_bits`` in the field so per-party
    integer noise of ≥ 1 field unit (the distributed-noise floor) costs
    only ``2^-frac_bits`` of a count: without the scaling, one field
    unit per party would force σ_total ≥ √n *whole counts* of noise.
    Noise is added post-quantize, in integer field space, by
    ``DPFederatedAveraging.submit_update`` — never before quantization,
    where the quantizer's coordinate clamp would truncate it and void
    the accounting. ``finish`` center-lifts and rescales, so noisy
    counts are floats and may dip negative.
    """

    def __init__(
        self,
        bins: int,
        lo: float,
        hi: float,
        n_participants: int,
        *,
        noise_multiplier: float,
        delta: float = 1e-6,
        max_values_per_participant: int = 1,
        mechanism: str = "dgauss",
        frac_bits: int = 16,
        rng=None,
    ):
        self._init_geometry(bins, lo, hi, max_values_per_participant)
        self.dp = DPConfig(
            l2_clip=float(max_values_per_participant),
            noise_multiplier=noise_multiplier,
            expected_participants=n_participants,
            delta=delta,
            mechanism=mechanism,
        )
        self.spec, self.sharing = DPFederatedAveraging.fitted_spec(
            frac_bits, self.dp, bins
        )
        self.fed = DPFederatedAveraging(
            self.spec, {"counts": np.zeros(bins)}, self.dp, rng=rng
        )

    def submit(self, participant, aggregation_id, values, *, rng=None) -> None:
        self.fed.submit_update(
            participant, aggregation_id,
            {"counts": self.local_counts(values)}, rng=rng,
        )

    def finish(self, recipient, aggregation_id, n_submitted: int) -> np.ndarray:
        """-> (bins,) float64 noisy counts (noise scale σ_total/2^f per
        bin; may be negative — clamp/round at the consumer if needed)."""
        raw = self.fed.reveal_field_sum(recipient, aggregation_id, n_submitted)
        return self.spec.dequantize_sum(raw)

    def privacy(self, n_actual: int | None = None) -> PrivacyAccount:
        return self.fed.privacy(n_actual)
