"""Secure federated model evaluation: cohort metrics over private data.

Choosing or monitoring a global model needs its loss/accuracy over the
cohort's data — but per-participant metrics leak (a hospital's local
accuracy reveals how well the model fits *its* patients). Evaluation is
a weighted secure sum: each participant submits
``(n_k·loss_k, n_k·acc_k, n_k)`` — its local example count times its
local metric means, plus the count — and the revealed sums give the
example-weighted cohort metrics ``Σ n_k·m_k / Σ n_k`` without revealing
any participant's metrics or dataset size.

Rides ``WeightedFederatedAveraging`` (the metrics vector is the "update",
the local example count is the weight), so it inherits masking, sharing,
sealed transport, and dropout tolerance. No reference twin (the
reference ships no model layer); this is the evaluation half of the
stated purpose its README only describes.
"""

from __future__ import annotations

import numpy as np

from .federated import WeightedFederatedAveraging


def _checked_metric_layout(metric_names):
    """Validate the metric-name layout; returns (names, template)."""
    names = list(metric_names)
    if not names:
        raise ValueError("need at least one metric")
    if "examples" in names:
        raise ValueError('"examples" is reserved for the total count')
    if len(set(names)) != len(names):
        raise ValueError("duplicate metric names")
    return names, {"metrics": np.zeros(len(names))}


class SecureEvaluation:
    """One evaluation round: example-weighted cohort means of ``metrics``.

    ``metric_names`` fixes the vector layout every participant must use
    (``"examples"`` is reserved for the revealed total count); ``bound``
    is the largest |metric| accepted — out-of-bound submissions are
    rejected, not clipped (a silently clipped loss would corrupt the
    cohort mean); ``max_examples`` bounds one participant's local
    example count.
    """

    def __init__(self, metric_names, n_participants: int, *,
                 bound: float = 100.0, max_examples: int = 1 << 20,
                 frac_bits: int = 16):
        self.metric_names, template = _checked_metric_layout(metric_names)
        self.fed, self.sharing = WeightedFederatedAveraging.fitted(
            frac_bits, float(bound), float(max_examples), n_participants,
            template,
        )

    def open_round(self, recipient, recipient_key):
        return self.fed.open_round(
            recipient, recipient_key, self.sharing, title="secure-evaluation"
        )

    def submit(self, participant, aggregation_id, metrics: dict,
               n_examples: int) -> None:
        """``metrics``: {name: local mean over this participant's
        ``n_examples`` examples} — every configured name required."""
        missing = [m for m in self.metric_names if m not in metrics]
        if missing:
            raise ValueError(f"missing metrics: {missing}")
        if n_examples < 1:
            raise ValueError("n_examples must be >= 1")
        vec = np.array([float(metrics[m]) for m in self.metric_names])
        self.fed.submit_update(
            participant, aggregation_id, {"metrics": vec},
            weight=float(n_examples),
        )

    def close_round(self, recipient, aggregation_id) -> None:
        self.fed.close_round(recipient, aggregation_id)

    def finish(self, recipient, aggregation_id, n_submitted: int) -> dict:
        """-> {name: example-weighted cohort mean} plus ``"examples"``
        (total example count across the cohort)."""
        mean, total = self.fed.finish_round(
            recipient, aggregation_id, n_submitted
        )
        out = dict(zip(self.metric_names, mean["metrics"]))
        out["examples"] = self._format_examples(total)
        return out

    @staticmethod
    def _format_examples(total: float):
        """Policy hook: the noise-free total is an exact integer count.
        The DP subclass keeps the noisy float instead."""
        return int(round(total))


class DPSecureEvaluation(SecureEvaluation):
    """Model evaluation under distributed DP: the revealed cohort
    metrics AND the total example count carry noise no party can strip
    (exact totals themselves leak — e.g. a site joining changes the
    count by its private dataset size).

    Same round flow as ``SecureEvaluation``; the weighted channel runs
    over ``DPWeightedFederatedAveraging``, whose sensitivity bound
    covers one site's worst case ``(n·metrics, n)`` contribution. The
    revealed example count is noisy (reported rounded; noise std
    ~σ_total/2^f).
    """

    def __init__(self, metric_names, n_participants: int, *,
                 noise_multiplier: float, delta: float = 1e-6,
                 bound: float = 100.0, max_examples: int = 1 << 20,
                 frac_bits: int = 16, mechanism: str = "dgauss", rng=None):
        from .dp import DPWeightedFederatedAveraging

        self.metric_names, template = _checked_metric_layout(metric_names)
        self.fed, self.sharing = DPWeightedFederatedAveraging.fitted_dp(
            frac_bits, float(bound), float(max_examples), n_participants,
            template, noise_multiplier=noise_multiplier, delta=delta,
            mechanism=mechanism, rng=rng,
        )

    @staticmethod
    def _format_examples(total: float):
        """``"examples"`` stays the noisy float — for a tiny cohort it
        can legitimately come back <= 0 (metrics are NaN then); rounding
        it to an int would dress noise up as an exact count, and raising
        would waste the already-charged privacy budget. The caller
        judges usability."""
        return float(total)

    def privacy(self, n_actual: int | None = None):
        return self.fed.privacy(n_actual)
