"""Structured JSON log sink keyed by trace-id.

Every telemetry event (spans today; callers may emit their own via
:func:`emit`) becomes one JSON object on the ``sda.telemetry`` logger at
DEBUG — invisible by default, and one ``install()`` away from a greppable
JSON-lines file whose every line carries the trace id, so
``grep <trace-id> telemetry.jsonl`` reconstructs a request's journey
through client, REST, service, and store.

Kept separate from :mod:`.spans` so the stdlib ``logging`` import and
json encoding stay off the span hot path until a record is actually
emitted.
"""

from __future__ import annotations

import json
import logging

log = logging.getLogger("sda.telemetry")


def emit(event: str, fields: dict) -> None:
    """One JSON log line for ``fields`` (must already carry trace_id when
    there is one). No-op unless something listens at DEBUG."""
    if not log.isEnabledFor(logging.DEBUG):
        return
    try:
        log.debug("%s", json.dumps({"event": event, **fields}, default=repr))
    except (TypeError, ValueError):
        log.debug('{"event": %r, "error": "unserializable record"}', event)


def install(path, level: int = logging.DEBUG) -> logging.Handler:
    """Attach a JSON-lines file handler to the telemetry logger and
    return it (pass to :func:`uninstall` to detach). The formatter is
    bare ``%(message)s`` — records are already JSON."""
    handler = logging.FileHandler(path)
    handler.setFormatter(logging.Formatter("%(message)s"))
    handler.setLevel(level)
    log.addHandler(handler)
    if log.level == logging.NOTSET or log.level > level:
        log.setLevel(level)
    return handler


def uninstall(handler: logging.Handler) -> None:
    log.removeHandler(handler)
    handler.close()
