"""sda_tpu.telemetry — the measurement plane.

One process-global registry (counters / gauges / histograms with
thread-local write shards and a locked merge), lightweight spans with a
trace-id propagated client -> REST (``X-SDA-Trace``) -> service -> store,
a Prometheus text exposition (served at ``GET /v1/metrics``), and a
structured JSON log sink keyed by trace-id.

Module-level helpers front the global registry — instrumentation sites
do ``from .. import telemetry`` and call ``telemetry.counter(...)`` /
``telemetry.span(...)``. Everything honors the kill switch: start the
process with ``SDA_TELEMETRY=0`` (or call ``set_enabled(False)``) and
every operation becomes a branch-and-return no-op.

Metric names and label conventions are documented in
``docs/observability.md``; the snapshot/export surface is:

- ``snapshot()``     — merged dict of every series + recent spans (what
  ``bench.py`` banks as ``telemetry-<stamp>.json``);
- ``prometheus_text()`` — the ``/v1/metrics`` exposition body;
- ``spans(...)``     — recent span records for inspection/tests.
"""

from __future__ import annotations

from .prom import CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE
from .prom import render as render_prometheus
from .registry import DEFAULT_BUCKETS, Counter, Gauge, Histogram, Registry
from .spans import (
    TRACE_HEADER,
    SpanLog,
    current_trace_id,
    new_trace_id,
    sanitize_trace_id,
    set_trace_id,
    trace,
)
from .timeseries import TimeSeriesSampler, histogram_quantile, read_rss_mib

_REGISTRY = Registry()
_SPANS = SpanLog(_REGISTRY)


def get_registry() -> Registry:
    return _REGISTRY


def enabled() -> bool:
    return _REGISTRY.enabled


def set_enabled(value: bool) -> None:
    """Flip the whole plane on/off at runtime (bench overhead A/B, tests)."""
    _REGISTRY.enabled = bool(value)


def counter(name: str, help: str = "", **labels) -> Counter:
    return _REGISTRY.counter(name, help=help, **labels)


def gauge(name: str, help: str = "", **labels) -> Gauge:
    return _REGISTRY.gauge(name, help=help, **labels)


def histogram(name: str, help: str = "", buckets=DEFAULT_BUCKETS, **labels) -> Histogram:
    return _REGISTRY.histogram(name, help=help, buckets=buckets, **labels)


def span(name: str, **attrs):
    """Context manager: time a block, record a span carrying the current
    trace id."""
    return _SPANS.span(name, **attrs)


def spans(name: str | None = None, trace_id: str | None = None) -> list:
    return _SPANS.recent(name=name, trace_id=trace_id)


def snapshot(include_spans: int = 200) -> dict:
    """JSON-ready merged view: all series, metadata, and the newest
    ``include_spans`` span records."""
    snap = _REGISTRY.snapshot()
    out = {
        "enabled": _REGISTRY.enabled,
        "counters": [
            {"name": name, "labels": dict(labels), "value": value}
            for (name, labels), value in sorted(snap["counters"].items())
        ],
        "gauges": [
            {"name": name, "labels": dict(labels), "value": value}
            for (name, labels), value in sorted(snap["gauges"].items())
        ],
        "histograms": [
            {"name": name, "labels": dict(labels), **hist}
            for (name, labels), hist in sorted(snap["histograms"].items())
        ],
    }
    if include_spans:
        out["spans"] = _SPANS.recent()[-include_spans:]
    return out


def prometheus_text() -> str:
    return render_prometheus(_REGISTRY.snapshot())


def reset() -> None:
    """Zero every series and drop recorded spans (tests, bench reruns)."""
    _REGISTRY.reset()
    _SPANS.reset()


__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "SpanLog",
    "TimeSeriesSampler",
    "histogram_quantile",
    "read_rss_mib",
    "DEFAULT_BUCKETS",
    "PROMETHEUS_CONTENT_TYPE",
    "TRACE_HEADER",
    "counter",
    "gauge",
    "histogram",
    "span",
    "spans",
    "snapshot",
    "prometheus_text",
    "render_prometheus",
    "get_registry",
    "enabled",
    "set_enabled",
    "reset",
    "trace",
    "set_trace_id",
    "current_trace_id",
    "new_trace_id",
    "sanitize_trace_id",
]
