"""Process-wide metrics registry: counters, gauges, histograms.

Design constraints (ISSUE 2): zero dependencies, enabled by default,
cheap enough to sit on the REST/crypto/store hot paths, with an honest
disabled-mode no-op (one attribute load + branch per operation).

Hot-path writes go to *thread-local shards* — a per-thread dict of
``key -> int`` for counters and ``key -> _HistCell`` for histograms — so
the common case takes no lock at all (the GIL makes each individual dict
update atomic). ``snapshot()`` merges every live shard plus the retired
pool under one lock. Shards of dead threads are folded into the retired
pool by a ``weakref.finalize`` on the thread-local holder (the same
lifecycle trick ``native/bignum._Scratch`` uses for BN_CTX state), so a
thread-per-request HTTP server does not leak a shard per request thread
and totals stay exact across thread deaths.

Metric identity is ``(name, sorted(label items))``. Handles are cached on
the registry, so call sites may re-resolve ``counter(...)`` per event or
hold the handle — holding it is cheaper and is what the instrumented hot
paths do.
"""

from __future__ import annotations

import bisect
import os
import threading
import weakref

#: default histogram buckets (seconds): tuned for request/op latencies
#: from ~100us (mem-store gets) to tens of seconds (engine steps)
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class _HistCell:
    """Per-(shard, metric) histogram accumulator."""

    __slots__ = ("counts", "sum", "count", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1 for the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.max = 0.0


class _Shard:
    """One thread's unlocked write buffer."""

    __slots__ = ("counters", "hists", "__weakref__")

    def __init__(self):
        self.counters: dict = {}
        self.hists: dict = {}


class _ShardHolder:
    """Lives in a ``threading.local`` slot; its collection (thread death)
    triggers the finalizer that folds the shard into the retired pool."""

    __slots__ = ("shard", "__weakref__")

    def __init__(self, shard: _Shard):
        self.shard = shard


class Counter:
    __slots__ = ("_registry", "name", "labels", "_key")

    def __init__(self, registry: "Registry", name: str, labels: dict):
        self._registry = registry
        self.name = name
        self.labels = dict(labels)
        self._key = (name, _labels_key(labels))

    def inc(self, delta: int = 1) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        counters = reg._shard().counters
        key = self._key
        counters[key] = counters.get(key, 0) + delta

    def value(self) -> int:
        """Merged current value (snapshot-priced; not for hot paths)."""
        return self._registry.snapshot()["counters"].get(self._key, 0)


class Gauge:
    """Last-write-wins; writes go straight to a registry-level dict
    (one GIL-atomic store — no shard needed, merging gauges is meaningless)."""

    __slots__ = ("_registry", "name", "labels", "_key")

    def __init__(self, registry: "Registry", name: str, labels: dict):
        self._registry = registry
        self.name = name
        self.labels = dict(labels)
        self._key = (name, _labels_key(labels))

    def set(self, value: float) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        reg._gauges[self._key] = value


class Histogram:
    __slots__ = ("_registry", "name", "labels", "_key", "buckets")

    def __init__(self, registry: "Registry", name: str, labels: dict, buckets: tuple):
        self._registry = registry
        self.name = name
        self.labels = dict(labels)
        self._key = (name, _labels_key(labels))
        self.buckets = buckets

    def observe(self, value: float) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        hists = reg._shard().hists
        cell = hists.get(self._key)
        if cell is None:
            cell = hists[self._key] = _HistCell(len(self.buckets))
        cell.counts[bisect.bisect_left(self.buckets, value)] += 1
        cell.sum += value
        cell.count += 1
        if value > cell.max:
            cell.max = value


def _retire_shard(registry: "Registry", shard: _Shard) -> None:
    """finalize callback: fold a dead thread's shard into the retired pool
    so its totals survive (runs on whatever thread drives GC)."""
    with registry._lock:
        _merge_counters(registry._retired_counters, shard.counters)
        _merge_hists(registry._retired_hists, shard.hists)


def _merge_counters(into: dict, frm: dict) -> None:
    for key, v in list(frm.items()):
        into[key] = into.get(key, 0) + v


def _merge_hists(into: dict, frm: dict) -> None:
    for key, cell in list(frm.items()):
        tgt = into.get(key)
        if tgt is None:
            tgt = into[key] = _HistCell(len(cell.counts) - 1)
        for i, c in enumerate(cell.counts):
            tgt.counts[i] += c
        tgt.sum += cell.sum
        tgt.count += cell.count
        if cell.max > tgt.max:
            tgt.max = cell.max


class Registry:
    def __init__(self, enabled: bool | None = None):
        if enabled is None:
            enabled = os.environ.get("SDA_TELEMETRY", "1") != "0"
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._live_shards: "weakref.WeakSet[_Shard]" = weakref.WeakSet()
        self._retired_counters: dict = {}
        self._retired_hists: dict = {}
        self._gauges: dict = {}
        #: metric metadata: name -> (kind, buckets|None, help); registered at
        #: handle creation so the exposition can emit TYPE lines for series
        #: that exist but have no samples yet
        self._meta: dict = {}
        self._handles: dict = {}

    # -- shard lifecycle -----------------------------------------------------

    def _shard(self) -> _Shard:
        holder = getattr(self._local, "holder", None)
        if holder is None:
            shard = _Shard()
            holder = _ShardHolder(shard)
            weakref.finalize(holder, _retire_shard, self, shard)
            self._local.holder = holder
            with self._lock:
                self._live_shards.add(shard)
        return holder.shard

    # -- handle factories ----------------------------------------------------

    def _handle(self, kind: str, cls, name: str, labels: dict, buckets=None, help=""):
        key = (kind, name, _labels_key(labels))
        handle = self._handles.get(key)
        if handle is None:
            with self._lock:
                handle = self._handles.get(key)
                if handle is None:
                    prior = self._meta.get(name)
                    if prior is not None and prior[0] != kind:
                        raise ValueError(
                            f"metric {name} already registered as {prior[0]}"
                        )
                    self._meta[name] = (kind, buckets, help or (prior[2] if prior else ""))
                    args = (self, name, labels) if buckets is None else (
                        self, name, labels, buckets
                    )
                    handle = cls(*args)
                    self._handles[key] = handle
        return handle

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._handle("counter", Counter, name, labels, help=help)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._handle("gauge", Gauge, name, labels, help=help)

    def histogram(
        self, name: str, help: str = "", buckets: tuple = DEFAULT_BUCKETS, **labels
    ) -> Histogram:
        return self._handle(
            "histogram", Histogram, name, labels, buckets=tuple(buckets), help=help
        )

    # -- read side -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Merged view of every shard + the retired pool.

        Returns ``{"counters": {key: int}, "gauges": {key: float},
        "histograms": {key: {buckets, counts, sum, count, max}},
        "meta": {name: (kind, buckets, help)}}`` with
        ``key = (name, ((label, value), ...))``. Totals are exact for all
        work that happened-before the call (in-flight increments on other
        threads may or may not be visible — the usual counter contract)."""
        counters: dict = {}
        hists: dict = {}
        with self._lock:
            _merge_counters(counters, self._retired_counters)
            _merge_hists(hists, self._retired_hists)
            for shard in list(self._live_shards):
                _merge_counters(counters, shard.counters)
                _merge_hists(hists, shard.hists)
            gauges = dict(self._gauges)
            meta = dict(self._meta)
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {
                key: {
                    "buckets": self._buckets_of(key[0], meta),
                    "counts": list(cell.counts),
                    "sum": cell.sum,
                    "count": cell.count,
                    "max": cell.max,
                }
                for key, cell in hists.items()
            },
            "meta": meta,
        }

    @staticmethod
    def _buckets_of(name: str, meta: dict):
        entry = meta.get(name)
        return list(entry[1]) if entry and entry[1] else list(DEFAULT_BUCKETS)

    def reset(self) -> None:
        """Clear every series (tests/bench reruns). Live shards are wiped
        in place; handles and metadata survive so held references stay
        valid."""
        with self._lock:
            self._retired_counters.clear()
            self._retired_hists.clear()
            self._gauges.clear()
            for shard in list(self._live_shards):
                shard.counters.clear()
                shard.hists.clear()
