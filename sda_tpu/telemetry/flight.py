"""Round flight recorder: per-trace span assembly and export.

The SpanLog ring answers "what spans happened recently"; a soak
investigation needs "what did round 317 *look like*" — which stage was
the critical path, how well did the download/compute pipeline overlap,
where did the wall-clock go. This module stitches the flat span records
sharing one trace id into that picture:

- ``chrome_trace(spans)`` exports Chrome trace-event JSON (load it in
  ``chrome://tracing`` or https://ui.perfetto.dev): one "X" complete
  event per span with microsecond timestamps, grouped into per-stage
  tracks (``ingest``, ``clerk``, ``reveal``, ``rest``, ``store``, ...)
  via thread-name metadata events;
- ``round_report(spans)`` computes the numbers ``scripts/trace_report.py``
  prints: a per-stage waterfall (offset/duration/share of wall clock),
  overlap efficiency (how much span time ran concurrently with other
  spans), and the greedy critical path through the timeline.

Input is the plain span-record shape the ring stores —
``{name, trace_id, start (epoch s), duration_s, attrs}`` — so both the
live ring (``telemetry.spans(trace_id=...)``) and spans banked inside a
``soak-*.json`` artifact feed it unchanged. Export is deterministic for
a fixed span list: ties sort on (start, name), ids are assigned in
sorted order, and nothing consults the clock.
"""

from __future__ import annotations

import json

#: span-name prefix -> display track (tid) for the trace viewer; prefixes
#: are matched longest-first so e.g. "clerk.chunk" beats "clerk"
_TRACKS = (
    ("ingest", 1),
    ("client", 2),
    ("clerk", 3),
    ("reveal", 4),
    ("rest", 5),
    ("http", 5),
    ("service", 6),
    ("store", 7),
    ("crypto", 8),
)
_OTHER_TRACK = 9

_TRACK_NAMES = {
    1: "ingest",
    2: "client",
    3: "clerk",
    4: "reveal",
    5: "rest",
    6: "service",
    7: "store",
    8: "crypto",
    9: "other",
}


def _track_of(name: str) -> int:
    for prefix, tid in _TRACKS:
        if name == prefix or name.startswith(prefix + "."):
            return tid
    return _OTHER_TRACK


def _stage_of(name: str) -> str:
    """Waterfall grouping key: the first dotted component."""
    return name.split(".", 1)[0]


def _finished(spans) -> list:
    """Finished spans only (a live ring may hold records mid-flight),
    sorted deterministically by (start, name)."""
    out = [s for s in spans if s.get("duration_s") is not None]
    out.sort(key=lambda s: (s["start"], s["name"]))
    return out


# -- Chrome trace-event export ----------------------------------------------


def chrome_trace(spans, pid: int = 1) -> dict:
    """Chrome trace-event JSON for a span list (Perfetto-loadable).

    Timestamps are microseconds relative to the earliest span start, so
    the viewer opens at t=0 regardless of wall-clock epoch.
    """
    spans = _finished(spans)
    events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": "sda-round"},
        }
    ]
    used_tracks = sorted({_track_of(s["name"]) for s in spans})
    for tid in used_tracks:
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": _TRACK_NAMES[tid]},
            }
        )
        events.append(
            {
                "name": "thread_sort_index",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"sort_index": tid},
            }
        )
    t0 = spans[0]["start"] if spans else 0.0
    for s in spans:
        args = {"trace_id": s.get("trace_id")}
        if s.get("attrs"):
            args.update(s["attrs"])
        events.append(
            {
                "name": s["name"],
                "cat": _stage_of(s["name"]),
                "ph": "X",
                "pid": pid,
                "tid": _track_of(s["name"]),
                "ts": round((s["start"] - t0) * 1e6, 1),
                "dur": round(s["duration_s"] * 1e6, 1),
                "args": args,
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def chrome_trace_json(spans, pid: int = 1) -> str:
    return json.dumps(chrome_trace(spans, pid=pid), indent=1, sort_keys=True)


# -- interval math -----------------------------------------------------------


def _union_coverage(intervals) -> float:
    """Total length covered by a union of (start, end) intervals."""
    if not intervals:
        return 0.0
    intervals = sorted(intervals)
    covered = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            covered += cur_e - cur_s
            cur_s, cur_e = s, e
        elif e > cur_e:
            cur_e = e
    return covered + (cur_e - cur_s)


def critical_path(spans) -> list:
    """Greedy walk over the timeline: at each point pick, among spans
    covering it, the one reaching furthest; gaps jump to the next start.

    Returns the chosen span records in order. For a pipelined round this
    reads as "the stage that was holding the wall clock at each moment".
    """
    spans = _finished(spans)
    if not spans:
        return []
    path = []
    t = spans[0]["start"]
    i = 0
    n = len(spans)
    while i < n:
        best = None
        j = i
        while j < n and spans[j]["start"] <= t + 1e-12:
            end = spans[j]["start"] + spans[j]["duration_s"]
            if best is None or end > best["start"] + best["duration_s"]:
                best = spans[j]
            j += 1
        if best is None:
            t = spans[i]["start"]  # gap: jump to the next span's start
            continue
        path.append(best)
        t = max(t, best["start"] + best["duration_s"])
        while i < n and spans[i]["start"] <= t + 1e-12 and (
            spans[i]["start"] + spans[i]["duration_s"] <= t + 1e-12
        ):
            i += 1
    return path


# -- round report ------------------------------------------------------------


def round_report(spans) -> dict:
    """The numbers behind ``scripts/trace_report.py``:

    - ``wall_s`` — earliest start to latest end;
    - ``busy_s`` — union coverage (time with >=1 span running);
    - ``span_s`` — sum of all span durations;
    - ``overlap_efficiency`` — (span_s - busy_s) / span_s: 0 means fully
      sequential, ->1 means heavily pipelined;
    - ``stages`` — per-stage waterfall rows, ordered by first start:
      {stage, spans, offset_s, busy_s, span_s, share} where share is
      busy_s / wall_s;
    - ``tier_close`` — one row per ``tier.close`` span: the level's
      dispatch mode/width and the per-level ``overlap_efficiency`` the
      fanned-out driver stamped on the span (client/tiers.py);
    - ``critical_path`` — {name, offset_s, duration_s} hops.
    """
    spans = _finished(spans)
    if not spans:
        return {
            "spans": 0,
            "wall_s": 0.0,
            "busy_s": 0.0,
            "span_s": 0.0,
            "overlap_efficiency": 0.0,
            "stages": [],
            "tier_close": [],
            "critical_path": [],
        }
    t0 = spans[0]["start"]
    t1 = max(s["start"] + s["duration_s"] for s in spans)
    wall = t1 - t0
    span_sum = sum(s["duration_s"] for s in spans)
    busy = _union_coverage(
        [(s["start"], s["start"] + s["duration_s"]) for s in spans]
    )

    stages: dict = {}
    order: list = []
    for s in spans:
        stage = _stage_of(s["name"])
        if stage not in stages:
            stages[stage] = {"spans": [], "first": s["start"]}
            order.append(stage)
        stages[stage]["spans"].append(s)
    stage_rows = []
    for stage in order:
        group = stages[stage]["spans"]
        g_busy = _union_coverage(
            [(s["start"], s["start"] + s["duration_s"]) for s in group]
        )
        stage_rows.append(
            {
                "stage": stage,
                "spans": len(group),
                "offset_s": round(stages[stage]["first"] - t0, 6),
                "busy_s": round(g_busy, 6),
                "span_s": round(sum(s["duration_s"] for s in group), 6),
                "share": round(g_busy / wall, 4) if wall > 0 else 0.0,
            }
        )

    tier_rows = []
    for s in spans:
        if s["name"] != "tier.close":
            continue
        attrs = s.get("attrs") or {}
        tier_rows.append(
            {
                "tier": attrs.get("tier"),
                "mode": attrs.get("mode"),
                "width": attrs.get("width"),
                "nodes": attrs.get("nodes"),
                "overlap_efficiency": attrs.get("overlap_efficiency"),
                "duration_s": round(s["duration_s"], 6),
            }
        )

    path = [
        {
            "name": s["name"],
            "offset_s": round(s["start"] - t0, 6),
            "duration_s": round(s["duration_s"], 6),
        }
        for s in critical_path(spans)
    ]
    return {
        "spans": len(spans),
        "wall_s": round(wall, 6),
        "busy_s": round(busy, 6),
        "span_s": round(span_sum, 6),
        "overlap_efficiency": round((span_sum - busy) / span_sum, 4)
        if span_sum > 0
        else 0.0,
        "stages": stage_rows,
        "tier_close": tier_rows,
        "critical_path": path,
    }


def traces_in(spans) -> list:
    """Distinct trace ids in a span list, ordered by first appearance,
    with span counts: [{trace_id, spans, wall_s}]."""
    seen: dict = {}
    order: list = []
    for s in _finished(spans):
        tid = s.get("trace_id")
        if tid is None:
            continue
        if tid not in seen:
            seen[tid] = []
            order.append(tid)
        seen[tid].append(s)
    out = []
    for tid in order:
        group = seen[tid]
        t0 = min(s["start"] for s in group)
        t1 = max(s["start"] + s["duration_s"] for s in group)
        out.append({"trace_id": tid, "spans": len(group), "wall_s": round(t1 - t0, 6)})
    return out
