"""Lightweight spans with a propagated trace-id.

A *trace-id* is an opaque hex token that follows one logical operation
across layers: the client stamps it on every HTTP request
(``X-SDA-Trace``), the REST server adopts it for the handler thread, and
every ``span()`` recorded below — service, stores, crypto — carries it.
Propagation rides a ``contextvars.ContextVar``, so it is correct per
thread *and* per async task without any locking.

Spans are deliberately cheap records (name, trace_id, wall start,
duration, attrs), kept in a bounded ring buffer for inspection
(``recent()`` / the ``/v1/metrics.json`` view) and optionally mirrored as
structured JSON log lines keyed by trace-id (see :mod:`.logsink`).
"""

from __future__ import annotations

import contextlib
import contextvars
import re
import threading
import time
import uuid
from collections import deque

#: the wire header carrying the trace id (client -> REST -> service -> store)
TRACE_HEADER = "X-SDA-Trace"

#: accepted wire shape for an incoming trace id — anything else is replaced
#: rather than stored/logged verbatim (header values end up in log lines)
_TRACE_RE = re.compile(r"[A-Za-z0-9_.:-]{1,64}")

_trace_var: contextvars.ContextVar = contextvars.ContextVar(
    "sda_trace_id", default=None
)


def new_trace_id() -> str:
    return uuid.uuid4().hex


def current_trace_id():
    """The trace id bound to this context, or None."""
    return _trace_var.get()


def sanitize_trace_id(raw) -> str | None:
    """A safe trace id from an untrusted wire value, or None."""
    if not raw:
        return None
    raw = str(raw).strip()
    return raw if _TRACE_RE.fullmatch(raw) else None


@contextlib.contextmanager
def trace(trace_id: str | None = None):
    """Bind ``trace_id`` (fresh one if None) for the dynamic extent;
    yields the bound id."""
    token = _trace_var.set(trace_id or new_trace_id())
    try:
        yield _trace_var.get()
    finally:
        _trace_var.reset(token)


def set_trace_id(trace_id: str | None):
    """Imperatively bind a trace id (REST handler threads, where the
    request lifecycle doesn't nest as a ``with`` block)."""
    return _trace_var.set(trace_id)


class SpanLog:
    """Bounded ring of finished spans + the span() timing entry point."""

    def __init__(self, registry, maxlen: int = 4096):
        self._registry = registry
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=maxlen)

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Time a block; record {name, trace_id, start, duration_s, attrs}.

        Disabled telemetry short-circuits to a bare yield — no clock
        reads, no record, no log line."""
        if not self._registry.enabled:
            yield None
            return
        record = {
            "name": name,
            "trace_id": _trace_var.get(),
            "start": time.time(),
            "attrs": attrs or None,
        }
        t0 = time.perf_counter()
        try:
            yield record
        finally:
            record["duration_s"] = time.perf_counter() - t0
            with self._lock:
                self._spans.append(record)
            from .logsink import emit as _log_emit

            _log_emit("span", record)

    def recent(self, name: str | None = None, trace_id: str | None = None) -> list:
        """Finished spans, oldest first, optionally filtered by name
        prefix and/or exact trace id."""
        with self._lock:
            spans = list(self._spans)
        if name is not None:
            spans = [s for s in spans if s["name"].startswith(name)]
        if trace_id is not None:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        return spans

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
