"""Longitudinal telemetry: a background sampler over the registry.

The PR-2 registry answers point-in-time questions only — counters and
histograms accumulate since process start, so nobody can say "what was
p99 at minute 40 of a two-hour soak". This module closes that gap with a
*time-series sampler*: a daemon thread scrapes the process-global
registry on a fixed interval (``SDA_TS_INTERVAL_S``, default 5s),
subtracts the previous scrape to get **per-window deltas**, and derives
the longitudinal series a sustained soak is judged by:

- per-route request throughput (``sda_http_requests_total`` deltas) and
  windowed p50/p95/p99 latency via bucket interpolation over the
  window's ``sda_http_request_seconds`` bucket deltas;
- per-(store, op) rates and windowed p99 from ``sda_store_op_seconds``;
- wire payload bytes/s in each direction (``sda_wire_bytes_total``);
- process RSS (VmRSS from ``/proc/self/status``) and the crypto pool's
  last-dispatch utilization gauge;
- window rates for a small allowlist of volume counters (client
  participations, seals/opens, store rows, fault injections, retries).

Samples land in a bounded in-memory window (``SDA_TS_WINDOW``, default
720 — one hour at the default interval) served by the unauthenticated
``GET /v1/metrics/history`` REST route, and optionally in a bounded
on-disk JSONL ring (``SDA_TS_FILE`` / ``SDA_TS_FILE_MAX_BYTES``): when
the file outgrows the bound it is atomically rewritten keeping the
newest half, so a week-long soak can't fill the disk.

Every banked window also increments ``sda_ts_samples_total`` in the
registry it samples, so a Prometheus scrape (and scripts/check_metrics.py)
can verify the sampler is alive.

Lifecycle: the asyncio REST server acquires the process-wide sampler in
``serve_forever`` and releases it at shutdown (refcounted — N in-process
servers share one thread); ``SDA_TS=0`` disables the autostart.
Everything is also directly constructible (``TimeSeriesSampler`` with an
explicit registry and manual ``sample_once()`` ticks) for tests and the
soak rider's A/B arms.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

# -- knobs -------------------------------------------------------------------


def _interval_s() -> float:
    """Sampling interval (``SDA_TS_INTERVAL_S``, default 5s)."""
    try:
        return max(0.01, float(os.environ.get("SDA_TS_INTERVAL_S", "5")))
    except ValueError:
        return 5.0


def _window() -> int:
    """In-memory samples retained (``SDA_TS_WINDOW``, default 720)."""
    try:
        return max(1, int(os.environ.get("SDA_TS_WINDOW", "720")))
    except ValueError:
        return 720


def _file_max_bytes() -> int:
    """On-disk JSONL ring bound (``SDA_TS_FILE_MAX_BYTES``, default 16 MiB)."""
    try:
        return max(4096, int(os.environ.get("SDA_TS_FILE_MAX_BYTES", str(16 << 20))))
    except ValueError:
        return 16 << 20


# -- process RSS -------------------------------------------------------------


def read_rss_kib() -> int:
    """Current VmRSS in KiB from /proc/self/status (0 where unreadable)."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    return 0


def read_rss_mib() -> float:
    return round(read_rss_kib() / 1024.0, 2)


# -- windowed quantile math --------------------------------------------------


def histogram_quantile(q: float, buckets, counts):
    """Bucket-interpolated quantile over one window's bucket-count deltas.

    ``buckets`` are the finite upper edges; ``counts`` has one extra
    trailing entry for the +Inf bucket (the registry's layout: value v
    lands in the first bucket whose edge >= v, i.e. bucket i covers
    (edge[i-1], edge[i]]). Linear interpolation inside the containing
    bucket, Prometheus ``histogram_quantile`` style; observations in the
    +Inf bucket clamp to the top finite edge. Returns None on an empty
    window.
    """
    total = sum(counts)
    if total <= 0:
        return None
    q = min(1.0, max(0.0, q))
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c and cum + c >= target:
            if i >= len(buckets):
                return float(buckets[-1])  # +Inf bucket: clamp
            lo = 0.0 if i == 0 else float(buckets[i - 1])
            hi = float(buckets[i])
            return lo + (hi - lo) * ((target - cum) / c)
        cum += c
    return float(buckets[-1])


def _delta_counts(new: list, old) -> list:
    """Element-wise window delta, clamped at zero (a registry reset mid-
    window must yield an empty-ish window, not negative counts)."""
    if not old:
        return list(new)
    return [max(0, n - o) for n, o in zip(new, old)]


# -- the sampler -------------------------------------------------------------

#: counter families whose window *rates* ride along in every sample
#: (labels summed away); the soak rider reads fault/retry activity here
_RATE_COUNTERS = (
    "sda_client_participations_total",
    "sda_crypto_seals_total",
    "sda_crypto_opens_total",
    "sda_store_rows_written_total",
    "sda_fault_injections_total",
    "sda_rest_retries_total",
    "sda_rest_shed_total",
    "sda_slow_requests_total",
)


class TimeSeriesSampler:
    """Scrape-and-difference sampler over one registry.

    ``start()``/``stop()`` manage the daemon thread; ``sample_once()``
    is the synchronous tick (tests and the thread both call it).
    """

    def __init__(self, registry=None, interval_s: float | None = None,
                 window: int | None = None, path: str | None = None,
                 max_bytes: int | None = None):
        if registry is None:
            from .. import telemetry

            registry = telemetry.get_registry()
        self.registry = registry
        self.interval_s = float(interval_s if interval_s is not None else _interval_s())
        self.path = path if path is not None else os.environ.get("SDA_TS_FILE")
        self.max_bytes = int(max_bytes if max_bytes is not None else _file_max_bytes())
        self._samples: deque = deque(maxlen=window if window is not None else _window())
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._file_bytes = 0
        self._samples_total = registry.counter(
            "sda_ts_samples_total",
            "time-series windows banked by the background sampler",
        )
        # baseline: deltas of the first sample are measured against the
        # state at construction, not against zero (a sampler attached to
        # a warm process must not report the whole history as one window)
        self._prev_t = time.time()
        self._prev = self._scrape()

    # -- scrape + delta ------------------------------------------------------

    def _scrape(self) -> dict:
        snap = self.registry.snapshot()
        return {
            "counters": dict(snap["counters"]),
            "gauges": dict(snap["gauges"]),
            "hists": {
                key: (hist["buckets"], list(hist["counts"]))
                for key, hist in snap["histograms"].items()
            },
        }

    @staticmethod
    def _label(labels: tuple, name: str):
        for k, v in labels:
            if k == name:
                return v
        return None

    def sample_once(self, now: float | None = None) -> dict:
        """One synchronous tick: scrape, difference against the previous
        scrape, bank the sample (memory + optional JSONL ring)."""
        now = time.time() if now is None else now
        cur = self._scrape()
        prev, prev_t = self._prev, self._prev_t
        self._prev, self._prev_t = cur, now
        dt = max(1e-9, now - prev_t)

        counter_deltas: dict = {}
        for key, value in cur["counters"].items():
            d = value - prev["counters"].get(key, 0)
            if d > 0:
                counter_deltas[key] = d

        hist_deltas: dict = {}
        for key, (buckets, counts) in cur["hists"].items():
            old = prev["hists"].get(key)
            d = _delta_counts(counts, old[1] if old else None)
            if sum(d) > 0:
                hist_deltas[key] = (buckets, d)

        # per-route throughput + windowed latency quantiles
        routes: dict = {}
        for (name, labels), d in counter_deltas.items():
            if name != "sda_http_requests_total":
                continue
            route = self._label(labels, "route")
            if route:
                entry = routes.setdefault(route, {"rps": 0.0})
                entry["rps"] = round(entry["rps"] + d / dt, 3)
        for (name, labels), (buckets, d) in hist_deltas.items():
            if name != "sda_http_request_seconds":
                continue
            route = self._label(labels, "route")
            if not route:
                continue
            entry = routes.setdefault(route, {"rps": 0.0})
            merged = entry.setdefault("_counts", [0] * len(d))
            entry.setdefault("_buckets", buckets)
            for i, c in enumerate(d):
                merged[i] += c
        for entry in routes.values():
            counts = entry.pop("_counts", None)
            buckets = entry.pop("_buckets", None)
            if counts:
                for q, field in ((0.5, "p50_s"), (0.95, "p95_s"), (0.99, "p99_s")):
                    v = histogram_quantile(q, buckets, counts)
                    if v is not None:
                        entry[field] = round(v, 6)

        # per-(store, op) rates + windowed p99
        store_ops: dict = {}
        for (name, labels), (buckets, d) in hist_deltas.items():
            if name != "sda_store_op_seconds":
                continue
            key = f"{self._label(labels, 'store')}.{self._label(labels, 'op')}"
            n = sum(d)
            entry = {"ops_s": round(n / dt, 3)}
            p99 = histogram_quantile(0.99, buckets, d)
            if p99 is not None:
                entry["p99_s"] = round(p99, 6)
            store_ops[key] = entry

        wire = {"in": 0, "out": 0}
        for (name, labels), d in counter_deltas.items():
            if name == "sda_wire_bytes_total":
                direction = self._label(labels, "direction")
                if direction in wire:
                    wire[direction] += d

        rates: dict = {}
        for (name, labels), d in counter_deltas.items():
            if name in _RATE_COUNTERS:
                rates[name] = round(rates.get(name, 0.0) + d / dt, 3)

        # per-shard routing rates (the sharded store's request split);
        # empty on unsharded deployments, so the column only appears when
        # there are shards to observe
        shards: dict = {}
        for (name, labels), d in counter_deltas.items():
            if name != "sda_shard_requests_total":
                continue
            shard = self._label(labels, "shard")
            if shard is not None:
                shards[shard] = round(shards.get(shard, 0.0) + d / dt, 3)

        pool_util = None
        for (name, labels), value in cur["gauges"].items():
            if name == "sda_pool_utilization":
                pool_util = value

        sample = {
            "t": round(now, 3),
            "dt_s": round(dt, 3),
            "rss_mib": read_rss_mib(),
            "routes": routes,
            "store_ops": store_ops,
            "wire_bytes_per_s": {
                k: round(v / dt, 1) for k, v in wire.items()
            },
            "rates": rates,
        }
        if shards:
            sample["shards"] = shards
        if pool_util is not None:
            sample["pool_utilization"] = round(pool_util, 4)

        with self._lock:
            self._samples.append(sample)
        self._samples_total.inc()
        if self.path:
            self._append_to_ring(sample)
        return sample

    # -- on-disk JSONL ring --------------------------------------------------

    def _append_to_ring(self, sample: dict) -> None:
        line = json.dumps(sample, separators=(",", ":")) + "\n"
        try:
            if self._file_bytes == 0 and os.path.exists(self.path):
                self._file_bytes = os.path.getsize(self.path)
            with open(self.path, "a") as fh:
                fh.write(line)
            self._file_bytes += len(line)
            if self._file_bytes > self.max_bytes:
                self._truncate_ring()
        except OSError:
            pass  # a full/read-only disk must never kill the sampler

    def _truncate_ring(self) -> None:
        """Atomically rewrite the ring keeping the newest lines that fit
        in half the bound — amortized O(1) per append."""
        with open(self.path) as fh:
            lines = fh.readlines()
        keep: list = []
        budget = self.max_bytes // 2
        size = 0
        for line in reversed(lines):
            if size + len(line) > budget:
                break
            keep.append(line)
            size += len(line)
        keep.reverse()
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as fh:
            fh.writelines(keep)
        os.replace(tmp, self.path)
        self._file_bytes = size

    # -- reads ---------------------------------------------------------------

    def history(self, n: int | None = None) -> list:
        """Newest-last banked samples (the last ``n`` if given)."""
        with self._lock:
            samples = list(self._samples)
        return samples[-n:] if n else samples

    # -- thread lifecycle ----------------------------------------------------

    def start(self) -> "TimeSeriesSampler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample_once()
                except Exception:
                    pass  # a bad scrape must not kill the series

        self._thread = threading.Thread(
            target=run, name="sda-ts-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)


# -- process-wide sampler (refcounted: N in-process servers, one thread) -----

_global_lock = threading.Lock()
_global_sampler: TimeSeriesSampler | None = None
_global_refs = 0


def acquire() -> TimeSeriesSampler:
    """Start (or join) the process-wide sampler; pair with ``release()``."""
    global _global_sampler, _global_refs
    with _global_lock:
        if _global_sampler is None:
            _global_sampler = TimeSeriesSampler().start()
        _global_refs += 1
        return _global_sampler


def release() -> None:
    global _global_sampler, _global_refs
    with _global_lock:
        if _global_refs > 0:
            _global_refs -= 1
        if _global_refs == 0 and _global_sampler is not None:
            _global_sampler.stop()
            _global_sampler = None


def get() -> TimeSeriesSampler | None:
    return _global_sampler


def merge_histories(histories, bucket_s: float | None = None) -> list:
    """Merge per-process ``/v1/metrics/history`` bodies into one fleet
    series.

    A multi-process deployment (N ``sdad httpd`` frontends plus committee
    daemons) has N independent samplers, each banking its own windows on
    its own clock. This aligns them on wall-clock buckets of ``bucket_s``
    seconds (default: the largest ``interval_s`` reported, else 5s) and
    folds every bucket's samples into one:

    - additive columns are **summed** across processes: route ``rps``,
      ``rates``, ``wire_bytes_per_s``, per-shard request rates, store-op
      ``ops_s``, and ``rss_mib`` (total fleet RSS);
    - latency quantiles are **maxed** — per-process quantiles cannot be
      re-aggregated without the underlying buckets, and the conservative
      fleet p99 is the slowest process's p99;
    - ``procs`` counts the processes contributing to the bucket, so a
      gap (dead frontend, late scrape) is visible instead of silently
      deflating the fleet rate.

    Accepts either full history bodies (``{"samples": [...]}``) or bare
    sample lists. Returns merged samples sorted by bucket time.
    """
    sample_lists = []
    intervals = []
    for h in histories:
        if isinstance(h, dict):
            sample_lists.append(h.get("samples") or [])
            if h.get("interval_s"):
                intervals.append(float(h["interval_s"]))
        else:
            sample_lists.append(list(h or []))
    if bucket_s is None:
        bucket_s = max(intervals) if intervals else 5.0
    bucket_s = max(1e-3, float(bucket_s))

    _QUANTS = ("p50_s", "p95_s", "p99_s")
    buckets: dict = {}
    for samples in sample_lists:
        for s in samples:
            key = int(s["t"] // bucket_s)
            m = buckets.setdefault(
                key,
                {
                    "t": (key + 1) * bucket_s,
                    "dt_s": bucket_s,
                    "procs": 0,
                    "rss_mib": 0.0,
                    "routes": {},
                    "store_ops": {},
                    "wire_bytes_per_s": {},
                    "rates": {},
                },
            )
            m["procs"] += 1
            m["rss_mib"] = round(m["rss_mib"] + s.get("rss_mib", 0.0), 2)
            for route, entry in (s.get("routes") or {}).items():
                out = m["routes"].setdefault(route, {"rps": 0.0})
                out["rps"] = round(out["rps"] + entry.get("rps", 0.0), 3)
                for q in _QUANTS:
                    if q in entry:
                        out[q] = max(out.get(q, 0.0), entry[q])
            for op, entry in (s.get("store_ops") or {}).items():
                out = m["store_ops"].setdefault(op, {"ops_s": 0.0})
                out["ops_s"] = round(out["ops_s"] + entry.get("ops_s", 0.0), 3)
                if "p99_s" in entry:
                    out["p99_s"] = max(out.get("p99_s", 0.0), entry["p99_s"])
            for k, v in (s.get("wire_bytes_per_s") or {}).items():
                m["wire_bytes_per_s"][k] = round(
                    m["wire_bytes_per_s"].get(k, 0.0) + v, 1
                )
            for k, v in (s.get("rates") or {}).items():
                m["rates"][k] = round(m["rates"].get(k, 0.0) + v, 3)
            for k, v in (s.get("shards") or {}).items():
                m.setdefault("shards", {})
                m["shards"][k] = round(m["shards"].get(k, 0.0) + v, 3)
    return [buckets[k] for k in sorted(buckets)]


def history(n: int | None = None) -> dict:
    """The ``/v1/metrics/history`` response body: sampler state + the
    newest ``n`` samples (all retained samples when ``n`` is omitted)."""
    sampler = _global_sampler
    if sampler is None:
        return {"running": False, "interval_s": None, "samples": []}
    return {
        "running": sampler._thread is not None,
        "interval_s": sampler.interval_s,
        "samples": sampler.history(n),
    }
