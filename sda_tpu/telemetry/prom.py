"""Prometheus text-format exposition (version 0.0.4) of a registry snapshot.

Pure string building over :meth:`Registry.snapshot` — no client library,
no HTTP. Series render in deterministic (sorted) order so two scrapes of
the same state are byte-identical, which the CI parse gate and the
replay-minded tests rely on.

Format notes:
- counters render as ``name{labels} value`` with ``# TYPE name counter``;
- histograms render cumulative ``name_bucket{le=...}`` plus ``_sum`` and
  ``_count`` (the ``le`` label is appended after user labels);
- label values are escaped per the exposition spec (backslash, quote,
  newline);
- metric names registered but never observed still emit HELP/TYPE, so a
  scrape taken before traffic proves the series exists.
"""

from __future__ import annotations

import re

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")


def _esc(value) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _name(raw: str) -> str:
    if _NAME_OK.fullmatch(raw):
        return raw
    safe = re.sub(r"[^a-zA-Z0-9_:]", "_", raw)
    return safe if _NAME_OK.fullmatch(safe) else "_" + safe


def _labelstr(labels: tuple, extra: str = "") -> str:
    parts = [f'{_name(k)}="{_esc(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(value)


def render(snapshot: dict, prefix_comment: str | None = None) -> str:
    """The full exposition for one registry snapshot."""
    meta = snapshot.get("meta", {})
    out: list = []
    if prefix_comment:
        out.append(f"# {prefix_comment}")

    by_name: dict = {}
    for key, value in snapshot.get("counters", {}).items():
        by_name.setdefault(key[0], []).append((key[1], "counter", value))
    for key, value in snapshot.get("gauges", {}).items():
        by_name.setdefault(key[0], []).append((key[1], "gauge", value))
    for key, hist in snapshot.get("histograms", {}).items():
        by_name.setdefault(key[0], []).append((key[1], "histogram", hist))
    # registered-but-unsampled series still announce themselves
    for name in meta:
        by_name.setdefault(name, [])

    for raw_name in sorted(by_name):
        name = _name(raw_name)
        kind, _, help_text = meta.get(raw_name, (None, None, ""))
        if kind is None:
            kind = by_name[raw_name][0][1] if by_name[raw_name] else "untyped"
        if help_text:
            out.append(f"# HELP {name} {_esc(help_text)}")
        out.append(f"# TYPE {name} {kind}")
        for labels, series_kind, value in sorted(
            by_name[raw_name], key=lambda item: item[0]
        ):
            if series_kind == "histogram":
                cumulative = 0
                bounds = [*value["buckets"], float("inf")]
                for bound, count in zip(bounds, value["counts"]):
                    cumulative += count
                    le = 'le="' + _fmt(bound) + '"'
                    out.append(f"{name}_bucket{_labelstr(labels, le)} {cumulative}")
                out.append(f"{name}_sum{_labelstr(labels)} {_fmt(value['sum'])}")
                out.append(f"{name}_count{_labelstr(labels)} {value['count']}")
            else:
                out.append(f"{name}{_labelstr(labels)} {_fmt(value)}")
    return "\n".join(out) + "\n"
