"""Pallas TPU kernel: fused per-participant limb share matmul + reduce.

The per-participant engine path (bench ``--engine participant``) computes
every participant's share limb-partials individually — (L, C·nb, n) int32
— and then reduces over participants. Under XLA those partials round-trip
HBM between the dot and the reduction. This kernel fuses them: each grid
step loads one participant block, runs the L const-folded limb dots
(``limbmatmul.fold_const_limbs``) on the MXU, reduces its block over the
participant axis in VMEM, and accumulates into the tiny (L, nb, n) output
— per-participant shares exist (transiently, like the reference's
per-phone loop) but never touch HBM.

Everything in-kernel is int32: partials are bounded by L·K·127² and the
participant accumulation by C_total·L·K·127², which must stay < 2^31
(checked at trace time — the bench chunk of 2000 is well inside). The
mod-p recombine (int64 multiply + one rem) happens outside on the reduced
accumulator, exactly like the jnp path.

Narrow fields only (p < 2^31: int32 limb extraction); the wide path keeps
the jnp formulation. CPU runs use the Pallas interpreter (tests).
"""

from __future__ import annotations

import numpy as np

from ..ops.jaxcfg import ensure_x64
from .limbmatmul import fold_const_limbs


def participant_limb_sums_pallas(values, stacks, block_c: int = 250):
    """(C, nb, K) int32 canonical values -> (L, nb, n) int32 partial sums.

    ``stacks`` from ``fold_const_limbs`` (L, L*K, n) int8. Drop-in for
    ``limb_partials_const`` + participant reduction with weights 128^m.
    ``block_c`` participants per grid step (VMEM-sized).
    """
    ensure_x64()
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C, nb, K = values.shape
    L, LK, n = stacks.shape
    if LK != L * K:
        raise ValueError(f"stacks contraction {LK} != L*K = {L * K}")
    if C * LK * 127 * 127 >= (1 << 31):
        raise ValueError(
            f"participant accumulation over C={C} overflows int32; chunk first"
        )
    if C % block_c != 0:
        # keep blocks VMEM-sized for odd C: the largest divisor <= block_c
        # (whole-C would be unbounded VMEM and fail to compile on TPUs)
        block_c = max(d for d in range(1, block_c + 1) if C % d == 0)
    n_blocks = C // block_c

    def kernel(values_ref, stacks_ref, out_ref):
        j = pl.program_id(0)
        x = values_ref[...].reshape(block_c * nb, K)  # int32 canonical
        a = jnp.concatenate(
            [
                ((x >> jnp.int32(7 * i)) & jnp.int32(0x7F)).astype(jnp.int8)
                for i in range(L)
            ],
            axis=-1,
        )  # (M, LK) int8
        for m in range(L):
            prod = lax.dot_general(
                a,
                stacks_ref[m],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # (M, n)
            # dtype pinned: under x64, jnp.sum(int32) promotes its
            # accumulator to int64, which Mosaic rejects; the int32 bound
            # is already guaranteed by the C*LK*127^2 trace-time check
            red = jnp.sum(
                prod.reshape(block_c, nb, n), axis=0, dtype=jnp.int32
            )  # (nb, n)

            @pl.when(j == 0)
            def _():
                out_ref[m] = red

            @pl.when(j > 0)
            def _():
                out_ref[m] += red

    from ..ops.jaxcfg import I32_ZERO as z  # literal 0 would trace as i64
    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(
                (block_c, nb, K), lambda j: (j, z, z), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((L, LK, n), lambda j: (z, z, z), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (L, nb, n), lambda j: (z, z, z), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((L, nb, n), jnp.int32),
        interpret=jax.default_backend() == "cpu",
    )(values, jnp.asarray(stacks))


def share_combine_limb_pallas(secrets, key, plan, draw=None):
    """Fused-kernel twin of ``engine.share_combine_limb`` for p < 2^31:
    same (W, b, n) int64 contract (weights 128^m), bit-identical results
    for the same key/draw."""
    ensure_x64()
    import jax.numpy as jnp

    from .engine import _batch_secrets, _device_randomness

    if draw is None:
        draw = _device_randomness
    p = plan.modulus
    if p >= (1 << 31):
        raise ValueError("pallas participant path is narrow-field only (p < 2^31)")
    batches = _batch_secrets(secrets, plan)  # (C, b, k)
    C, nb = batches.shape[0], batches.shape[1]
    randomness = draw(key, (C, nb, plan.rand_size), p)
    values = jnp.concatenate(
        [batches.astype(jnp.int32), randomness.astype(jnp.int32)], axis=-1
    )
    stacks = fold_const_limbs(plan.share_matrix.T, p)
    acc = participant_limb_sums_pallas(values, stacks)
    return acc.astype(jnp.int64)  # (W=L, b, n)
