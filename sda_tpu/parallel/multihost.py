"""Multi-host distribution: hybrid ICI x DCN meshes and hierarchical sums.

The reference's "distributed backend" is HTTP pull-queues between
independent phone processes (SURVEY.md §5 — no NCCL/MPI anywhere); it
scales hosts by adding more clerks. The TPU fabric's equivalent for
multi-host *pods* is jax.distributed + a hybrid mesh: a fast ICI axis
inside each slice and a slow DCN axis across hosts, with the reduction
staged so that only the tiny per-clerk partial sums ever cross DCN.

Topology mapping:

- axis ``h`` (hosts / slices, DCN): coarse participant sharding — each
  host ingests its own participant population, like each region of
  phones talking to its nearest collector.
- axis ``p`` (chips within a slice, ICI): fine participant sharding.
- The per-device work is the usual share+combine; the cross-device sum
  runs ``psum`` over ``p`` first (ICI — cheap, wide), then over ``h``
  (DCN — only ``(n, B)`` int64 partials, KBs, regardless of how many
  participants each host holds). Like the sum-first engine
  (parallel/sumfirst.py), linearity is what keeps the big tensors local.

Everything here is expressed in mesh axes, not transport: on one
process with 8 CPU devices the same code runs with ``h`` and ``p`` both
mapped to local devices (how tests and the driver dry-run validate it);
on a real multi-host pod the identical program runs under
``jax.distributed`` with ``h`` spanning slices.
"""

from __future__ import annotations


def initialize_distributed(coordinator_address=None, num_processes=None, process_id=None):
    """Join the multi-process JAX runtime (call once per host, before any
    jax op). Thin, explicit wrapper over ``jax.distributed.initialize`` —
    on TPU pods all three arguments are auto-detected from the metadata
    server and may be omitted."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_hybrid_mesh(h_size: int | None = None, p_size: int | None = None):
    """Mesh with axes ``("h", "p")``: hosts (DCN) x chips-per-host (ICI).

    Under ``jax.distributed`` with multiple processes, uses
    ``mesh_utils.create_hybrid_device_mesh`` so ``h`` is laid out across
    slices and ``p`` within them (collectives over ``p`` ride ICI).
    Single-process (tests, dry runs): plain reshape of local devices —
    same program, simulated topology.
    """
    import jax
    import numpy as np

    devices = jax.devices()
    n_proc = jax.process_count()
    if n_proc > 1:
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh

        h_size = h_size or n_proc
        p_size = p_size or (len(devices) // h_size)
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1, p_size),
            dcn_mesh_shape=(h_size, 1),
            devices=devices,
        )
        return Mesh(grid, ("h", "p"))
    from jax.sharding import Mesh

    if h_size is None:
        h_size = 2 if len(devices) % 2 == 0 and len(devices) > 1 else 1
    p_size = p_size or (len(devices) // h_size)
    need = h_size * p_size
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(h_size, p_size)
    return Mesh(grid, ("h", "p"))


def shard_participants_hybrid(array, mesh):
    """(P, dim) participants sharded over both host and chip axes."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(array, NamedSharding(mesh, P(("h", "p"), None)))


def hierarchical_clerk_sums(scheme, dim: int, mesh):
    """Jitted share+combine over a hybrid mesh with a staged reduction.

    Returns ``fn(secrets_sharded, key) -> (n, B)`` clerk sums (replicated).
    Stage 1 shares + locally combines each device's participant slice;
    stage 2 psums over ``p`` (ICI); stage 3 psums the already-reduced
    ``(n, B)`` partials over ``h`` (DCN) — the only cross-host traffic.
    Bit-identical to the single-mesh engine for the same key-folding
    layout (tested on a virtual hybrid mesh).
    """
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from .engine import TpuAggregator, clerk_combine, share_participants

    agg = TpuAggregator(scheme, dim, mesh=mesh)
    plan = agg.plan
    import jax.numpy as jnp

    def local_step(secrets, key):
        # distinct randomness per device: fold in both mesh coordinates
        key = jax.random.fold_in(key, lax.axis_index("h"))
        key = jax.random.fold_in(key, lax.axis_index("p"))
        shares = share_participants(secrets, key, plan, False)
        partial = lax.rem(clerk_combine(shares), jnp.int64(plan.modulus))
        partial = lax.rem(lax.psum(partial, axis_name="p"), jnp.int64(plan.modulus))
        # DCN stage: (n, B) int64 per host — KBs, independent of P
        total = lax.psum(partial, axis_name="h")
        return lax.rem(total, jnp.int64(plan.modulus))

    mapped = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(("h", "p"), None), P()),
        out_specs=P(),
        check_vma=False,
    )
    return agg, jax.jit(mapped)


def hierarchical_secure_sum(scheme, dim: int, mesh):
    """Full multi-host round: sharded share/combine + reconstruct + an
    independent plaintext-sum verification path (same contract as
    ``engine.full_training_step``, over the hybrid mesh)."""
    from .engine import verified_step

    agg, sums_fn = hierarchical_clerk_sums(scheme, dim, mesh)
    return agg, verified_step(agg, sums_fn)
