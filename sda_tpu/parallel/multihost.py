"""Multi-host distribution: hybrid ICI x DCN meshes and hierarchical sums.

The reference's "distributed backend" is HTTP pull-queues between
independent phone processes (SURVEY.md §5 — no NCCL/MPI anywhere); it
scales hosts by adding more clerks. The TPU fabric's equivalent for
multi-host *pods* is jax.distributed + a hybrid mesh: a fast ICI axis
inside each slice and a slow DCN axis across hosts, with the reduction
staged so that only the tiny per-clerk partial sums ever cross DCN.

Topology mapping:

- axis ``h`` (hosts / slices, DCN): coarse participant sharding — each
  host ingests its own participant population, like each region of
  phones talking to its nearest collector.
- axis ``p`` (chips within a slice, ICI): fine participant sharding.
- The per-device work is the usual share+combine; the cross-device sum
  runs ``psum`` over ``p`` first (ICI — cheap, wide), then over ``h``
  (DCN — only ``(n, B)`` int64 partials, KBs, regardless of how many
  participants each host holds). Like the sum-first engine
  (parallel/sumfirst.py), linearity is what keeps the big tensors local.

Everything here is expressed in mesh axes, not transport: on one
process with 8 CPU devices the same code runs with ``h`` and ``p`` both
mapped to local devices (how tests and the driver dry-run validate it);
on a real multi-host pod the identical program runs under
``jax.distributed`` with ``h`` spanning slices.
"""

from __future__ import annotations


def initialize_distributed(coordinator_address=None, num_processes=None, process_id=None):
    """Join the multi-process JAX runtime (call once per host, before any
    jax op). Thin, explicit wrapper over ``jax.distributed.initialize`` —
    on TPU pods all three arguments are auto-detected from the metadata
    server and may be omitted."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_hybrid_mesh(
    h_size: int | None = None, p_size: int | None = None, d_size: int = 1
):
    """Mesh with axes ``("h", "p", "d")``: hosts (DCN) x chips-per-host
    (ICI, participant axis) x dim batches (ICI, the dimension-batching /
    sequence-parallel axis for 100K-dim vectors).

    Under ``jax.distributed`` with multiple processes, uses
    ``mesh_utils.create_hybrid_device_mesh`` so ``h`` is laid out across
    slices and ``p``/``d`` within them (those collectives ride ICI).
    There ``h_size`` is *derived* from the topology (the slice count on
    multi-slice pods, else the process count); passing it explicitly is
    only a cross-check — a value that miscounts the granule raises.
    Single-process (tests, dry runs): plain reshape of local devices —
    same program, simulated topology — and ``h_size`` is free.
    """
    import jax
    import numpy as np

    devices = jax.devices()
    n_proc = jax.process_count()
    if n_proc > 1:
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh

        # multi-slice TPU pods: the DCN unit is the slice. Anywhere
        # slice_index doesn't distinguish devices (multi-process CPU
        # reports slice 0 everywhere; single-slice multi-host pods too),
        # the process is the outer-network unit — and the h default must
        # count the same granules the mesh builder will group by.
        slice_ids = {getattr(d, "slice_index", None) for d in devices}
        by_process = (None in slice_ids) or len(slice_ids) == 1
        granules = n_proc if by_process else len(slice_ids)
        if h_size is not None and h_size != granules:
            # the mesh builder groups devices by granule (process or
            # slice); an h_size counting the wrong unit — e.g. processes
            # on a multi-slice pod where the DCN unit is the slice —
            # would otherwise surface as an opaque reshape error deep in
            # create_hybrid_device_mesh
            unit = "process" if by_process else "slice"
            raise ValueError(
                f"h_size {h_size} != {granules} DCN granules: the outer "
                f"mesh axis is laid out per {unit} on this topology, so "
                f"h_size must equal the {unit} count ({granules}); omit "
                "h_size to use it"
            )
        h_size = granules
        p_size = p_size or (len(devices) // (h_size * d_size))
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1, p_size, d_size),
            dcn_mesh_shape=(h_size, 1, 1),
            devices=devices,
            process_is_granule=by_process,
        )
        return Mesh(grid, ("h", "p", "d"))
    from jax.sharding import Mesh

    if h_size is None:
        h_size = 2 if len(devices) % 2 == 0 and len(devices) > 1 else 1
    p_size = p_size or (len(devices) // (h_size * d_size))
    need = h_size * p_size * d_size
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(h_size, p_size, d_size)
    return Mesh(grid, ("h", "p", "d"))


def shard_participants_hybrid(array, mesh):
    """(P, dim) sharded: participants over host+chip axes, dim over d."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(array, NamedSharding(mesh, P(("h", "p"), "d")))


def hierarchical_clerk_sums(scheme, dim: int, mesh):
    """Jitted share+combine over a hybrid mesh with a staged reduction.

    Returns ``fn(secrets_sharded, key) -> (n, B)`` clerk sums (replicated).
    Stage 1 shares + locally combines each device's participant slice;
    stage 2 psums over ``p`` (ICI); stage 3 psums the already-reduced
    ``(n, B)`` partials over ``h`` (DCN) — the only cross-host traffic.
    Bit-identical to the single-mesh engine for the same key-folding
    layout (tested on a virtual hybrid mesh).
    """
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from .engine import (
        TpuAggregator,
        _check_psum_bound,
        clerk_combine_mod,
        share_participants,
    )

    agg = TpuAggregator(scheme, dim, mesh=mesh)
    plan = agg.plan
    agg.validate_d_sharding(dim)
    _check_psum_bound(mesh.shape["p"], plan.modulus, "hierarchical_clerk_sums(p)")
    _check_psum_bound(mesh.shape["h"], plan.modulus, "hierarchical_clerk_sums(h)")
    import jax.numpy as jnp

    from .engine import fold_mesh_axes

    def local_step(secrets, key):
        key = fold_mesh_axes(key, mesh)
        shares = share_participants(secrets, key, plan, False)
        partial = clerk_combine_mod(shares, plan.modulus)
        partial = lax.rem(lax.psum(partial, axis_name="p"), jnp.int64(plan.modulus))
        # DCN stage: (n, B_local) int64 per host — KBs, independent of P
        total = lax.psum(partial, axis_name="h")
        return lax.rem(total, jnp.int64(plan.modulus))

    from . import compat

    d_spec = "d" if "d" in mesh.axis_names else None
    mapped = compat.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(("h", "p"), d_spec), P()),
        out_specs=P(None, d_spec),  # clerk sums replicated; B stays d-sharded
        check_vma=False,
    )
    return agg, jax.jit(mapped)


def hierarchical_limb_accumulators(scheme, dim: int, mesh):
    """Wide-modulus (61-bit) twin of :func:`hierarchical_clerk_sums`.

    Per-device fused limb share+combine (no mod ops on device — see
    ``engine.sharded_limb_accumulators``), int64 partial psum over ``p``
    (ICI), then over ``h`` — the only DCN traffic is the tiny
    ``(W, B_local, n)`` accumulator. Epilogue: one exact host
    ``limb_recombine_host(acc, p).T`` then ``reconstruct``. int64 stays
    exact to ~5e12 total participants.

    Returns ``(agg, fn)`` with ``fn(secrets_sharded, key) -> (W, B, n)``
    int64 accumulators (replicated; B d-sharded).
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from . import compat
    from .engine import TpuAggregator

    agg = TpuAggregator(scheme, dim, mesh=mesh)
    agg.validate_d_sharding(dim)

    d_spec = "d" if "d" in mesh.axis_names else None
    mapped = compat.shard_map(
        # ICI ("p") before DCN ("h"): only the tiny accumulator crosses hosts
        agg._limb_accumulator_local_step(("p", "h")),
        mesh=mesh,
        in_specs=(P(("h", "p"), d_spec), P()),
        out_specs=P(None, d_spec, None),
        check_vma=False,
    )
    return agg, jax.jit(mapped)


def hierarchical_secure_sum(scheme, dim: int, mesh):
    """Full multi-host round: sharded share/combine + reconstruct + an
    independent plaintext-sum verification path (same contract as
    ``engine.full_training_step``, over the hybrid mesh)."""
    from .engine import verified_step

    agg, sums_fn = hierarchical_clerk_sums(scheme, dim, mesh)
    return agg, verified_step(agg, sums_fn)
