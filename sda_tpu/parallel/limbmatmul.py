"""Exact mod-p matmul on the MXU via base-128 limb decomposition.

TPUs have no native 64-bit integer multiply; XLA emulates int64 products in
many 32-bit VPU ops. But the MXU multiplies int8 x int8 -> int32 natively
and fast. So: decompose canonical residues (0 <= x < p < 2^31) into
base-128 limbs (values 0..127, stored int8), matmul every limb pair on the
MXU, and recombine partials with ``128^(i+j) mod p`` weights in int64.

Exactness bounds: each partial product <= 127*127; an int32 accumulator
holds K <= 2^31 / 127^2 = ~133k contraction elements. The share matmul
contracts over k+t (tiny); bigger contractions would chunk K. The limb
count is ceil(bits(p)/7), so a 31-bit modulus costs 25 int8 matmuls —
still far cheaper on the MXU than one emulated int64 matmul on the VPU.
"""

from __future__ import annotations


from ..ops.jaxcfg import ensure_x64

def _max_contraction(L: int) -> int:
    """int32 bound for one weight group: up to L partial matmuls summed,
    each elementwise <= K * 127^2."""
    return (1 << 31) // (127 * 127 * L)


def limb_count(p: int) -> int:
    return -(-p.bit_length() // 7)


def limb_partials(A, B, p: int):
    """Weight-grouped limb partial products of (M, K) @ (K, N) mod p.

    Returns int32 ``(W, M, N)`` with ``W = 2*L-1`` such that the true
    product is ``sum_w partials[w] * 128^w (mod p)``. This is the MXU-only
    piece: recombination (the int64 multiply/rem work) can be deferred —
    crucially, *summed over batch axes first* (linearity), which is how the
    clerk-combine keeps all mod-p arithmetic out of the participant loop.
    """
    ensure_x64()
    import jax.numpy as jnp
    from jax import lax

    K = A.shape[-1]
    L = limb_count(p)
    if K > _max_contraction(L):
        raise ValueError(f"contraction {K} overflows int32 accumulator; chunk first")

    def limbs(x, count):
        # canonical values < p < 2^31 fit int32: extract limbs in 32-bit
        # lanes (native on TPU) instead of emulated 64-bit shifts
        x = x.astype(jnp.int32) if p <= (1 << 31) else x.astype(jnp.int64)
        seven = x.dtype.type(0x7F)
        return [
            ((x >> x.dtype.type(7 * i)) & seven).astype(jnp.int8) for i in range(count)
        ]

    a_limbs = limbs(A, L)
    b_limbs = limbs(B, L)
    partials = [None] * (2 * L - 1)
    for i in range(L):
        for j in range(L):
            prod = lax.dot_general(
                a_limbs[i],
                b_limbs[j],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            w = i + j
            partials[w] = prod if partials[w] is None else partials[w] + prod
    return jnp.stack(partials)  # (W, M, N) int32


def limb_recombine(partials, p: int):
    """(W, ...) partials (each < 2^31) -> canonical mod-p values.

    int64 multiply + rem on whatever shape you pass — call this on the
    *reduced* accumulator, never inside the hot loop.
    """
    ensure_x64()
    import jax.numpy as jnp
    from jax import lax

    if p >= (1 << 31):
        raise ValueError(
            "device recombine needs p < 2^31 (weight products would overflow "
            "int64); reduce the accumulator and use limb_recombine_host"
        )
    W = partials.shape[0]
    weights = jnp.asarray(
        [pow(128, w, p) for w in range(W)], dtype=jnp.int64
    ).reshape((W,) + (1,) * (partials.ndim - 1))
    acc = jnp.sum(
        lax.rem(partials.astype(jnp.int64) * weights, jnp.int64(p)), axis=0
    )
    return lax.rem(acc, jnp.int64(p))


def limb_modmatmul(A, B, p: int):
    """(M, K) @ (K, N) mod p, inputs canonical [0, p), output canonical.

    Jittable; int8 MXU matmuls inside, int64 only in the recombine. When
    the product feeds a sum over a batch axis, prefer ``limb_partials`` +
    reduce + ``limb_recombine`` to keep the int64 work off the big tensor.
    """
    return limb_recombine(limb_partials(A, B, p), p)


def fold_const_limbs(B_host, p: int):
    """Weight-folded limb decomposition of a *constant* matrix B (K, N).

    For a host-known B (the share matrix: ops/shamir.py precomputes it once
    per scheme), the cross-limb weight structure can be folded into B ahead
    of time:  ``A @ B = Σ_i a_i·128^i @ B = Σ_i a_i @ (128^i·B mod p)``.
    Decomposing each ``D_i = 128^i·B mod p`` back into base-128 limbs
    ``d_{i,m}`` and stacking the ``i`` axis onto the contraction gives

        ``A @ B ≡ Σ_m 128^m · (A_limbs @ stacks[m])  (mod p)``

    with ``A_limbs = [a_0 | … | a_{L-1}]`` of shape (M, L·K). Compared to
    the generic ``limb_partials`` this is L matmuls instead of L² and L
    weight groups instead of 2L−1 — and each partial is bounded by
    ``L·K·127²``, small enough that the whole recombine needs ONE int64
    ``rem`` at the very end (no per-weight division on the big tensor).

    Returns int8 ``(L, L·K, N)`` stacks. Exact for any p (host python-int
    arithmetic); device recombine still requires p < 2^31.
    """
    import numpy as np

    L = limb_count(p)
    B_obj = np.asarray(B_host, dtype=object)
    K, N = B_obj.shape
    stacks = np.empty((L, L * K, N), dtype=np.int8)
    for i in range(L):
        D_i = (pow(128, i, p) * B_obj) % p
        for m in range(L):
            stacks[m, i * K : (i + 1) * K] = ((D_i >> (7 * m)) & 0x7F).astype(
                np.int8
            )
    return stacks


def limb_partials_const(A, stacks, p: int):
    """Weight-grouped partials of ``A @ B mod p`` from ``fold_const_limbs(B)``.

    ``A`` (M, K) canonical; returns int32 ``(L, M, N)`` such that the true
    product is ``Σ_m partials[m]·128^m (mod p)`` — drop-in for
    ``limb_partials`` (just a shorter weight axis) wherever B is constant,
    e.g. the fused share+combine hot loop. Each partial ≤ L·K·127².
    """
    ensure_x64()
    import jax.numpy as jnp
    from jax import lax

    L, LK, N = stacks.shape
    K = LK // L
    if A.shape[-1] != K:
        raise ValueError(f"A contraction {A.shape[-1]} != stacks K {K}")
    if LK * 127 * 127 >= (1 << 31):
        raise ValueError(f"contraction {LK} overflows int32 accumulator")

    x = A.astype(jnp.int32) if p <= (1 << 31) else A.astype(jnp.int64)
    seven = x.dtype.type(0x7F)
    a_limbs = jnp.concatenate(
        [((x >> x.dtype.type(7 * i)) & seven).astype(jnp.int8) for i in range(L)],
        axis=-1,
    )  # (M, L*K)
    import jax

    if jax.default_backend() == "cpu":
        # XLA's CPU emitter mis-fuses the int64->int8 limb extraction into
        # the int8 dot for some degenerate shapes (k=1 wide), producing
        # invalid IR ("add i32, i8"). A barrier cuts that fusion; the TPU
        # path (where a_limbs materializes for the L dots anyway) is left
        # untouched.
        a_limbs = lax.optimization_barrier(a_limbs)
    partials = [
        lax.dot_general(
            a_limbs,
            jnp.asarray(stacks[m]),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        for m in range(L)
    ]
    return jnp.stack(partials)  # (L, M, N) int32


def limb_modmatmul_const(A, B_host, p: int):
    """(M, K) @ const (K, N) mod p with one final division.

    The single-rem recombine is exact because every partial is bounded by
    ``L·K·127²`` (not 2^31): the weighted int64 accumulator stays below
    ``L · L·K·127² · (p−1)``, checked against 2^63 at trace time.
    """
    ensure_x64()
    import jax.numpy as jnp
    from jax import lax

    if p >= (1 << 31):
        raise ValueError(
            "device recombine needs p < 2^31; use limb_partials_const + "
            "reduce + limb_recombine_host"
        )
    stacks = fold_const_limbs(B_host, p)
    L, LK, _ = stacks.shape
    if L * (LK * 127 * 127) * (p - 1) >= (1 << 63):
        # fall back to per-weight reduction (never hit at SDA shapes)
        return limb_recombine(limb_partials_const(A, stacks, p), p)
    partials = limb_partials_const(A, stacks, p)
    weights = jnp.asarray([pow(128, m, p) for m in range(L)], dtype=jnp.int64)
    acc = jnp.sum(
        partials.astype(jnp.int64) * weights.reshape((L,) + (1,) * (partials.ndim - 1)),
        axis=0,
    )
    return lax.rem(acc, jnp.int64(p))


def limb_recombine_host(partials, p: int):
    """Exact host recombine for wide moduli (p >= 2^31): the weighted sum
    ``sum_w partials[w] * 128^w mod p`` overflows int64 on device, but the
    accumulator this runs on is tiny (W x batches x clerks), so python-int
    arithmetic is fine. Returns canonical int64 values."""
    import numpy as np

    arr = np.asarray(partials, dtype=object)
    out = np.zeros(arr.shape[1:], dtype=object)
    for w in range(arr.shape[0]):
        out = (out + arr[w] * pow(128, w, p)) % p
    return out.astype(np.int64)
