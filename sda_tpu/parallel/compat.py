"""JAX API compatibility for the mesh plane.

``shard_map`` has moved across JAX releases: newer builds expose
``jax.shard_map`` at top level, while 0.4.x ships it only as
``jax.experimental.shard_map.shard_map``. Every mesh call site (engine,
sum-first fabric, multihost, and the test-suite capability probe) routes
through this resolver so the whole plane agrees on one binding — a repo
that half-works on a given JAX build is worse than one that cleanly
skips.
"""

from __future__ import annotations

import functools

import jax

try:
    shard_map = jax.shard_map
except AttributeError:  # jax 0.4.x: experimental namespace only
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    @functools.wraps(_experimental_shard_map)
    def shard_map(f, *args, **kwargs):
        # The experimental API spells the replication check ``check_rep``;
        # the top-level API renamed it ``check_vma``. Call sites use the
        # modern spelling, so translate here.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(f, *args, **kwargs)


__all__ = ["shard_map"]
