"""sda_tpu.parallel — the TPU aggregation fabric.

Mesh sharding, the end-to-end ``TpuAggregator`` engine, and the int8-limb
MXU mod-p matmul.
"""

from .engine import AggregationPlan, TpuAggregator, full_training_step, make_plan
from .mesh import make_mesh, shard_participants
from .sumfirst import clerk_sums_sum_first, sharded_value_limb_sums

__all__ = [
    "TpuAggregator",
    "AggregationPlan",
    "make_plan",
    "full_training_step",
    "make_mesh",
    "shard_participants",
    "clerk_sums_sum_first",
    "sharded_value_limb_sums",
]
