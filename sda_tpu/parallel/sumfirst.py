"""Sum-first clerk sums: ``share(Σ_c v_c) = Σ_c share(v_c)`` (linearity).

Packed-Shamir share generation is a fixed linear map ``v ↦ v @ S`` over the
prime field (ops/shamir.py), and the clerk's job is the *sum* of all
participants' shares (reference: client/src/clerk.rs:85-86,
client/src/crypto/sharing/combiner.rs:16-30). Matmul and participant-sum
commute, so when the fabric's goal is the clerk sums themselves — the
co-hosted/simulated-participant setting the TPU aggregation fabric exists
for (SURVEY.md §2.3) — the hot loop over the big ``(participants, dim)``
tensor reduces to one streaming integer reduction, and the share matmul
runs once on the tiny ``(B, K)`` participant-sum. Bit-exact: both orders
compute the same field elements.

Do NOT use this path when individual participants' shares must exist —
e.g. to be sealed per clerk for transport (the real multi-party protocol
plane, client/participate.py); that's ``engine.share_participants``.

Overflow discipline: the reduction is carried as *exact integer* sums in
base-2³² limb space — no mod ops touch the big tensor at all. Canonical
values ``v < p < 2⁶²`` split into ``lo = v & (2³²−1)`` and ``hi = v ≫ 32``;
limb sums over ``C_total`` participants are bounded by ``C_total · (2³²−1)``,
so int64 accumulators are exact for up to 2³¹ participants (2048× the 1M
north star). For ``p < 2³¹`` a single limb suffices. The epilogue
(recombine mod p + share matmul) runs host-side with exact python-int
arithmetic on the tiny accumulator.
"""

from __future__ import annotations

import numpy as np

from . import compat
from ..ops import shamir
from ..ops.jaxcfg import ensure_x64
from ..ops.modular import modmatmul_np
from .engine import AggregationPlan, _batch_secrets, _device_randomness

#: participant bound for exact int64 limb accumulation (see module doc)
MAX_PARTICIPANTS = 1 << 31

#: chunk bound for the int32 narrow reduction: C * (2^16 - 1) < 2^31
MAX_NARROW_CHUNK = 1 << 15


def limb_count_sum(p: int) -> int:
    """Limbs needed for exact base-2^32 sum accumulation of values < p."""
    return 1 if p <= (1 << 31) else 2


def exact_sum_narrow(x):
    """Exact axis-0 sums of nonneg int32 values < 2^31 using only native
    int32 lane ops — delegates to the uint32 variant (the int32→uint32
    bit-cast is lossless for nonneg values, and logical shift equals
    arithmetic shift there). ``(C, ...) -> (...)`` int64."""
    import jax.numpy as jnp

    # canonical values < 2^31: int32 cast lossless, uint32 view identical
    return exact_sum_narrow_u32(x.astype(jnp.int32).astype(jnp.uint32))


def exact_sum_narrow_u32(x):
    """Exact axis-0 sums of uint32 values using only native 32-bit lane
    ops: split into 2^16 halves (logical shift on uint32), sum each in
    int32 (exact while ``x.shape[0] <= MAX_NARROW_CHUNK``), widen only the
    reduced result. ``(C, ...) -> (...)`` int64."""
    ensure_x64()
    import jax.numpy as jnp

    if x.shape[0] > MAX_NARROW_CHUNK:
        raise ValueError(f"narrow reduction bound is {MAX_NARROW_CHUNK} rows")
    x = x.astype(jnp.uint32)
    lo = jnp.sum((x & jnp.uint32(0xFFFF)).astype(jnp.int32), axis=0, dtype=jnp.int32)
    hi = jnp.sum((x >> jnp.uint32(16)).astype(jnp.int32), axis=0, dtype=jnp.int32)
    return lo.astype(jnp.int64) + (hi.astype(jnp.int64) << jnp.int64(16))


def value_limb_sums_chunk_pair(hi, lo, key, plan: AggregationPlan, draw_pair):
    """The wide-modulus twin of :func:`value_limb_sums_chunk` over
    ``(hi, lo)`` uint32 pair tensors (value = hi·2³² + lo < p, p < 2⁶²).

    The base-2³² limb sums the epilogue needs are exactly ``Σ lo`` and
    ``Σ hi`` — so when values arrive as halves, no int64 tensor (emulated
    on 32-bit TPU lanes) ever materializes: both halves reduce via the
    16-bit-split narrow int32 sums. ``draw_pair(key, shape) -> (hi, lo)``
    supplies the share randomness in the same representation. Returns
    ``(2, B, K)`` int64 exact limb sums — accumulate and feed
    ``clerk_sums_from_limb_acc`` exactly like the int64-path chunks
    (parity-tested bit-exact against :func:`value_limb_sums_chunk`).
    """
    ensure_x64()
    import jax.numpy as jnp

    C = hi.shape[0]
    batches_hi = _batch_secrets(hi, plan)  # (C, b, k) — pad/reshape, dtype-agnostic
    batches_lo = _batch_secrets(lo, plan)
    rand_hi, rand_lo = draw_pair(key, (C, batches_hi.shape[1], plan.rand_size))
    cols_hi = jnp.concatenate([batches_hi, rand_hi], axis=-1)  # (C, b, K)
    cols_lo = jnp.concatenate([batches_lo, rand_lo], axis=-1)
    return jnp.stack([exact_sum_narrow_u32(cols_lo), exact_sum_narrow_u32(cols_hi)])


def value_limb_sums_chunk(secrets, key, plan: AggregationPlan, draw=None):
    """One streaming chunk of the sum-first hot loop.

    ``(C, dim)`` canonical secrets -> ``(L, B, K)`` int64 *exact integer*
    limb sums over the chunk's participants of the per-participant value
    rows ``[batched secrets | fresh randomness]`` (the same rows
    ``engine.share_participants`` feeds the share matmul). ``L`` is
    ``limb_count_sum(p)``. Accumulate chunks with plain ``+`` — no mod ops —
    while total participants stay below ``MAX_PARTICIPANTS``.

    Secrets and randomness are limb-summed separately and joined on the
    tiny ``(B, ·)`` results — the big ``(C, B, K)`` concatenation the share
    matmul needs never materializes. ``draw(key, shape, p) -> int64 in
    [0, p)`` overrides the randomness generator (the benchmark passes a
    division-free masked-bits draw; default is the simulation-grade
    ``uniform_mod_device``, which keeps this bit-identical to
    ``share_participants`` for the same key).
    """
    ensure_x64()
    import jax.numpy as jnp

    p = plan.modulus
    batches = _batch_secrets(secrets, plan)  # (C, b, k)
    C, nb = batches.shape[0], batches.shape[1]
    if draw is None:
        draw = _device_randomness
    randomness = draw(key, (C, nb, plan.rand_size), p)

    # narrow path (p <= 2^31, chunk <= 2^15): all big-tensor ops stay in
    # native int32 lanes (exact_sum_narrow) and only the tiny (b, cols)
    # result widens. ~2x over emulated int64 lanes on TPU.
    narrow = limb_count_sum(p) == 1 and C <= MAX_NARROW_CHUNK

    def limb_sums(x):  # (C, b, cols) -> (L, b, cols) exact integer sums
        if narrow:
            return exact_sum_narrow(x)[None]
        x = x.astype(jnp.int64)
        if limb_count_sum(p) == 1:
            return jnp.sum(x, axis=0)[None]
        mask = jnp.int64(0xFFFFFFFF)
        return jnp.stack(
            [jnp.sum(x & mask, axis=0), jnp.sum(x >> jnp.int64(32), axis=0)]
        )

    return jnp.concatenate([limb_sums(batches), limb_sums(randomness)], axis=-1)


def exact_value_sums(limb_acc):
    """``(L, B, K)`` int64 limb accumulator -> ``(B, K)`` exact integer
    participant sums (object dtype, python ints — no modulus applied)."""
    acc = np.asarray(limb_acc, dtype=object)
    out = np.zeros(acc.shape[1:], dtype=object)
    for w in range(acc.shape[0]):
        out = out + acc[w] * (1 << (32 * w))
    return out


def clerk_sums_from_limb_acc(limb_acc, plan: AggregationPlan, exact=None):
    """Host epilogue: ``(L, B, K)`` int64 limb accumulator -> clerk sums.

    Returns ``(clerk_sums, value_sums)``: ``clerk_sums`` is the ``(n, B)``
    int64 canonical per-clerk share sums (exactly what per-participant
    sharing + clerk-combine produces), ``value_sums`` the ``(B, K)``
    canonical participant-sums (whose first ``k`` columns are the plain
    batched secret sums — the free verification handle). All arithmetic on
    this tiny accumulator is exact python-int / object-dtype. Pass a
    precomputed ``exact_value_sums(limb_acc)`` as ``exact`` to reuse it.
    """
    p = plan.modulus
    if exact is None:
        exact = exact_value_sums(limb_acc)
    vsum = exact % p  # exact sums >= 0: % == canonical rem
    if plan.share_matrix is None:
        raise ValueError("sum-first epilogue requires a packed share matrix")
    S_T = plan.share_matrix.T.astype(np.int64)  # (K, n)
    clerk = modmatmul_np(vsum, S_T, p)  # (B, n) in (-p, p)
    clerk = np.where(clerk < 0, clerk + p, clerk).astype(np.int64)
    return clerk.T.copy(), vsum.astype(np.int64)


def clerk_sums_sum_first(secrets, key, plan: AggregationPlan):
    """Single-shot convenience: ``(P, dim)`` -> ``(n, B)`` clerk sums.

    Parity twin of ``share_participants`` + ``clerk_combine`` + rem (see
    tests/test_parallel_engine.py); the streaming bench drives the chunk /
    epilogue pieces directly.
    """
    if secrets.shape[0] > MAX_PARTICIPANTS:
        raise ValueError(f"chunk the input: exact bound is {MAX_PARTICIPANTS}")
    acc = value_limb_sums_chunk(secrets, key, plan)
    clerk, _ = clerk_sums_from_limb_acc(np.asarray(acc), plan)
    return clerk


def reconstruct_from_clerk_sums(clerk_sums, indices, scheme, dim: int):
    """Host-exact reconstruction for any modulus width (tiny inputs; the
    bench epilogue). Same helper backs ``engine.reconstruct``'s wide path."""
    return shamir.reconstruct_clerk_sums_host(clerk_sums, indices, scheme, dim)


def sharded_value_limb_sums(plan: AggregationPlan, mesh):
    """The sum-first hot loop over a device mesh: each device limb-sums its
    own participant shard (``value_limb_sums_chunk``), then one int64
    ``psum`` over the participant axis ``p`` carries only the tiny
    ``(L, B, K)`` accumulator across ICI — the sharded twin of the
    streaming single-chip bench loop, with the same exactness bound
    (``MAX_PARTICIPANTS`` *total*, summed over shards, since the psum adds
    pre-bounded per-shard limb sums).

    Returns ``fn(secrets_sharded, key) -> (L, B, K)`` int64 limb sums
    (replicated over ``p``, sharded over ``d`` on the B axis). Feed the
    gathered result to :func:`clerk_sums_from_limb_acc` on host, exactly
    like the single-chip chunks.
    """
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from .engine import fold_mesh_axes, validate_d_sharding

    validate_d_sharding(mesh, plan.dim, plan.input_size)
    p_size = mesh.shape["p"]

    def local_step(secrets, key):
        # shapes are static under shard_map, so this enforces the documented
        # *global* exactness bound at trace time (psum adds p_size shards),
        # mirroring clerk_sums_sum_first's guard
        if secrets.shape[0] * p_size > MAX_PARTICIPANTS:
            raise ValueError(
                f"global participant count {secrets.shape[0] * p_size} "
                f"exceeds the exact limb-sum bound {MAX_PARTICIPANTS}; "
                "chunk the input"
            )
        key = fold_mesh_axes(key, mesh)
        acc = value_limb_sums_chunk(secrets, key, plan)
        return lax.psum(acc, axis_name="p")

    d_spec = "d" if "d" in mesh.axis_names else None
    mapped = compat.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P("p", d_spec), P()),
        out_specs=P(None, d_spec, None),
        check_vma=False,
    )
    return jax.jit(mapped)
