"""Device-mesh construction for the aggregation fabric.

Axes: ``p`` shards participants (the "many phones" axis), ``d`` shards the
dim/batch axis (the reference's dimension-batching, SURVEY.md §2.3). On a
v5e-8 slice the default is all 8 chips on ``p`` — participant count dwarfs
everything else — with ``d`` available for 100K-dim vectors when per-chip
batch memory binds first.
"""

from __future__ import annotations


def make_mesh(p_size: int | None = None, d_size: int = 1):
    """Mesh over the first p_size*d_size local devices, axes ('p', 'd')."""
    import jax
    from jax.sharding import Mesh

    import numpy as np

    devices = jax.devices()
    if p_size is None:
        p_size = len(devices) // d_size
    need = p_size * d_size
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(p_size, d_size)
    return Mesh(grid, ("p", "d"))


def shard_participants(array, mesh):
    """Place a (P, dim) array sharded (p, d) over the mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(array, NamedSharding(mesh, P("p", "d")))
