"""The TPU aggregation fabric — SDA's hot loop as sharded mod-p kernels.

This is the ``device="tpu"`` execution path of the north star
(/root/repo/BASELINE.json): the share-generate / clerk-combine / reconstruct
pipeline over an HBM-resident ``(participants, dim)`` tensor, replacing the
reference's per-phone Rust loops (client/src/crypto/sharing/*,
client/src/clerk.rs:85-86) when participants are simulated or co-hosted on
an accelerator slice.

Pipeline (all mod p, truncated-remainder representatives):

1. *share*: reshape ``(P, dim) -> (P, B, k)`` batches (zero-padding the dim
   tail exactly like batched.rs:30-43), append ``(P, B, t)`` counter-based
   randomness, one batched matmul with the precomputed share matrix
   ``(k+t, n)`` -> ``(P, B, n)``. The NTT pipeline is folded into that
   matrix on host (ops/shamir.py) — on the MXU a matmul IS the fast NTT at
   these domain sizes.
2. *transpose + clerk-combine*: the server-side (participants x clerks)
   transpose (server/src/snapshot.rs, stores.rs:86-101) is an axis
   permutation here; the per-clerk modular sum is a single reduction over
   the participant axis. Sharded over a mesh ``p`` axis this is a local
   partial sum + ``psum`` riding ICI — no per-participant traffic at all.
3. *reconstruct*: gather any ``reconstruction_threshold`` surviving clerk
   rows, one ``(R, k)`` Lagrange matmul, truncate the pad
   (batched.rs:68-98).

Sharding model: ``Mesh(axes p, d)`` — participants shard over ``p``
(the reference's "many phones" axis), the dim/batch axis shards over ``d``
(the reference's dimension-batching axis, SURVEY.md §2.3). Clerk results
are tiny (n x B); they end replicated after the psum, which is exactly what
the recipient needs.

dtype discipline: values live in int32 (p < 2^31), arithmetic widens to
int64 only where products/sums require it. The int8-limb MXU path
(``limbmatmul``) replaces the widening matmul on TPU for the bench path.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from .. import telemetry
from . import compat
from ..ops import shamir
from ..ops.jaxcfg import ensure_x64
from ..protocol import AdditiveSharing, BasicShamirSharing, PackedShamirSharing


def _step_hist(step: str):
    return telemetry.histogram(
        "sda_engine_step_seconds",
        "secure_sum stage / sharded-fabric invocation timing (host "
        "dispatch unless JAX blocks)",
        step=step,
    )


def _instrument_fabric(fn, fabric: str, axis_size: int):
    """Wrap a jitted sharded fabric fn(secrets, key): invocation timing
    plus nominal psum traffic (result size x participant-axis size).

    Transparent under tracing — ``verified_step`` re-jits over fabric
    fns, and trace-time side effects would count compilations as
    invocations — and under disabled telemetry.
    """

    def instrumented(secrets, key):
        if not telemetry.enabled():
            return fn(secrets, key)
        import jax.core

        if isinstance(secrets, jax.core.Tracer):
            return fn(secrets, key)
        t0 = time.perf_counter()
        out = fn(secrets, key)
        _step_hist(fabric).observe(time.perf_counter() - t0)
        telemetry.counter(
            "sda_engine_psum_bytes_total",
            "nominal bytes moved per psum/all_to_all by sharded fabrics",
            fabric=fabric,
        ).inc(int(out.size) * out.dtype.itemsize * axis_size)
        return out

    return instrumented


@dataclass(frozen=True)
class AggregationPlan:
    """Host-precomputed constants for a scheme + dimension."""

    modulus: int
    dim: int
    input_size: int  # k (1 for additive)
    rand_size: int  # t for packed, n-1 for additive
    share_count: int  # n
    n_batches: int  # B = ceil(dim / k)
    share_matrix: np.ndarray | None  # (n, k+t) packed; None for additive


def make_plan(scheme, dim: int) -> AggregationPlan:
    if isinstance(scheme, (BasicShamirSharing, PackedShamirSharing)):
        k = scheme.input_size  # secret_count for packed, 1 for basic
        return AggregationPlan(
            modulus=scheme.prime_modulus,
            dim=dim,
            input_size=k,
            rand_size=scheme.privacy_threshold,
            share_count=scheme.share_count,
            n_batches=-(-dim // k),
            share_matrix=shamir.share_matrix(scheme),
        )
    if isinstance(scheme, AdditiveSharing):
        return AggregationPlan(
            modulus=scheme.modulus,
            dim=dim,
            input_size=1,
            rand_size=scheme.share_count - 1,
            share_count=scheme.share_count,
            n_batches=dim,
            share_matrix=None,
        )
    raise TypeError(f"unknown sharing scheme {scheme!r}")


# ---------------------------------------------------------------------------
# Device kernels (pure, jittable). All take/return jnp arrays.
# ---------------------------------------------------------------------------


def _jnp():
    ensure_x64()
    import jax.numpy as jnp

    return jnp


def _batch_secrets(secrets, plan: AggregationPlan):
    """(P, d) -> (P, b, k) with zero-padded tail (batched.rs semantics).

    Shape-driven (not plan.dim-driven): inside ``shard_map`` the dim axis is
    a local shard, so the batch count comes from the actual input. The
    sharded path requires dim divisible by k * d_size, so padding only ever
    happens at the true global tail.
    """
    jnp = _jnp()
    P, d = secrets.shape
    nb = -(-d // plan.input_size)
    pad = nb * plan.input_size - d
    padded = jnp.pad(secrets, ((0, 0), (0, pad)))
    return padded.reshape(P, nb, plan.input_size)


def _device_randomness(key, shape, modulus):
    """Counter-based uniform draws in [0, modulus) (simulation-grade RNG —
    real participants draw on their own hosts; see ops/rng.py)."""
    from ..ops.rng import uniform_mod_device

    return uniform_mod_device(key, shape, modulus)


def share_participants(
    secrets, key, plan: AggregationPlan, use_limbs: bool = False, draw=None
):
    """(P, dim) secrets -> (P, n, B) per-clerk share tensor.

    ``draw(key, shape, p) -> int in [0, p)`` overrides the randomness
    generator (benchmarks pass a division-free masked-bits draw; default is
    the simulation-grade ``uniform_mod_device``).
    """
    jnp = _jnp()
    from jax import lax

    if draw is None:
        draw = _device_randomness
    p = plan.modulus
    if plan.share_matrix is None:
        # additive: n-1 uniform draws + closing share (additive.rs:42-48)
        P, d = secrets.shape
        draws = draw(key, (P, plan.share_count - 1, d), p)  # (P, n-1, d)
        # a plain int64 sum of the n-1 draws overflows once
        # (n-1)*(p-1) >= 2^63, silently corrupting the closing share;
        # the auto dispatch switches to the halving mod-sum there
        from ..ops.modular import mod_sum_auto_jnp

        total = mod_sum_auto_jnp(draws, p, axis=1)
        last = lax.rem(secrets.astype(jnp.int64) - total, jnp.int64(p))
        return jnp.concatenate([draws.astype(jnp.int64), last[:, None, :]], axis=1)

    batches = _batch_secrets(secrets, plan)  # (P, b, k)
    P, nb = batches.shape[0], batches.shape[1]
    randomness = draw(key, (P, nb, plan.rand_size), p)
    if use_limbs:
        from .limbmatmul import limb_modmatmul_const

        # keep the big tensor in native int32 lanes when the field fits
        dt = jnp.int32 if p <= (1 << 31) else jnp.int64
        values = jnp.concatenate(
            [batches.astype(dt), randomness.astype(dt)], axis=-1
        )
        flat = values.reshape(-1, values.shape[-1])
        shares = limb_modmatmul_const(flat, plan.share_matrix.T, p).reshape(P, nb, -1)
    else:
        values = jnp.concatenate(
            [batches.astype(jnp.int64), randomness.astype(jnp.int64)], axis=-1
        )
        S_T = jnp.asarray(plan.share_matrix.T)  # (k+t, n)
        if p >= (1 << 31):
            raise ValueError(
                "int64 share products overflow for p >= 2^31; use the limb "
                "path (share_combine_limb + limb_recombine_host)"
            )
        prods = lax.rem(values[..., :, None] * S_T[None, None, :, :], jnp.int64(p))
        shares = lax.rem(jnp.sum(prods, axis=-2), jnp.int64(p))  # (P, B, n)
    return jnp.swapaxes(shares, 1, 2)  # (P, n, B)


def share_combine_limb(secrets, key, plan: AggregationPlan, draw=None):
    """Fused share + clerk-combine in limb space: (C, d) -> (W, b, n) int64.

    The hot loop stays division-free: int8 MXU matmuls produce weight-grouped
    partials, which are *summed over the participant axis first* (linearity)
    and only then carried as a tiny (W, b, n) accumulator. Callers reduce
    accumulators across chunks with ``lax.rem`` (values stay < p) and call
    ``limb_recombine`` once at the very end. This is what makes the bench
    path ~10x the naive int64 formulation on TPU: emulated 64-bit
    multiply/divide never touches the (participants x dim) tensor.
    """
    jnp = _jnp()
    from .limbmatmul import fold_const_limbs, limb_partials_const

    if draw is None:
        draw = _device_randomness
    p = plan.modulus
    batches = _batch_secrets(secrets, plan)  # (C, b, k)
    C, nb = batches.shape[0], batches.shape[1]
    randomness = draw(key, (C, nb, plan.rand_size), p)
    # keep the big tensor in native int32 lanes when the field fits
    dt = jnp.int32 if p <= (1 << 31) else jnp.int64
    values = jnp.concatenate([batches.astype(dt), randomness.astype(dt)], axis=-1)
    stacks = fold_const_limbs(plan.share_matrix.T, p)  # (L, L*(k+t), n)
    partials = limb_partials_const(
        values.reshape(C * nb, -1), stacks, p
    )  # (W=L, C*nb, n)
    W, LK = stacks.shape[0], stacks.shape[1]
    per_part = partials.reshape(W, C, nb, -1)
    # participant-axis reduction: stay in int32 when the bound allows
    # (partial elements <= L*K * 127^2), halving the reduction cost
    if C * LK * 127 * 127 < 2**31:
        return jnp.sum(per_part, axis=1).astype(jnp.int64)  # (W, b, n)
    return jnp.sum(per_part.astype(jnp.int64), axis=1)  # (W, b, n)


def clerk_combine(shares):
    """(P, n, B) -> (n, B) local modular sums — the clerk hot loop
    (combiner.rs:16-30) as one reduction; caller supplies the modulus rem.

    Exact only while P*(p-1) < 2^63 — use :func:`clerk_combine_mod` when
    the modulus/participant count may exceed that bound."""
    jnp = _jnp()
    return jnp.sum(shares.astype(jnp.int64), axis=0)


def clerk_combine_mod(shares, p: int):
    """Reduced clerk sums over the participant axis, exact for any p < 2^62.

    In the narrow regime (P*(p-1) < 2^63) this is bit-identical to
    ``lax.rem(clerk_combine(shares), p)``; past the bound a plain int64 sum
    silently wraps, so the halving mod-sum takes over — required for
    additive sharing at 61-bit moduli (additive.rs:55-73 semantics)."""
    _jnp()
    from ..ops.modular import mod_sum_auto_jnp

    return mod_sum_auto_jnp(shares, p, axis=0)


def reconstruct(clerk_sums, indices, scheme, dim: int):
    """(n, B) clerk sums + surviving ``indices`` -> (dim,) aggregate."""
    jnp = _jnp()
    from jax import lax

    if isinstance(scheme, AdditiveSharing):
        # wide moduli: n reduced rows still overflow a plain int64 sum
        from ..ops.modular import mod_sum_auto_jnp

        return mod_sum_auto_jnp(clerk_sums.astype(jnp.int64), scheme.modulus, axis=0)[
            :dim
        ]
    p = scheme.prime_modulus
    if p >= (1 << 31):
        # wide modulus: tiny matrices, exact host interpolation
        return jnp.asarray(
            shamir.reconstruct_clerk_sums_host(clerk_sums, indices, scheme, dim)
        )
    L = jnp.asarray(shamir.reconstruction_matrix(scheme, list(indices)))  # (k, R)
    rows = clerk_sums[jnp.asarray(list(indices))]  # (R, B)
    prods = lax.rem(L[:, :, None] * rows[None, :, :], jnp.int64(p))
    secrets = lax.rem(jnp.sum(prods, axis=1), jnp.int64(p))  # (k, B)
    return secrets.T.reshape(-1)[:dim]


# ---------------------------------------------------------------------------
# The sharded engine
# ---------------------------------------------------------------------------


class TpuAggregator:
    """End-to-end secure-sum engine over a device mesh.

    ``mesh`` axes: ``"p"`` shards participants, ``"d"`` shards the
    batch/dim axis. Single-device use passes ``mesh=None``.
    """

    def __init__(self, scheme, dim: int, mesh=None, use_limbs: bool = False):
        self.scheme = scheme
        self.dim = dim
        self.plan = make_plan(scheme, dim)
        self.mesh = mesh
        self.use_limbs = use_limbs

    # -- single-device reference path --------------------------------------

    def secure_sum(self, secrets, key, indices=None):
        """(P, dim) -> (dim,) aggregate, all on device."""
        p = self.plan.modulus
        with telemetry.span("engine.secure_sum", dim=self.dim):
            t0 = time.perf_counter()
            shares = share_participants(secrets, key, self.plan, self.use_limbs)
            t1 = time.perf_counter()
            _step_hist("share").observe(t1 - t0)
            sums = clerk_combine_mod(shares, p)
            t2 = time.perf_counter()
            _step_hist("combine").observe(t2 - t1)
            if indices is None:
                indices = range(self.plan.share_count)
            out = reconstruct(sums, indices, self.scheme, self.dim)
            _step_hist("reconstruct").observe(time.perf_counter() - t2)
        return out

    # -- sharded paths -------------------------------------------------------

    def sharded_clerk_sums_all_to_all(self):
        """Clerk-sharded variant: the server-side transpose as an all_to_all.

        Where ``sharded_clerk_sums`` keeps participants sharded and psums
        per-clerk partials (bandwidth ~ n*B per device, replicated result),
        this variant physically reshards shares from participant-major to
        clerk-major over the ``p`` axis — the device-side realization of the
        snapshot transpose (server/src/snapshot.rs, SURVEY.md §3.2) — and
        each device then locally sums *all* participants for its own clerk
        slice. Right when clerks are many (n >= mesh size) and per-clerk
        downstream work (e.g. sealing results) should stay clerk-local.

        Returns fn(secrets_sharded, key) -> (n, B) clerk sums sharded over
        ``p`` on the clerk axis.
        """
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        plan = self.plan
        use_limbs = self.use_limbs
        modulus = plan.modulus
        p_size = self.mesh.shape["p"]
        if plan.share_count % p_size != 0:
            raise ValueError(
                f"share_count {plan.share_count} must divide over mesh axis p={p_size}"
            )

        def local_step(secrets, key):
            key = fold_mesh_axes(key, self.mesh)
            shares = share_participants(secrets, key, plan, use_limbs)  # (Pl, n, B)
            # reshard: split the clerk axis across "p", gather participants —
            # afterwards each device holds (P_total_local_group, n/p, B)
            resharded = lax.all_to_all(
                shares, "p", split_axis=1, concat_axis=0, tiled=True
            )
            # all participants sum locally — wide-safe reduction
            return clerk_combine_mod(resharded, modulus)  # (n/p, B)

        mapped = compat.shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(P("p", None), P()),
            out_specs=P("p", None),
            check_vma=False,
        )
        return _instrument_fabric(jax.jit(mapped), "all_to_all", p_size)

    def _limb_accumulator_local_step(self, psum_axes):
        """Shared per-device body of the wide-modulus fabric: fused limb
        share+combine, then int64 partial psums over ``psum_axes`` in
        order (single-slice: ('p',); hybrid: ('p', 'h') — ICI before
        DCN). One definition so overflow-bound or chunking fixes apply to
        every fabric at once."""
        from jax import lax

        plan = self.plan
        mesh = self.mesh

        def local_step(secrets, key):
            key = fold_mesh_axes(key, mesh)
            acc = share_combine_limb(secrets, key, plan)  # (W, b_local, n)
            for ax in psum_axes:
                acc = lax.psum(acc, axis_name=ax)
            return acc

        return local_step

    def validate_d_sharding(self, dim: int) -> None:
        """With a sharded dim axis every d-shard must hold whole batches;
        unsharded (d=1) keeps the usual zero-pad/truncate tail handling."""
        validate_d_sharding(self.mesh, dim, self.plan.input_size)

    def sharded_limb_accumulators(self):
        """Wide-modulus sharded fabric (BASELINE config 5 is 61-bit on
        v5e-8): each device runs the fused limb share+combine over its
        participant shard, partial accumulators psum over ``p`` — tiny
        ``(W, B, n)`` int64 tensors riding ICI — and the exact mod-p
        recombine of the reduced accumulator happens once on host
        (``limbmatmul.limb_recombine_host``), exactly like the single-chip
        streaming bench epilogue.

        Exactness: per-device partials are bounded by ``C_local·L·K·127²``;
        the psum multiplies by the number of participant shards, so int64
        stays exact up to ~5e12 total participants — no rem needed on
        device at all.

        Returns fn(secrets_sharded, key) -> (W, B, n) int64 accumulators
        (replicated over ``p``, sharded over ``d`` on the B axis). Feed
        ``limb_recombine_host(acc, p).T`` then ``reconstruct``.
        """
        import jax
        from jax.sharding import PartitionSpec as P

        mapped = compat.shard_map(
            self._limb_accumulator_local_step(("p",)),
            mesh=self.mesh,
            # in_specs requires a "d" axis, so no d-less fallback here
            in_specs=(P("p", "d"), P()),
            out_specs=P(None, "d", None),
            check_vma=False,
        )
        return _instrument_fabric(
            jax.jit(mapped), "sharded_limb_accumulators", self.mesh.shape["p"]
        )

    def sharded_clerk_sums(self):
        """Build the jitted sharded share+combine step over the mesh.

        Returns fn(secrets_sharded, key) -> (n, B) clerk sums (replicated
        over ``p``, sharded over ``d`` on the B axis).
        """
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        jnp = _jnp()
        plan = self.plan
        use_limbs = self.use_limbs
        modulus = plan.modulus

        _check_psum_bound(self.mesh.shape["p"], modulus, "sharded_clerk_sums")

        def local_step(secrets, key):
            # per-device: share own participant slice, sum locally, psum.
            # every device folds all mesh coordinates into the key, so
            # every shard draws distinct randomness (see fold_mesh_axes)
            key = fold_mesh_axes(key, self.mesh)
            shares = share_participants(secrets, key, plan, use_limbs)
            partial = clerk_combine_mod(shares, modulus)  # (n, B_local)
            total = lax.psum(partial, axis_name="p")
            return lax.rem(total, jnp.int64(modulus))

        mapped = compat.shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(P("p", "d"), P()),
            out_specs=P(None, "d") if "d" in self.mesh.axis_names else P(),
            check_vma=False,
        )
        return _instrument_fabric(
            jax.jit(mapped), "sharded_clerk_sums", self.mesh.shape["p"]
        )



def _check_psum_bound(axis_size: int, modulus: int, where: str) -> None:
    """psum adds ``axis_size`` reduced partials (each in (-m, m)) in int64 —
    past ``axis_size*(m-1) < 2^63`` it silently wraps. Wide moduli must use
    the limb-accumulator fabrics instead, which psum small exact int64
    accumulators and recombine mod p once on host."""
    if axis_size * (modulus - 1) >= 2**63:
        raise ValueError(
            f"{where}: psum of {axis_size} partials overflows int64 at "
            f"modulus {modulus}; use sharded_limb_accumulators / "
            "hierarchical_limb_accumulators for wide moduli"
        )


def validate_d_sharding(mesh, dim: int, input_size: int) -> None:
    """With a sharded dim axis every d-shard zero-pads its own tail batch
    independently — non-divisible dims would misalign batch boundaries and
    silently reconstruct a wrong aggregate. One definition of the rule for
    every fabric (engine, multihost, sumfirst)."""
    d_size = mesh.shape.get("d", 1)
    if d_size > 1 and dim % (input_size * d_size) != 0:
        raise ValueError(
            f"dim {dim} must divide over input_size {input_size} x d={d_size} "
            "so every d-shard holds whole batches"
        )


def fold_mesh_axes(key, mesh):
    """Fold every mesh-axis index into the PRNG key (inside shard_map).

    Folding only one axis would hand devices that differ on another axis
    the SAME key: with the dim axis ``d`` sharded, two d-shards of one
    participant row would then draw identical share randomness for
    different dim slices — subtracting a clerk's shares across shards
    cancels it, a zero-privacy failure. Every sharded path (here and
    multihost.py) derives per-device randomness through this one helper.
    """
    import jax
    from jax import lax

    for axis in mesh.axis_names:
        key = jax.random.fold_in(key, lax.axis_index(axis))
    return key


def verified_step(agg, sums_fn):
    """Jitted round with verification handle: ``fn(secrets, key) ->
    (aggregate, plaintext-sum)`` — reconstruct from ``sums_fn``'s clerk
    sums plus an independent plaintext reduction of the same secrets.
    Shared by the single-mesh and multi-host (multihost.py) fabrics."""
    import jax

    jnp = _jnp()
    scheme, dim = agg.scheme, agg.dim

    def step(secrets, key):
        sums = sums_fn(secrets, key)
        out = reconstruct(sums, range(agg.plan.share_count), scheme, dim)
        from ..ops.modular import mod_sum_auto_jnp

        plain = mod_sum_auto_jnp(
            secrets.astype(jnp.int64), agg.plan.modulus, axis=0
        )
        return out, plain

    return jax.jit(step)


def full_training_step(scheme, dim, mesh):
    """One full secure-aggregation round as a single jitted computation:
    share + transpose + clerk-combine (sharded) then reconstruct + verify.

    This is the "training step" analog the driver dry-runs multi-chip.
    """
    agg = TpuAggregator(scheme, dim, mesh=mesh)
    return agg, verified_step(agg, agg.sharded_clerk_sums())
