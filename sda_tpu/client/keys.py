"""Signed-encryption-key fetch + verification, shared by the client roles.

Participants verify each clerk's key before sealing shares to it
(reference: client/src/participate.rs:82-101) and clerks verify the
recipient's key before sealing the combined vector (client/src/clerk.rs:
88-100) — the same fetch/verify sequence, so it lives once here.
"""

from __future__ import annotations

from ..crypto import signing


class VerifiedKeys:
    """Mixin: ``_fetch_verified_key`` with a per-client cache."""

    #: verified-key cache bound (committee + recipient keys are few; the
    #: cap only matters for a client touching thousands of aggregations)
    _VERIFIED_KEY_CACHE_MAX = 4096

    def _fetch_verified_key(self, agent_id, key_id):
        """Fetch a signed encryption key + its owner, verify the signature.

        Successfully verified keys are cached per client: a key id names
        immutable content (create-if-identical store semantics), so a
        multi-round participant or clerk daemon pays the two fetches and
        the Ed25519 verify once per key, not once per participation/job.
        Failures are never cached."""
        cache = getattr(self, "_verified_keys", None)
        if cache is None:
            cache = self._verified_keys = {}
        hit = cache.get((agent_id, key_id))
        if hit is not None:
            return hit
        signed_key = self.service.get_encryption_key(self.agent, key_id)
        if signed_key is None:
            raise ValueError("Unknown encryption key")
        owner = self.service.get_agent(self.agent, agent_id)
        if owner is None:
            raise ValueError("Unknown agent")
        if not signing.signature_is_valid(owner, signed_key):
            raise ValueError("Signature verification failed for key")
        if len(cache) >= self._VERIFIED_KEY_CACHE_MAX:
            cache.clear()
        key_body = signed_key.body.body  # the EncryptionKey
        cache[(agent_id, key_id)] = key_body
        return key_body
