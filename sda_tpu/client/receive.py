"""Recipient role: create/open/close aggregations and reveal results.

Mirrors /root/reference/client/src/receive.rs: committee election follows
the service suggestion blindly (first output_size candidates), closing
creates one snapshot if none exists, and reveal decrypts + combines masks,
decrypts clerk results into indexed share vectors, reconstructs, and
unmasks. ``RecipientOutput.positive()`` lifts truncated-remainder residues
into [0, m) (receive.rs:8-21).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..ops.modular import positive
from ..protocol import Committee, Snapshot, SnapshotId


@dataclass
class RecipientOutput:
    modulus: int
    values: np.ndarray

    def positive(self) -> "RecipientOutput":
        return RecipientOutput(self.modulus, positive(self.values, self.modulus))


class Receiving:
    def upload_aggregation(self, aggregation) -> None:
        self.service.create_aggregation(self.agent, aggregation)

    def begin_aggregation(self, aggregation_id, *, chosen_clerks=None) -> None:
        """Elect the committee and open the aggregation for participation.

        Default: the first ``output_size`` suggested candidates — the
        reference's behavior (receive.rs:48-62). ``chosen_clerks`` (a
        list of AgentIds) lets the recipient pick its own committee —
        the reference's README "Doing more" roadmap item ("allow
        recipient to actually chose the clerks"), delivered here. Order
        defines committee position; every chosen clerk must be a
        candidate (i.e. has uploaded a signed encryption key), and the
        server still independently validates size and key signatures.
        """
        aggregation = self.service.get_aggregation(self.agent, aggregation_id)
        if aggregation is None:
            raise ValueError(f"Unknown aggregation {aggregation_id}")
        candidates = self.service.suggest_committee(self.agent, aggregation_id)
        size = aggregation.committee_sharing_scheme.output_size
        if chosen_clerks is None:
            selected = [(c.id, c.keys[0]) for c in candidates[:size]]
        else:
            if len(chosen_clerks) != size:
                raise ValueError(
                    f"committee needs exactly {size} clerks, "
                    f"{len(chosen_clerks)} chosen"
                )
            if len(set(chosen_clerks)) != len(chosen_clerks):
                raise ValueError("chosen clerks contain duplicates")
            by_id = {c.id: c for c in candidates}
            missing = [str(c) for c in chosen_clerks if c not in by_id]
            if missing:
                raise ValueError(
                    "chosen clerks are not candidates (no signed "
                    f"encryption key): {', '.join(missing)}"
                )
            selected = [(cid, by_id[cid].keys[0]) for cid in chosen_clerks]
        self.service.create_committee(
            self.agent, Committee(aggregation=aggregation_id, clerks_and_keys=selected)
        )

    def end_aggregation(self, aggregation_id) -> None:
        status = self.service.get_aggregation_status(self.agent, aggregation_id)
        if status is None:
            raise ValueError("Unknown aggregation")
        if len(status.snapshots) >= 1:
            return
        self.service.create_snapshot(
            self.agent, Snapshot(id=SnapshotId.random(), aggregation=aggregation_id)
        )

    def reveal_aggregation(self, aggregation_id) -> RecipientOutput:
        aggregation = self.service.get_aggregation(self.agent, aggregation_id)
        if aggregation is None:
            raise ValueError(f"Unknown aggregation {aggregation_id}")
        committee = self.service.get_committee(self.agent, aggregation_id)
        if committee is None:
            raise ValueError(f"Unknown committee {aggregation_id}")

        status = self.service.get_aggregation_status(self.agent, aggregation_id)
        if status is None:
            raise ValueError("Unknown aggregation")
        ready = [s for s in status.snapshots if s.result_ready]
        if not ready:
            raise ValueError("Aggregation not ready")
        result = self.service.get_snapshot_result(self.agent, aggregation_id, ready[0].id)
        if result is None:
            raise ValueError("Missing aggregation result")

        # one decryptor serves both mask and clerk-result payloads (same key)
        decryptor = self.crypto.new_share_decryptor(
            aggregation.recipient_key, aggregation.recipient_encryption_scheme
        )

        # decrypt and combine masks
        if result.recipient_encryptions is None:
            mask = np.empty(0, dtype=np.int64)
        else:
            decrypted = decryptor.decrypt_batch(result.recipient_encryptions)
            mask_combiner = self.crypto.new_mask_combiner(aggregation.masking_scheme)
            mask = mask_combiner.combine(decrypted)

        # decrypt clerk results into (committee index, share vector) pairs
        clerk_positions = {
            clerk: ix for ix, (clerk, _) in enumerate(committee.clerks_and_keys)
        }
        indexed_shares = []
        for clerking_result in result.clerk_encryptions:
            if clerking_result.clerk not in clerk_positions:
                raise ValueError(f"Missing clerk {clerking_result.clerk}")
            indexed_shares.append(
                (
                    clerk_positions[clerking_result.clerk],
                    decryptor.decrypt(clerking_result.encryption),
                )
            )

        if all(len(shares) == 0 for _, shares in indexed_shares):
            # an empty snapshot cut (every clerk combined zero
            # participations): the aggregate over the empty set is the
            # zero vector — don't run the reconstructor on empty batches
            return RecipientOutput(
                modulus=aggregation.modulus,
                values=np.zeros(aggregation.vector_dimension, dtype=np.int64),
            )

        reconstructor = self.crypto.new_secret_reconstructor(
            aggregation.committee_sharing_scheme, aggregation.vector_dimension
        )
        masked_output = reconstructor.reconstruct(indexed_shares)

        unmasker = self.crypto.new_secret_unmasker(aggregation.masking_scheme)
        output = unmasker.unmask(mask, masked_output)
        return RecipientOutput(modulus=aggregation.modulus, values=output)
