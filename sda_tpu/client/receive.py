"""Recipient role: create/open/close aggregations and reveal results.

Mirrors /root/reference/client/src/receive.rs: committee election follows
the service suggestion blindly (first output_size candidates), closing
creates one snapshot if none exists, and reveal decrypts + combines masks,
decrypts clerk results into indexed share vectors, reconstructs, and
unmasks. ``RecipientOutput.positive()`` lifts truncated-remainder residues
into [0, m) (receive.rs:8-21).

Large snapshot results arrive PAGED: above ``SDA_RESULT_PAGE_THRESHOLD``
the server answers ``get_snapshot_result`` with counts only and the
recipient streams the mask-encryption column and the clerk-result list
range-by-range. Download and compute overlap in a bounded pipeline —
up to ``SDA_PREFETCH_DEPTH`` range requests in flight while the main
thread runs the native batched sealed-box open on the current chunk and
folds the plaintext masks into a streaming modular accumulator
(``MaskCombiner.accumulator``) —
so recipient memory stays flat in cohort size and wall time approaches
max(download, decrypt+fold) instead of their sum. Small results keep the
legacy bulk wire shape but route through the same accumulator as a
single chunk, so both paths share one fold semantics (and are
byte-identical — see tests/test_reveal_chunks.py). Both paged range
routes (mask chunks and clerk-result chunks) are fetched as
``application/x-sda-binary`` frames by default — raw ciphertext/uuid
bytes instead of base64'd JSON — with ``SDA_WIRE=json`` pinning the
legacy bodies.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from .. import telemetry
from ..ops.modular import positive
from ..protocol import AdditiveSharing, Committee, SdaError, Snapshot, SnapshotId
from . import prefetch


def require_reconstructible(scheme, present: int, committee_size: int) -> None:
    """Gate the degraded reveal: Shamir-family schemes reconstruct from
    any ``reconstruction_threshold``-sized subset of clerk results, so
    missing clerks are tolerated down to the threshold; additive sharing
    has no redundancy — summing a strict subset of shares silently
    yields a wrong aggregate, so anything short of full attendance must
    fail loudly here. The server's ``result_ready`` applies the same
    threshold, but the client re-checks because it must never hand back
    a wrong sum even against a miscounting (or malicious) server."""
    threshold = scheme.reconstruction_threshold
    if present >= threshold:
        return
    if isinstance(scheme, AdditiveSharing):
        raise SdaError(
            f"additive sharing cannot tolerate missing clerks: only "
            f"{present} of {committee_size} clerk results present and "
            "every share is required — a partial sum would be silently "
            "wrong, not approximate"
        )
    raise SdaError(
        f"not enough surviving clerk results to reconstruct: {present} of "
        f"{committee_size} present, {type(scheme).__name__} needs at "
        f"least {threshold}"
    )

#: reveal pipeline stage latency — one histogram per stage; the bench
#: rider and scripts/check_metrics.py key on this series name
_STAGE_SERIES = "sda_reveal_stage_seconds"
_STAGE_HELP = "recipient reveal pipeline stage latency by stage"


def _iter_result_chunks(fetch, total: int, what: str, stage_times: dict):
    """Yield a paged snapshot-result column as decrypt-ready blocks.

    ``fetch(start)`` is the range read (``get_snapshot_result_masks`` or
    ``get_snapshot_result_clerks``); chunks stream through the shared
    bounded pipeline (client/prefetch.py ``iter_chunks``): up to
    ``SDA_PREFETCH_DEPTH`` range requests in flight while the consumer
    decrypts the current chunk. The range cursor advances by the length
    the server actually returned, so a server configured with a
    different chunk size stays in lockstep.
    """
    if total <= 0:
        return

    download_hist = telemetry.histogram(_STAGE_SERIES, _STAGE_HELP, stage="download")

    def timed_fetch(start: int):
        t0 = time.perf_counter()
        with telemetry.span("reveal.download", what=what, start=start):
            chunk = fetch(start)
        dt = time.perf_counter() - t0
        download_hist.observe(dt)
        stage_times["download"] += dt
        if chunk is None:
            raise SdaError(f"snapshot result {what} disappeared mid-download")
        if not chunk:
            raise SdaError(f"snapshot result {what} truncated at {start}/{total}")
        return chunk

    yield from prefetch.iter_chunks(timed_fetch, total)


@dataclass
class RecipientOutput:
    modulus: int
    values: np.ndarray

    def positive(self) -> "RecipientOutput":
        return RecipientOutput(self.modulus, positive(self.values, self.modulus))


class Receiving:
    def upload_aggregation(self, aggregation) -> None:
        self.service.create_aggregation(self.agent, aggregation)

    def delete_aggregation(self, aggregation_id) -> None:
        """Remove an aggregation this agent is the recipient of (a tiered
        root's derived sub-aggregations cascade server-side)."""
        self.service.delete_aggregation(self.agent, aggregation_id)

    def begin_aggregation(self, aggregation_id, *, chosen_clerks=None) -> None:
        """Elect the committee and open the aggregation for participation.

        Default: the first ``output_size`` suggested candidates that are
        not the recipient itself — the reference's behavior
        (receive.rs:48-62) minus its footgun: a recipient with a signed
        encryption key is a candidate too, and drafting it as a clerk
        would let one party hold both a share column and the combined
        result. ``chosen_clerks`` (a list of AgentIds) lets the
        recipient pick its own committee — the reference's README
        "Doing more" roadmap item ("allow recipient to actually chose
        the clerks"), delivered here. Order defines committee position;
        every chosen clerk must be a candidate (i.e. has uploaded a
        signed encryption key), and the server still independently
        validates size and key signatures. An explicit ``chosen_clerks``
        containing the recipient is honored as chosen.
        """
        aggregation = self.service.get_aggregation(self.agent, aggregation_id)
        if aggregation is None:
            raise ValueError(f"Unknown aggregation {aggregation_id}")
        candidates = self.service.suggest_committee(self.agent, aggregation_id)
        size = aggregation.committee_sharing_scheme.output_size
        if chosen_clerks is None:
            eligible = [c for c in candidates if c.id != aggregation.recipient]
            selected = [(c.id, c.keys[0]) for c in eligible[:size]]
        else:
            if len(chosen_clerks) != size:
                raise ValueError(
                    f"committee needs exactly {size} clerks, "
                    f"{len(chosen_clerks)} chosen"
                )
            if len(set(chosen_clerks)) != len(chosen_clerks):
                raise ValueError("chosen clerks contain duplicates")
            by_id = {c.id: c for c in candidates}
            missing = [str(c) for c in chosen_clerks if c not in by_id]
            if missing:
                raise ValueError(
                    "chosen clerks are not candidates (no signed "
                    f"encryption key): {', '.join(missing)}"
                )
            selected = [(cid, by_id[cid].keys[0]) for cid in chosen_clerks]
        self.service.create_committee(
            self.agent, Committee(aggregation=aggregation_id, clerks_and_keys=selected)
        )

    def end_aggregation(self, aggregation_id):
        """Freeze the aggregation behind one snapshot (idempotent).
        Returns the snapshot's id — callers that go on to read the cut
        (tier promoters folding their mask column) can skip the status
        round-trip they'd otherwise need to rediscover it."""
        status = self.service.get_aggregation_status(self.agent, aggregation_id)
        if status is None:
            raise ValueError("Unknown aggregation")
        if len(status.snapshots) >= 1:
            return status.snapshots[0].id
        snapshot = Snapshot(id=SnapshotId.random(), aggregation=aggregation_id)
        self.service.create_snapshot(self.agent, snapshot)
        return snapshot.id

    def combined_snapshot_mask(
        self, aggregation_id, *, aggregation=None, snapshot_id=None
    ) -> np.ndarray:
        """Decrypt + fold the first snapshot's MASK column only, without
        touching (or waiting for) any clerk results.

        This is the tier promoter's whole job under share-promotion
        (client/tiers.py): the child owner cancels its sub-cohort's mask
        sum one tier up via a correction row, and the mask sum is the ONLY
        thing it ever decrypts — data-independent by the masking schemes'
        construction, so no promotion path reconstructs a partial. Works
        as soon as the snapshot is cut (``get_snapshot_result`` serves
        masks regardless of clerk readiness, and reshare children never
        turn result_ready at all). Returns the canonical [0, m) fold; the
        empty vector when the scheme stores no mask.

        ``aggregation`` and ``snapshot_id`` let a caller that already
        holds the record / just cut the snapshot (``end_aggregation``
        returns its id) skip the rediscovery round-trips — the correction
        sits on the tier round's per-node critical path."""
        if aggregation is None:
            aggregation = self.service.get_aggregation(self.agent, aggregation_id)
        if aggregation is None:
            raise ValueError(f"Unknown aggregation {aggregation_id}")
        if snapshot_id is None:
            status = self.service.get_aggregation_status(self.agent, aggregation_id)
            if status is None:
                raise ValueError("Unknown aggregation")
            if not status.snapshots:
                raise ValueError("Aggregation has no snapshot yet")
            snapshot_id = status.snapshots[0].id
        result = self.service.get_snapshot_result(self.agent, aggregation_id, snapshot_id)
        if result is None:
            raise ValueError("Missing aggregation result")

        decryptor = self.crypto.new_share_decryptor(
            aggregation.recipient_key, aggregation.recipient_encryption_scheme
        )
        stage_times = {"download": 0.0}
        if result.is_paged():
            def fetch_masks(start):
                return self.service.get_snapshot_result_masks(
                    self.agent, aggregation_id, snapshot_id, start
                )

            mask_chunks = (
                None
                if result.mask_encryption_count is None
                else _iter_result_chunks(
                    fetch_masks, result.mask_encryption_count, "masks", stage_times
                )
            )
        else:
            mask_chunks = (
                None
                if result.recipient_encryptions is None
                else iter([result.recipient_encryptions])
            )
        if mask_chunks is None:
            return np.empty(0, dtype=np.int64)
        accumulator = self.crypto.new_mask_combiner(
            aggregation.masking_scheme
        ).accumulator()
        for block in mask_chunks:
            with telemetry.span("reveal.decrypt", what="masks", rows=len(block)):
                accumulator.fold(decryptor.decrypt_batch(block))
        return accumulator.finish()

    def reveal_aggregation(self, aggregation_id) -> RecipientOutput:
        aggregation = self.service.get_aggregation(self.agent, aggregation_id)
        if aggregation is None:
            raise ValueError(f"Unknown aggregation {aggregation_id}")
        committee = self.service.get_committee(self.agent, aggregation_id)
        if committee is None:
            raise ValueError(f"Unknown committee {aggregation_id}")

        status = self.service.get_aggregation_status(self.agent, aggregation_id)
        if status is None:
            raise ValueError("Unknown aggregation")
        ready = [s for s in status.snapshots if s.result_ready]
        if not ready:
            raise ValueError("Aggregation not ready")
        snapshot_id = ready[0].id
        result = self.service.get_snapshot_result(self.agent, aggregation_id, snapshot_id)
        if result is None:
            raise ValueError("Missing aggregation result")

        # one decryptor serves both mask and clerk-result payloads (same key)
        decryptor = self.crypto.new_share_decryptor(
            aggregation.recipient_key, aggregation.recipient_encryption_scheme
        )

        decrypt_hist = telemetry.histogram(_STAGE_SERIES, _STAGE_HELP, stage="decrypt")
        fold_hist = telemetry.histogram(_STAGE_SERIES, _STAGE_HELP, stage="fold")
        stage_times = {"download": 0.0, "decrypt": 0.0, "fold": 0.0, "reconstruct": 0.0}
        t_wall0 = time.perf_counter()

        # both wire shapes feed one streaming machinery: paged results
        # arrive as pipelined range reads, legacy bulk results as a
        # single chunk — fold semantics (and bytes) are identical
        if result.is_paged():
            def fetch_masks(start):
                return self.service.get_snapshot_result_masks(
                    self.agent, aggregation_id, snapshot_id, start
                )

            def fetch_clerks(start):
                return self.service.get_snapshot_result_clerks(
                    self.agent, aggregation_id, snapshot_id, start
                )

            mask_chunks = (
                None
                if result.mask_encryption_count is None  # snapshot stored no mask
                else _iter_result_chunks(
                    fetch_masks, result.mask_encryption_count, "masks", stage_times
                )
            )
            clerk_chunks = _iter_result_chunks(
                fetch_clerks, result.clerk_result_count, "clerk results", stage_times
            )
        else:
            mask_chunks = (
                None
                if result.recipient_encryptions is None
                else iter([result.recipient_encryptions])
            )
            clerk_chunks = iter([result.clerk_encryptions])

        # decrypt + fold masks chunk by chunk: peak memory is one chunk
        # of ciphertexts (plus the prefetched next) and one combined
        # partial — never the whole cohort's mask column
        if mask_chunks is None:
            mask = np.empty(0, dtype=np.int64)
        else:
            accumulator = self.crypto.new_mask_combiner(
                aggregation.masking_scheme
            ).accumulator()
            for block in mask_chunks:
                t0 = time.perf_counter()
                with telemetry.span("reveal.decrypt", what="masks", rows=len(block)):
                    decrypted = decryptor.decrypt_batch(block)
                dt = time.perf_counter() - t0
                decrypt_hist.observe(dt)
                stage_times["decrypt"] += dt
                t0 = time.perf_counter()
                with telemetry.span("reveal.fold"):
                    accumulator.fold(decrypted)
                dt = time.perf_counter() - t0
                fold_hist.observe(dt)
                stage_times["fold"] += dt
            mask = accumulator.finish()

        # stream clerk results, batch-decrypt each block into
        # (committee index, share vector) pairs
        clerk_positions = {
            clerk: ix for ix, (clerk, _) in enumerate(committee.clerks_and_keys)
        }
        indexed_shares = []
        for block in clerk_chunks:
            if not block:
                continue
            for clerking_result in block:
                if clerking_result.clerk not in clerk_positions:
                    raise ValueError(f"Missing clerk {clerking_result.clerk}")
            t0 = time.perf_counter()
            with telemetry.span("reveal.decrypt", what="clerks", rows=len(block)):
                share_vectors = decryptor.decrypt_batch(
                    [cr.encryption for cr in block]
                )
            dt = time.perf_counter() - t0
            decrypt_hist.observe(dt)
            stage_times["decrypt"] += dt
            indexed_shares.extend(
                (clerk_positions[cr.clerk], shares)
                for cr, shares in zip(block, share_vectors)
            )

        # degraded reveal: any >= reconstruction_threshold subset of the
        # committee suffices for Shamir/packed (the vanished clerks'
        # positions simply don't appear in indexed_shares and the
        # Lagrange matrix is built from the survivors); additive requires
        # all of them. Checked before the empty-cut shortcut so zero
        # results can never masquerade as an empty aggregate.
        require_reconstructible(
            aggregation.committee_sharing_scheme,
            len(indexed_shares),
            len(committee.clerks_and_keys),
        )

        if all(len(shares) == 0 for _, shares in indexed_shares):
            # an empty snapshot cut (every clerk combined zero
            # participations): the aggregate over the empty set is the
            # zero vector — don't run the reconstructor on empty batches
            self._record_reveal_pipeline(stage_times, time.perf_counter() - t_wall0)
            return RecipientOutput(
                modulus=aggregation.modulus,
                values=np.zeros(aggregation.vector_dimension, dtype=np.int64),
            )

        t0 = time.perf_counter()
        with telemetry.span("reveal.reconstruct", shares=len(indexed_shares)):
            reconstructor = self.crypto.new_secret_reconstructor(
                aggregation.committee_sharing_scheme, aggregation.vector_dimension
            )
            masked_output = reconstructor.reconstruct(indexed_shares)

            unmasker = self.crypto.new_secret_unmasker(aggregation.masking_scheme)
            output = unmasker.unmask(mask, masked_output)
        dt = time.perf_counter() - t0
        telemetry.histogram(_STAGE_SERIES, _STAGE_HELP, stage="reconstruct").observe(dt)
        stage_times["reconstruct"] += dt
        self._record_reveal_pipeline(stage_times, time.perf_counter() - t_wall0)
        return RecipientOutput(modulus=aggregation.modulus, values=output)

    @staticmethod
    def _record_reveal_pipeline(stage_times: dict, t_wall: float) -> None:
        """Gauge how much download cost the prefetch pipeline hid behind
        compute: 1.0 = fully overlapped, 0.0 = fully serial. Only paged
        reveals accumulate download time (bulk results ride the one
        ``get_snapshot_result`` call), so the gauge tracks paged reveals.
        """
        if stage_times["download"] <= 0:
            return
        compute = (
            stage_times["decrypt"] + stage_times["fold"] + stage_times["reconstruct"]
        )
        overlap = (stage_times["download"] + compute - t_wall) / stage_times["download"]
        telemetry.gauge(
            "sda_reveal_overlap_efficiency",
            "fraction of download time hidden behind decrypt+fold by the "
            "paged-result reveal pipeline (last reveal)",
        ).set(min(1.0, max(0.0, overlap)))
