"""Concurrent committee runner: drain every clerk's queue in parallel.

A committee round used to be driven round-robin — ``for c in clerks:
c.run_chores(-1)`` — which serializes the whole committee on one core
even though each clerk's job is independent and the hot loops (native
sealed-box opens, chunk range GETs) release the GIL or block on the
network. ``run_committee`` dispatches each clerk as one task through
``workpool.scatter`` (one worker per clerk) so committee wall time
approaches the slowest member instead of the sum.

The scatter layer rebinds the caller's trace id, so every clerk's job
processing still joins the same trace. Per-clerk results stay
independent (distinct keys, distinct jobs, distinct HTTP sessions when
each clerk has its own service proxy), so no cross-thread state is
shared beyond the process-wide crypto worker pool — which is itself
thread-safe and shared deliberately (utils/workpool.py).
"""

from __future__ import annotations

import functools

from ..utils import workpool


def run_committee(clerks, max_iterations: int = -1) -> int:
    """Run ``run_chores(max_iterations)`` for every clerk concurrently.

    ``clerks`` is a sequence of clerk-capable clients (anything with
    ``clerk_once``); ``max_iterations`` follows ``run_chores`` semantics
    (negative = drain until no work is left). Returns the total number
    of jobs processed across the committee. The lowest-index worker
    exception is re-raised after all workers finish (the drains are
    never cancelled mid-committee — a half-drained clerk queue would
    leave durable jobs in limbo).
    """
    clerks = list(clerks)
    if not clerks:
        return 0

    def drain(clerk) -> int:
        n = 0
        if max_iterations < 0:
            while clerk.clerk_once():
                n += 1
        else:
            for _ in range(max_iterations):
                if not clerk.clerk_once():
                    break
                n += 1
        return n

    outcomes = workpool.scatter(
        "committee",
        [functools.partial(drain, c) for c in clerks],
        len(clerks),
    )
    for out in outcomes:
        if out.error is not None:
            raise out.error
    return sum(out.value for out in outcomes)
