"""Concurrent committee runner: drain every clerk's queue in parallel.

A committee round used to be driven round-robin — ``for c in clerks:
c.run_chores(-1)`` — which serializes the whole committee on one core
even though each clerk's job is independent and the hot loops (native
sealed-box opens, chunk range GETs) release the GIL or block on the
network. ``run_committee`` gives each clerk its own worker thread so
committee wall time approaches the slowest member instead of the sum.

Each worker rebinds the caller's trace id, so every clerk's job
processing still joins the same trace. Per-clerk results stay
independent (distinct keys, distinct jobs, distinct HTTP sessions when
each clerk has its own service proxy), so no cross-thread state is
shared beyond the process-wide crypto worker pool — which is itself
thread-safe and shared deliberately (utils/workpool.py).
"""

from __future__ import annotations

import threading

from .. import telemetry


def run_committee(clerks, max_iterations: int = -1) -> int:
    """Run ``run_chores(max_iterations)`` for every clerk concurrently.

    ``clerks`` is a sequence of clerk-capable clients (anything with
    ``clerk_once``); ``max_iterations`` follows ``run_chores`` semantics
    (negative = drain until no work is left). Returns the total number
    of jobs processed across the committee. The first worker exception
    is re-raised after all workers finish.
    """
    clerks = list(clerks)
    if not clerks:
        return 0
    counts = [0] * len(clerks)
    errors: list = []
    trace_id = telemetry.current_trace_id()

    def drain(ix: int, clerk) -> None:
        if trace_id:
            telemetry.set_trace_id(trace_id)
        try:
            n = 0
            if max_iterations < 0:
                while clerk.clerk_once():
                    n += 1
            else:
                for _ in range(max_iterations):
                    if not clerk.clerk_once():
                        break
                    n += 1
            counts[ix] = n
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            errors.append(exc)

    if len(clerks) == 1:  # no thread overhead for a committee of one
        drain(0, clerks[0])
    else:
        workers = [
            threading.Thread(target=drain, args=(ix, c), daemon=True)
            for ix, c in enumerate(clerks)
        ]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
    if errors:
        raise errors[0]
    return sum(counts)
