"""Hierarchical round driver: provision the derived tree, run it bottom-up.

The client half of tiered aggregation (arXiv 2201.00864 via
protocol/tiers.py): a tiered aggregation is a TREE of ordinary
aggregations, and a round is the flat pipeline run once per node —
leaves first — with each sub-committee's revealed partial sum PROMOTED
one tier up as an ordinary participation. The server never cascades
anything; this module sequences the tree client-side, exactly like the
flat flow sequences begin/participate/end/clerk/reveal.

Roles per node: the root's recipient is the real recipient; every other
node is owned by a PROMOTER — a throwaway agent that acts as the
sub-aggregation's recipient (reveals the sub-cohort partial) and as a
participant of the parent (re-submits it). Promoters therefore see their
sub-cohort's partial sum in the clear; the paper's full scheme re-shares
without revealing, which is future work (docs/ARCHITECTURE.md notes the
deviation) — individual contributions remain protected by each leaf's
masking + sharing either way.

Exactness: every tier sums in the same modular group, so the root reveal
equals the flat reveal byte-for-byte (partial residues are lifted to
[0, m) with ``.positive()`` before promotion — the same lift the flat
recipient applies at the end; tests/test_tiers.py holds the equality
across schemes, stores, and transports).

Dropout tolerance composes per tier: within a sub-committee, Shamir-family
sharing reveals from any ``reconstruction_threshold`` survivors
(receive.require_reconstructible); a whole sub-cohort that vanishes is
simply absent from the parent's snapshot cut under ``strict=False``, and
the root reveals the exact sum of the survivors.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..protocol import tiers as tiers_mod
from .committee import run_committee
from .receive import RecipientOutput


@dataclass
class TierRoundNode:
    """One provisioned node: its topology position, the stored
    sub-aggregation record, the client that owns it (root recipient or
    promoter), its committee's clerk clients, and the frontend index the
    pure placement function assigns its traffic (0 on single-frontend
    deployments)."""

    node: tiers_mod.TierNode
    aggregation: object
    owner: object
    clerks: list
    frontend: int = 0


@dataclass
class TierRound:
    """A fully provisioned tiered round: root record, real recipient, and
    every node of the derived tree (breadth-first, root first — the order
    ``protocol.tiers.iter_tier_nodes`` enumerates)."""

    root: object
    recipient: object
    nodes: list

    def node(self, aggregation_id) -> Optional[TierRoundNode]:
        for tn in self.nodes:
            if tn.aggregation.id == aggregation_id:
                return tn
        return None

    def leaves(self) -> list:
        return [tn for tn in self.nodes if tn.node.is_leaf_of(self.root)]


@dataclass
class TierRoundResult:
    """Outcome of ``run_tier_round``: the root reveal plus the
    sub-aggregations skipped under ``strict=False`` (vanished sub-cohorts
    or unrevealable sub-committees — the root total is the exact sum over
    everything that did promote)."""

    output: RecipientOutput
    skipped: list = field(default_factory=list)


def setup_tier_round(
    recipient,
    aggregation,
    new_promoter: Callable[[str], object],
    clerk_pool: list,
    *,
    disjoint_committees: bool = False,
    frontends: int = 1,
) -> TierRound:
    """Provision the whole derived tree of a tiered ``aggregation``:
    upload the root, derive + upload every sub-aggregation (parents
    first), register one fresh promoter per non-root node, and elect
    every node's committee from ``clerk_pool``.

    ``new_promoter(name)`` must return a FRESH, unregistered client
    (e.g. tests' ``new_client``); this function uploads its agent and
    sodium key — the key the derived child record pins as its
    recipient key. ``clerk_pool`` entries are registered clerk clients
    that have already uploaded signed encryption keys (i.e. committee
    candidates). Committees are consecutive slices of the pool, wrapping
    — with ``disjoint_committees`` the pool must be large enough that no
    clerk serves two nodes (the deployment shape the paper's per-clerk
    bound assumes; a wrapped pool still COMPUTES correctly, each clerk
    just works more than one node's share).

    ``frontends`` is the frontend-process count of the deployment the
    round runs against: each node is stamped with its deterministic
    frontend index (``protocol.tiers.tier_placement``) so launchers can
    place per-node committee daemons next to the frontend that will
    serve their node's traffic.
    """
    if not aggregation.is_tiered():
        raise ValueError("setup_tier_round requires a tiered aggregation")
    topology = tiers_mod.iter_tier_nodes(aggregation)
    placement = tiers_mod.tier_placement(aggregation, frontends)
    size = aggregation.committee_sharing_scheme.output_size
    if disjoint_committees:
        if len(clerk_pool) < size * len(topology):
            raise ValueError(
                f"disjoint committees need {size * len(topology)} clerks, "
                f"pool has {len(clerk_pool)}"
            )
    elif len(clerk_pool) < size:
        raise ValueError(
            f"clerk pool smaller than one committee ({len(clerk_pool)} < {size})"
        )

    recipient.upload_aggregation(aggregation)
    records = {aggregation.id: aggregation}
    nodes = []
    for position, node in enumerate(topology):
        if node.parent is None:
            agg, owner = aggregation, recipient
        else:
            promoter = new_promoter(f"tier{node.tier}-sub{position}")
            promoter.upload_agent()
            promoter_key = promoter.new_encryption_key()
            promoter.upload_encryption_key(promoter_key)
            agg = tiers_mod.child_aggregation(
                records[node.parent], node.index, promoter.agent.id, promoter_key
            )
            promoter.upload_aggregation(agg)
            records[agg.id] = agg
            owner = promoter
        clerks = [
            clerk_pool[(position * size + j) % len(clerk_pool)] for j in range(size)
        ]
        owner.begin_aggregation(agg.id, chosen_clerks=[c.agent.id for c in clerks])
        nodes.append(
            TierRoundNode(
                node=node,
                aggregation=agg,
                owner=owner,
                clerks=clerks,
                frontend=placement[agg.id],
            )
        )
    return TierRound(root=aggregation, recipient=recipient, nodes=nodes)


def promote_partial(promoter, values, parent_aggregation_id):
    """Submit a revealed sub-cohort partial sum as an ordinary
    participation of the PARENT tier. ``route=False`` is the whole trick:
    a promoter targets its parent node directly instead of being hashed
    down to a leaf like a real participant. Returns the participation id
    (idempotently replayable like any other participation)."""
    parts = promoter.new_participations(
        [values], parent_aggregation_id, route=False
    )
    promoter.upload_participations(parts)
    return parts[0].id


def _await_results(entries, poll_interval: float, deadline: float) -> None:
    """External-clerks drain: the committees run as separate ``sdad
    committee`` daemon processes over the wire, so instead of running
    the clerk loop in-process this polls each node's aggregation status
    until its snapshot reports ``result_ready`` (results count reached
    the reconstruction threshold) — the exact condition the reveal
    needs. Raises TimeoutError past ``deadline`` so a dead daemon fails
    the round loudly instead of spinning forever."""
    waiting = list(entries)
    while waiting:
        still = []
        for tn in waiting:
            status = tn.owner.service.get_aggregation_status(
                tn.owner.agent, tn.aggregation.id
            )
            ready = status is not None and any(
                s.result_ready for s in status.snapshots
            )
            if not ready:
                still.append(tn)
        waiting = still
        if not waiting:
            return
        if time.monotonic() > deadline:
            ids = [str(tn.aggregation.id) for tn in waiting]
            raise TimeoutError(
                f"external committees did not finish clerking: {ids}"
            )
        time.sleep(poll_interval)


def _drain_clerks(entries, max_iterations: int) -> None:
    # one clerk client may serve several nodes' committees (wrapped
    # pool); drain each AGENT once per tier or the same durable queue
    # would be polled by several equivalent client objects
    seen, clerks = set(), []
    for tn in entries:
        for clerk in tn.clerks:
            if clerk.agent.id not in seen:
                seen.add(clerk.agent.id)
                clerks.append(clerk)
    run_committee(clerks, max_iterations)


def run_tier_round(
    round: TierRound,
    *,
    max_iterations: int = -1,
    strict: bool = True,
    external_clerks: bool = False,
    poll_interval: float = 0.1,
    poll_timeout: float = 120.0,
) -> TierRoundResult:
    """Run a provisioned tiered round bottom-up and reveal the root.

    Per tier, deepest first: close every node (freezing its sub-cohort's
    participations into a snapshot), drain that tier's clerks, then each
    promoter reveals its partial sum — lifted to ``[0, modulus)`` — and
    promotes it into the parent. The root closes last, over exactly its
    children's promotions, and the real recipient reveals the total.

    ``strict=False`` tolerates failed sub-aggregations (vanished
    sub-cohort, unrevealable sub-committee): they are recorded in
    ``TierRoundResult.skipped`` and the root reveals the exact sum of
    the survivors. Under ``strict=True`` any sub-tier failure raises.

    ``external_clerks=True`` is the process-spanning mode: committees
    run as separate ``sdad committee`` daemons over the wire, so the
    driver never runs a clerk loop in-process — it just waits (up to
    ``poll_timeout`` seconds per tier) for each closed node's snapshot
    to report ``result_ready`` before revealing.
    """
    depth = tiers_mod.tier_depth(round.root)
    skipped = []

    def _drain(entries):
        if external_clerks:
            _await_results(
                entries, poll_interval, time.monotonic() + poll_timeout
            )
        else:
            _drain_clerks(entries, max_iterations)

    for tier in range(depth - 1, 0, -1):
        entries = [tn for tn in round.nodes if tn.node.tier == tier]
        live = []
        for tn in entries:
            try:
                tn.owner.end_aggregation(tn.aggregation.id)
            except Exception:
                if strict:
                    raise
                skipped.append(tn.aggregation.id)
                continue
            live.append(tn)
        _drain(live)
        for tn in live:
            try:
                partial = tn.owner.reveal_aggregation(tn.aggregation.id).positive()
            except Exception:
                if strict:
                    raise
                skipped.append(tn.aggregation.id)
                continue
            promote_partial(tn.owner, partial.values, tn.node.parent)
    round.recipient.end_aggregation(round.root.id)
    _drain([round.nodes[0]])
    output = round.recipient.reveal_aggregation(round.root.id)
    return TierRoundResult(output=output, skipped=skipped)
