"""Hierarchical round driver: provision the derived tree, run it bottom-up.

The client half of tiered aggregation (arXiv 2201.00864 via
protocol/tiers.py): a tiered aggregation is a TREE of ordinary
aggregations, and a round is the flat pipeline run once per node —
leaves first — with each sub-committee's aggregate PROMOTED one tier up
as ordinary participations of the parent. The server never cascades
anything; this module sequences the tree client-side, exactly like the
flat flow sequences begin/participate/end/clerk/reveal.

Two promotion paths (``protocol.tiers.effective_promotion``):

* **Share-promotion** (``reshare`` — the default for Shamir-family
  committee schemes): each sub-committee clerk expands its combined
  share column through the precomputed Lagrange re-share row
  (ops/shamir.reshare_coefficients / reshare_column) and submits the
  result directly to the PARENT as an ordinary tagged participation
  (client/clerk.py). The node's owner only submits a mask-correction
  row — ``(m - sum of the sub-cohort's masks) % m`` — so the child-level
  masks telescope out of the reshared columns; it never sees any
  partial sum (the mask sum is data-independent). No plaintext exists
  anywhere between the participants and the root recipient.

* **Reveal-promotion** (``reveal`` — additive committees, and the A/B
  baseline behind ``tier_promotion="reveal"``): the node's owner acts as
  the sub-aggregation's recipient, reveals the sub-cohort partial, and
  re-submits it to the parent. The owner sees the partial in the clear;
  kept only because additive sharing has no Lagrange structure to
  re-share through, and for benchmarking the old path.

Exactness: every tier sums in the same modular group, so the root reveal
equals the flat reveal byte-for-byte under either path (re-shared
columns are exact share expansions of the sub-cohort sum; revealed
partials are lifted to [0, m) with ``.positive()`` before promotion —
tests/test_tiers.py holds the equality across schemes, stores, and
transports).

Dropout tolerance composes per tier and now ACROSS tiers: within a
sub-committee, Shamir-family sharing survives down to
``reconstruction_threshold`` clerks — under share-promotion the
surviving clerks re-issue their cached columns against the survivor set
(epoch 1) and the parent's prepare stage keeps exactly one consistent
epoch per child (server/snapshot.py). A sub-cohort that falls below
threshold is absent from the parent's cut under ``strict=False``, and
the root reveals the exact sum of the survivors.
"""

from __future__ import annotations

import functools
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from .. import telemetry
from ..protocol import SdaError, TierReshare
from ..protocol import tiers as tiers_mod
from ..utils import workpool
from ..utils.faults import Backoff
from .committee import run_committee
from .receive import RecipientOutput

# driver-side critical-path latency of promoting one node into its
# parent, labelled by path — the share-promotion A/B headline. Under
# ``reveal`` a sample covers reveal_aggregation + promote_partial (mask
# fold + clerk-column fetch/decrypt/reconstruct + re-submit); under
# ``reshare`` it covers only the mask-correction row (and any epoch-1
# re-issue), since the column expansion rides the clerk drain off the
# driver's critical path (client/clerk.py, sda_tier_reshare_seconds).
# Samples are observed on SUCCESS only: an aborted promotion (skipped
# under ``strict=False``) must never drag the per-path averages the
# ``promote_reshare_speedup`` gate compares.
_PROMOTE_SERIES = "sda_tier_promote_seconds"
_PROMOTE_HELP = "driver-side per-node tier promotion latency by path"

# wall seconds spent closing+promoting one whole tier level, labelled by
# dispatch mode — the serial-vs-fanout A/B series the flagship campaign
# banks (scripts/flagship.py ``tier_close_ab``)
_CLOSE_SERIES = "sda_tier_close_seconds"
_CLOSE_HELP = "per-tier-level close+promote wall seconds by dispatch mode"
_FANOUT_SERIES = "sda_tier_fanout_nodes"
_FANOUT_HELP = "sibling-node tasks dispatched concurrently in the last tier level"


def tier_fanout(nodes: int) -> int:
    """Concurrent sibling-node width for one tier level.

    ``SDA_TIER_FANOUT`` in the environment, else ``2 x`` the crypto
    pool's worker count (``SDA_WORKERS`` / cpu count) — sibling closes
    are REST round-trips plus server-side snapshot staging on *other*
    processes, so the driver profitably holds more requests in flight
    than it has cores. Always clamped to the node count;
    ``SDA_TIER_FANOUT=1`` is the kill switch: ``run_tier_round`` takes
    the exact legacy serial loop, bit for bit.
    """
    raw = os.environ.get("SDA_TIER_FANOUT")
    if raw:
        try:
            width = max(1, int(raw))
        except ValueError:
            raise ValueError(
                f"SDA_TIER_FANOUT must be an integer, got {raw!r}"
            ) from None
    else:
        width = 2 * workpool.workers()
    return max(1, min(nodes, width))


def _poll_backoff(poll_interval: float) -> Backoff:
    """Full-jitter schedule for the external-daemon poll loops — the
    REST client's policy: start at the configured interval, double
    toward a ~2 s idle cap, ``reset()`` whenever a poll observes
    progress so an active tier drains at ``poll_interval`` cadence while
    a stalled daemon is probed at most every couple of seconds."""
    return Backoff(base=poll_interval, cap=max(2.0, poll_interval))


@dataclass
class TierRoundNode:
    """One provisioned node: its topology position, the stored
    sub-aggregation record, the client that owns it (root recipient or
    promoter), its committee's clerk clients, and the frontend index the
    pure placement function assigns its traffic (0 on single-frontend
    deployments)."""

    node: tiers_mod.TierNode
    aggregation: object
    owner: object
    clerks: list
    frontend: int = 0


@dataclass
class TierRound:
    """A fully provisioned tiered round: root record, real recipient, and
    every node of the derived tree (breadth-first, root first — the order
    ``protocol.tiers.iter_tier_nodes`` enumerates)."""

    root: object
    recipient: object
    nodes: list

    def node(self, aggregation_id) -> Optional[TierRoundNode]:
        for tn in self.nodes:
            if tn.aggregation.id == aggregation_id:
                return tn
        return None

    def leaves(self) -> list:
        return [tn for tn in self.nodes if tn.node.is_leaf_of(self.root)]


@dataclass
class TierRoundResult:
    """Outcome of ``run_tier_round``: the root reveal plus the
    sub-aggregations skipped under ``strict=False`` (vanished sub-cohorts
    or unrevealable sub-committees — the root total is the exact sum over
    everything that did promote)."""

    output: RecipientOutput
    skipped: list = field(default_factory=list)


def setup_tier_round(
    recipient,
    aggregation,
    new_promoter: Callable[[str], object],
    clerk_pool: list,
    *,
    disjoint_committees: bool = False,
    frontends: int = 1,
) -> TierRound:
    """Provision the whole derived tree of a tiered ``aggregation``:
    upload the root, derive + upload every sub-aggregation (parents
    first), register one fresh promoter per non-root node, and elect
    every node's committee from ``clerk_pool``.

    ``new_promoter(name)`` must return a FRESH, unregistered client
    (e.g. tests' ``new_client``); this function uploads its agent and
    sodium key — the key the derived child record pins as its
    recipient key. ``clerk_pool`` entries are registered clerk clients
    that have already uploaded signed encryption keys (i.e. committee
    candidates). Committees are consecutive slices of the pool, wrapping
    — with ``disjoint_committees`` the pool must be large enough that no
    clerk serves two nodes (the deployment shape the paper's per-clerk
    bound assumes; a wrapped pool still COMPUTES correctly, each clerk
    just works more than one node's share).

    ``frontends`` is the frontend-process count of the deployment the
    round runs against: each node is stamped with its deterministic
    frontend index (``protocol.tiers.tier_placement``) so launchers can
    place per-node committee daemons next to the frontend that will
    serve their node's traffic.
    """
    if not aggregation.is_tiered():
        raise ValueError("setup_tier_round requires a tiered aggregation")
    topology = tiers_mod.iter_tier_nodes(aggregation)
    placement = tiers_mod.tier_placement(aggregation, frontends)
    size = aggregation.committee_sharing_scheme.output_size
    if disjoint_committees:
        if len(clerk_pool) < size * len(topology):
            raise ValueError(
                f"disjoint committees need {size * len(topology)} clerks, "
                f"pool has {len(clerk_pool)}"
            )
    elif len(clerk_pool) < size:
        raise ValueError(
            f"clerk pool smaller than one committee ({len(clerk_pool)} < {size})"
        )

    recipient.upload_aggregation(aggregation)
    records = {aggregation.id: aggregation}
    nodes = []
    for position, node in enumerate(topology):
        if node.parent is None:
            agg, owner = aggregation, recipient
        else:
            promoter = new_promoter(f"tier{node.tier}-sub{position}")
            promoter.upload_agent()
            promoter_key = promoter.new_encryption_key()
            promoter.upload_encryption_key(promoter_key)
            agg = tiers_mod.child_aggregation(
                records[node.parent], node.index, promoter.agent.id, promoter_key
            )
            promoter.upload_aggregation(agg)
            records[agg.id] = agg
            owner = promoter
        clerks = [
            clerk_pool[(position * size + j) % len(clerk_pool)] for j in range(size)
        ]
        owner.begin_aggregation(agg.id, chosen_clerks=[c.agent.id for c in clerks])
        nodes.append(
            TierRoundNode(
                node=node,
                aggregation=agg,
                owner=owner,
                clerks=clerks,
                frontend=placement[agg.id],
            )
        )
    return TierRound(root=aggregation, recipient=recipient, nodes=nodes)


def promote_partial(promoter, values, parent_aggregation_id):
    """Submit a revealed sub-cohort partial sum as an ordinary
    participation of the PARENT tier. ``route=False`` is the whole trick:
    a promoter targets its parent node directly instead of being hashed
    down to a leaf like a real participant. Returns the participation id
    (idempotently replayable like any other participation)."""
    parts = promoter.new_participations(
        [values], parent_aggregation_id, route=False
    )
    promoter.upload_participations(parts)
    return parts[0].id


def promote_mask_correction(
    owner, node_aggregation, parent_aggregation_id, snapshot_id=None
):
    """Share-promotion's entire owner-side job: fold the node's snapshot
    mask sum (data-independent — the owner learns nothing about the
    values) and submit ``(m - mask_sum) % m`` to the parent as a tagged
    ordinary participation, cancelling the child-level masks still
    embedded in the clerks' re-shared columns. No-op when the node's
    masking scheme carries no mask. The row's id is deterministic
    (``protocol.tiers.reshare_participation_id``) so replays collide
    idempotently; returns the participation id, or None when skipped.
    ``snapshot_id`` (``end_aggregation``'s return) skips the
    status/record rediscovery round-trips on this critical path."""
    if not node_aggregation.masking_scheme.has_mask():
        return None
    mask = owner.combined_snapshot_mask(
        node_aggregation.id, aggregation=node_aggregation, snapshot_id=snapshot_id
    )
    if mask.size == 0:
        # empty sub-cohort under a sealed-mask scheme: nothing was
        # folded, the correction is exactly zero
        mask = np.zeros(node_aggregation.vector_dimension, dtype=np.int64)
    correction = (node_aggregation.modulus - mask) % node_aggregation.modulus
    tag = TierReshare(child=node_aggregation.id, epoch=0)
    pid = tiers_mod.reshare_participation_id(node_aggregation.id, 0)
    parts = owner.new_participations(
        [correction], parent_aggregation_id, route=False, ids=[pid], tier_reshare=tag
    )
    try:
        owner.upload_participations(parts)
    except Exception as e:
        if "already exists" not in str(e):
            raise
    return pid


def _await_results(entries, poll_interval: float, deadline: float) -> None:
    """External-clerks drain: the committees run as separate ``sdad
    committee`` daemon processes over the wire, so instead of running
    the clerk loop in-process this polls each node's aggregation status
    until its snapshot reports ``result_ready`` (results count reached
    the reconstruction threshold) — the exact condition the reveal
    needs. Raises TimeoutError past ``deadline`` so a dead daemon fails
    the round loudly instead of spinning forever. Polls ride the shared
    full-jitter :class:`Backoff` (reset whenever a node turns ready), so
    a long wait on slow daemons converges to ~2 s probes instead of
    hammering every ``poll_interval``."""
    waiting = list(entries)
    backoff = _poll_backoff(poll_interval)
    while waiting:
        still = []
        for tn in waiting:
            status = tn.owner.service.get_aggregation_status(
                tn.owner.agent, tn.aggregation.id
            )
            ready = status is not None and any(
                s.result_ready for s in status.snapshots
            )
            if not ready:
                still.append(tn)
        if len(still) < len(waiting):
            backoff.reset()  # progress: stay at the base cadence
        waiting = still
        if not waiting:
            return
        if time.monotonic() > deadline:
            ids = [str(tn.aggregation.id) for tn in waiting]
            raise TimeoutError(
                f"external committees did not finish clerking: {ids}"
            )
        backoff.sleep()


def _drain_clerks(entries, max_iterations: int) -> None:
    # one clerk client may serve several nodes' committees (wrapped
    # pool); drain each AGENT once per tier or the same durable queue
    # would be polled by several equivalent client objects
    seen, clerks = set(), []
    for tn in entries:
        for clerk in tn.clerks:
            if clerk.agent.id not in seen:
                seen.add(clerk.agent.id)
                clerks.append(clerk)
    run_committee(clerks, max_iterations)


def _ensure_reshared(tn: TierRoundNode) -> None:
    """In-process survivor check after a share-promotion drain: if every
    committee clerk is still attached to the node, the epoch-0 columns
    (full committee, exact by construction) already landed in the parent
    and nothing remains. Otherwise the survivors — who each cached their
    combined column while processing their clerking job — re-issue
    against the surviving position set as epoch 1; the parent's prepare
    stage keeps the highest complete epoch and discards the rest. Raises
    SdaError when the survivors cannot reconstruct (below threshold):
    the caller skips or aborts per ``strict``."""
    scheme = tn.aggregation.committee_sharing_scheme
    if len(tn.clerks) == scheme.output_size:
        # full committee still attached (setup elected exactly these
        # clerks): the epoch-0 columns already landed during the drain,
        # so skip the committee fetch on the no-death fast path
        return
    committee = tn.owner.service.get_committee(tn.owner.agent, tn.aggregation.id)
    if committee is None:
        raise SdaError(f"no committee for tier node {tn.aggregation.id}")
    positions = {
        clerk_id: ix for ix, (clerk_id, _) in enumerate(committee.clerks_and_keys)
    }
    survivors = sorted(
        positions[c.agent.id] for c in tn.clerks if c.agent.id in positions
    )
    if len(survivors) == scheme.output_size:
        return
    if len(survivors) < scheme.reconstruction_threshold:
        raise SdaError(
            f"tier node {tn.aggregation.id}: {len(survivors)} surviving "
            f"clerks cannot re-share (threshold "
            f"{scheme.reconstruction_threshold})"
        )
    for clerk in tn.clerks:
        if clerk.agent.id in positions:
            clerk.reshare_tier_child(tn.aggregation, survivors, epoch=1)


def _await_promotions(
    round: TierRound,
    entries,
    poll_interval: float,
    deadline: float,
    strict: bool,
    skipped: list,
) -> None:
    """External-clerks wait for share-promotion: the committees run as
    separate daemons, so the driver polls each PARENT's participation
    count until every live child's promotion rows have landed —
    ``share_count`` tagged columns per child plus one mask-correction
    row when the scheme masks. Children never turn ``result_ready``
    under share-promotion (their clerks submit upward instead of sealing
    clerking results), which is why this polls the parent instead of
    ``_await_results``. On timeout, ``strict`` raises; otherwise the
    round proceeds and the parent's prepare stage drops whichever
    children stayed incomplete — which child stalled cannot be
    attributed from out here (the count is per parent), so every child
    of a stalled parent is recorded in ``skipped`` conservatively; the
    root total remains the exact sum over the complete children."""
    per_child = round.root.committee_sharing_scheme.output_size
    if round.root.masking_scheme.has_mask():
        per_child += 1
    by_parent: dict = {}
    for tn in entries:
        by_parent.setdefault(tn.node.parent, []).append(tn)
    waiting = {parent: len(children) * per_child for parent, children in by_parent.items()}
    backoff = _poll_backoff(poll_interval)
    while waiting:
        done = []
        for parent_id, expected in waiting.items():
            owner = round.node(parent_id).owner
            status = owner.service.get_aggregation_status(owner.agent, parent_id)
            if status is not None and status.number_of_participations >= expected:
                done.append(parent_id)
        for parent_id in done:
            del waiting[parent_id]
        if done:
            backoff.reset()  # progress: stay at the base cadence
        if not waiting:
            return
        if time.monotonic() > deadline:
            ids = [str(p) for p in waiting]
            if strict:
                raise TimeoutError(
                    f"tier promotions did not land in parents: {ids}"
                )
            for parent_id in waiting:
                for tn in by_parent[parent_id]:
                    skipped.append(tn.aggregation.id)
            return
        backoff.sleep()


def _gather(entries, outcomes, strict: bool, skipped: list) -> list:
    """Fold fanned-out per-node outcomes back into the serial loop's
    exact semantics, in NODE-INDEX order regardless of completion order:
    under ``strict`` the lowest-index failure re-raises (its outstanding
    siblings were cancelled by the pool); otherwise failed nodes land in
    ``skipped`` and the survivors come back in order."""
    if strict:
        for out in outcomes:
            if out.error is not None:
                raise out.error
    live = []
    for tn, out in zip(entries, outcomes):
        if out.error is not None or out.cancelled:
            skipped.append(tn.aggregation.id)
        else:
            live.append(tn)
    return live


def _note_overlap(span_record, outcomes, wall: float, width: int) -> None:
    """Per-tier overlap efficiency onto the enclosing span's attrs —
    busy task seconds over ``wall x width``, 1.0 meaning the fanned-out
    siblings kept every lane busy the whole time. The flight recorder
    (telemetry/flight.py ``round_report``) surfaces these per tier."""
    if span_record is None or wall <= 0 or width <= 0:  # telemetry off
        return
    busy = sum(o.seconds for o in outcomes if not o.cancelled)
    span_record["attrs"]["overlap_efficiency"] = round(
        min(1.0, busy / (wall * width)), 4
    )


def run_tier_round(
    round: TierRound,
    *,
    max_iterations: int = -1,
    strict: bool = True,
    external_clerks: bool = False,
    poll_interval: float = 0.1,
    poll_timeout: float = 120.0,
) -> TierRoundResult:
    """Run a provisioned tiered round bottom-up and reveal the root.

    Per tier, deepest first: close every node (freezing its sub-cohort's
    participations into a snapshot), then promote it into the parent
    along the round's path (``protocol.tiers.effective_promotion``):

    * ``reshare`` (default for Shamir-family schemes): the node's owner
      submits only the mask-correction row; the tier's clerks — drained
      next — expand their combined columns through the Lagrange re-share
      row straight into the parent (client/clerk.py). After the drain,
      ``_ensure_reshared`` re-issues from the survivors (epoch 1) when
      clerks died, so the round survives any sub-committee down to its
      reconstruction threshold without anyone revealing a partial.

    * ``reveal`` (additive committees / A/B baseline): drain the tier's
      clerks, then each owner reveals its partial sum — lifted to
      ``[0, modulus)`` — and re-submits it to the parent.

    The root closes last, over exactly its children's promotions, and
    the real recipient reveals the total. Per-node promotion latency is
    observed into ``sda_tier_promote_seconds{path=...}`` either way.

    ``strict=False`` tolerates failed sub-aggregations (vanished
    sub-cohort, sub-committee below threshold): they are recorded in
    ``TierRoundResult.skipped`` and the root reveals the exact sum of
    the survivors. Under ``strict=True`` any sub-tier failure raises.

    ``external_clerks=True`` is the process-spanning mode: committees
    run as separate ``sdad committee`` daemons over the wire, so the
    driver never runs a clerk loop in-process — per tier it waits (up to
    ``poll_timeout`` seconds) for the daemons to finish: under reveal,
    for each closed node's snapshot to report ``result_ready``; under
    share-promotion, for each parent's participation count to reach its
    children's expected promotion rows (children never turn
    ``result_ready`` on this path — their clerks submit upward instead
    of sealing clerking results).

    Fanout contract: sibling nodes within one tier level are independent
    (different sub-cohorts, different frontends under the placement
    function), so their closes — and the reveal path's promotions — are
    dispatched :func:`tier_fanout`-wide through ``workpool.scatter``.
    Observable behaviour is unchanged from the serial loop: ``skipped``
    and the live set are ordered by node index regardless of completion
    order, a ``strict`` failure cancels outstanding siblings and
    re-raises the lowest-index error, and ``SDA_TIER_FANOUT=1`` takes
    the exact legacy serial loop. Each level's wall lands in
    ``sda_tier_close_seconds{mode=serial|fanout}`` and the effective
    width in ``sda_tier_fanout_nodes``; the ``tier.close`` span carries
    the per-level ``overlap_efficiency``.
    """
    depth = tiers_mod.tier_depth(round.root)
    reshare = (
        tiers_mod.effective_promotion(round.root) == tiers_mod.PROMOTION_RESHARE
    )
    skipped = []
    promote_hist = telemetry.histogram(
        _PROMOTE_SERIES,
        _PROMOTE_HELP,
        path=tiers_mod.PROMOTION_RESHARE if reshare else tiers_mod.PROMOTION_REVEAL,
    )

    def _drain(entries):
        if external_clerks:
            _await_results(
                entries, poll_interval, time.monotonic() + poll_timeout
            )
        else:
            _drain_clerks(entries, max_iterations)

    path_label = (
        tiers_mod.PROMOTION_RESHARE if reshare else tiers_mod.PROMOTION_REVEAL
    )

    def _close_node(tn: TierRoundNode) -> None:
        # closing the node (snapshot pipeline) is common to both paths
        # and untimed; only the promotion work itself is observed, so
        # the per-path samples compare like for like — and only on
        # success, so an aborted promotion (skipped under strict=False)
        # never leaves a sample
        snapshot_id = tn.owner.end_aggregation(tn.aggregation.id)
        if reshare:
            t0 = time.perf_counter()
            promote_mask_correction(
                tn.owner,
                tn.aggregation,
                tn.node.parent,
                snapshot_id=snapshot_id,
            )
            promote_hist.observe(time.perf_counter() - t0)

    def _reveal_promote_node(tn: TierRoundNode) -> None:
        t0 = time.perf_counter()
        partial = tn.owner.reveal_aggregation(tn.aggregation.id).positive()
        promote_partial(tn.owner, partial.values, tn.node.parent)
        promote_hist.observe(time.perf_counter() - t0)

    for tier in range(depth - 1, 0, -1):
        entries = [tn for tn in round.nodes if tn.node.tier == tier]
        width = tier_fanout(len(entries))
        mode = "serial" if width <= 1 else "fanout"
        close_hist = telemetry.histogram(_CLOSE_SERIES, _CLOSE_HELP, mode=mode)
        telemetry.gauge(_FANOUT_SERIES, _FANOUT_HELP).set(width)
        live = []
        t_level = time.perf_counter()
        with telemetry.span(
            "tier.close", tier=tier, nodes=len(entries), path=path_label,
            mode=mode, width=width,
        ) as close_span:
            if width <= 1:
                # SDA_TIER_FANOUT=1 kill switch: the legacy serial loop
                for tn in entries:
                    try:
                        _close_node(tn)
                    except Exception:
                        if strict:
                            raise
                        skipped.append(tn.aggregation.id)
                        continue
                    live.append(tn)
            else:
                # one close task per sibling node through a bounded
                # pool: the round-trips and the server-side snapshot
                # staging on different frontends overlap; a strict
                # failure cancels the outstanding siblings before
                # _gather re-raises it
                t0 = time.perf_counter()
                outcomes = workpool.scatter(
                    "tier_close",
                    [functools.partial(_close_node, tn) for tn in entries],
                    width,
                    cancel_on_error=strict,
                )
                _note_overlap(
                    close_span, outcomes, time.perf_counter() - t0, width
                )
                live = _gather(entries, outcomes, strict, skipped)
        with telemetry.span(
            "tier.promote", tier=tier, nodes=len(live), path=path_label,
            mode=mode, width=width,
        ) as promote_span:
            if not reshare:
                _drain(live)
                if width <= 1:
                    for tn in live:
                        try:
                            _reveal_promote_node(tn)
                        except Exception:
                            if strict:
                                raise
                            skipped.append(tn.aggregation.id)
                            continue
                else:
                    t0 = time.perf_counter()
                    outcomes = workpool.scatter(
                        "tier_promote",
                        [
                            functools.partial(_reveal_promote_node, tn)
                            for tn in live
                        ],
                        width,
                        cancel_on_error=strict,
                    )
                    _note_overlap(
                        promote_span, outcomes, time.perf_counter() - t0, width
                    )
                    _gather(live, outcomes, strict, skipped)
            elif external_clerks:
                _await_promotions(
                    round,
                    live,
                    poll_interval,
                    time.monotonic() + poll_timeout,
                    strict,
                    skipped,
                )
            else:
                _drain_clerks(live, max_iterations)
                # the survivor re-issue check stays serial under fanout
                # on purpose: the no-death fast path is a local length
                # check, and the rare epoch-1 re-issue walks clerk
                # clients a wrapped pool may share between siblings —
                # concurrent re-issue through one clerk object is the
                # only unsafe interleaving the fan-out could introduce
                for tn in live:
                    t0 = time.perf_counter()
                    try:
                        _ensure_reshared(tn)
                    except Exception:
                        if strict:
                            raise
                        skipped.append(tn.aggregation.id)
                        continue
                    promote_hist.observe(time.perf_counter() - t0)
        close_hist.observe(time.perf_counter() - t_level)
    with telemetry.span("tier.root_close", path=path_label):
        round.recipient.end_aggregation(round.root.id)
        _drain([round.nodes[0]])
    with telemetry.span("tier.root_reveal", path=path_label):
        output = round.recipient.reveal_aggregation(round.root.id)
    return TierRoundResult(output=output, skipped=skipped)
