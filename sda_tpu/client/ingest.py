"""Arrival-driven cohort ingest: plan, build ahead, micro-batch upload.

The flagship profile (ROADMAP item 1) located the per-rung wall in
participant ingest: every phone's participation was built as a
batch-of-1 (forfeiting the shared-ephemeral seal and
``encrypt_share_matrix`` amortization of ``new_participations``) and
uploaded as a single POST, all serialized with the arrival-trace sleep
on the driver core. But arrival times are a *pure function* of
``(seed, index)`` (:mod:`sda_tpu.utils.arrivals`), so nothing about the
trace requires building at arrival time. This module is the pipelined
discipline — the cohort-level analogue of the packed-SS accelerator
pipelines (PAPERS.md 2601.13041):

* **plan** — precompute the whole arrival schedule up front by stepping
  the trace cursor without sleeping: ``(slot, trace index, arrival
  offset, churned?)`` per phone.
* **build** — construct participations *ahead of* their arrival times
  in windows of W phones: within a window, rows are grouped by owning
  participant and each group is ONE ``new_participations`` engine call
  (shared-ephemeral seal + share-matrix amortization restored), the
  groups optionally fanned over ``SDA_WORKERS`` via the PR-5 workpool.
  A per-participant resource cache skips the repeated
  aggregation/committee fetches across windows.
* **upload** — release built rows as micro-batches on the bulk batch
  route. The batch-route ACL requires every row of one POST to belong
  to the calling participant, and one participant's real rows all land
  on its single leaf aggregation — so per-participant grouping IS
  per-frontend grouping under the deterministic tier placement. A row
  is held until its arrival time has passed, within a bounded release
  tolerance (``SDA_ARRIVAL_SLACK_S``, default 0.05s: a row may leave at
  most that much early, never more). Churned phones are deferred to a
  bulk drain at the end of the round, exactly like the serial path.

Backpressure invariant: the builder blocks once ``max_backlog`` rows
are built but unreleased, so build-ahead never grows RSS with the
cohort — the in-flight window is bounded regardless of how far the
trace sleeps fall behind the build rate.

Trace-fidelity contract: release order is slot order (arrival times are
monotone in the trace index), no row is handed to the service before
``arrival_time - slack``, and churned rows upload only after every live
row — byte-identical reveals to the serial path by construction.

``SDA_INGEST_PIPELINE=0`` keeps callers on their legacy serial loop
(the A/B baseline); the knob is read by the drivers, not here.

Telemetry: ``sda_ingest_stage_seconds{stage=plan|build|upload}`` (plan:
the whole schedule; build: per window; upload: per micro-batch),
``sda_arrival_lag_seconds`` (per-row release lag behind the planned
arrival), and the ``sda_ingest_backlog`` gauge (rows built but not yet
released).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

from .. import telemetry
from ..utils import workpool

_STAGE_SERIES = "sda_ingest_stage_seconds"
_STAGE_HELP = (
    "arrival-pipeline stage latency (plan: whole schedule; build: per "
    "window; upload: per micro-batch)"
)
_LAG_SERIES = "sda_arrival_lag_seconds"
_LAG_HELP = "per-row release lag behind the planned arrival time"
_BACKLOG_SERIES = "sda_ingest_backlog"
_BACKLOG_HELP = "rows built but not yet released to the service"

#: phones per builder engine call — the share-matrix amortization unit
DEFAULT_WINDOW = 64
DEFAULT_SLACK_S = 0.05


def pipeline_enabled() -> bool:
    """Whether callers should take the pipelined ingest path (default
    on; ``SDA_INGEST_PIPELINE=0`` pins the legacy serial loop as the
    A/B baseline)."""
    return os.environ.get("SDA_INGEST_PIPELINE", "1") != "0"


def arrival_slack_s() -> float:
    """Bounded release tolerance: a row may be handed to the service at
    most this many seconds before its planned arrival time."""
    raw = os.environ.get("SDA_ARRIVAL_SLACK_S")
    if raw is None or not raw.strip():
        return DEFAULT_SLACK_S
    try:
        return max(0.0, float(raw))
    except ValueError:
        raise ValueError(
            f"SDA_ARRIVAL_SLACK_S must be a number, got {raw!r}"
        ) from None


@dataclass(frozen=True)
class PlannedArrival:
    """One planned phone: its position in the cohort (``slot`` indexes
    the values/participants lists), the global trace index its draws
    came from, the arrival offset in trace time, and the churn flag."""

    slot: int
    index: int
    at: float
    churned: bool


def plan_arrivals(trace, cursor: dict, n: int) -> list:
    """Advance the shared trace cursor ``n`` arrivals WITHOUT sleeping
    and return the schedule. ``cursor`` is the drivers' persistent
    ``{"index": k, "t": last trace time, ...}`` dict — mutated exactly
    as the serial loop would, so serial and pipelined rounds interleave
    on one continuous trace."""
    out = []
    for slot in range(n):
        k = cursor["index"]
        cursor["index"] = k + 1
        cursor["t"] = trace.next_arrival(k, cursor["t"])
        out.append(
            PlannedArrival(
                slot=slot, index=k, at=cursor["t"], churned=trace.is_churned(k)
            )
        )
    return out


@dataclass
class IngestReport:
    """What one pipelined cohort did: row/churn counts, how many build
    windows and upload POSTs it took, the peak built-but-unreleased
    backlog (the backpressure bound held iff ``max_backlog_seen <=
    max_backlog``), and the worst per-row release lag."""

    rows: int = 0
    churned: int = 0
    windows: int = 0
    batches: int = 0
    deferred_batches: int = 0
    max_backlog_seen: int = 0
    max_lag_s: float = 0.0


def ingest_cohort(
    participants,
    values_list,
    aggregation_id,
    *,
    trace=None,
    cursor: Optional[dict] = None,
    window: int = DEFAULT_WINDOW,
    slack_s: Optional[float] = None,
    max_backlog: Optional[int] = None,
    route: bool = True,
) -> IngestReport:
    """Ingest a cohort through the plan/build/upload pipeline.

    ``values_list[i]`` belongs to ``participants[i % len(participants)]``
    — the flagship's identity-cycling convention; a single-participant
    cohort is the ``[participant]`` special case. With ``trace`` (an
    :class:`~sda_tpu.utils.arrivals.ArrivalTrace`) and its ``cursor``
    (``{"index", "t", "t0"}``, mutated in place), rows are released on
    the arrival schedule; without a trace every row is immediately
    releasable and the pipeline degenerates to windowed batch submit.

    The builder runs on a worker thread so window k+1 seals while
    window k's rows wait out their arrival sleeps or ride the wire;
    ``max_backlog`` (default ``4 * window``) bounds how far it may run
    ahead. Build or upload failures propagate to the caller after the
    other stage is stopped; rows already uploaded stay stored and are
    idempotently replayable, exactly like ``participate_many``.
    """
    values_list = list(values_list)
    n = len(values_list)
    report = IngestReport(rows=n)
    if n == 0:
        return report
    if not participants:
        raise ValueError("ingest_cohort needs at least one participant")
    n_p = len(participants)
    if trace is not None and cursor is None:
        raise ValueError("a trace needs its cursor ({'index','t','t0'})")
    window = max(1, int(window))
    slack = arrival_slack_s() if slack_s is None else max(0.0, float(slack_s))
    bound = max(window, int(max_backlog) if max_backlog is not None else 4 * window)

    plan_hist = telemetry.histogram(_STAGE_SERIES, _STAGE_HELP, stage="plan")
    build_hist = telemetry.histogram(_STAGE_SERIES, _STAGE_HELP, stage="build")
    upload_hist = telemetry.histogram(_STAGE_SERIES, _STAGE_HELP, stage="upload")
    lag_hist = telemetry.histogram(_LAG_SERIES, _LAG_HELP)
    backlog_gauge = telemetry.gauge(_BACKLOG_SERIES, _BACKLOG_HELP)
    built_total = telemetry.counter(
        "sda_client_participations_total",
        "participations built by the batched client path",
    )

    # -- plan: the whole schedule up front, no sleeping ------------------
    t_plan = time.perf_counter()
    with telemetry.span("ingest.plan", rows=n):
        if trace is not None:
            schedule = plan_arrivals(trace, cursor, n)
            t0 = cursor["t0"]
        else:
            schedule = [PlannedArrival(s, s, 0.0, False) for s in range(n)]
            t0 = None
    plan_hist.observe(time.perf_counter() - t_plan)

    buf: deque = deque()
    cv = threading.Condition()
    state = {"done": False, "stop": False, "error": None}
    # one resource cache per participant slot: the aggregation record,
    # leaf resolution, and committee are fetched once per phone per
    # cohort instead of once per engine call
    caches: dict = {}
    trace_id = telemetry.current_trace_id()

    def _note_backlog_locked() -> None:
        backlog_gauge.set(len(buf))
        if len(buf) > report.max_backlog_seen:
            report.max_backlog_seen = len(buf)

    def _build() -> None:
        # worker threads start with a fresh contextvars context: rebind
        # the caller's trace id so build spans join the round's trace
        if trace_id:
            telemetry.set_trace_id(trace_id)
        try:
            for lo in range(0, n, window):
                entries = schedule[lo : lo + window]
                groups: dict = {}
                for e in entries:
                    groups.setdefault(e.slot % n_p, []).append(e)
                group_list = list(groups.items())

                def kernel(sub, n_threads):
                    out = []
                    for pix, es in sub:
                        p = participants[pix]
                        parts = p.new_participations(
                            [values_list[e.slot] for e in es],
                            aggregation_id,
                            route=route,
                            cache=caches.setdefault(pix, {}),
                        )
                        out.append(parts)
                    return out

                t_b = time.perf_counter()
                with telemetry.span("ingest.build", rows=len(entries)):
                    built = workpool.map_items("ingest_build", group_list, kernel)
                build_hist.observe(time.perf_counter() - t_b)
                built_total.inc(len(entries))
                report.windows += 1
                rows = [
                    (e, pix, part)
                    for (pix, es), parts in zip(group_list, built)
                    for e, part in zip(es, parts)
                ]
                rows.sort(key=lambda r: r[0].slot)
                with cv:
                    for row in rows:
                        while len(buf) >= bound and not state["stop"]:
                            cv.wait(0.5)
                        if state["stop"]:
                            return
                        buf.append(row)
                        _note_backlog_locked()
                        cv.notify_all()
        except BaseException as e:  # surfaced by the uploader
            with cv:
                state["error"] = e
                cv.notify_all()
        finally:
            with cv:
                state["done"] = True
                cv.notify_all()

    # -- upload: release at arrival time, per-participant micro-batches --
    deferred: dict = {}
    pending: list = []

    def _flush() -> None:
        if not pending:
            return
        by_phone: dict = {}
        for e, pix, part in pending:
            by_phone.setdefault(pix, []).append((e, part))
        now = time.perf_counter()
        for pix, rows in by_phone.items():
            t_u = time.perf_counter()
            with telemetry.span("ingest.upload", rows=len(rows)):
                participants[pix].upload_participations([p for _, p in rows])
            upload_hist.observe(time.perf_counter() - t_u)
            report.batches += 1
            if t0 is not None:
                for e, _ in rows:
                    lag = max(0.0, now - (t0 + e.at))
                    lag_hist.observe(lag)
                    if lag > report.max_lag_s:
                        report.max_lag_s = lag
        pending.clear()

    builder = threading.Thread(target=_build, name="sda-ingest-build")
    builder.start()
    try:
        taken = 0
        while taken < n:
            with cv:
                while not buf and state["error"] is None and not state["done"]:
                    cv.wait()
                if state["error"] is not None:
                    raise state["error"]
                if not buf:
                    raise RuntimeError(
                        "ingest builder exited before the schedule drained"
                    )
                row = buf.popleft()
                _note_backlog_locked()
                cv.notify_all()
            taken += 1
            e, pix, part = row
            if e.churned:
                deferred.setdefault(pix, []).append(part)
                report.churned += 1
                continue
            if t0 is not None:
                delay = t0 + e.at - slack - time.perf_counter()
                if delay > 0:
                    # arrivals are monotone in slot, so everything
                    # pending is already due: flush it, then sleep
                    _flush()
                    time.sleep(delay)
            pending.append(row)
            if len(pending) >= window:
                _flush()
        _flush()
        # churned phones reconnect after every live arrival: one bulk
        # POST per participant (= per frontend under tier placement)
        for pix, parts in deferred.items():
            t_u = time.perf_counter()
            with telemetry.span("ingest.upload", rows=len(parts), deferred=True):
                participants[pix].upload_participations(parts)
            upload_hist.observe(time.perf_counter() - t_u)
            report.deferred_batches += 1
    finally:
        with cv:
            state["stop"] = True
            cv.notify_all()
        builder.join()
        backlog_gauge.set(0)
    if state["error"] is not None:
        raise state["error"]
    return report
