"""Participant role: mask, share, seal, upload.

Mirrors /root/reference/client/src/participate.rs:37-113: fetch aggregation
and committee, mask the secrets (optionally sealing the mask to the
recipient), share the masked vector across the committee, then per clerk
fetch + signature-verify the encryption key and seal that clerk's share
vector. ``new_participation`` is separate from upload so retries are
idempotent under the client-chosen ParticipationId.

``new_participations``/``participate_many`` are the batched forms: the
aggregation, committee, and verified clerk keys are fetched once, every
clerk share across the whole batch is sealed in one engine call
(crypto.encrypt_share_matrix), and upload goes through the service's bulk
``create_participations`` — the client half of the batched ingest pipeline.

Over REST, each batch upload is ONE keep-alive POST on the batch route,
carried as an ``application/x-sda-binary`` frame by default (rest/wire.py
packs ids as raw uuid bytes and sealed boxes as raw ciphertext bytes —
no base64, no per-row JSON). ``SDA_WIRE=json`` pins the legacy JSON
array body; either way the sealed bytes on the wire are identical.
"""

from __future__ import annotations

import time

import numpy as np

from .. import telemetry
from ..protocol import Participation, ParticipationId
from ..protocol import tiers as tiers_mod
from .keys import VerifiedKeys


class Participating(VerifiedKeys):
    def participate(self, values, aggregation_id, *, route: bool = True) -> None:
        participation = self.new_participation(values, aggregation_id, route=route)
        self.upload_participation(participation)

    def participate_many(
        self, values_list, aggregation_id, chunk_size: int = 256, *, route: bool = True
    ) -> list:
        """Build + upload one participation per entry of ``values_list``,
        batching both the crypto and the submission. Returns the ids.

        Chunks of ``chunk_size`` are PIPELINED: while chunk k uploads on a
        worker thread (one keep-alive POST on the batch route), the main
        thread is already sealing chunk k+1 — build and network never
        serialize. Each chunk is one atomic submit; a failed chunk raises
        before any later chunk is submitted (earlier chunks stay stored,
        and are idempotently replayable)."""
        import threading

        values_list = list(values_list)
        ids: list = []
        errors: list = []

        build_hist = telemetry.histogram(
            "sda_client_chunk_seconds",
            "participate_many per-chunk latency by stage",
            stage="build",
        )
        upload_hist = telemetry.histogram(
            "sda_client_chunk_seconds",
            "participate_many per-chunk latency by stage",
            stage="upload",
        )
        built_total = telemetry.counter(
            "sda_client_participations_total",
            "participations built by the batched client path",
        )
        # the upload rides a worker thread, which starts with a FRESH
        # contextvars context — rebind the caller's trace id there so the
        # batch POST still carries X-SDA-Trace
        trace_id = telemetry.current_trace_id()

        def submit(batch):
            if trace_id:
                telemetry.set_trace_id(trace_id)
            t0 = time.perf_counter()
            try:
                with telemetry.span("ingest.upload", rows=len(batch)):
                    self.upload_participations(batch)
            except BaseException as e:
                errors.append(e)
            finally:
                upload_hist.observe(time.perf_counter() - t0)

        inflight = None
        for lo in range(0, len(values_list), chunk_size):
            t0 = time.perf_counter()
            with telemetry.span("ingest.build", rows=min(chunk_size, len(values_list) - lo)):
                batch = self.new_participations(
                    values_list[lo : lo + chunk_size], aggregation_id, route=route
                )
            build_hist.observe(time.perf_counter() - t0)
            built_total.inc(len(batch))
            if inflight is not None:
                inflight.join()
                if errors:
                    raise errors[0]
            ids.extend(p.id for p in batch)
            inflight = threading.Thread(target=submit, args=(batch,))
            inflight.start()
        if inflight is not None:
            inflight.join()
            if errors:
                raise errors[0]
        return ids

    def upload_participation(self, participation) -> None:
        self.service.create_participation(self.agent, participation)

    def upload_participations(self, participations) -> None:
        self.service.create_participations(self.agent, list(participations))

    def new_participation(self, values, aggregation_id, *, route: bool = True) -> Participation:
        return self.new_participations([values], aggregation_id, route=route)[0]

    def new_participations(
        self,
        values_list,
        aggregation_id,
        *,
        route: bool = True,
        ids=None,
        tier_reshare=None,
        cache=None,
    ) -> list:
        """``ids`` pins client-chosen participation ids (share-promotion
        rows use deterministic uuid5 ids so re-drains collide idempotently
        instead of double-counting); ``tier_reshare`` tags every built row
        as a tier promotion (protocol.resources.TierReshare). Both default
        off, leaving ordinary participations byte-unchanged.

        ``cache`` (a caller-owned dict) memoizes the aggregation record,
        leaf resolution, and committee across repeated calls against the
        same round — the windowed ingest pipeline builds many small
        batches per phone, and without it every window re-pays the same
        service round-trips. Scope a cache to one round: it never
        observes committee changes made after the first fetch."""
        secrets_rows = [np.asarray(v, dtype=np.int64) for v in values_list]
        if ids is not None and len(ids) != len(secrets_rows):
            raise ValueError("ids must match values_list one to one")

        def cached(kind, key, fetch):
            if cache is None:
                return fetch()
            value = cache.get((kind, key))
            if value is None:
                value = fetch()
                if value is not None:
                    cache[(kind, key)] = value
            return value

        aggregation = cached(
            "aggregation", aggregation_id,
            lambda: self.service.get_aggregation(self.agent, aggregation_id),
        )
        if aggregation is None:
            raise ValueError("Could not find aggregation")
        if route and aggregation.is_tiered():
            # hierarchical root: real participations belong to this
            # participant's LEAF sub-aggregation, derived by pure hashing
            # from the root record (protocol/tiers.py) — no extra server
            # round-trips. Only tier promoters pass route=False to hit a
            # tiered node directly (client/tiers.py).
            leaf_id = tiers_mod.leaf_aggregation_id(aggregation, self.agent.id)
            aggregation = cached(
                "aggregation", leaf_id,
                lambda: self.service.get_aggregation(self.agent, leaf_id),
            )
            if aggregation is None:
                raise ValueError(
                    "tiered aggregation's sub-committees are not provisioned yet "
                    "(run setup_tier_round first)"
                )
        for secrets in secrets_rows:
            if len(secrets) != aggregation.vector_dimension:
                raise ValueError("The input length does not match the aggregation.")

        committee = cached(
            "committee", aggregation.id,
            lambda: self.service.get_committee(self.agent, aggregation.id),
        )
        if committee is None:
            raise ValueError("Could not find committee")

        # mask the secrets
        masker = self.crypto.new_secret_masker(aggregation.masking_scheme)
        masked = [masker.mask(secrets) for secrets in secrets_rows]

        # recipient mask encryptions (absent under NoMasking)
        recipient_encryptions = [None] * len(masked)
        mask_rows = [m for m, _ in masked]
        if mask_rows and len(mask_rows[0]) > 0:
            recipient_key = self._fetch_verified_key(
                aggregation.recipient, aggregation.recipient_key
            )
            mask_encryptor = self.crypto.new_share_encryptor(
                recipient_key, aggregation.recipient_encryption_scheme
            )
            if hasattr(mask_encryptor, "encrypt_batch"):
                recipient_encryptions = mask_encryptor.encrypt_batch(mask_rows)
            else:
                recipient_encryptions = [mask_encryptor.encrypt(m) for m in mask_rows]

        # share the masked secrets: one share vector per clerk, for every
        # participation in the batch, then seal the whole P x C matrix in
        # one engine call
        generator = self.crypto.new_share_generator(aggregation.committee_sharing_scheme)
        share_rows = [generator.generate(masked_secrets) for _, masked_secrets in masked]

        clerk_ids = [clerk_id for clerk_id, _ in committee.clerks_and_keys]
        clerk_keys = [
            self._fetch_verified_key(clerk_id, clerk_key_id)
            for clerk_id, clerk_key_id in committee.clerks_and_keys
        ]
        encryption_rows = self.crypto.encrypt_share_matrix(
            clerk_keys, aggregation.committee_encryption_scheme, share_rows
        )

        return [
            Participation(
                id=ids[i] if ids is not None else ParticipationId.random(),
                participant=self.agent.id,
                aggregation=aggregation.id,
                recipient_encryption=recipient_encryptions[i],
                clerk_encryptions=list(zip(clerk_ids, encryption_rows[i])),
                tier_reshare=tier_reshare,
            )
            for i in range(len(secrets_rows))
        ]
